"""Continuous-batching request scheduler — the serving admission policy.

The bucketed-length machinery (``autotune.choose_bucket_bounds`` /
``token_fill``) was built as a *training* input policy; this module is
the observation from ROADMAP item 1 made concrete: the same machinery IS
a serving admission policy.  Requests queue with an observed length; the
scheduler admits them into **fixed slot batches** — a fixed decode/batch
slot count so every admitted batch compiles to one signature per bucket
— padding each admitted prompt to the smallest bucket bound that covers
it, and recycles a finished request's slot to the next queued request
without draining the rest of the batch (continuous batching: one
finished sequence never stalls the other slots).

The scheduler is **pure control logic**: no executor, no device, no
wall-clock dependence — time enters only through the injected ``clock``
callable, so every admission decision (bucket selection, FIFO fill,
slot recycling, timeout expiry) is deterministic under a fake clock
(tests drive it tick by tick).  Thread safety is one condition variable:
``submit`` may be called from any thread; the engine's single loop
thread calls ``admit``/``complete``/``fail``.
"""

import collections
import itertools
import threading
import time

from ..monitor import tracing

__all__ = [
    "ServingRequest", "BatchPlan", "ContinuousBatchingScheduler",
    "RequestTimeoutError", "PoisonedRequestError", "EngineClosedError",
]


class RequestTimeoutError(RuntimeError):
    """The request spent longer than its timeout budget (queued or
    running); it was dropped without touching the batch it never made
    or the batch it was evicted from."""


class PoisonedRequestError(RuntimeError):
    """The request's forward produced non-finite outputs; it was
    quarantined (guardian-style poison handling at serving time) and the
    engine kept serving the rest of the batch."""


class EngineClosedError(RuntimeError):
    """The engine shut down before the request completed."""


_req_ids = itertools.count()


class ServingRequest:
    """One queued unit of serving work.

    ``payload`` is engine-defined (a feed dict for the one-shot engine,
    a token list for the generation engine); ``length`` is the bucketed
    dimension (prompt/sequence length; 0 for fixed-shape requests);
    ``rows`` is how many batch slots the request occupies (a client may
    ship a micro-batch per request — the predictor's Run unit — which
    amortizes per-request bookkeeping exactly like the reference's
    multi-example PaddleTensor inputs).  The request doubles as the
    caller's future: ``result()`` blocks until the engine completes or
    fails it."""

    def __init__(self, payload, length=0, arrival=0.0, deadline=None,
                 rows=1):
        self.id = "req-%06d" % next(_req_ids)
        self.payload = payload
        self.length = int(length)
        self.rows = max(1, int(rows))
        self.slots_held = []
        self.arrival = arrival
        self.deadline = deadline
        self.status = "queued"     # queued|running|ok|failed|expired|
        self.slot = None           # quarantined|cancelled
        self.admitted_at = None
        self.finished_at = None
        self.bucket = None
        # per-request trace context (monitor/tracing.RequestTrace) hung
        # here by the engine's submit when FLAGS_trace is on; None means
        # every downstream site skips tracing without calling into it
        self.trace = None
        self._result = None
        self._error = None
        self._done = threading.Event()

    # -- caller side ---------------------------------------------------
    def result(self, timeout=None):
        """Block for the engine's verdict; returns the result payload or
        raises the failure (timeout/poison/engine errors)."""
        if not self._done.wait(timeout):
            raise TimeoutError("request %s still pending" % self.id)
        if self._error is not None:
            raise self._error
        return self._result

    def done(self):
        return self._done.is_set()

    # -- engine side ---------------------------------------------------
    def _finish(self, result, status="ok", now=None):
        self.status = status
        self.finished_at = now
        self._result = result
        self._done.set()

    def _fail(self, error, status="failed", now=None):
        self.status = status
        self.finished_at = now
        self._error = error
        self._done.set()

    def __repr__(self):
        return "ServingRequest(%s, len=%d, %s)" % (self.id, self.length,
                                                   self.status)


class BatchPlan:
    """One admission decision: which requests run, in which slots, at
    which padded bucket length."""

    def __init__(self, requests, slots, bucket):
        self.requests = list(requests)
        self.slots = list(slots)
        self.bucket = bucket

    def __repr__(self):
        return "BatchPlan(%d reqs, bucket=%s, slots=%s)" % (
            len(self.requests), self.bucket, self.slots)


class ContinuousBatchingScheduler:
    """Thread-safe FIFO queue + fixed-slot admission + timeout expiry.

    ``slots``: the fixed batch slot count (the compiled signature's
    batch dim — from the TunedConfig batch_size decision upstream).
    ``bucket_bounds``: sorted padded-length bounds (None = unbucketed,
    fixed-shape requests).  ``clock``: injectable monotonic-seconds
    callable.  ``default_timeout_s``: per-request budget from submit to
    completion (None = no expiry)."""

    def __init__(self, slots, bucket_bounds=None, clock=time.monotonic,
                 default_timeout_s=None, max_queue=4096,
                 admission_gate=None, trace_kind="request"):
        if slots < 1:
            raise ValueError("need at least one slot")
        self.slots = int(slots)
        # the `kind` attr stamped on request trace roots (the engines
        # pass "infer"/"generate")
        self.trace_kind = str(trace_kind)
        self.bucket_bounds = (sorted(int(b) for b in bucket_bounds)
                              if bucket_bounds else None)
        self._clock = clock
        self.default_timeout_s = default_timeout_s
        self.max_queue = int(max_queue)
        # optional resource gate consulted per admission candidate:
        # ``admission_gate(req, picked_so_far) -> bool``.  The paged-KV
        # engine gates on FREE PAGES here (a free slot is no longer
        # sufficient — the pool is deliberately under-provisioned);
        # a refused request stays QUEUED, never fails (exhaustion =
        # queued-not-crashed, retried next admission after releases)
        self.admission_gate = admission_gate
        self._cv = threading.Condition()
        self._queue = collections.deque()
        self._free = collections.deque(range(self.slots))
        self._running = {}           # slot -> request
        self._closed = False

    # -- submission ----------------------------------------------------
    def bucket_for(self, length):
        """Smallest bound covering ``length`` (admission padding
        target), or None when unbucketed.  Over-long requests are a
        submit-time error, not a silent truncation."""
        if self.bucket_bounds is None:
            return None
        for b in self.bucket_bounds:
            if b >= length:
                return b
        raise ValueError(
            "request length %d exceeds the top bucket bound %d"
            % (length, self.bucket_bounds[-1]))

    def submit(self, payload, length=0, timeout_s=None, rows=1):
        """Enqueue one request; returns it (the caller's future)."""
        timeout_s = (self.default_timeout_s if timeout_s is None
                     else timeout_s)
        if rows > self.slots:
            raise ValueError(
                "request rows %d exceed the %d-slot batch" % (rows,
                                                              self.slots))
        now = self._clock()
        # `is not None`, not truthiness: timeout_s=0 means an already-
        # expired budget (expire on the next admission), not "no limit"
        req = ServingRequest(
            payload, length, arrival=now,
            deadline=(now + timeout_s) if timeout_s is not None else None,
            rows=rows)
        req.bucket = self.bucket_for(req.length)   # validates length
        # the trace attaches BEFORE the request becomes visible to the
        # admission loop: an admit racing this submit must already see
        # req.trace, or its queue_wait/dispatch spans are silently lost.
        # The submitting thread's current span (a fleet replica's
        # rpc_server leg) becomes the request tree's parent, so a
        # routed request joins its remote caller's trace; direct
        # submits have no current span and root their own tree.
        if tracing.enabled():
            req.trace = tracing.RequestTrace(
                req.id, kind=self.trace_kind, length=req.length,
                rows=req.rows, parent=tracing.current())
        with self._cv:
            if self._closed:
                raise EngineClosedError("scheduler is closed")
            if len(self._queue) >= self.max_queue:
                raise RuntimeError(
                    "serving queue full (%d requests)" % self.max_queue)
            self._queue.append(req)
            self._cv.notify_all()
        return req

    # -- admission (engine loop thread) --------------------------------
    def admit(self, now=None, max_batch=None):
        """One admission decision: ``(plan_or_None, expired_requests)``.

        Expires timed-out queued requests first (marking them
        ``expired``; the caller publishes).  Then admits up to
        free-slot-count requests FIFO: the HEAD request picks the
        bucket (smallest bound covering it) and the scan fills the
        batch with queued requests that fit the same bucket — later
        shorter requests may jump a longer head-of-line request only
        within the head's own admission, never delay it."""
        now = self._clock() if now is None else now
        with self._cv:
            expired = self._expire_queued_locked(now)
            limit = len(self._free)
            if max_batch is not None:
                limit = min(limit, int(max_batch))
            if not self._queue or limit < 1:
                return None, expired
            bucket = self._queue[0].bucket
            # one FIFO pass: pop-and-pick keeps admission O(queue), not
            # O(queue * batch) — the serving hot path scans thousands of
            # queued requests per second
            picked, kept, rows = [], collections.deque(), 0
            while self._queue and rows < limit:
                req = self._queue.popleft()
                if (bucket is None or req.length <= bucket) \
                        and rows + req.rows <= limit \
                        and (self.admission_gate is None
                             or self.admission_gate(req, picked)):
                    picked.append(req)
                    rows += req.rows
                else:
                    kept.append(req)
            kept.extend(self._queue)      # the unscanned tail, in order
            self._queue = kept
            if not picked:
                return None, expired
            slots = []
            for req in picked:
                req.slots_held = [self._free.popleft()
                                  for _ in range(req.rows)]
                req.slot = req.slots_held[0]
                req.status = "running"
                req.admitted_at = now
                self._running[req.slot] = req
                slots.extend(req.slots_held)
            return BatchPlan(picked, slots, bucket), expired

    def _expire_queued_locked(self, now):
        expired = []
        keep = collections.deque()
        for req in self._queue:
            if req.deadline is not None and now >= req.deadline:
                req._fail(RequestTimeoutError(
                    "request %s expired after %.3fs in queue"
                    % (req.id, now - req.arrival)), status="expired",
                    now=now)
                expired.append(req)
            else:
                keep.append(req)
        self._queue = keep
        return expired

    def expired_running(self, now=None):
        """Running requests past their deadline (the generation loop
        evicts these mid-decode); the caller must ``fail`` each."""
        now = self._clock() if now is None else now
        with self._cv:
            return [r for r in self._running.values()
                    if r.deadline is not None and now >= r.deadline]

    # -- completion / recycling ----------------------------------------
    def _release_locked(self, req):
        if req.slot is not None and self._running.get(req.slot) is req:
            del self._running[req.slot]
            self._free.extend(req.slots_held or [req.slot])
            self._cv.notify_all()

    def complete(self, req, result, now=None):
        """Finish one running request and recycle its slot — the other
        slots keep running; the freed slot is admit()-able immediately
        (in-flight recycling, no batch drain).  Returns False when the
        request already reached a terminal state (e.g. cancelled by
        close() while its batch was in flight) — the late result must
        not overwrite the decision the caller already observed."""
        now = self._clock() if now is None else now
        with self._cv:
            self._release_locked(req)
        if req.done():
            return False
        req._finish(result, now=now)
        return True

    def fail(self, req, error, status="failed", now=None):
        now = self._clock() if now is None else now
        with self._cv:
            self._release_locked(req)
        if req.done():
            return False
        req._fail(error, status=status, now=now)
        return True

    # -- engine loop support -------------------------------------------
    def wait_for_work(self, timeout=None):
        """Block until a request is queued (and a slot is free) or the
        scheduler closes; returns whether work might be available."""
        with self._cv:
            if self._closed:
                return False
            if self._queue and self._free:
                return True
            self._cv.wait(timeout)
            return bool(self._queue and self._free) and not self._closed

    def close(self, error=None):
        """Refuse new work and fail everything in flight."""
        error = error or EngineClosedError("serving engine closed")
        with self._cv:
            self._closed = True
            pending = list(self._queue) + list(self._running.values())
            self._queue.clear()
            self._running.clear()
            self._free = collections.deque(range(self.slots))
            self._cv.notify_all()
        for req in pending:
            req._fail(error, status="cancelled")

    @property
    def closed(self):
        return self._closed

    # -- observability -------------------------------------------------
    def queue_depth(self):
        with self._cv:
            return len(self._queue)

    def busy_slots(self):
        with self._cv:
            return sum(r.rows for r in self._running.values())

    def occupancy(self):
        """Busy fraction of the fixed slot batch (the SLO gauge)."""
        return self.busy_slots() / float(self.slots)

    def running(self):
        with self._cv:
            return dict(self._running)

    def pending(self):
        """Snapshot of the queued (not yet admitted) requests, FIFO
        order — the watchdog's in-flight request dump reads this."""
        with self._cv:
            return list(self._queue)
