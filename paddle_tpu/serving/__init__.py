"""Serving subsystem: the framework's first non-training workload.

ROADMAP item 1 ("millions of users, heavy traffic") realized over the
existing substrate — the bucketed-length machinery as an admission
policy, the TunedConfig artifact as the admitted-batch/bucket source,
the goodput ledger for chip-utilization-per-request, and guardian-style
request health (timeouts, poison quarantine).  See the package modules:

* ``scheduler``  — continuous-batching queue/admission (pure, fake-
  clock-testable control logic);
* ``engine``     — :class:`InferenceEngine` (one-shot forward serving)
  and :class:`GenerationEngine` (prefill + donated KV-cache decode);
* ``decoder``    — score/prefill/decode program builder for decoder
  LMs;
* ``kv_cache``   — per-slot cache state over executor scope variables,
  plus the paged page-pool store and its host-side page allocator
  (prefix sharing, int8 pages, leak accounting);
* ``metrics``    — SLO observability (p50/p99, queue/occupancy gauges,
  per-request JSONL events, serving goodput view, fleet routing
  counters);
* ``fleet``      — the pod-scale serving fabric: N replica hosts
  behind a ClusterMaster-backed routing master (least-loaded
  admission, session affinity, quarantine + epoch-guarded re-dispatch
  on lease expiry).
"""

from .scheduler import (ContinuousBatchingScheduler, ServingRequest,
                        BatchPlan, RequestTimeoutError,
                        PoisonedRequestError, EngineClosedError)
from .metrics import ServingMetrics, FleetMetrics
from .kv_cache import (KVCacheStore, OutOfPagesError, PageAllocator,
                       PagedKVCacheStore)
from .decoder import DecoderSpec, build_decoder_lm, sync_draft_weights
from .engine import InferenceEngine, GenerationEngine
from .fleet import (FleetMaster, FleetReplica, FleetClient,
                    ReplicaService, FleetError, NoReplicasError,
                    FleetRouteError)

__all__ = [
    "ContinuousBatchingScheduler", "ServingRequest", "BatchPlan",
    "RequestTimeoutError", "PoisonedRequestError", "EngineClosedError",
    "ServingMetrics", "FleetMetrics", "KVCacheStore", "PageAllocator",
    "PagedKVCacheStore", "OutOfPagesError", "DecoderSpec",
    "build_decoder_lm", "sync_draft_weights", "InferenceEngine",
    "GenerationEngine", "FleetMaster", "FleetReplica", "FleetClient",
    "ReplicaService", "FleetError", "NoReplicasError",
    "FleetRouteError",
]
