"""Serving SLO observability: request latency percentiles, queue/batch
gauges, per-request JSONL events, and a goodput-ledger serving view.

Rides the PR-2/PR-8 monitor stack rather than inventing a sink: every
counter/gauge/histogram lands in ``monitor.registry()`` under
``serving/*`` (Prometheus exposition + console reporter for free), every
request emits a run_id-stamped ``serving_request`` JSONL event (the
Dapper-style correlation the monitor already does for steps), and the
serving view divides the goodput ledger's attributed compute seconds by
completed requests — chip-utilization-per-request without new
accounting.  Exact p50/p99 come from a bounded in-memory latency window
(the artifact's SLO numbers must be exact, not bucket-interpolated); the
registry histogram carries the same observations for scraping.

Poison quarantine follows the guardian's batch-quarantine format
(``batch_*.npz`` + json sidecar): a request whose forward produces NaN
is rejected with :class:`~.scheduler.PoisonedRequestError` and its
payload persisted for repro — the engine keeps serving."""

import json
import os
import threading
import time

import numpy as np

__all__ = ["ServingMetrics", "FleetMetrics"]

# latency-shaped buckets in seconds for the registry histogram: serving
# requests span ~1ms (warm single dispatch) to tens of seconds (long
# decode); the step-stats DEFAULT_BUCKETS top out too early for queues
LATENCY_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# speculation acceptance rate is a fraction of proposed draft tokens the
# target accepted per verify round — eighth-width buckets resolve the
# "is the draft any good on this workload" question at a glance
ACCEPTANCE_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServingMetrics:
    """One instance per engine; every entry point is cheap and never
    raises into the serving path (telemetry contract shared with the
    monitor)."""

    WINDOW = 8192                  # exact-percentile latency window

    def __init__(self, name="serving", quarantine_dir=None):
        self.name = name
        self.quarantine_dir = quarantine_dir
        self._mu = threading.Lock()
        self._lat = []             # latency seconds, bounded WINDOW
        # (latency, trace_id) pairs riding the same window: the p99
        # exemplars — "why is p99 high" resolves to concrete trace_ids
        # whose assembled trees show where the time went
        self._exemplars = []
        self._first_ts = None
        self._last_ts = None
        self._counts = {"submitted": 0, "completed": 0, "failed": 0,
                        "expired": 0, "quarantined": 0, "batches": 0,
                        "decode_steps": 0, "generated_tokens": 0,
                        "prefix_hits": 0, "prefix_misses": 0,
                        "spec_rounds": 0, "spec_proposed": 0,
                        "spec_accepted": 0}
        # registry handles cached per generation (the monitor's own
        # pattern): the submit/complete hot path must not pay a
        # get-or-create registry lock per request
        self._handles = {}
        self._handle_gen = -1

    # -- registry handles (gated on the monitor, like every producer) --
    def _reg(self):
        from .. import monitor

        return monitor.registry() if monitor.enabled() else None

    def _handle(self, reg, kind, metric, **kw):
        if self._handle_gen != reg.generation:
            self._handles.clear()
            self._handle_gen = reg.generation
        h = self._handles.get(metric)
        if h is None:
            h = self._handles[metric] = getattr(reg, kind)(
                "%s/%s" % (self.name, metric), **kw)
        return h

    def _count(self, key, metric, amount=1):
        with self._mu:
            self._counts[key] = self._counts.get(key, 0) + amount
        reg = self._reg()
        if reg is not None:
            self._handle(reg, "counter", metric).inc(amount)

    def _gauge(self, metric, value):
        reg = self._reg()
        if reg is not None:
            self._handle(reg, "gauge", metric).set(value)

    def _event(self, record):
        from .. import monitor

        record.setdefault("ts", time.time())
        monitor.log_event(record)

    # -- request lifecycle ---------------------------------------------
    def note_submit(self, req, queue_depth):
        self._count("submitted", "requests_total")
        self._gauge("queue_depth", queue_depth)
        with self._mu:
            if self._first_ts is None:
                self._first_ts = time.time()

    def note_admit(self, plan, occupancy, queue_depth):
        self._count("batches", "batches_total")
        self._gauge("batch_occupancy", occupancy)
        self._gauge("queue_depth", queue_depth)

    def note_decode_step(self, active, occupancy):
        self._count("decode_steps", "decode_steps_total")
        self._gauge("batch_occupancy", occupancy)

    # -- paged-KV / speculation telemetry (ISSUE 16) -------------------
    def note_kv_pages(self, in_use, free):
        self._gauge("kv_pages_in_use", in_use)
        self._gauge("kv_pages_free", free)

    def note_prefix_cache(self, hits, misses):
        """Increment the prefix-sharing counters by this admission's
        delta (full prompt pages aliased vs freshly written)."""
        if hits:
            self._count("prefix_hits", "prefix_cache_hits", hits)
        if misses:
            self._count("prefix_misses", "prefix_cache_misses", misses)

    def note_speculation(self, accepted, proposed):
        """One verify round: ``accepted`` of ``proposed`` draft tokens
        survived the target's greedy check."""
        self._count("spec_rounds", "speculation_rounds_total")
        self._count("spec_proposed", "speculation_proposed_total",
                    proposed)
        self._count("spec_accepted", "speculation_accepted_total",
                    accepted)
        reg = self._reg()
        if reg is not None and proposed:
            self._handle(reg, "histogram",
                         "speculation_acceptance_rate",
                         buckets=ACCEPTANCE_BUCKETS).observe(
                             accepted / float(proposed))

    def paged_snapshot(self):
        """The paged/speculation counters as a dict — the engine stamps
        this into each completion's JSONL record (run_id-stamped by
        ``monitor.log_event`` like every serving event)."""
        with self._mu:
            c = self._counts
            snap = {k: c[k] for k in ("prefix_hits", "prefix_misses",
                                      "spec_rounds", "spec_proposed",
                                      "spec_accepted")}
        total = snap["prefix_hits"] + snap["prefix_misses"]
        snap["prefix_hit_rate"] = (round(snap["prefix_hits"] / total, 4)
                                   if total else None)
        snap["spec_acceptance_rate"] = (
            round(snap["spec_accepted"] / snap["spec_proposed"], 4)
            if snap["spec_proposed"] else None)
        return snap

    def note_complete(self, req, now=None, extra=None):
        now = time.time() if now is None else now
        queue_s = ((req.admitted_at - req.arrival)
                   if req.admitted_at is not None else 0.0)
        # latency on the engine's own clock base: arrival/finished are
        # scheduler-clock stamps, so the difference is wall seconds
        lat = ((req.finished_at - req.arrival)
               if req.finished_at is not None and req.arrival else 0.0)
        trace = getattr(req, "trace", None)
        tid = trace.trace_id if trace is not None else None
        self._count("completed", "completed_total")
        with self._mu:
            self._lat.append(lat)
            del self._lat[:-self.WINDOW]
            self._exemplars.append((lat, tid))
            del self._exemplars[:-self.WINDOW]
            self._last_ts = now
        reg = self._reg()
        if reg is not None:
            self._handle(reg, "histogram", "request_latency_seconds",
                         buckets=LATENCY_BUCKETS).observe(lat)
        rec = {"event": "serving_request", "request_id": req.id,
               "status": "ok", "latency_ms": round(lat * 1e3, 3),
               "queue_ms": round(queue_s * 1e3, 3),
               "bucket": req.bucket, "slot": req.slot,
               "length": req.length}
        if tid is not None:
            rec["trace_id"] = tid
        if extra:
            rec.update(extra)
        self._event(rec)
        # the terminal is the ONE place every engine path funnels
        # through, so the request's root span closes here (idempotent)
        if trace is not None:
            trace.finish("ok", latency_ms=rec["latency_ms"])

    def note_failure(self, req, error, status="failed"):
        # quarantined requests are counted by quarantine() itself (the
        # decision record); here only the terminal event is published
        if status != "quarantined":
            # count under the RESOLVED key so summary() and /metrics
            # agree (an unknown status like "cancelled" is a failure on
            # both surfaces, not a phantom metric family)
            key = status if status in self._counts else "failed"
            self._count(key, "timeout_total" if key == "expired"
                        else "%s_total" % key)
        trace = getattr(req, "trace", None)
        rec = {"event": "serving_request", "request_id": req.id,
               "status": status, "error": str(error)[:200],
               "bucket": req.bucket, "length": req.length}
        if trace is not None:
            rec["trace_id"] = trace.trace_id
        self._event(rec)
        if trace is not None:
            trace.finish(status, error=str(error)[:120])

    # -- poison quarantine (guardian-style request health) -------------
    def quarantine(self, req, feed=None, reason="non-finite output"):
        """Persist the poisoned request for repro and publish the
        decision; returns the quarantine record."""
        from .. import monitor

        self._count("quarantined", "quarantined_total")
        rec = {"event": "serving_quarantine", "request_id": req.id,
               "reason": reason, "run_id": monitor.run_id(),
               "ts": time.time(), "path": None}
        if feed is not None:
            names = sorted(feed)
            rec["feed_signature"] = [
                (n, list(np.shape(feed[n])), str(np.asarray(feed[n]).dtype))
                for n in names]
            if self.quarantine_dir:
                try:
                    os.makedirs(self.quarantine_dir, exist_ok=True)
                    base = os.path.join(
                        self.quarantine_dir, "request_%s_%s"
                        % (monitor.run_id(), req.id))
                    # positional npz members + a name list in the
                    # sidecar (the guardian's batch-quarantine scheme:
                    # npz member names can't carry '/' etc. across
                    # numpy versions)
                    with open(base + ".npz", "wb") as f:
                        np.savez(f, **{"arr_%d" % i: np.asarray(feed[n])
                                       for i, n in enumerate(names)})
                    rec["feed_names"] = names
                    rec["path"] = base + ".npz"
                    with open(base + ".json", "w") as f:
                        json.dump(rec, f)
                except OSError as e:
                    # telemetry never breaks the serving path: an
                    # unwritable quarantine dir degrades to an event
                    # without a dump, not an engine-batch failure
                    rec["path"] = None
                    rec["dump_error"] = str(e)[:200]
        self._event(dict(rec))
        return rec

    # -- read side ------------------------------------------------------
    def percentiles(self):
        with self._mu:
            vals = sorted(self._lat)
        return {"p50_s": _percentile(vals, 0.50),
                "p90_s": _percentile(vals, 0.90),
                "p99_s": _percentile(vals, 0.99),
                "mean_s": (sum(vals) / len(vals)) if vals else None,
                "n": len(vals)}

    def p99_exemplars(self, k=5):
        """The trace_ids of the slowest traced requests in the current
        latency window, slowest first — p99 attribution: each id
        resolves to an assembled span tree (tools/request_trace.py)
        showing where that request's time went."""
        with self._mu:
            pairs = [p for p in self._exemplars if p[1] is not None]
        pairs.sort(key=lambda p: -p[0])
        return [tid for _lat, tid in pairs[:max(1, int(k))]]

    def summary(self):
        """Counts, exact latency percentiles, observed throughput, and
        the serving goodput view (chip-utilization-per-request riding
        the PR-8 ledger)."""
        from .. import monitor

        with self._mu:
            counts = dict(self._counts)
            first, last = self._first_ts, self._last_ts
        pct = self.percentiles()
        out = {"counts": counts}
        for k in ("p50_s", "p90_s", "p99_s", "mean_s"):
            out[k.replace("_s", "_ms")] = (round(pct[k] * 1e3, 3)
                                           if pct[k] is not None else None)
        span = (last - first) if first and last and last > first else None
        out["throughput_rps"] = (round(counts["completed"] / span, 2)
                                 if span and counts["completed"] else None)
        gp = monitor.goodput_summary()
        view = {"goodput_ratio": gp.get("goodput_ratio"),
                "compute_seconds": gp["buckets"].get("compute")
                if gp.get("buckets") else None}
        if counts["completed"] and view["compute_seconds"] is not None:
            view["compute_seconds_per_request"] = round(
                view["compute_seconds"] / counts["completed"], 6)
        out["goodput_view"] = view
        out["p99_exemplars"] = self.p99_exemplars()
        return out


class FleetMetrics:
    """Fleet-master-side routing observability (``serving.fleet``):
    route/re-route/affinity counters under ``fleet/*`` in the monitor
    registry, plus an exact bounded re-route-latency window — "how long
    did a failed-over request take to land on a survivor" is an SLO
    number the failover artifact must state exactly, not estimate.

    Same telemetry contract as :class:`ServingMetrics`: every entry
    point is cheap, registry handles are generation-cached, and nothing
    in here ever raises into the routing path."""

    WINDOW = 2048                  # exact re-route latency window

    def __init__(self, name="fleet"):
        self.name = name
        self._mu = threading.Lock()
        self._reroute_lat = []     # seconds, bounded WINDOW
        self._counts = {"routes": 0, "reroutes": 0, "completions": 0,
                        "stale_completions": 0, "affinity_hits": 0,
                        "affinity_misses": 0, "orphaned": 0,
                        "quarantined_replicas": 0, "unavailable": 0,
                        "expired_tickets": 0, "failures_reported": 0}
        self._handles = {}
        self._handle_gen = -1

    def _reg(self):
        from .. import monitor

        return monitor.registry() if monitor.enabled() else None

    def _handle(self, reg, kind, metric, **kw):
        if self._handle_gen != reg.generation:
            self._handles.clear()
            self._handle_gen = reg.generation
        h = self._handles.get(metric)
        if h is None:
            h = self._handles[metric] = getattr(reg, kind)(
                "%s/%s" % (self.name, metric), **kw)
        return h

    def count(self, key, amount=1):
        with self._mu:
            self._counts[key] = self._counts.get(key, 0) + amount
        reg = self._reg()
        if reg is not None:
            self._handle(reg, "counter", "%s_total" % key).inc(amount)

    def note_route(self, affinity):
        """One routing decision; ``affinity`` is True (pinned replica
        honored), False (session re-pinned), or None (no session)."""
        self.count("routes")
        if affinity is True:
            self.count("affinity_hits")
        elif affinity is False:
            self.count("affinity_misses")

    def note_reroute_complete(self, latency_s):
        """A re-dispatched request completed: ``latency_s`` is first
        route to accepted completion — the failover cost the artifact
        reports as ``reroute_latency_ms``."""
        with self._mu:
            self._reroute_lat.append(float(latency_s))
            del self._reroute_lat[:-self.WINDOW]
        reg = self._reg()
        if reg is not None:
            self._handle(reg, "histogram", "reroute_latency_seconds",
                         buckets=LATENCY_BUCKETS).observe(
                             float(latency_s))

    def reroute_percentiles(self):
        with self._mu:
            vals = sorted(self._reroute_lat)
        return {"p50_s": _percentile(vals, 0.50),
                "p99_s": _percentile(vals, 0.99),
                "mean_s": (sum(vals) / len(vals)) if vals else None,
                "n": len(vals)}

    def summary(self):
        with self._mu:
            counts = dict(self._counts)
        pins = counts["affinity_hits"] + counts["affinity_misses"]
        pct = self.reroute_percentiles()
        return {"counts": counts,
                "affinity_hit_rate": (round(counts["affinity_hits"]
                                            / pins, 4) if pins else None),
                "reroute_latency_ms": {
                    k.replace("_s", "_ms"):
                        (round(v * 1e3, 3) if v is not None else None)
                    for k, v in pct.items() if k != "n"},
                "reroutes_measured": pct["n"]}
