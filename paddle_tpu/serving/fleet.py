"""Pod-scale serving fabric (ISSUE 18 tentpole): multi-replica routed
serving over the cluster runtime.

The reference's production story is a fleet — ``listen_and_serv``
pservers behind a dispatching master, with the Go/etcd master owning
membership and fault tolerance.  Here the same shape composes from
parts that already exist:

* **replica hosts** run an :class:`~.engine.InferenceEngine` /
  :class:`~.engine.GenerationEngine` behind a data-plane
  ``cloud.MasterServer`` (:class:`ReplicaService`), and hold a
  ``cluster.ClusterMember`` session against the fleet master whose
  heartbeats carry the engine's live load report
  (:meth:`~.engine._EngineBase.load_report` — queue depth, occupancy,
  SLO percentiles);
* the **fleet master** (:class:`FleetMaster`, a ``ClusterMaster``
  subclass served by the unmodified ``cloud.MasterServer``) routes:
  least-loaded admission over the heartbeat-reported queue depths plus
  its own in-flight ledger, **session affinity** so a multi-turn
  generation stays pinned to the replica holding its KV pages (the
  paged allocator's prefix sharing makes the pin worth keeping), and
  replica death handled the PR-13 way — lease expiry quarantines the
  replica and re-dispatches its in-flight tickets under epoch-guarded
  attempt fencing (the task-master lease pattern), never a drop;
* the **client** (:class:`FleetClient`) speaks the existing
  ``MasterClient`` TCP/JSON envelope for BOTH legs — control plane
  (route/complete) and data plane (generate/infer) — so the
  full-jitter exponential backoff, ``rpc_retry`` span markers, and
  per-method latency histograms are the one retry idiom everywhere.

Epoch-guarded semantics (who owns a request is the MASTER's decision,
never a zombie's): every routed ticket carries an ``attempt`` number;
any re-dispatch — a swept lease, a client-reported data-plane failure,
an explicit re-route — bumps it, and ``complete`` only retires the
ticket when the attempt matches.  A replica that was quarantined while
still computing (the network-partition zombie) produces a STALE
completion: the client discards that result and follows the master's
re-route, so exactly one accepted completion wins.  Requests are
client-anchored — the client holds the payload and retries until an
accepted completion — so a SIGKILLed replica loses work, never a
request.

Tracing: one fleet request assembles into ONE tree across three
processes — the client's ``fleet_request`` root, the master's ``route``
decision span (its context rides back on the route response), and the
replica-side ``request`` tree (adopted via the data-plane envelope +
the scheduler's current-span parent), i.e.
``fleet_request → rpc/route → route → rpc/generate →
rpc_server/generate → request → queue_wait/prefill/decode``.
"""

import collections
import itertools
import threading
import time

import numpy as np

from ..cloud.server import MasterClient, MasterServer
from ..cluster.membership import ClusterMaster
from ..cluster.runtime import ClusterMember, _transport
from ..monitor import tracing
from .metrics import FleetMetrics

__all__ = ["FleetMaster", "FleetReplica", "FleetClient",
           "ReplicaService", "FleetError", "NoReplicasError",
           "FleetRouteError", "encode_feed", "decode_feed"]


class FleetError(RuntimeError):
    """Base class for fleet routing failures."""


class NoReplicasError(FleetError):
    """No live replica advertised a data-plane address."""


class FleetRouteError(FleetError):
    """The route/dispatch/complete loop exhausted its attempt budget."""


def encode_feed(feed):
    """JSON-marshal an InferenceEngine feed dict (name -> ndarray):
    nested lists + dtype string.  float32 values survive the JSON
    double round-trip exactly (float32 -> double -> float32 is
    value-preserving), so fleet-routed inference stays bit-identical
    to direct dispatch."""
    out = {}
    for name, val in feed.items():
        arr = np.asarray(val)
        out[name] = {"data": arr.tolist(), "dtype": str(arr.dtype)}
    return out


def decode_feed(feed):
    return {name: np.array(v["data"], dtype=v["dtype"])
            for name, v in feed.items()}


# ---------------------------------------------------------------------------
# fleet master: ClusterMaster + routing
# ---------------------------------------------------------------------------

class FleetMaster(ClusterMaster):
    """Routing control plane over ClusterMaster's membership machinery.

    Replicas ``join`` with ``meta={"address": <data-plane host:port>,
    "kind": ...}`` and renew their lease with heartbeats carrying
    ``{"load": engine.load_report()}``; everything membership —
    deadlines in the snapshotted state, lazy ``_sweep`` expiry under
    the lock, epoch bumps on any change — is inherited unchanged.  This
    class adds the ticket ledger (``route``/``complete``/
    ``report_failure``) and the quarantine + re-dispatch reaction to a
    swept lease.

    Ticket bookkeeping is advisory observability + zombie fencing; the
    never-drop guarantee is client-anchored (the client holds the
    payload).  A master restart therefore answers ``complete`` for a
    pre-restart ticket with ``unknown_ticket`` — the client keeps the
    (valid) result; only a STALE attempt forces a discard."""

    def __init__(self, store=None, lease_timeout=10.0, clock=time.time,
                 ticket_timeout=600.0, **kw):
        super().__init__(store=store, lease_timeout=lease_timeout,
                         clock=clock, **kw)
        self.ticket_timeout = float(ticket_timeout)
        self._tickets = {}         # ticket -> assignment dict
        self._sessions = {}        # session_id -> pinned replica host
        self._quarantined = collections.OrderedDict()  # host -> record
        self._ticket_seq = itertools.count(1)
        self.fleet_metrics = FleetMetrics()

    @staticmethod
    def rpc_methods():
        return ClusterMaster.rpc_methods() + (
            "route", "complete", "report_failure", "fleet_stats")

    # -- membership reactions ------------------------------------------
    def _sweep(self):
        before = set(self._members)
        changed = super()._sweep()
        if changed:
            self._orphan_replicas(before - set(self._members),
                                  reason="lease_expired")
        self._expire_tickets()
        return changed

    def leave(self, host_id):
        """Graceful departure also orphans the replica's in-flight
        tickets (a draining replica may still abandon work — the
        clients re-route exactly like a death, minus the quarantine
        verdict)."""
        with self._mu:
            if str(host_id) in self._members:
                self._orphan_replicas({str(host_id)}, reason="leave")
            return super().leave(host_id)

    def _orphan_replicas(self, dead, reason):
        """Quarantine dead replicas and mark their in-flight tickets
        for re-dispatch (lock held).  Bumping each orphan's attempt IS
        the epoch guard: a quarantined-but-alive zombie finishing the
        old attempt can only produce a stale completion."""
        for host in sorted(dead):
            orphans = []
            for ticket, asn in self._tickets.items():
                if asn.get("replica") == host:
                    asn["attempt"] += 1
                    asn["replica"] = None
                    asn["address"] = None
                    asn["avoid"] = host
                    orphans.append(ticket)
            for sess, rep in list(self._sessions.items()):
                if rep == host:          # its KV pages died with it
                    del self._sessions[sess]
            if reason == "lease_expired":
                self._quarantined[host] = {
                    "at": self._clock(), "epoch": self._epoch,
                    "orphaned": list(orphans)}
                while len(self._quarantined) > 64:
                    self._quarantined.popitem(last=False)
                self.fleet_metrics.count("quarantined_replicas")
                if self._telemetry is not None:
                    try:
                        # feeds the replica-quarantine alert rule
                        self._telemetry.note_quarantined(host)
                    except Exception:
                        pass
            if orphans:
                self.fleet_metrics.count("orphaned", len(orphans))
            self._event({"event": "fleet_replica_quarantined",
                         "replica": host, "reason": reason,
                         "orphaned": orphans, "epoch": self._epoch})

    def _expire_tickets(self):
        """Drop tickets whose owner client went silent past the ticket
        timeout (lock held) — ledger hygiene, not a request drop: an
        expired ticket means the CLIENT died, and a request dies with
        its owner, never with a replica."""
        now = self._clock()
        stale = [t for t, a in self._tickets.items()
                 if a["deadline"] <= now]
        for t in stale:
            del self._tickets[t]
        if stale:
            self.fleet_metrics.count("expired_tickets", len(stale))

    # -- routing --------------------------------------------------------
    def _score(self, member):
        """Least-loaded rank (lock held): the master's own in-flight
        ledger (exact) plus the replica's last heartbeat-reported queue
        depth (fresh to within lease/3)."""
        inflight = sum(1 for a in self._tickets.values()
                       if a.get("replica") == member.host_id)
        load = member.meta.get("load") or {}
        return inflight + int(load.get("queue_depth") or 0)

    def route(self, session_id, kind, length, ticket=None):
        """One routing decision; returns the assignment
        ``{ticket, attempt, replica, address, epoch[, trace]}`` or
        ``{"unavailable": True}`` when no replica is routable.

        Passing an existing ``ticket`` re-routes it: the previous
        assignment (if any still stands) is fenced — attempt bumped,
        session unpinned from the failed replica — and the re-dispatch
        avoids that replica unless it is the sole survivor."""
        session_id = str(session_id) if session_id else None
        with self._mu:
            self._sweep()
            now = self._clock()
            asn = self._tickets.get(ticket) if ticket else None
            avoid = None
            if asn is not None:
                if asn.get("replica") is not None:
                    avoid = asn["replica"]
                    asn["avoid"] = avoid
                    if session_id and \
                            self._sessions.get(session_id) == avoid:
                        del self._sessions[session_id]
                else:
                    avoid = asn.get("avoid")
                self.fleet_metrics.count("reroutes")
            cands = {h: m for h, m in self._members.items()
                     if m.meta.get("address")}
            pick_from = {h: m for h, m in cands.items()
                         if h != avoid} or cands
            if not pick_from:
                self.fleet_metrics.count("unavailable")
                return {"unavailable": True, "epoch": self._epoch}
            affinity = None
            choice = None
            pinned = (self._sessions.get(session_id)
                      if session_id else None)
            if pinned is not None:
                affinity = pinned in pick_from
                if affinity:
                    choice = pinned
            if choice is None:
                # straggler verdicts (fleet telemetry) are a SOFT
                # deprioritization: a flagged replica loses score ties
                # but still serves when it is genuinely least loaded —
                # quarantine stays lease-driven
                strag = ()
                if self._telemetry is not None:
                    try:
                        strag = self._telemetry.straggler_hosts()
                    except Exception:
                        strag = ()
                # sorted first: equal scores break deterministically
                choice = min(sorted(pick_from),
                             key=lambda h: (self._score(pick_from[h]),
                                            h in strag))
            if session_id:
                self._sessions[session_id] = choice
            if asn is None:
                ticket = "tkt-%06d" % next(self._ticket_seq)
                asn = self._tickets[ticket] = {
                    "session": session_id, "kind": str(kind),
                    "length": int(length or 0), "attempt": 0,
                    "first_routed": now, "avoid": None}
            asn["attempt"] += 1
            asn["replica"] = choice
            asn["address"] = pick_from[choice].meta["address"]
            asn["routed_at"] = now
            asn["deadline"] = now + self.ticket_timeout
            self.fleet_metrics.note_route(affinity)
            resp = {"ticket": ticket, "attempt": asn["attempt"],
                    "replica": choice, "address": asn["address"],
                    "epoch": self._epoch}
            if tracing.enabled():
                # the routing-decision span; its context rides the
                # response so the client parents the data-plane
                # dispatch (and through it the replica's request tree)
                # under THIS span — the master's decision heads the
                # replica-side subtree across the process boundary
                s = tracing.Span("route", parent=tracing.current(),
                                 attrs={"ticket": ticket,
                                        "replica": choice,
                                        "attempt": asn["attempt"],
                                        "affinity": affinity})
                s.finish("ok")
                resp["trace"] = s.context()
            return resp

    def complete(self, ticket, attempt):
        """Retire a ticket — accepted only when ``attempt`` matches the
        current assignment (the epoch guard): a ticket re-dispatched
        after a quarantine rejects the zombie attempt's completion, and
        the client discards that result and follows the re-route."""
        with self._mu:
            self._sweep()
            asn = self._tickets.get(ticket)
            if asn is None:
                return {"accepted": False, "reason": "unknown_ticket"}
            if int(attempt) != asn["attempt"]:
                self.fleet_metrics.count("stale_completions")
                return {"accepted": False, "reason": "stale_attempt",
                        "attempt": asn["attempt"]}
            del self._tickets[ticket]
            self.fleet_metrics.count("completions")
            if asn["attempt"] > 1:
                # first route -> accepted completion: the failover cost
                self.fleet_metrics.note_reroute_complete(
                    self._clock() - asn["first_routed"])
            return {"accepted": True}

    def report_failure(self, ticket, attempt, error=None):
        """Client-observed data-plane failure: fence the assignment
        (attempt bump — any late result from the failed dispatch goes
        stale) and unpin the session, so the following ``route`` call
        re-dispatches away from the failed replica."""
        with self._mu:
            self._sweep()
            self.fleet_metrics.count("failures_reported")
            asn = self._tickets.get(ticket)
            if asn is None or int(attempt) != asn["attempt"]:
                return {"accepted": False}
            failed = asn.get("replica")
            if failed is not None:
                asn["attempt"] += 1
                asn["replica"] = None
                asn["address"] = None
                asn["avoid"] = failed
                if asn["session"] and \
                        self._sessions.get(asn["session"]) == failed:
                    del self._sessions[asn["session"]]
            self._event({"event": "fleet_data_failure",
                         "ticket": ticket, "replica": failed,
                         "error": str(error)[:200]})
            return {"accepted": True, "attempt": asn["attempt"]}

    def fleet_stats(self):
        with self._mu:
            self._sweep()
            replicas = {}
            for h, m in self._members.items():
                if not m.meta.get("address"):
                    continue
                replicas[h] = {
                    "address": m.meta["address"],
                    "kind": m.meta.get("kind"),
                    "load": m.meta.get("load") or {},
                    "inflight": sum(
                        1 for a in self._tickets.values()
                        if a.get("replica") == h)}
            return {"epoch": self._epoch, "replicas": replicas,
                    "tickets_inflight": len(self._tickets),
                    "pending_reroute": sum(
                        1 for a in self._tickets.values()
                        if a.get("replica") is None),
                    "sessions_pinned": len(self._sessions),
                    "quarantined": {
                        h: {"at": q["at"], "epoch": q["epoch"],
                            "orphaned": len(q["orphaned"])}
                        for h, q in self._quarantined.items()},
                    "fleet": self.fleet_metrics.summary()}


# ---------------------------------------------------------------------------
# replica side: data-plane service + fleet session
# ---------------------------------------------------------------------------

class ReplicaService:
    """The data-plane RPC surface of one replica host, served by the
    unmodified ``cloud.MasterServer`` (allowlist dispatch, threaded
    handlers — a blocking ``generate`` occupies only its own handler
    thread).  The server dispatches each call under its
    ``rpc_server/<method>`` span, so the engine's request tree —
    created by the scheduler with ``parent=tracing.current()`` — joins
    the remote caller's trace automatically."""

    def __init__(self, engine):
        self.engine = engine

    @staticmethod
    def rpc_methods():
        return ("generate", "infer", "load_report", "replica_stats")

    def generate(self, ticket, attempt, session_id, prompt_ids,
                 max_new_tokens=None, timeout_s=None):
        req = self.engine.submit([int(t) for t in prompt_ids],
                                 max_new_tokens=max_new_tokens,
                                 timeout_s=timeout_s)
        res = req.result(timeout=None)   # engine deadline bounds this
        # JSON-safe subset only (record_logits arrays stay host-side)
        return {"ticket": ticket, "attempt": attempt,
                "tokens": [int(t) for t in res["tokens"]],
                "prompt_len": int(res["prompt_len"])}

    def infer(self, ticket, attempt, feed, rows=1, timeout_s=None):
        req = self.engine.submit(decode_feed(feed), timeout_s=timeout_s,
                                 rows=rows)
        outs = req.result(timeout=None)
        return {"ticket": ticket, "attempt": attempt,
                "outputs": [{"data": np.asarray(a).tolist(),
                             "dtype": str(np.asarray(a).dtype)}
                            for a in outs]}

    def load_report(self):
        return self.engine.load_report()

    def replica_stats(self):
        return {"load": self.engine.load_report(),
                "summary": self.engine.metrics.summary()}


class FleetReplica:
    """One replica host: the engine's data-plane server plus a
    ``ClusterMember`` session against the fleet master.  The session's
    join meta advertises the data-plane address; every heartbeat (the
    member's daemon thread, lease/3 cadence) carries the engine's live
    load report, which is what the master's least-loaded admission
    ranks on.  The engine is caller-owned — ``close`` tears down the
    session and server, not the engine."""

    def __init__(self, master, engine, host_id, host="127.0.0.1",
                 port=0, kind="generate", register_local=False):
        self.engine = engine
        self.host_id = str(host_id)
        self.service = ReplicaService(engine)
        self.server = MasterServer(self.service, host=host,
                                   port=port).start()
        self.member = ClusterMember(
            master, host_id,
            meta={"address": self.server.address, "kind": str(kind)},
            register_local=register_local,
            heartbeat_meta=lambda: {"load": engine.load_report()})

    @property
    def address(self):
        return self.server.address

    @property
    def expelled(self):
        return self.member.expelled

    def close(self, leave=True):
        try:
            if leave:
                self.member.leave()
        except Exception:  # noqa: BLE001 — master may already be gone
            pass
        finally:
            self.member.close()
            self.server.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# client side: route -> dispatch -> complete, re-routing on failure
# ---------------------------------------------------------------------------

class FleetClient:
    """Routes requests through the fleet master and dispatches them to
    replicas — both legs over ``MasterClient`` (the ONE retry idiom:
    full-jitter exponential backoff, ``rpc_retry`` span events,
    ``master/reconnects`` counters, per-method latency histograms).

    The data-plane clients are pooled per replica address with a SHORT
    retry budget (``data_retries``): against a dead replica the right
    move after a couple of fast reconnect attempts is a RE-ROUTE, not
    more backoff against a corpse.  Control-plane calls keep the
    default long budget — the master is supposed to come back.

    Failure handling per dispatch attempt:

    * connection-class errors -> ``report_failure`` (fences the old
      attempt) and re-route to a survivor;
    * request-level errors marshalled from the replica (timeout,
      poison-quarantine) -> raised to the caller: re-routing a
      poisoned request would poison every replica in turn;
    * a STALE completion verdict -> the master re-dispatched this
      ticket while we were computing (zombie fence): discard the
      result and follow the master's re-route."""

    _POOL_MAX = 8                  # idle data clients kept per address

    def __init__(self, master, data_timeout=120.0, data_retries=3,
                 data_retry_interval=0.05, reroute_backoff=0.05,
                 max_route_attempts=16):
        self._master = _transport(master)
        self._data_timeout = float(data_timeout)
        self._data_retries = max(1, int(data_retries))
        self._data_retry_interval = float(data_retry_interval)
        self._reroute_backoff = float(reroute_backoff)
        self._max_route_attempts = max(1, int(max_route_attempts))
        self._pool = {}
        self._pool_mu = threading.Lock()

    # -- data-plane client pool ----------------------------------------
    def _acquire(self, address):
        with self._pool_mu:
            stack = self._pool.get(address)
            if stack:
                return stack.pop()
        return MasterClient(address, timeout=self._data_timeout,
                            retry_interval=self._data_retry_interval,
                            max_retries=self._data_retries,
                            max_retry_interval=1.0)

    def _release(self, address, cli):
        with self._pool_mu:
            stack = self._pool.setdefault(address, [])
            if len(stack) < self._POOL_MAX:
                stack.append(cli)
                return
        cli.close()

    # -- public surface -------------------------------------------------
    def generate(self, prompt_ids, max_new_tokens=None, session=None,
                 timeout=None):
        """Fleet-routed generation; returns the replica's result dict
        plus routing evidence (``replica``/``ticket``/``attempts``/
        ``reroutes``).  ``session`` pins multi-turn conversations to
        the replica holding their KV pages."""
        prompt = [int(t) for t in prompt_ids]
        return self._dispatch(
            "generate", session, len(prompt),
            lambda cli, tkt, att: cli.call(
                "generate", tkt, att, session, prompt, max_new_tokens,
                timeout),
            timeout=timeout)

    def infer(self, feed, rows=1, session=None, timeout=None):
        """Fleet-routed one-shot inference; returns the fetched arrays
        (dtype-preserving JSON round-trip) plus routing evidence."""
        enc = encode_feed(feed)
        res = self._dispatch(
            "infer", session, rows,
            lambda cli, tkt, att: cli.call(
                "infer", tkt, att, enc, rows, timeout),
            timeout=timeout)
        res["outputs"] = [np.array(o["data"], dtype=o["dtype"])
                          for o in res["outputs"]]
        return res

    def stats(self):
        return self._master.call("fleet_stats")

    def close(self):
        with self._pool_mu:
            pools, self._pool = self._pool, {}
        for stack in pools.values():
            for cli in stack:
                cli.close()
        close = getattr(self._master, "close", None)
        if callable(close):
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- the route/dispatch/complete loop ------------------------------
    @staticmethod
    def _count(name, amount=1):
        from .. import monitor

        monitor.count(name, amount)

    def _dispatch(self, kind, session, length, call, timeout=None):
        deadline = (time.monotonic() + float(timeout)
                    if timeout is not None else None)
        root = (tracing.Span("fleet_request",
                             attrs={"kind": kind, "length": int(length),
                                    "session": session})
                if tracing.enabled() else None)
        ticket = None
        reroutes = 0
        status = "error"
        try:
            for attempt_no in range(self._max_route_attempts):
                with tracing.use_span(root):
                    asn = self._master.call("route", session, kind,
                                            int(length), ticket)
                if asn.get("unavailable"):
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise NoReplicasError(
                            "no routable replica before the %.1fs "
                            "deadline" % float(timeout))
                    time.sleep(self._reroute_backoff)
                    continue
                ticket = asn["ticket"]
                # dispatch under the master's route-span context: the
                # replica-side request tree parents under the routing
                # decision, assembling one cross-process tree
                parent = ((tracing.extract(asn.get("trace")) or root)
                          if tracing.enabled() else None)
                cli = self._acquire(asn["address"])
                try:
                    with tracing.use_span(parent):
                        res = call(cli, ticket, asn["attempt"])
                except (ConnectionError, OSError) as e:
                    cli.close()
                    reroutes += 1
                    self._count("fleet_client/reroutes")
                    with tracing.use_span(root):
                        try:
                            self._master.call(
                                "report_failure", ticket,
                                asn["attempt"],
                                "%s: %s" % (type(e).__name__, e))
                        except Exception:  # noqa: BLE001
                            pass   # route() re-fences on its own
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise
                    continue
                self._release(asn["address"], cli)
                with tracing.use_span(root):
                    ack = self._master.call("complete", ticket,
                                            asn["attempt"])
                if ack.get("accepted") \
                        or ack.get("reason") == "unknown_ticket":
                    # unknown_ticket: the master restarted and lost the
                    # ledger — the computed result is still the answer
                    status = "ok"
                    return dict(res, replica=asn["replica"],
                                ticket=ticket,
                                attempts=attempt_no + 1,
                                reroutes=reroutes)
                # stale attempt: the master re-dispatched underneath us
                # (quarantine while this dispatch was in flight) — its
                # decision owns the request; drop ours and re-route
                reroutes += 1
                self._count("fleet_client/stale_results")
            if ticket is None:
                # never even assigned: every attempt found an empty
                # fleet — the typed error admission layers gate on
                raise NoReplicasError(
                    "no routable replica in %d route attempts"
                    % self._max_route_attempts)
            raise FleetRouteError(
                "request not completed after %d route attempts "
                "(%d re-routes)" % (self._max_route_attempts, reroutes))
        except BaseException:
            status = "error"
            raise
        finally:
            if root is not None:
                root.finish(status, reroutes=reroutes,
                            ticket=ticket)
