"""Serving engines: continuous-batching execution over compiled
inference programs.

Two engines share the scheduler/metrics substrate:

* :class:`InferenceEngine` — one-shot forward serving of any saved
  inference model (``io.save_inference_model`` artifact or a live
  program+scope).  Requests are single examples; the loop admits them
  into **fixed slot batches** (one compiled signature per length
  bucket — the first batch per bucket pays the compile, every later
  batch is a single dispatch through the program-profile AOT path the
  executor already runs), pads sequences to bucket bounds, and fans the
  batched fetches back out per request.
* :class:`GenerationEngine` — prefill/decode serving of a
  :class:`~.decoder.DecoderSpec`: admitted prompts prefill into
  recycled cache slots (scattered ``kv_cache_write``), then a single
  compiled decode step advances EVERY active slot one token per
  iteration with donated in-place cache updates; finished slots are
  refilled between decode steps without draining the batch.

Request health is guardian-shaped: per-request timeouts expire queued
work and evict wedged decodes, and a request whose forward produces
non-finite outputs is quarantined (npz + sidecar, same format as the
guardian's poisoned batches) and failed with
:class:`~.scheduler.PoisonedRequestError` — the engine itself never
dies from one bad request."""

import threading

import numpy as np

from .. import io as fluid_io
from ..executor import CPUPlace, Executor, TPUPlace
from ..monitor import tracing
from ..profiler import RecordEvent
from ..scope import Scope, scope_guard
from .kv_cache import OutOfPagesError
from .metrics import ServingMetrics
from .scheduler import (ContinuousBatchingScheduler, PoisonedRequestError,
                        RequestTimeoutError)

__all__ = ["InferenceEngine", "GenerationEngine"]


def _default_place(place):
    if place is not None:
        return place
    import jax

    accel = any(d.platform != "cpu" for d in jax.local_devices())
    return TPUPlace(0) if accel else CPUPlace()


def _load_tuned(tuned_config):
    """Resolve a TunedConfig (path or object) and apply it — the PR-7
    artifact is where serving reads its admitted batch size, bucket
    bounds, per-shape kernel rulings, and quantization ruling from."""
    if tuned_config is None:
        return None
    from .. import autotune

    tuned = (autotune.TunedConfig.load(tuned_config)
             if isinstance(tuned_config, str) else tuned_config)
    tuned.apply()
    return tuned


def _resolve_quantize(quantize, tuned):
    """The engine's quantization mode: an explicit ``quantize`` kwarg
    wins; else a TunedConfig ``quantization`` ruling (the accuracy-gated
    ``tune_quantization`` decision — ``chosen`` None means the gate kept
    full precision); None = off."""
    if quantize is None and tuned is not None:
        d = tuned.get("quantization")
        quantize = d.get("chosen") if d else None
    if not quantize:
        return None
    return "weight_only" if quantize is True else str(quantize)


def _finite_row(arrays, i, slots):
    """Whether request row ``i`` of every float fetch is finite."""
    for a in arrays:
        a = np.asarray(a)
        row = a[i] if a.ndim >= 1 and a.shape[0] == slots else a
        if np.issubdtype(row.dtype, np.floating) and \
                not np.isfinite(row).all():
            return False
    return True


class _EngineBase:
    """Loop-thread plumbing shared by both engines."""

    def __init__(self):
        self._thread = None
        self._stop = threading.Event()

    def _register_monitor(self):
        """Track the engine for watchdog dumps (weakly held): a stall
        report names the in-flight requests, not just the program."""
        from .. import monitor

        monitor.track(self)

    def _running_state(self, slot):
        return "prefill"

    def monitor_state(self):
        """The watchdog's in-flight request view: every queued/running
        request with its trace_id, age, and lifecycle state."""
        now = self._sched._clock()
        reqs = []
        for r in self._sched.pending():
            reqs.append({"id": r.id,
                         "trace_id": r.trace.trace_id
                         if r.trace is not None else None,
                         "state": "queued",
                         "age_s": round(now - r.arrival, 3)})
        for slot, r in sorted(self._sched.running().items()):
            reqs.append({"id": r.id,
                         "trace_id": r.trace.trace_id
                         if r.trace is not None else None,
                         "state": self._running_state(slot),
                         "age_s": round(now - r.arrival, 3)})
        return {"kind": "serving_engine", "name": self.metrics.name,
                "requests": reqs}

    def load_report(self):
        """The load/SLO snapshot a fleet replica's heartbeat carries
        (``serving.fleet``): queue depth + occupancy from the
        scheduler, latency percentiles from the SLO window.  Cheap and
        lock-light — it rides every lease renewal."""
        sched = self._sched
        pct = self.metrics.percentiles()
        return {"queue_depth": sched.queue_depth(),
                "busy_slots": sched.busy_slots(),
                "occupancy": round(sched.occupancy(), 4),
                "p50_ms": (round(pct["p50_s"] * 1e3, 3)
                           if pct["p50_s"] is not None else None),
                "p99_ms": (round(pct["p99_s"] * 1e3, 3)
                           if pct["p99_s"] is not None else None)}

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="serving-loop", daemon=True)
            self._thread.start()
        return self

    def close(self):
        """Stop the loop and fail everything still in flight."""
        self._stop.set()
        self._sched.close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _publish_expired(self, expired):
        for r in expired:
            self.metrics.note_failure(r, r._error, status="expired")

    def _loop(self):
        """Run iterations until close(); ANY iteration failure is
        contained — a dead loop thread would strand every queued caller
        in result(), so the engine logs and keeps serving."""
        import sys
        import time as _time

        while not self._stop.is_set():
            try:
                self._loop_once()
            except Exception as e:  # noqa: BLE001 — the loop must live
                print("[serving] loop iteration failed: %r" % e,
                      file=sys.stderr, flush=True)
                _time.sleep(0.05)


class InferenceEngine(_EngineBase):
    """Continuous-batching server over one inference program.

    ``model_dir`` loads a ``save_inference_model`` artifact into a
    private scope; alternatively pass a live
    ``(program, feed_names, fetch_vars, scope)``.  ``slots`` is the
    fixed admission batch (default: the TunedConfig ``batch_size``
    decision, else 8); ``bucket_bounds`` pads variable-length sequence
    feeds (default: the TunedConfig ``bucket_bounds`` decision, else
    unbucketed fixed shapes)."""

    def __init__(self, model_dir=None, program=None, feed_names=None,
                 fetch_vars=None, scope=None, place=None, slots=None,
                 bucket_bounds=None, tuned_config=None, timeout_s=30.0,
                 quarantine_dir=None, name="serving", start=True,
                 quantize=None):
        super().__init__()
        self.place = _default_place(place)
        self._exe = Executor(self.place, donate_state=False)
        if model_dir is not None:
            scope = Scope()
            with scope_guard(scope):
                program, feed_names, fetch_vars = \
                    fluid_io.load_inference_model(model_dir, self._exe)
        if program is None or scope is None:
            raise ValueError(
                "InferenceEngine needs model_dir or a live "
                "(program, feed_names, fetch_vars, scope)")
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch_vars = list(fetch_vars)
        self._scope = scope
        tuned = _load_tuned(tuned_config)
        # int8 execution: explicit kwarg or the TunedConfig ruling.  A
        # save_inference_model artifact that was ALREADY quantized
        # (dequant_matmul ops + @INT8 persistables) loads cold with no
        # work here; the pass is for live programs / fp artifacts.
        self.quantize_mode = _resolve_quantize(quantize, tuned)
        if self.quantize_mode:
            from ..transpiler.quantize_pass import quantize_inference

            self._program = program = quantize_inference(
                program, scope=scope, mode=self.quantize_mode)
            self._fetch_vars = [
                program.global_block().var(v.name if hasattr(v, "name")
                                           else v)
                for v in self._fetch_vars]
        if slots is None:
            slots = int(tuned.value("batch_size") or 0) if tuned else 0
            slots = slots or 8
        if bucket_bounds is None and tuned is not None:
            bucket_bounds = tuned.value("bucket_bounds")
        self.slots = int(slots)
        # feed classification from the program's own var shapes: two
        # leading dynamic dims = padded sequence (bucket the time dim)
        block = program.global_block()
        self._seq_feeds = set()
        self._len_feeds = {n for n in self._feed_names
                           if n.endswith("@LEN")}
        for n in self._feed_names:
            if n.endswith("@LEN"):
                continue
            v = block._find_var_recursive(n)
            shape = tuple(v.shape or ()) if v is not None else ()
            if len(shape) >= 2 and shape[0] in (-1, None) \
                    and shape[1] in (-1, None):
                self._seq_feeds.add(n)
        # fetches whose row layout carries the padded time dim: trimmed
        # back to each request's true length before fan-out, so engine
        # outputs match direct (unpadded) dispatch shapes
        self._seq_fetches = set()
        for j, v in enumerate(self._fetch_vars):
            shape = tuple(getattr(v, "shape", None) or ())
            if len(shape) >= 2 and shape[0] in (-1, None) \
                    and shape[1] in (-1, None):
                self._seq_fetches.add(j)
        if self._seq_feeds and not bucket_bounds:
            bucket_bounds = [2 ** i for i in range(3, 11)]
        self._sched = ContinuousBatchingScheduler(
            self.slots, bucket_bounds, default_timeout_s=timeout_s,
            trace_kind="infer")
        self.metrics = ServingMetrics(name=name,
                                      quarantine_dir=quarantine_dir)
        self._register_monitor()
        if start:
            self.start()

    # -- client side ---------------------------------------------------
    def submit(self, feed, timeout_s=None, rows=1):
        """Enqueue one request: a single example (arrays without the
        batch dim; sequence feeds are [T, ...]) or — with ``rows`` > 1 —
        a client micro-batch whose arrays carry a leading [rows, ...]
        dim (the predictor's Run unit); micro-batches from concurrent
        clients co-batch into one dispatch.  Returns the request
        future."""
        for n in feed:
            if n not in self._feed_names and not n.endswith("@LEN"):
                raise ValueError(
                    "input %r is not a feed target (expected %s)"
                    % (n, self._feed_names))
        missing = [n for n in self._feed_names
                   if n not in feed and not n.endswith("@LEN")]
        if missing:
            raise ValueError("missing inputs: %s" % missing)
        if rows > 1 and (self._seq_feeds or self._len_feeds):
            raise ValueError(
                "multi-row requests are fixed-shape only; submit "
                "variable-length sequences (or models with @LEN "
                "companions) one example per request")
        length = 0
        for n in self._seq_feeds:
            length = max(length, int(np.shape(feed[n])[0]))
        req = self._sched.submit(dict(feed), length=length,
                                 timeout_s=timeout_s, rows=rows)
        self.metrics.note_submit(req, self._sched.queue_depth())
        return req

    def run(self, feed, timeout=None):
        """Synchronous submit+wait; returns the per-request fetch list
        (ordered like the saved fetch targets)."""
        return self.submit(feed).result(timeout)

    @property
    def feed_names(self):
        return list(self._feed_names)

    # -- loop side -----------------------------------------------------
    def _loop_once(self):
        plan, expired = self._sched.admit()
        self._publish_expired(expired)
        if plan is None:
            self._sched.wait_for_work(timeout=0.05)
            return
        try:
            self._run_batch(plan)
        except Exception as e:  # noqa: BLE001 — a failed batch must
            for r in plan.requests:           # not kill the engine
                if r.done():     # already served/decided mid-batch
                    continue
                self._sched.fail(r, e)
                self.metrics.note_failure(r, e)

    def _pad_seq(self, arr, bucket):
        t = arr.shape[0]
        if bucket is None or t == bucket:
            return arr
        pad = [(0, bucket - t)] + [(0, 0)] * (arr.ndim - 1)
        return np.pad(arr, pad)

    def _run_batch(self, plan):
        reqs = plan.requests
        n_rows = sum(r.rows for r in reqs)
        self.metrics.note_admit(plan, n_rows / float(self.slots),
                                self._sched.queue_depth())
        traced = [r for r in reqs if r.trace is not None]
        for r in traced:
            r.trace.admitted(plan.bucket, self._sched.queue_depth(),
                             r is not reqs[0])
        feed = {}
        for name in self._feed_names:
            if name.endswith("@LEN"):
                base = name[:-len("@LEN")]
                # sequence requests are single-row (submit enforces it)
                lens = [int(r.payload.get(
                    name, np.shape(r.payload[base])[0])) for r in reqs]
                lens += [lens[0]] * (self.slots - n_rows)
                feed[name] = np.asarray(lens, "int32")
                continue
            rows = []
            for r in reqs:
                a = np.asarray(r.payload[name])
                if name in self._seq_feeds:
                    a = self._pad_seq(a, plan.bucket)
                rows.append(a if r.rows > 1 else a[None])
            batch = np.concatenate(rows)
            if n_rows < self.slots:
                # fixed slot batches: pad with copies of row 0 so every
                # bucket compiles exactly one signature
                batch = np.concatenate(
                    [batch, np.repeat(batch[:1], self.slots - n_rows, 0)])
            feed[name] = batch
        t0 = tracing.now_us() if traced else 0.0
        with RecordEvent("serving/batch",
                         args={"batch": len(reqs), "rows": n_rows,
                               "bucket": plan.bucket}):
            outs = self._exe.run(self._program, feed=feed,
                                 fetch_list=self._fetch_vars,
                                 scope=self._scope)
        if traced:
            dur = tracing.now_us() - t0
            for r in traced:
                r.trace.note_batch(
                    t0, dur, r.slot, len(reqs), plan.bucket,
                    (plan.bucket - r.length) if plan.bucket else 0)
        outs = [np.asarray(o) for o in outs]
        off = 0
        for req in reqs:
            lo, hi = off, off + req.rows
            off = hi
            ok = all(_finite_row(outs, i, self.slots)
                     for i in range(lo, hi))
            if not ok:
                self.metrics.quarantine(req, feed=req.payload)
                err = PoisonedRequestError(
                    "request %s produced non-finite outputs and was "
                    "quarantined" % req.id)
                self._sched.fail(req, err, status="quarantined")
                self.metrics.note_failure(req, err, status="quarantined")
                continue
            result = []
            for j, o in enumerate(outs):
                if o.ndim < 1 or o.shape[0] != self.slots:
                    result.append(o)
                    continue
                row = o[lo:hi] if req.rows > 1 else o[lo]
                if j in self._seq_fetches and req.length \
                        and req.rows == 1 and row.ndim >= 1 \
                        and row.shape[0] == plan.bucket:
                    # trim the bucket padding back off the time dim —
                    # the caller's contract is the direct-dispatch shape
                    row = row[:req.length]
                result.append(row)
            if self._sched.complete(req, result):
                self.metrics.note_complete(req,
                                           extra={"batch": len(reqs)})


class GenerationEngine(_EngineBase):
    """Prefill/decode continuous batching over a
    :class:`~.decoder.DecoderSpec`.

    The decode step is ONE compiled program over every cache slot —
    inactive slots ride along masked (their writes land at position 0 of
    a free slot, overwritten by the next prefill) — so slot recycling
    changes host bookkeeping only, never the compiled signature.
    Sampling is greedy argmax (deterministic; the decode-vs-recompute
    parity contract is test-enforced)."""

    def __init__(self, spec, place=None, scope=None, eos_id=None,
                 max_new_tokens=32, timeout_s=60.0, bucket_bounds=None,
                 tuned_config=None, quarantine_dir=None,
                 name="serving", record_logits=False, start=True,
                 quantize=None, draft_spec=None):
        super().__init__()
        self.spec = spec
        self.place = _default_place(place)
        self.eos_id = eos_id
        self.max_new_tokens = int(max_new_tokens)
        self.record_logits = bool(record_logits)
        # prefill keeps buffers alive (shared weights, occasional runs);
        # decode donates so the per-step cache update is in place
        self._exe_prefill = Executor(self.place, donate_state=False)
        self._exe_decode = Executor(self.place, donate_state=True)
        fresh_scope = scope is None
        if fresh_scope:
            scope = Scope()
            spec.init_scope(self._exe_prefill, scope)
        self._scope = scope
        tuned = _load_tuned(tuned_config)
        # int8 decode: the per-slot working set is weight-read-bound,
        # so int8 weights shrink it 4x vs the f32 masters.  The pass
        # rewrites all three programs over the SHARED scope (one int8
        # copy per weight name).
        self.quantize_mode = _resolve_quantize(quantize, tuned)
        if self.quantize_mode:
            self.spec = spec = spec.quantize(scope,
                                             mode=self.quantize_mode)
        # paged KV: the engine owns the host-side page allocator and the
        # [slots, max_pages] table it feeds both paged programs.  Unheld
        # table entries carry the OUT-OF-BOUNDS sentinel (num_pages):
        # writes routed through them DROP at the scatter, so a freed or
        # never-filled slot riding the fixed decode batch can never
        # corrupt another request's live pages.
        self.paged = bool(getattr(spec, "paged", False))
        self._alloc = spec.cache.make_allocator() if self.paged else None
        self._table = (np.full(
            (spec.slots, spec.cache.max_pages_per_slot),
            spec.cache.num_pages, "int32") if self.paged else None)
        # speculative decoding: a small fixed-region draft model shares
        # the serving scope; the target verifies spec_k tokens per
        # dispatch through its verify program
        self.draft_spec = draft_spec
        if draft_spec is not None:
            if spec.verify_program is None:
                raise ValueError(
                    "speculative decoding needs a spec built with "
                    "spec_k (no verify program present)")
            if getattr(draft_spec, "paged", False):
                raise ValueError(
                    "the draft model uses the fixed-region cache (it "
                    "is small by design; paging it buys nothing)")
            if draft_spec.slots != spec.slots \
                    or draft_spec.vocab_size != spec.vocab_size \
                    or draft_spec.max_len < spec.max_len:
                raise ValueError(
                    "draft spec must match the target's slots/vocab "
                    "and cover its max_len")
            if fresh_scope:
                draft_spec.init_scope(self._exe_prefill, scope)
        if bucket_bounds is None and tuned is not None:
            bucket_bounds = tuned.value("bucket_bounds")
        if not bucket_bounds:
            bucket_bounds, b = [], 8
            while b < spec.max_len:
                bucket_bounds.append(b)
                b *= 2
            bucket_bounds.append(spec.max_len)
        if self.paged:
            ps = spec.cache.page_size
            for b in bucket_bounds:
                if b % ps:
                    raise ValueError(
                        "bucket bound %d is not page-aligned (page_size "
                        "%d) — paged prefill scatters whole pages"
                        % (b, ps))
        self._sched = ContinuousBatchingScheduler(
            spec.slots, bucket_bounds, default_timeout_s=timeout_s,
            admission_gate=self._page_gate if self.paged else None,
            trace_kind="generate")
        self.metrics = ServingMetrics(name=name,
                                      quarantine_dir=quarantine_dir)
        self._active = {}             # slot -> decode state dict
        self._ticks = 0               # decode ticks served (trace attr)
        self._register_monitor()
        if start:
            self.start()

    def _running_state(self, slot):
        return "decode" if slot in self._active else "prefill"

    # -- paged-KV bookkeeping ------------------------------------------
    def _page_gate(self, req, picked):
        """Admission gate: admit only when the pool can cover this
        request's WORST CASE (no sharing assumed — intra-batch aliases
        and prefix hits only widen the margin) on top of what this
        admission already picked.  A refused request stays queued."""
        reserved = sum(
            self._alloc.pages_needed(len(r.payload["prompt"]),
                                     r.payload["max_new"])
            for r in picked)
        need = self._alloc.pages_needed(len(req.payload["prompt"]),
                                        req.payload["max_new"])
        ok = need <= self._alloc.free_pages() - reserved
        if not ok and req.trace is not None:
            # exhaustion back-pressure: the page_wait span opens at the
            # FIRST refusal and closes at the eventual grant
            req.trace.page_refused()
        return ok

    def _free_pages(self, slot):
        """Release every page ref a slot holds — called on EVERY
        terminal path (complete, expire, quarantine, prefill/decode
        failure, close); the leak regression test drives each."""
        if self._alloc is None:
            return 0
        freed = self._alloc.release(slot)
        self._table[slot, :] = self.spec.cache.num_pages
        self.metrics.note_kv_pages(self._alloc.pages_in_use(),
                                   self._alloc.free_pages())
        return freed

    # -- client side ---------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens=None, timeout_s=None):
        prompt = [int(t) for t in prompt_ids]
        if not prompt:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens or self.max_new_tokens)
        if len(prompt) + max_new > self.spec.max_len:
            raise ValueError(
                "prompt %d + max_new_tokens %d exceeds the cache "
                "capacity %d" % (len(prompt), max_new, self.spec.max_len))
        req = self._sched.submit(
            {"prompt": prompt, "max_new": max_new},
            length=len(prompt), timeout_s=timeout_s)
        self.metrics.note_submit(req, self._sched.queue_depth())
        return req

    def generate(self, prompt_ids, max_new_tokens=None, timeout=None):
        """Synchronous generation; returns the result dict
        ``{"tokens": [...generated ids...], "prompt_len": int}`` (plus
        per-step ``logits`` rows under ``record_logits``)."""
        return self.submit(prompt_ids, max_new_tokens).result(timeout)

    # -- loop side -----------------------------------------------------
    def _loop_once(self):
        plan, expired = self._sched.admit()
        self._publish_expired(expired)
        if plan is not None:
            try:
                self._prefill(plan)
            except Exception as e:  # noqa: BLE001
                for r in plan.requests:
                    if r.done():
                        continue
                    self._active.pop(r.slot, None)
                    self._free_pages(r.slot)
                    self._sched.fail(r, e)
                    self.metrics.note_failure(r, e)
        self._evict_expired_running()
        if self._active:
            try:
                self._decode_step()
            except Exception as e:  # noqa: BLE001 — fail the batch,
                for slot in list(self._active):    # keep the engine
                    st = self._active.pop(slot)
                    self._free_pages(slot)
                    self._sched.fail(st["req"], e)
                    self.metrics.note_failure(st["req"], e)
        elif plan is None:
            self._sched.wait_for_work(timeout=0.05)

    def _evict_expired_running(self):
        for req in self._sched.expired_running():
            self._active.pop(req.slot, None)
            # the timeout-expired generation goes terminal HERE: its KV
            # pages (and any prefix-page refs) free immediately, not at
            # slot-reuse time — a wedged decode must not pin pool pages
            self._free_pages(req.slot)
            err = RequestTimeoutError(
                "request %s evicted mid-decode after its timeout "
                "budget" % req.id)
            self._sched.fail(req, err, status="expired")
            self.metrics.note_failure(req, err, status="expired")

    def _prefill(self, plan):
        spec = self.spec
        reqs = plan.requests
        head = reqs[0]
        for r in reqs:
            if r.trace is not None:
                r.trace.admitted(plan.bucket,
                                 self._sched.queue_depth(),
                                 r is not head)
        if self.paged:
            # page allocation pre-pass: aliases shared prefix pages,
            # takes fresh ones for the rest.  The admission gate sized
            # this against the free list, so exhaustion here means the
            # gate's invariant broke — fail THAT request, keep the batch.
            kept = []
            for r in reqs:
                try:
                    pages, shared = self._alloc.alloc_for_prompt(
                        r.slot, r.payload["prompt"],
                        r.payload["max_new"])
                except OutOfPagesError as e:
                    self._sched.fail(r, e)
                    self.metrics.note_failure(r, e)
                    continue
                self._table[r.slot, :] = spec.cache.num_pages
                self._table[r.slot, :len(pages)] = pages
                full = len(r.payload["prompt"]) // spec.cache.page_size
                self.metrics.note_prefix_cache(shared, full - shared)
                if r.trace is not None:
                    r.trace.pages_granted(len(pages), shared,
                                          self._alloc.pages_in_use(),
                                          self._alloc.free_pages())
                kept.append(r)
            self.metrics.note_kv_pages(self._alloc.pages_in_use(),
                                       self._alloc.free_pages())
            reqs = kept
            if not reqs:
                return
        n, t, p = len(reqs), plan.bucket, spec.slots
        self.metrics.note_admit(plan, self._sched.occupancy(),
                                self._sched.queue_depth())
        tok = np.zeros((p, t, 1), "int64")
        lens = np.zeros((p,), "int32")
        slots = np.zeros((p,), "int32")
        for i, r in enumerate(reqs):
            prompt = r.payload["prompt"]
            tok[i, :len(prompt), 0] = prompt
            lens[i] = len(prompt)
            slots[i] = r.slot
        # fixed-signature padding: duplicate row 0 INCLUDING its slot —
        # the duplicate write re-writes identical content, a no-op
        for i in range(n, p):
            tok[i], lens[i], slots[i] = tok[0], lens[0], slots[0]
        pos = np.broadcast_to(
            np.arange(t, dtype="int64")[None, :, None], (p, t, 1)).copy()
        feed = {"tok": tok, "tok@LEN": lens, "pos": pos, "slot": slots,
                "wpos": np.zeros((p,), "int32")}
        if self.paged:
            feed["page_table"] = self._table
        traced = [r for r in reqs if r.trace is not None]
        pt0 = tracing.now_us() if traced else 0.0
        with RecordEvent("serving/prefill",
                         args={"batch": n, "bucket": t}):
            (logits,) = self._exe_prefill.run(
                spec.prefill_program, feed=feed,
                fetch_list=[spec.prefill_logits], scope=self._scope)
            if self.draft_spec is not None:
                # the draft shares the admitted batch: same prompts into
                # its own (fixed-region) cache, logits unused
                dfeed = dict(feed)
                dfeed.pop("page_table", None)
                self._exe_prefill.run(
                    self.draft_spec.prefill_program, feed=dfeed,
                    fetch_list=[self.draft_spec.prefill_logits],
                    scope=self._scope)
        if traced:
            pdur = tracing.now_us() - pt0
            for r in traced:
                r.trace.note_prefill(pt0, pdur, r.slot, n, t,
                                     t - len(r.payload["prompt"]))
        logits = np.asarray(logits)
        for i, r in enumerate(reqs):
            row = logits[i, int(lens[i]) - 1]
            if not np.isfinite(row).all():
                self._quarantine(r, reason="non-finite prefill logits")
                continue
            nxt = int(np.argmax(row))
            st = {"req": r, "generated": [nxt], "pos": int(lens[i]),
                  "max_new": r.payload["max_new"], "logits": []}
            if self.record_logits:
                st["logits"].append(row.copy())
            if self._finished(st, nxt):
                self._complete(r.slot, st)
            else:
                self._active[r.slot] = st

    def _decode_step(self):
        if self.draft_spec is not None:
            return self._speculative_step()
        spec = self.spec
        s = spec.slots
        tok = np.zeros((s, 1, 1), "int64")
        pos = np.zeros((s, 1, 1), "int64")
        wpos = np.zeros((s,), "int32")
        clen = np.ones((s,), "int32")
        for slot, st in self._active.items():
            tok[slot, 0, 0] = st["generated"][-1]
            pos[slot, 0, 0] = st["pos"]
            wpos[slot] = st["pos"]
            clen[slot] = st["pos"] + 1
        feed = {"tok": tok, "pos": pos, "wpos": wpos, "cache_len": clen}
        if self.paged:
            feed["page_table"] = self._table
        traced = any(st["req"].trace is not None
                     for st in self._active.values())
        t0 = tracing.now_us() if traced else 0.0
        with RecordEvent("serving/decode_step",
                         args={"active": len(self._active)}):
            (logits,) = self._exe_decode.run(
                spec.decode_program, feed=feed,
                fetch_list=[spec.decode_logits], scope=self._scope)
        logits = np.asarray(logits)
        self._ticks += 1
        if traced:
            # every rider pays (and is attributed) the full tick: the
            # batch is one dispatch, each request was waiting on it
            dur = tracing.now_us() - t0
            for slot, st in self._active.items():
                if st["req"].trace is not None:
                    st["req"].trace.note_decode(t0, dur, slot,
                                                self._ticks,
                                                len(self._active))
        self.metrics.note_decode_step(len(self._active),
                                      self._sched.occupancy())
        for slot in list(self._active):
            st = self._active[slot]
            row = logits[slot, 0]
            if not np.isfinite(row).all():
                self._active.pop(slot)
                self._quarantine(st["req"],
                                 reason="non-finite decode logits")
                continue
            nxt = int(np.argmax(row))
            st["generated"].append(nxt)
            st["pos"] += 1
            if self.record_logits:
                st["logits"].append(row.copy())
            if self._finished(st, nxt):
                self._active.pop(slot)
                self._complete(slot, st)

    def _speculative_step(self):
        """One speculative round: the draft proposes ``k-1`` tokens
        (sequential single-token steps on the SMALL model), the target
        rules on all of them in ONE ``spec_k``-token verify dispatch,
        and the host accepts the longest matching prefix plus the
        target's own next token (correction or bonus).  Greedy outputs
        are IDENTICAL to the non-speculative path by construction:
        every emitted token is the argmax of a target logits row, and
        verify row ``j`` conditions only on tokens the target already
        ruled valid.  Rollback of rejected draft positions is free —
        they sit past the slot's valid length, stale-masked by
        ``cache_len``, overwritten by the next round's writes (both
        caches)."""
        spec, draft = self.spec, self.draft_spec
        s, k = spec.slots, spec.spec_k
        last = np.zeros((s,), "int64")
        base = np.zeros((s,), "int32")
        for slot, st in self._active.items():
            last[slot] = st["generated"][-1]
            base[slot] = st["pos"]
        toks = np.zeros((s, k), "int64")
        toks[:, 0] = last
        cur = last.copy()
        traced = any(st["req"].trace is not None
                     for st in self._active.values())
        t0 = tracing.now_us() if traced else 0.0
        with RecordEvent("serving/speculative_step",
                         args={"active": len(self._active), "k": k}):
            for j in range(k - 1):
                wp = base + j
                dfeed = {"tok": cur.reshape(s, 1, 1),
                         "pos": wp.astype("int64").reshape(s, 1, 1),
                         "wpos": wp.astype("int32"),
                         "cache_len": (wp + 1).astype("int32")}
                (dl,) = self._exe_decode.run(
                    draft.decode_program, feed=dfeed,
                    fetch_list=[draft.decode_logits], scope=self._scope)
                cur = np.asarray(dl)[:, 0].argmax(-1).astype("int64")
                toks[:, j + 1] = cur
            pos = base[:, None].astype("int64") + np.arange(k, dtype="int64")
            vfeed = {"tok": toks.reshape(s, k, 1),
                     "pos": pos.reshape(s, k, 1),
                     "wpos": base.astype("int32"),
                     "cache_len": (base + k).astype("int32")}
            if self.paged:
                vfeed["page_table"] = self._table
            (vl,) = self._exe_decode.run(
                spec.verify_program, feed=vfeed,
                fetch_list=[spec.verify_logits], scope=self._scope)
        vl = np.asarray(vl)                       # [s, k, V]
        greedy = vl.argmax(-1)                    # [s, k]
        self._ticks += 1
        dur = (tracing.now_us() - t0) if traced else 0.0
        n_active = len(self._active)
        self.metrics.note_decode_step(len(self._active),
                                      self._sched.occupancy())
        for slot in list(self._active):
            st = self._active[slot]
            if not np.isfinite(vl[slot]).all():
                self._active.pop(slot)
                self._quarantine(st["req"],
                                 reason="non-finite verify logits")
                continue
            accepted = 0
            while accepted < k - 1 and \
                    int(toks[slot, accepted + 1]) == \
                    int(greedy[slot, accepted]):
                accepted += 1
            self.metrics.note_speculation(accepted, k - 1)
            if st["req"].trace is not None:
                st["req"].trace.note_decode(t0, dur, slot, self._ticks,
                                            n_active,
                                            spec_accepted=accepted,
                                            spec_proposed=k - 1)
            emitted = [int(toks[slot, j + 1]) for j in range(accepted)]
            emitted.append(int(greedy[slot, accepted]))
            for j, t in enumerate(emitted):
                st["generated"].append(t)
                st["pos"] += 1
                if self.record_logits:
                    st["logits"].append(vl[slot, j].copy())
                if self._finished(st, t):
                    self._active.pop(slot)
                    self._complete(slot, st)
                    break

    def _finished(self, st, last_tok):
        return (len(st["generated"]) >= st["max_new"]
                or (self.eos_id is not None and last_tok == self.eos_id))

    def _complete(self, slot, st):
        req = st["req"]
        self._free_pages(slot)
        result = {"tokens": list(st["generated"]),
                  "prompt_len": len(req.payload["prompt"])}
        if self.record_logits:
            result["logits"] = st["logits"]
        if not self._sched.complete(req, result):
            return      # cancelled by close() while its batch ran
        extra = {"generated": len(st["generated"])}
        if self.paged or self.draft_spec is not None:
            extra.update(self.metrics.paged_snapshot())
        self.metrics.note_complete(req, extra=extra)
        self.metrics._count("generated_tokens", "generated_tokens_total",
                            len(st["generated"]))

    def _quarantine(self, req, reason):
        self._free_pages(req.slot)
        self.metrics.quarantine(
            req, feed={"prompt": np.asarray(req.payload["prompt"])},
            reason=reason)
        err = PoisonedRequestError(
            "request %s: %s (quarantined)" % (req.id, reason))
        self._sched.fail(req, err, status="quarantined")
        self.metrics.note_failure(req, err, status="quarantined")

    def close(self):
        super().close()
        # in-flight generations were failed by the scheduler's close;
        # their pages go with them
        if self._alloc is not None:
            for slot in list(self._alloc._slot_pages):
                self._free_pages(slot)
        self._active.clear()
