"""Decode KV-cache state: per-slot, per-layer K/V tensors living in the
engine's scope as ordinary persistable variables.

The executor already gives caches everything they need: a persistable
var that an op reads and re-emits under the same name is read-modify-
write state, donated on the decode executor (``donate_state=True``), so
the per-step update compiled by ``kv_cache_write`` is a true in-place
stripe write — the cache never round-trips HBM.  Slot recycling is free
by construction: stale content past a slot's valid length is masked by
the attention op's ``k_len``, and a re-prefill overwrites positions
``0..len-1``, so freeing a slot is a host-side bookkeeping change, not a
device memset."""

import numpy as np

__all__ = ["KVCacheStore"]


class KVCacheStore:
    """Names, declares, and initializes the cache variables shared by
    the prefill and decode programs of one decoder."""

    def __init__(self, n_layer, slots, n_head, max_len, head_dim,
                 dtype="float32", prefix="declm"):
        self.n_layer = int(n_layer)
        self.slots = int(slots)
        self.n_head = int(n_head)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.prefix = prefix

    @property
    def shape(self):
        return (self.slots, self.n_head, self.max_len, self.head_dim)

    def name(self, kind, layer):
        return "%s_cache_%s_%d" % (self.prefix, kind, layer)

    def names(self):
        return [self.name(kind, i) for i in range(self.n_layer)
                for kind in ("k", "v")]

    def declare(self, block, layer):
        """Create (or fetch) this layer's cache vars in ``block`` —
        persistable, so the executor treats them as scope state and
        writes the op's same-name output back."""
        out = []
        for kind in ("k", "v"):
            name = self.name(kind, layer)
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name, shape=self.shape,
                                     dtype=self.dtype, persistable=True)
            out.append(v)
        return out

    def init_scope(self, scope):
        """Zero-fill every cache var (engine startup; content before a
        slot's valid length is never read thanks to k_len masking)."""
        for name in self.names():
            scope.set_var(name, np.zeros(self.shape, self.dtype))

    def bytes(self):
        itemsize = np.dtype(self.dtype).itemsize
        return 2 * self.n_layer * int(np.prod(self.shape)) * itemsize
