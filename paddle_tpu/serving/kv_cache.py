"""Decode KV-cache state: per-slot, per-layer K/V tensors living in the
engine's scope as ordinary persistable variables.

The executor already gives caches everything they need: a persistable
var that an op reads and re-emits under the same name is read-modify-
write state, donated on the decode executor (``donate_state=True``), so
the per-step update compiled by ``kv_cache_write`` is a true in-place
stripe write — the cache never round-trips HBM.  Slot recycling is free
by construction: stale content past a slot's valid length is masked by
the attention op's ``k_len``, and a re-prefill overwrites positions
``0..len-1``, so freeing a slot is a host-side bookkeeping change, not a
device memset."""

import hashlib

import numpy as np

__all__ = ["KVCacheStore", "PageAllocator", "PagedKVCacheStore",
           "OutOfPagesError"]


class KVCacheStore:
    """Names, declares, and initializes the cache variables shared by
    the prefill and decode programs of one decoder."""

    def __init__(self, n_layer, slots, n_head, max_len, head_dim,
                 dtype="float32", prefix="declm"):
        self.n_layer = int(n_layer)
        self.slots = int(slots)
        self.n_head = int(n_head)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.dtype = dtype
        self.prefix = prefix

    @property
    def shape(self):
        return (self.slots, self.n_head, self.max_len, self.head_dim)

    def name(self, kind, layer):
        return "%s_cache_%s_%d" % (self.prefix, kind, layer)

    def names(self):
        return [self.name(kind, i) for i in range(self.n_layer)
                for kind in ("k", "v")]

    def declare(self, block, layer):
        """Create (or fetch) this layer's cache vars in ``block`` —
        persistable, so the executor treats them as scope state and
        writes the op's same-name output back."""
        out = []
        for kind in ("k", "v"):
            name = self.name(kind, layer)
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name, shape=self.shape,
                                     dtype=self.dtype, persistable=True)
            out.append(v)
        return out

    def init_scope(self, scope):
        """Zero-fill every cache var (engine startup; content before a
        slot's valid length is never read thanks to k_len masking)."""
        for name in self.names():
            scope.set_var(name, np.zeros(self.shape, self.dtype))

    def bytes(self):
        itemsize = np.dtype(self.dtype).itemsize
        return 2 * self.n_layer * int(np.prod(self.shape)) * itemsize


# ---------------------------------------------------------------------------
# paged KV cache (ISSUE 16)
# ---------------------------------------------------------------------------

class OutOfPagesError(RuntimeError):
    """The pool has no free page for the requested allocation; the
    admission layer queues the request instead of crashing the engine."""


class PageAllocator:
    """Host-side page bookkeeping for one paged cache pool: free list,
    per-page refcounts, per-slot page lists, copy-on-write split, and
    the content-hash prefix index.

    Pure control logic (no device, no clock): every decision is
    deterministic and unit-testable without a compiled program.  Device
    content is only ever APPENDED page-aligned by deterministic prefill/
    decode writes, so two slots aliasing a page always wrote (or would
    write) identical K/V into it — sharing is a table-aliasing decision
    here, never a device copy.

    The prefix index maps a chain hash of full page-sized token chunks
    to a physical page: requests admitted with a common system prompt
    alias those pages and the prefill skips nothing device-side (the
    duplicate write is content-identical), but the HBM cost is paid
    once.  Partial trailing pages are never shared — decode appends
    into them, and divergent continuations must not alias."""

    def __init__(self, num_pages, page_size):
        if num_pages < 1 or page_size < 1:
            raise ValueError("need at least one page and one token")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._ref = {}                  # page -> refcount
        self._slot_pages = {}           # slot -> [page, ...]
        self._prefix = {}               # chain hash -> page
        self._page_prefix = {}          # page -> chain hash (owner)
        self.prefix_hits = 0
        self.prefix_misses = 0

    # -- capacity ------------------------------------------------------
    def free_pages(self):
        return len(self._free)

    def pages_in_use(self):
        return self.num_pages - len(self._free)

    def pages_needed(self, prompt_len, max_new):
        """Pages a fresh (no-sharing) generation needs end to end; the
        admission gate's worst case."""
        total = int(prompt_len) + int(max_new)
        return -(-total // self.page_size)

    def can_admit(self, prompt_len, max_new, prompt_ids=None):
        """Whether alloc_for_prompt would succeed right now (sharing
        counted when ``prompt_ids`` is given)."""
        need = self.pages_needed(prompt_len, max_new)
        if prompt_ids is not None:
            for h in self._chunk_hashes(prompt_ids):
                if h in self._prefix:
                    need -= 1
                else:
                    break
        return need <= len(self._free)

    # -- allocation ----------------------------------------------------
    def _take(self):
        if not self._free:
            raise OutOfPagesError(
                "page pool exhausted (%d pages in use)" % self.num_pages)
        p = self._free.pop()
        self._ref[p] = 1
        return p

    def _chunk_hashes(self, prompt_ids):
        """Chain hashes of the FULL page-sized prefix chunks: chunk j's
        hash covers tokens 0..(j+1)*ps, so a page is shared only with a
        request whose entire preceding prefix matches (K/V at a position
        depend on every earlier token)."""
        ps = self.page_size
        out, h = [], hashlib.sha1(b"kv-prefix")
        for j in range(len(prompt_ids) // ps):
            for t in prompt_ids[j * ps:(j + 1) * ps]:
                h.update(b"%d," % int(t))
            out.append(h.hexdigest())
        return out

    def alloc_for_prompt(self, slot, prompt_ids, max_new):
        """Allocate slot's page list for a prompt + decode budget,
        aliasing shared full-prefix pages from the index.  Returns
        ``(pages, shared_count)``; raises :class:`OutOfPagesError`
        (allocating nothing) when the pool cannot cover it."""
        if slot in self._slot_pages:
            raise ValueError("slot %r already holds pages" % (slot,))
        hashes = self._chunk_hashes(prompt_ids)
        shared = []
        for h in hashes:
            p = self._prefix.get(h)
            if p is None:
                break
            shared.append((h, p))
        total = self.pages_needed(len(prompt_ids), max_new)
        fresh_needed = total - len(shared)
        if fresh_needed > len(self._free):
            self.prefix_misses += len(hashes) - len(shared)
            self.prefix_hits += 0
            raise OutOfPagesError(
                "need %d fresh pages, %d free" % (fresh_needed,
                                                  len(self._free)))
        pages = []
        for h, p in shared:
            self._ref[p] += 1
            pages.append(p)
        self.prefix_hits += len(shared)
        for j in range(len(shared), total):
            p = self._take()
            pages.append(p)
            # full prompt-covered pages enter the prefix index owned by
            # their chain hash; the trailing partial/decode pages never
            # do (divergent continuations must not alias)
            if j < len(hashes):
                self._prefix[hashes[j]] = p
                self._page_prefix[p] = hashes[j]
                self.prefix_misses += 1
        self._slot_pages[slot] = pages
        return pages, len(shared)

    def extend(self, slot, n=1):
        """Append n fresh pages to a live slot (a generation outgrowing
        its initial budget)."""
        pages = self._slot_pages[slot]
        for _ in range(n):
            pages.append(self._take())
        return pages

    def cow_split(self, slot, index):
        """Copy-on-write split: give ``slot`` a private copy of its
        ``index``-th page.  Returns ``(old_page, new_page)`` — the
        caller owns copying device content old -> new before the next
        write — or ``(page, page)`` when the page was already private
        (refcount 1), which needs no copy."""
        pages = self._slot_pages[slot]
        old = pages[index]
        if self._ref[old] <= 1:
            return old, old
        new = self._take()
        self._ref[old] -= 1
        pages[index] = new
        return old, new

    def release(self, slot):
        """Drop every page ref the slot holds (terminal request: done,
        failed, expired, quarantined).  Shared prefix pages stay alive
        while other slots (or the index, for re-use) reference them;
        pages whose refcount hits zero return to the free list and
        leave the prefix index."""
        pages = self._slot_pages.pop(slot, None)
        if pages is None:
            return 0
        freed = 0
        for p in pages:
            self._ref[p] -= 1
            if self._ref[p] <= 0:
                del self._ref[p]
                h = self._page_prefix.pop(p, None)
                if h is not None and self._prefix.get(h) == p:
                    del self._prefix[h]
                self._free.append(p)
                freed += 1
        return freed

    def slot_pages(self, slot):
        return list(self._slot_pages.get(slot, ()))

    def holds(self, slot):
        return slot in self._slot_pages

    def refcount(self, page):
        return self._ref.get(page, 0)

    def check_leaks(self):
        """Invariant: every non-free page is referenced by some slot.
        Returns the orphaned pages (must be empty — the leak
        regression contract)."""
        held = set()
        for pages in self._slot_pages.values():
            held.update(pages)
        return sorted(p for p in self._ref if p not in held)

    def stats(self):
        return {"pages_in_use": self.pages_in_use(),
                "pages_free": self.free_pages(),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses}


class PagedKVCacheStore:
    """Names, declares, and initializes the paged pool variables shared
    by the prefill and decode programs of one decoder.

    Per layer and kind the pool is ``[P, H, page_size, D]`` plus, under
    ``kv_dtype='int8'``, a ``[P, H, page_size]`` f32 scale pool (the
    per-token-row per-channel grid from ``ops/quantize``'s machinery).
    HBM is paid per page written, not per slot at the bucket bound:
    ``bytes()`` is the whole pool, ``bytes_per_session(len)`` what one
    session actually pins."""

    def __init__(self, n_layer, slots, n_head, max_len, head_dim,
                 num_pages, page_size=16, dtype="float32",
                 kv_dtype=None, prefix="declm"):
        self.n_layer = int(n_layer)
        self.slots = int(slots)
        self.n_head = int(n_head)
        self.max_len = int(max_len)
        self.head_dim = int(head_dim)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        if self.max_len % self.page_size:
            raise ValueError(
                "max_len %d is not page-aligned (page_size %d)"
                % (self.max_len, self.page_size))
        self.dtype = dtype
        self.kv_dtype = kv_dtype or dtype
        self.quantized = str(self.kv_dtype) == "int8"
        self.prefix = prefix

    @property
    def max_pages_per_slot(self):
        return self.max_len // self.page_size

    @property
    def pool_shape(self):
        return (self.num_pages, self.n_head, self.page_size,
                self.head_dim)

    @property
    def scale_shape(self):
        return (self.num_pages, self.n_head, self.page_size)

    def name(self, kind, layer):
        return "%s_pool_%s_%d" % (self.prefix, kind, layer)

    def scale_name(self, kind, layer):
        return "%s_pool_%s_scale_%d" % (self.prefix, kind, layer)

    def names(self):
        out = [self.name(kind, i) for i in range(self.n_layer)
               for kind in ("k", "v")]
        if self.quantized:
            out += [self.scale_name(kind, i)
                    for i in range(self.n_layer) for kind in ("k", "v")]
        return out

    def declare(self, block, layer):
        """Create (or fetch) this layer's pool (and scale) vars in
        ``block`` — persistable scope state, same-name re-emitted by
        the paged write op for donated in-place updates.  Returns
        ``(k_pool, v_pool, k_scale_or_None, v_scale_or_None)``."""
        out = []
        for kind in ("k", "v"):
            name = self.name(kind, layer)
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name, shape=self.pool_shape,
                                     dtype=self.kv_dtype,
                                     persistable=True)
            out.append(v)
        for kind in ("k", "v"):
            if not self.quantized:
                out.append(None)
                continue
            name = self.scale_name(kind, layer)
            v = block._find_var_recursive(name)
            if v is None:
                v = block.create_var(name=name, shape=self.scale_shape,
                                     dtype="float32", persistable=True)
            out.append(v)
        return out

    def init_scope(self, scope):
        for i in range(self.n_layer):
            for kind in ("k", "v"):
                scope.set_var(self.name(kind, i),
                              np.zeros(self.pool_shape, self.kv_dtype))
                if self.quantized:
                    scope.set_var(self.scale_name(kind, i),
                                  np.ones(self.scale_shape, "float32"))

    def make_allocator(self):
        return PageAllocator(self.num_pages, self.page_size)

    def bytes(self):
        """Whole-pool HBM (every layer, K and V, scales included)."""
        n = 2 * self.n_layer * int(np.prod(self.pool_shape)) \
            * np.dtype(self.kv_dtype).itemsize
        if self.quantized:
            n += 2 * self.n_layer * int(np.prod(self.scale_shape)) * 4
        return n

    def bytes_per_page(self):
        n = 2 * self.n_layer * self.n_head * self.page_size \
            * self.head_dim * np.dtype(self.kv_dtype).itemsize
        if self.quantized:
            n += 2 * self.n_layer * self.n_head * self.page_size * 4
        return n

    def bytes_per_session(self, seq_len):
        """HBM one session of ``seq_len`` tokens pins — the
        sessions-at-fixed-HBM numerator (vs the fixed-region store's
        constant ``bytes() / slots``)."""
        return self.bytes_per_page() * -(-int(seq_len) // self.page_size)
