"""Decoder-LM program builder for the serving engine's prefill/decode
split.

A decoder model serves in two phases: **prefill** runs the whole padded
prompt through causal self-attention once (and writes every layer's K/V
into the slot cache), **decode** then runs one token per step with
``Tq=1`` suffix-causal attention (``ops/attention.py``) against the
cache — compiled once per bucket shape for prefill and exactly once for
decode, with the cache updated in place via buffer donation
(``ops/kv_cache.py``).

Three programs are built over ONE parameter set (every parameter name is
explicit, so the programs share weights through the engine's scope the
same way ``Clone()`` predictors do):

* ``score``   — full causal forward, logits [B, T, V]: the training/
  eval-shaped graph and the decode loop's parity oracle;
* ``prefill`` — score plus per-layer ``kv_cache_write`` at the admitted
  slots (scattered write path);
* ``decode``  — single-token step over ALL cache slots, logits
  [S, 1, V] (identity write path, one vmapped in-place stripe).

The architecture is a post-norm decoder-only Transformer (the
``models/transformer.py`` decoder without cross-attention), dropout-free
— serving is deterministic by construction."""

from .. import layers, unique_name
from ..framework import Program, program_guard
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr
from .kv_cache import KVCacheStore, PagedKVCacheStore

__all__ = ["DecoderSpec", "build_decoder_lm", "sync_draft_weights"]


def sync_draft_weights(scope, target, draft):
    """Copy the target spec's parameters onto the draft spec's names in
    ``scope`` (matched by stripped prefix — both models must share the
    architecture).  This is the *self-draft* setup: the draft is a
    cheaper copy of the target (int8-quantized via
    :meth:`DecoderSpec.quantize`, or simply the same weights for a
    perfect-acceptance test rig), so draft proposals track the target's
    greedy path closely and speculative acceptance stays high without a
    separately trained model."""
    import numpy as np

    from ..framework import Parameter

    tp = target.cache.prefix + "_"
    dp = draft.cache.prefix + "_"
    copied = 0
    for v in target.score_program.list_vars():
        if not isinstance(v, Parameter) or not v.name.startswith(tp):
            continue
        dst = dp + v.name[len(tp):]
        src = scope.find_var(v.name)
        if src is None or not draft.score_program.global_block() \
                .has_var(dst):
            continue
        scope.set_var(dst, np.asarray(src).copy())
        copied += 1
    if not copied:
        raise ValueError(
            "no parameters copied — do the specs share an architecture "
            "(prefixes %r -> %r)?" % (target.cache.prefix,
                                      draft.cache.prefix))
    return copied


def _fc(x, size, name, act=None, bias=True):
    return layers.fc(
        x, size=size, num_flatten_dims=2, act=act,
        param_attr=ParamAttr(name=name + ".w_0"),
        bias_attr=ParamAttr(name=name + ".b_0") if bias else False,
        name=name)


def _ln(x, name):
    return layers.layer_norm(
        x, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + ".scale"),
        bias_attr=ParamAttr(name=name + ".bias"))


def _split_heads(x, n_head, d_head):
    r = layers.reshape(x, shape=[0, 0, n_head, d_head])
    return layers.transpose(r, perm=[0, 2, 1, 3])


def _merge_heads(x, d_model):
    r = layers.transpose(x, perm=[0, 2, 1, 3])
    return layers.reshape(r, shape=[0, 0, d_model])


class DecoderSpec:
    """The built program bundle the :class:`~.engine.GenerationEngine`
    runs.  ``slots`` is the fixed decode batch (cache rows)."""

    def __init__(self, vocab_size, max_len, slots, n_layer, n_head,
                 d_model, d_inner, cache, programs, startup, spec_k=None):
        self.vocab_size = vocab_size
        self.max_len = max_len
        self.slots = slots
        self.n_layer = n_layer
        self.n_head = n_head
        self.d_model = d_model
        self.d_inner = d_inner
        self.cache = cache
        # programs: {"score": (prog, logits_var), ...}
        self.score_program, self.score_logits = programs["score"]
        self.prefill_program, self.prefill_logits = programs["prefill"]
        self.decode_program, self.decode_logits = programs["decode"]
        # speculative verify: k-token decode-shaped step (present only
        # when built with spec_k)
        self.verify_program, self.verify_logits = programs.get(
            "verify", (None, None))
        self.spec_k = spec_k
        self.startup_program = startup

    @property
    def paged(self):
        return isinstance(self.cache, PagedKVCacheStore)

    def init_scope(self, executor, scope):
        """Run the startup program (parameter init) and zero the cache
        into ``scope`` — everything the three programs read as state."""
        from ..scope import scope_guard

        with scope_guard(scope):
            executor.run(self.startup_program, scope=scope)
        self.cache.init_scope(scope)

    def quantize(self, scope, mode="weight_only", weight_bits=8):
        """Return a new spec whose score/prefill/decode programs run
        int8 weights (``transpiler.quantize_inference`` over the SHARED
        ``scope``: the three programs name the same parameters, so each
        weight quantizes once and every program reads the same
        ``@INT8`` persistables).  Call after ``init_scope`` — the pass
        reads materialized weights."""
        from ..transpiler.quantize_pass import quantize_inference

        triple = [("score", self.score_program, self.score_logits),
                  ("prefill", self.prefill_program, self.prefill_logits),
                  ("decode", self.decode_program, self.decode_logits)]
        if self.verify_program is not None:
            triple.append(("verify", self.verify_program,
                           self.verify_logits))
        programs = {}
        for i, (name, prog, logits) in enumerate(triple):
            # the first rewrite quantizes the shared weights; the later
            # programs reuse the scope values instead of re-quantizing
            q = quantize_inference(prog, scope=scope, mode=mode,
                                   weight_bits=weight_bits,
                                   reuse_existing=(i > 0))
            programs[name] = (q, q.global_block().var(logits.name))
        return DecoderSpec(self.vocab_size, self.max_len, self.slots,
                           self.n_layer, self.n_head, self.d_model,
                           self.d_inner, self.cache, programs,
                           self.startup_program, spec_k=self.spec_k)


def _layer_stack(x, klen_var, spec_dims, prefix, cache=None, slot_var=None,
                 wpos_var=None, decode=False, table_var=None):
    """The shared decoder trunk.  ``cache`` set => write each layer's
    K/V; ``decode`` => attend over the cache vars instead of the local
    K/V (``Tq`` may exceed 1 — the speculative verify program is this
    same stack with a k-token suffix query).  A
    :class:`~.kv_cache.PagedKVCacheStore` cache routes the writes
    through ``kv_cache_paged_write`` against ``table_var`` and the
    decode attention through ``paged_attention`` (int8 pools carry
    their scale vars along)."""
    n_layer, n_head, d_model, d_inner = spec_dims
    d_head = d_model // n_head
    paged = isinstance(cache, PagedKVCacheStore)
    for i in range(n_layer):
        base = "%s_l%d" % (prefix, i)
        q = _split_heads(_fc(x, d_model, base + "_q", bias=False),
                         n_head, d_head)
        k = _split_heads(_fc(x, d_model, base + "_k", bias=False),
                         n_head, d_head)
        v = _split_heads(_fc(x, d_model, base + "_v", bias=False),
                         n_head, d_head)
        if cache is not None and paged:
            k_pool, v_pool, k_scale, v_scale = cache.declare(
                x.block.program.global_block(), i)
            helper = LayerHelper("kv_cache_paged_write")
            for c, sc, new in ((k_pool, k_scale, k), (v_pool, v_scale, v)):
                inputs = {"Cache": [c], "X": [new], "Pos": [wpos_var],
                          "PageTable": [table_var]}
                outputs = {"Out": [c]}
                if slot_var is not None:
                    inputs["Slot"] = [slot_var]
                if sc is not None:
                    inputs["Scale"] = [sc]
                    outputs["OutScale"] = [sc]
                helper.append_op(type="kv_cache_paged_write",
                                 inputs=inputs, outputs=outputs)
            if decode:
                ctx = layers.paged_attention(
                    q, k_pool, v_pool, table_var, k_len=klen_var,
                    k_scale=k_scale, v_scale=v_scale, causal=True,
                    scale=d_head ** -0.5)
            else:
                ctx = layers.fused_attention(
                    q, k, v, k_len=klen_var, causal=True, is_test=True,
                    scale=d_head ** -0.5)
        else:
            if cache is not None:
                cache_k, cache_v = cache.declare(
                    x.block.program.global_block(), i)
                helper = LayerHelper("kv_cache_write")
                for c, new in ((cache_k, k), (cache_v, v)):
                    inputs = {"Cache": [c], "X": [new], "Pos": [wpos_var]}
                    if slot_var is not None:
                        inputs["Slot"] = [slot_var]
                    helper.append_op(type="kv_cache_write", inputs=inputs,
                                     outputs={"Out": [c]})
                if decode:
                    k, v = cache_k, cache_v
            ctx = layers.fused_attention(
                q, k, v, k_len=klen_var, causal=True, is_test=True,
                scale=d_head ** -0.5)
        o = _fc(_merge_heads(ctx, d_model), d_model, base + "_o",
                bias=False)
        x = _ln(layers.elementwise_add(x, o), base + "_ln1")
        h = _fc(x, d_inner, base + "_fc1", act="relu")
        h = _fc(h, d_model, base + "_fc2")
        x = _ln(layers.elementwise_add(x, h), base + "_ln2")
    return x


def _embed(tok, pos, vocab_size, max_len, d_model, prefix):
    emb = layers.embedding(
        tok, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=prefix + "_tok_emb"))
    pos_e = layers.embedding(
        pos, size=[max_len, d_model],
        param_attr=ParamAttr(name=prefix + "_pos_emb"))
    return layers.elementwise_add(emb, pos_e)


def build_decoder_lm(vocab_size, max_len, slots, n_layer=2, n_head=2,
                     d_model=32, d_inner=64, dtype="float32",
                     prefix="declm", seed=7, paged=False, page_size=16,
                     num_pages=None, kv_dtype=None, spec_k=None):
    """Build the score/prefill/decode program triple plus one startup
    program; returns a :class:`DecoderSpec`.

    ``paged=True`` swaps the fixed-region cache for a
    :class:`~.kv_cache.PagedKVCacheStore` pool of ``num_pages`` pages of
    ``page_size`` tokens (default pool = the fixed-region footprint;
    shrink it to UNDER-provision — admission then gates on free pages
    and HBM is paid per page written).  ``kv_dtype='int8'`` quantizes
    the pool per token-row (f32 scale pools ride along).  ``spec_k``
    additionally builds a ``verify`` program — a k-token decode-shaped
    step for speculative decoding (bottom-aligned suffix queries; same
    cache, same weights, one extra compile)."""
    if paged:
        if num_pages is None:
            num_pages = slots * (max_len // page_size)
        cache = PagedKVCacheStore(
            n_layer, slots, n_head, max_len, d_model // n_head,
            num_pages=num_pages, page_size=page_size, dtype=dtype,
            kv_dtype=kv_dtype, prefix=prefix)
    else:
        if kv_dtype not in (None, dtype):
            raise ValueError(
                "kv_dtype %r needs paged=True (the fixed-region cache "
                "has no scale storage)" % (kv_dtype,))
        cache = KVCacheStore(n_layer, slots, n_head, max_len,
                             d_model // n_head, dtype=dtype,
                             prefix=prefix)
    dims = (n_layer, n_head, d_model, d_inner)
    startup = Program()
    startup.random_seed = seed
    programs = {}

    def _table_feed():
        # the page table is DATA, not state: the host allocator owns it
        # and feeds the full [slots, max_pages] int32 map every step —
        # fixed shape, so it never perturbs the compile-once signature
        return layers.data(
            "page_table", shape=[slots, cache.max_pages_per_slot],
            append_batch_size=False, dtype="int32")

    # -- score: full causal forward -----------------------------------
    score = Program()
    score.random_seed = seed
    with program_guard(score, startup), unique_name.guard(prefix + "_s_"):
        tok = layers.data("tok", shape=[1], dtype="int64", lod_level=1)
        pos = layers.data("pos", shape=[-1, -1, 1],
                          append_batch_size=False, dtype="int64")
        klen = tok.block._find_var_recursive(tok._seq_len_name)
        x = _embed(tok, pos, vocab_size, max_len, d_model, prefix)
        x = _layer_stack(x, klen, dims, prefix)
        logits = _fc(x, vocab_size, prefix + "_logits")
        programs["score"] = (score, logits)

    # -- prefill: score + scattered cache writes ----------------------
    # (its own startup: parameters already exist in `startup`, and the
    # duplicate init ops there must not re-randomize a live scope)
    prefill = Program()
    prefill.random_seed = seed
    with program_guard(prefill, Program()), \
            unique_name.guard(prefix + "_p_"):
        tok = layers.data("tok", shape=[1], dtype="int64", lod_level=1)
        pos = layers.data("pos", shape=[-1, -1, 1],
                          append_batch_size=False, dtype="int64")
        slot = layers.data("slot", shape=[-1], append_batch_size=False,
                           dtype="int32")
        wpos = layers.data("wpos", shape=[-1], append_batch_size=False,
                           dtype="int32")
        table = _table_feed() if paged else None
        klen = tok.block._find_var_recursive(tok._seq_len_name)
        x = _embed(tok, pos, vocab_size, max_len, d_model, prefix)
        x = _layer_stack(x, klen, dims, prefix, cache=cache,
                         slot_var=slot, wpos_var=wpos, table_var=table)
        logits = _fc(x, vocab_size, prefix + "_logits")
        programs["prefill"] = (prefill, logits)

    # -- decode: one token over every slot, cache-attending ------------
    decode = Program()
    decode.random_seed = seed
    with program_guard(decode, Program()), \
            unique_name.guard(prefix + "_d_"):
        tok = layers.data("tok", shape=[-1, 1, 1],
                          append_batch_size=False, dtype="int64")
        pos = layers.data("pos", shape=[-1, 1, 1],
                          append_batch_size=False, dtype="int64")
        wpos = layers.data("wpos", shape=[-1], append_batch_size=False,
                           dtype="int32")
        cache_len = layers.data("cache_len", shape=[-1],
                                append_batch_size=False, dtype="int32")
        table = _table_feed() if paged else None
        x = _embed(tok, pos, vocab_size, max_len, d_model, prefix)
        x = _layer_stack(x, cache_len, dims, prefix, cache=cache,
                         wpos_var=wpos, decode=True, table_var=table)
        logits = _fc(x, vocab_size, prefix + "_logits")
        programs["decode"] = (decode, logits)

    # -- verify: k-token decode-shaped step (speculative decoding) -----
    # Feeds [last_accepted, d_1..d_{k-1}] per slot at positions
    # pos..pos+k-1; query i sits bottom-aligned at cache_len - k + i, so
    # greedy argmax of logits[:, i] is the target model's next token
    # after draft token i — acceptance is a host-side prefix match,
    # rollback is free (rejected positions stay stale-masked past the
    # slot's cache_len and the next write overwrites them).
    if spec_k is not None:
        if spec_k < 2:
            raise ValueError("spec_k must be >= 2 (k-1 draft tokens + "
                             "the accepted anchor), got %r" % (spec_k,))
        verify = Program()
        verify.random_seed = seed
        with program_guard(verify, Program()), \
                unique_name.guard(prefix + "_v_"):
            tok = layers.data("tok", shape=[-1, spec_k, 1],
                              append_batch_size=False, dtype="int64")
            pos = layers.data("pos", shape=[-1, spec_k, 1],
                              append_batch_size=False, dtype="int64")
            wpos = layers.data("wpos", shape=[-1],
                               append_batch_size=False, dtype="int32")
            cache_len = layers.data("cache_len", shape=[-1],
                                    append_batch_size=False,
                                    dtype="int32")
            table = _table_feed() if paged else None
            x = _embed(tok, pos, vocab_size, max_len, d_model, prefix)
            x = _layer_stack(x, cache_len, dims, prefix, cache=cache,
                             wpos_var=wpos, decode=True, table_var=table)
            logits = _fc(x, vocab_size, prefix + "_logits")
            programs["verify"] = (verify, logits)

    return DecoderSpec(vocab_size, max_len, slots, n_layer, n_head,
                       d_model, d_inner, cache, programs, startup,
                       spec_k=spec_k)
