"""Executor: lowers a Program to one jit-compiled XLA computation and runs it.

Capability parity with the reference's single-device Executor
(``paddle/fluid/framework/executor.cc:295-428``: Prepare ops from a block,
interpret them in order on one place, GC dead tensors) — re-designed
TPU-first:

* Instead of an op-by-op interpreter, ``Executor.run`` *traces* every op's
  JAX compute function in program order into a single function
  ``f(feeds, state, key) -> (fetches, new_state)`` and ``jax.jit``-compiles
  it once per (program, feed-signature).  The whole step — forward, backward,
  optimizer update — is one HLO module: XLA fuses elementwise chains into
  the matmuls (HBM-bandwidth win) and schedules for the MXU.  This is the
  TPU answer to the reference's per-op kernel launches.
* "State" is the set of persistable variables (parameters, optimizer
  accumulators, LR, step counters) read from / written back to the Scope.
  Input state buffers are donated to the computation, so parameter updates
  are in-place at the XLA level — the analog of the reference's var reuse,
  without a garbage collector (temporaries die inside the fused module).
* Feed/fetch: no feed/fetch ops are injected (reference executor.py:290-334
  injects feed_op/fetch_op); feeds bind program input vars directly and
  fetches are read off the traced environment.
* PRNG: programs are deterministic given ``program.random_seed``; each run
  folds a step counter into the key so dropout masks differ per step while
  remaining reproducible (replaces the reference's per-op seed attrs).
"""

import collections
import time

import numpy as np

import jax

from . import compile_cache, fault, flags, guardian, monitor, registry
from .core import materialize_dtype
from .framework import Program, Variable, default_main_program
from .monitor import program_profile
from .profiler import RecordEvent, is_profiling
from .registry import ComputeContext
from .scope import Scope, global_scope

__all__ = ["Executor", "AsyncDispatchQueue", "CPUPlace", "TPUPlace",
           "place_from_string"]


class Place:
    """Device abstraction (reference platform/place.h:25-51).  On TPU builds
    there are two interesting places: host CPU and TPU chips; XLA handles
    everything below this level."""

    def jax_device(self):
        raise NotImplementedError

    def __repr__(self):
        return self.__class__.__name__


class CPUPlace(Place):
    def jax_device(self):
        # local_devices: under multi-host (jax.distributed) the first
        # GLOBAL device may belong to another process
        try:
            return jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            return jax.local_devices()[0]

    def __eq__(self, other):
        return isinstance(other, CPUPlace)

    def __hash__(self):
        return hash("CPUPlace")


class TPUPlace(Place):
    """The first-class TPU place named in the north star (BASELINE.json)."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def jax_device(self):
        devs = [d for d in jax.local_devices() if d.platform != "cpu"]
        if not devs:
            devs = jax.local_devices()
        return devs[self.device_id % len(devs)]

    def __eq__(self, other):
        return isinstance(other, TPUPlace) and other.device_id == self.device_id

    def __hash__(self):
        return hash(("TPUPlace", self.device_id))

    def __repr__(self):
        return "TPUPlace(%d)" % self.device_id


# CUDAPlace alias for scripts written against the reference API surface:
# on this framework "the accelerator" is the TPU.
CUDAPlace = TPUPlace


class CUDAPinnedPlace(CPUPlace):
    """Pinned-host staging place (reference platform/place.h:36
    CUDAPinnedPlace).  Page-locked memory is a CUDA-transfer concept;
    on this runtime host arrays already stage through the PJRT transfer
    path, so this is the host place under a parity name."""

    def __eq__(self, other):
        return isinstance(other, CUDAPinnedPlace)

    def __hash__(self):
        return hash("CUDAPinnedPlace")


def place_from_string(s):
    s = s.lower()
    if s in ("cpu",):
        return CPUPlace()
    if s in ("tpu", "cuda", "gpu", "device"):
        return TPUPlace(0)
    raise ValueError("unknown place %r" % s)


def _coerce_feed(block, name, v):
    """Convert one feed value to the program var's MATERIALIZED dtype.

    Device arrays pass through without a host round-trip; under x64-off
    a device array fed back (PyReader staging) is already int32, and
    asking jax for int64 would warn-and-truncate."""
    if not isinstance(v, jax.Array):
        v = np.asarray(v)
    pv = block._find_var_recursive(name)
    if pv is not None and pv.dtype is not None:
        want = materialize_dtype(pv.dtype)
        if np.dtype(v.dtype) != np.dtype(want):
            v = v.astype(want)
    return v


def _feed_signature(feed):
    return tuple(
        (name, tuple(np.shape(v)), str(np.asarray(v).dtype))
        for name, v in sorted(feed.items())
    )


def _sparse_feed_info(program):
    """(ids feed names tuple, total sparse-table bytes) for telemetry:
    the is_sparse lookup tables' directly-fed Ids vars + total table
    bytes.  The one-time program walk caches ON the program object
    keyed by its version (an id()-keyed module dict would go stale when
    a freed program's id is recycled); the per-step cost is a np.unique
    over the id feeds."""
    cached = getattr(program, "_sparse_feed_cache", None)
    if cached is not None and cached[0] == program._version:
        return cached[1]
    from .ops.selected_rows import sparse_lookup_tables

    tables = {w: int(np.prod(v.shape)) * np.dtype(
        materialize_dtype(v.dtype)).itemsize
        for w, v in sparse_lookup_tables(program).items()}
    feeds = []
    for blk in program.blocks:
        for op in blk.ops:
            if op.type != "lookup_table" or \
                    not op.attrs.get("is_sparse", False):
                continue
            for n in op.inputs.get("Ids", []):
                v = blk._find_var_recursive(n)
                if v is not None and getattr(v, "is_data", False) \
                        and n not in feeds:
                    feeds.append(n)
    hit = (tuple(feeds), sum(tables.values()))
    program._sparse_feed_cache = (program._version, hit)
    return hit


def _sparse_step_extras(program, feed_names, feed_vals):
    """Step-record extras for the sparse embedding path: distinct rows
    touched this step (summed over id feeds) + static table bytes.
    Host feeds only — counting a device-resident id feed would force a
    per-step sync on the async path.  Also bumps the
    ``sparse/touched_rows`` registry counter.  None when the program
    has no is_sparse tables."""
    feeds, table_bytes = _sparse_feed_info(program)
    if not feeds:
        return None
    touched = 0
    by_name = dict(zip(feed_names, feed_vals))
    for n in feeds:
        v = by_name.get(n)
        if isinstance(v, np.ndarray) and v.size:
            touched += int(np.unique(v).size)
    monitor.count("sparse/touched_rows", touched)
    return {"sparse_touched_rows": touched,
            "sparse_table_bytes": int(table_bytes)}


def _batch_examples(block, feed_names, feed_vals):
    """Examples-per-step for StepStats: the leading dim of a feed whose
    program var declares a batch dim (shape[0] == -1/None); fallback is
    the max leading dim over array feeds (an alphabetically-first scalar
    aux feed must not report examples=1)."""
    best = 0
    for n, v in zip(feed_names, feed_vals):
        if getattr(v, "ndim", 0) < 1:
            continue
        pv = block._find_var_recursive(n)
        if pv is not None and pv.shape is not None \
                and len(pv.shape) >= 1 and pv.shape[0] in (-1, None):
            return int(v.shape[0])
        best = max(best, int(v.shape[0]))
    return best


def trace_program(program, feed_names, state_names, writeback, fetch_names,
                  platform=None, mesh=None, sequence_parallel=True,
                  pipeline_schedule=None, pipeline_microbatches=None,
                  state_specs=None):
    """Build the pure step function for ``program``'s global block:
    ``fn(feed_vals, state_vals, key) -> (fetches, new_state)``.

    This is the single lowering point shared by the single-device Executor,
    the mesh ParallelExecutor, and ``__graft_entry__`` — a Program becomes
    one traceable JAX function that pjit/jit compile to one HLO module.
    ``platform`` names the executing device platform ("cpu"/"tpu") so
    Pallas call sites pick mosaic vs interpret.  Returns
    ``(fn, state_in, state_out)``.
    """
    block = program.global_block()
    ops = list(block.ops)
    state_in = list(state_names)
    # every read state var is also returned so XLA donation never leaves
    # a dangling (invalidated) buffer in the scope
    state_out = list(dict.fromkeys(list(state_names) + list(writeback)))

    def fn(feed_vals, state_vals, key):
        env = {}
        env.update(zip(feed_names, feed_vals))
        env.update(zip(state_in, state_vals))
        ctx = ComputeContext(key=key, platform=platform, mesh=mesh)
        ctx.sequence_parallel = sequence_parallel
        ctx.pipeline_schedule = pipeline_schedule
        ctx.pipeline_microbatches = pipeline_microbatches
        if state_specs:
            # how the PE placed each persistable on the mesh: sharded
            # sparse-table lowerings consult this at trace time
            ctx.state_specs = dict(state_specs)
        ctx.program = program
        ctx.amp = getattr(program, '_amp_policy', None)
        for i, op in enumerate(ops):
            registry.compute_op(op, env, ctx, op_index=i)
        fetches = [env[n] for n in fetch_names]
        new_state = [env[n] for n in state_out]
        return fetches, new_state

    return fn, state_in, state_out


class _CompiledProgram:
    """One lowered+jitted (program, feed-signature) entry."""

    def __init__(self, fn, feed_names, state_in, state_out, fetch_names,
                 guarded=False, probe=None):
        self.fn = fn
        self.feed_names = feed_names
        self.state_in = state_in      # read from scope before the step
        self.state_out = state_out    # written back to scope after
        self.fetch_names = fetch_names
        # lowered with the guardian's in-graph skip guard: the step
        # returns a trailing `ok` fetch (stripped before user fetches)
        # and suppresses its state update when a float fetch is
        # non-finite
        self.guarded = guarded
        # lowered with the model-health probe (FLAGS_health): a HealthProbe
        # whose (L, 4) per-layer stats array rides as one extra fetch
        # between the user fetches and the guard's ok; None = every
        # health call site in run() is skipped (disabled-is-free)
        self.probe = probe
        # feed signatures already dispatched through this entry.  jax.jit
        # retraces+recompiles per feed shape, and the entry is shared
        # process-globally (trace cache), so warmth is per-signature: an
        # unseen shape's first call pays trace + XLA compile (or a
        # persistent-cache deserialize) and is recorded as a "compile"
        # span, seen shapes as "dispatch"
        self.seen_sigs = set()
        # AOT-captured executables keyed (feed_sig, device id): while
        # profile capture is on, the cold dispatch compiles through
        # program_profile.capture (so cost/memory analyses are readable)
        # and every later step of that signature dispatches through the
        # same executable — jax's AOT and jit call paths do not share a
        # backend-compile cache, so mixing them would compile twice
        self.aot = {}


class AsyncDispatchQueue:
    """Bounded window of in-flight (dispatched, not-yet-synced) steps.

    jax dispatch is already asynchronous; what needs managing is the
    HOST's run-ahead: an unbounded `return_numpy=False` loop enqueues
    work (and keeps fetch buffers alive) faster than the device retires
    it.  Each dispatched step's fetch handles are ``push``ed; once more
    than ``max_inflight`` steps are outstanding the OLDEST is
    ``block_until_ready``-ed — the only sync on the fast path, at the
    window edge, never per step.  ``drain`` syncs everything (epoch
    boundaries, checkpointing, reading host values)."""

    def __init__(self, max_inflight=None, name="executor"):
        # None = re-read FLAGS_max_inflight_steps on every push, so
        # set_flags keeps working after the executor is constructed
        self._max_inflight = max_inflight
        self._name = name
        self._inflight = collections.deque()
        # watchdog diagnostics read the queue state through monitor's
        # weak tracking — a stalled window edge is then visible as
        # depth == max_inflight in the stall dump
        monitor.track(self)

    def monitor_state(self):
        return {"kind": "dispatch_queue", "name": self._name,
                "depth": len(self._inflight),
                "max_inflight": self.max_inflight}

    @property
    def max_inflight(self):
        lim = self._max_inflight
        if lim is None:
            lim = flags.flag("max_inflight_steps")
        return max(1, int(lim))

    def __len__(self):
        return len(self._inflight)

    def push(self, handles):
        """Register one dispatched step's output handles; blocks on the
        oldest step iff the window is over-full."""
        self._inflight.append(handles)
        while len(self._inflight) > self.max_inflight:
            self._sync_oldest()

    def push_step(self, fetches, new_state):
        """Register one async-dispatched step: its fetch handles when
        present, else a tiny sync token derived from the state.  A
        fetch-less step has nothing un-donated to wait on — the next
        step's dispatch donates every new_state buffer — so the token
        (a one-element gather, NOT ravel(): an eager reshape copies the
        whole array and forces a layout change on sharded state) is
        what keeps the window a real bound.  Multihost non-addressable
        state can't be sliced from one process; those fetch-less loops
        go unbounded rather than crash."""
        handles = fetches
        if not handles and new_state and \
                getattr(new_state[0], "is_fully_addressable", True):
            s0 = new_state[0]
            handles = [s0[(0,) * s0.ndim]]
        if handles:
            self.push(handles)

    @staticmethod
    def _live_leaves(handles):
        return [l for l in jax.tree_util.tree_leaves(handles)
                if not getattr(l, "is_deleted", lambda: False)()]

    def _sync_oldest(self):
        oldest = self._inflight.popleft()
        # liveness signal for the watchdog: a window-edge sync that
        # never returns (device wedge) leaves this heartbeat stale while
        # the blocked thread looks merely "busy"
        monitor.heartbeat(self._name + "/dispatch")
        with RecordEvent(self._name + "/fetch_sync"):
            live = self._live_leaves(oldest)
            if not live:
                # a fetch-less step's handles are its new_state, and the
                # NEXT step donates those buffers (donate_argnums), so
                # the popped entry may hold nothing waitable.  Blocking
                # on the oldest still-live leaf among the younger
                # in-flight steps retires this one too (same-device
                # program order) and keeps the window a real bound —
                # skipping outright would let the host run ahead
                # without limit.
                for entry in self._inflight:
                    live = self._live_leaves(entry)
                    if live:
                        break
            jax.block_until_ready(live)

    def drain(self):
        """Block until every in-flight step has retired."""
        while self._inflight:
            self._sync_oldest()


class Executor:
    """Runs Programs on a Place (reference executor.py:256 / executor.cc:85)."""

    def __init__(self, place=None, donate_state=True):
        """``donate_state=False`` keeps input state buffers alive after
        the step (no XLA donation): required when several executors
        share one scope concurrently (inference predictor clones) —
        donation would delete the weight buffers under the other
        executors.  Training keeps the default in-place donation."""
        self.place = place if place is not None else TPUPlace(0)
        self.donate_state = donate_state
        self._cache = {}
        self._run_counter = 0
        self._warned_unobserved_guard = False
        self._dispatch_queue = AsyncDispatchQueue(name="executor")

    # ------------------------------------------------------------------
    def sync(self):
        """Retire every in-flight async-dispatched step (the
        ``return_numpy=False`` fast path never syncs per step; call this
        at epoch/checkpoint boundaries to force completion)."""
        self._dispatch_queue.drain()

    def state_dict(self):
        """Host-side executor state an exact resume must carry: the PRNG
        fold-in counter (each ``run`` folds it into the program seed, so
        dropout masks etc. at step N depend on how many steps ran
        before).  Captured into ``TrainState`` checkpoints; exactness
        additionally requires a nonzero ``program.random_seed`` (a
        seedless program draws a fresh seed per process)."""
        return {"run_counter": int(self._run_counter)}

    def load_state_dict(self, state):
        self._run_counter = int(state["run_counter"])

    def close(self):
        self.sync()
        self._cache.clear()

    def _program_key(self, program, feed_sig, fetch_names, scope):
        # program._version bumps on structural mutation (op append/insert,
        # rename_var) so stale compiled functions are not reused; direct
        # attr edits on existing ops are NOT tracked — clone() instead.
        # the policy object itself goes in the key (kept alive by the
        # cache) — id() could alias a recycled address after GC
        return (id(program), program._version, program.random_seed, feed_sig,
                tuple(fetch_names), id(scope),
                getattr(program, '_amp_policy', None),
                # trace-time flag choices are baked into the jaxpr
                compile_cache.trace_flag_values())

    def _analyze(self, program, feed_names, scope, fetch_names=()):
        """Split program vars into feeds / state-from-scope / temporaries."""
        block = program.global_block()
        produced = set(feed_names)
        state = []
        for op in block.ops:
            for n in op.input_arg_names:
                if n and n not in produced and n not in state:
                    if scope.has_var(n):
                        state.append(n)
                    else:
                        raise RuntimeError(
                            "input var %r of op %r is neither fed, produced by "
                            "an earlier op, nor present in the scope. Feed it "
                            "or run the startup program first." % (n, op.type)
                        )
            for n in op.output_arg_names:
                if n:
                    produced.add(n)
        # fetch targets no op produces but the scope holds (evaluator
        # state reads, plain var inspection) load like any other state
        for n in fetch_names:
            if n and n not in produced and n not in state \
                    and scope.has_var(n):
                state.append(n)
        # persistable outputs must be written back even if never read
        writeback = []
        for op in block.ops:
            for n in op.output_arg_names:
                v = block._find_var_recursive(n) if n else None
                if v is not None and v.persistable and n not in writeback:
                    writeback.append(n)
        return state, writeback

    def _lower(self, program, feed_names, state_names, writeback, fetch_names):
        platform = self.place.jax_device().platform
        # process-global trace cache: a second executor over the same
        # program structure + signature (bench reruns, evaluator clones)
        # reuses the jitted step — zero new lowerings
        tkey = compile_cache.trace_key(
            program, feed_names, tuple(state_names), fetch_names,
            "jit", platform, self.donate_state,
            compile_cache.trace_flag_values())
        cached = compile_cache.lookup(tkey)
        if cached is not None:
            return cached
        # FLAGS_health: per-layer grad/param/update stats ride the step as
        # one fused extra fetch.  The grad vars are added to the traced
        # fetch list (XLA sees them as outputs); enablement is part of
        # trace_flag_values so the probe-free trace is never served stale
        probe = monitor.health.build_probe(program, state_names) \
            if monitor.health.probe_enabled() else None
        with RecordEvent("executor/trace"):
            traced_fetches = list(fetch_names) + \
                (list(probe.grad_names) if probe is not None else [])
            fn, state_in, state_out = trace_program(
                program, feed_names, state_names, writeback, traced_fetches,
                platform=platform,
            )
            guarded = guardian.skip_guard_enabled()
            if guarded:
                # in-graph sentinel + skip: non-finite float fetches
                # suppress the whole state update on-device (the
                # guardian's skip-step rung); baked into the trace key
                # via trace_flag_values.  n_watch excludes the probe's
                # grad fetches: an exploding-but-finite gradient is the
                # probe's business, and a non-finite one already poisons
                # a watched fetch downstream
                fn = guardian.wrap_step_guard(fn, state_in, state_out,
                                              n_watch=len(fetch_names))
            if probe is not None:
                fn = monitor.health.wrap_step_probe(
                    fn, probe, len(fetch_names), guarded, state_in,
                    state_out)
            donate = (1,) if self.donate_state else ()
            jitted = jax.jit(fn, donate_argnums=donate)
        return compile_cache.store(tkey, _CompiledProgram(
            jitted, feed_names, state_in, state_out, fetch_names,
            guarded=guarded, probe=probe))

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
    ):
        """Execute ``program``: feed dict name->array, fetch list of
        Variables/names; persistable results are committed back to scope."""
        if program is None:
            program = default_main_program()
        feed = dict(feed or {})
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()
        # a single module-global bool read when telemetry is off — the
        # whole StepStats assembly is behind it
        mon_t0 = time.perf_counter() if monitor.enabled() else None

        fetch_names = [
            v.name if isinstance(v, Variable) else v for v in fetch_list
        ]
        feed_names = sorted(feed.keys())
        # cast feeds to declared var dtype when the program declares one;
        # jax arrays already on device pass through untouched (the input-
        # pipeline fast path: py_reader/double-buffer feeds stay device-
        # resident instead of re-crossing the host link every step)
        block = program.global_block()
        feed_vals = [_coerce_feed(block, n, feed[n]) for n in feed_names]

        # this run's step index (the PRNG fold-in counter before this
        # step bumps it): fault schedules and guardian records key on it
        step_idx = self._run_counter
        if fault.active():
            # drills mutate feed_vals in place (poison_batch); shapes/
            # dtypes are preserved, so the signature below is unaffected
            fault.fire("executor/feed", step_idx,
                       feed_names=feed_names, feed_vals=feed_vals)

        feed_sig = tuple(
            (n, tuple(v.shape), str(v.dtype))
            for n, v in zip(feed_names, feed_vals)
        )
        key = self._program_key(program, feed_sig, fetch_names, scope)
        compiled = self._cache.get(key)
        if compiled is None:
            # the reference wraps op instantiation in RecordBlock
            # (executor.cc Prepare); here the analog is the trace+jit
            # (_lower consults the process-global trace cache first)
            with RecordEvent("executor/compile"):
                state_names, writeback = self._analyze(
                    program, feed_names, scope, fetch_names)
                compiled = self._lower(
                    program, feed_names, state_names, writeback, fetch_names
                )
            self._cache[key] = compiled

        dev = self.place.jax_device()
        with RecordEvent("executor/h2d_transfer"):
            state_vals = [
                jax.device_put(scope.var(n), dev) for n in compiled.state_in
            ]
            feed_dev = [jax.device_put(v, dev) for v in feed_vals]
        seed = program.random_seed or 0
        rng = jax.random.key(
            np.uint32(seed) if seed else np.random.randint(0, 2**31 - 1),
            impl="rbg" if flags.flag("fast_prng") else None,
        )
        rng = jax.random.fold_in(rng, self._run_counter)
        self._run_counter += 1

        t0 = time.perf_counter() if flags.flag("benchmark") else None
        # an unseen feed signature's first call pays jaxpr trace + XLA
        # compile (or a persistent-cache deserialize) — recorded as a
        # compile span so cache hits are observable as its disappearance
        cold = feed_sig not in compiled.seen_sigs
        step_span = "executor/compile" if cold else "executor/dispatch"
        # correlation tags: fingerprint is memoized per program version
        # (one attribute read when warm), computed only when some
        # observability layer is on — a dark process pays nothing here
        fp = compile_cache.program_fingerprint(program) \
            if (mon_t0 is not None or is_profiling()) else None
        # bucket hint: the goodput ledger (and offline trace_summary)
        # classify the cold step span as compile badput, the warm one as
        # the compute remainder — by the producer's own verdict, not by
        # name guessing
        span_args = {"run_id": monitor.run_id(), "fingerprint": fp[:12],
                     "step": self._run_counter - 1,
                     "bucket": "trace_compile" if cold else "compute"} \
            if fp else None
        if fault.active():
            fault.fire("executor/dispatch", step_idx)
        with RecordEvent("executor/run"):
            with RecordEvent(step_span, args=span_args):
                with jax.default_device(dev):
                    fn = compiled.fn
                    if cold and program_profile.capture_enabled() \
                            and (feed_sig, getattr(dev, "id", 0)) \
                            not in compiled.aot \
                            and not flags.flag("debug_nans"):
                        # the step is AOT-compiled here — profiled
                        # (cost/memory analysis) and HBM-preflighted
                        # BEFORE its first dispatch — and the same
                        # executable serves every later step of this
                        # signature: one compile total.  debug_nans
                        # keeps the jit path (its nan re-run machinery
                        # lives there).
                        aotex = program_profile.capture(
                            fp if fp is not None else
                            compile_cache.program_fingerprint(program),
                            feed_sig, compiled.fn,
                            (feed_dev, state_vals, rng),
                            device=dev, kind="executor",
                            fetch_names=tuple(fetch_names))
                        if aotex is not None:
                            compiled.aot[
                                (feed_sig, getattr(dev, "id", 0))] = aotex
                    # debug_nans checked at dispatch too: a previously
                    # captured executable must not bypass the jit
                    # path's op-level nan re-run machinery
                    if compiled.aot and not flags.flag("debug_nans"):
                        fn = compiled.aot.get(
                            (feed_sig, getattr(dev, "id", 0)), compiled.fn)
                    try:
                        fetches, new_state = fn(feed_dev, state_vals, rng)
                    except (TypeError, ValueError):
                        if fn is compiled.fn:
                            raise
                        # the AOT executable rejected the args (device/
                        # layout drift a jit dispatch would absorb):
                        # drop it and fall back to the jit path
                        compiled.aot.pop(
                            (feed_sig, getattr(dev, "id", 0)), None)
                        fetches, new_state = compiled.fn(
                            feed_dev, state_vals, rng)
        compiled.seen_sigs.add(feed_sig)

        ok_flag = None
        if compiled.guarded:
            # the in-graph sentinel's verdict rides as a trailing fetch;
            # user-visible fetches exclude it
            ok_flag = fetches[-1]
            fetches = fetches[:-1]
        if compiled.probe is not None:
            # per-layer health stats ride second-to-last (before ok);
            # note_step stashes the replay context every step and syncs
            # the stats to host only on the FLAGS_health_every cadence
            health_stats = fetches[-1]
            fetches = fetches[:-1]
            monitor.health.note_step(
                "executor", step_idx, compiled.probe, health_stats,
                program=program, scope=scope, rng=rng,
                feed_names=feed_names, feed_vals=feed_vals,
                platform=dev.platform)

        for n, v in zip(compiled.state_out, new_state):
            scope.set_var(n, v)

        if fault.active():
            fetches = list(fetches)
            fault.fire("executor/step_done", step_idx, scope=scope,
                       state_names=compiled.state_out,
                       fetch_names=compiled.fetch_names, fetches=fetches)

        if flags.flag("check_nan_inf"):
            ctx = lambda: "run_id=%s fp12=%s step=%d" % (  # noqa: E731
                monitor.run_id(),
                compile_cache.program_fingerprint(program)[:12], step_idx)
            try:
                _check_finite(zip(compiled.fetch_names, fetches),
                              context=ctx)
                _check_finite(zip(compiled.state_out, new_state),
                              context=ctx)
            except RuntimeError as e:
                raise _with_provenance(e, compiled.probe, step_idx) \
                    from None
        if t0 is not None:
            jax.block_until_ready(new_state if new_state else fetches)
            print("[benchmark] step %.3f ms"
                  % ((time.perf_counter() - t0) * 1e3))

        if return_numpy:
            with RecordEvent("executor/fetch_sync"):
                fetches = [np.asarray(f) for f in fetches]
        else:
            # async fast path: fetches stay device arrays; bound the
            # host's run-ahead on the dispatch window (sync only at
            # window edges, never per step)
            self._dispatch_queue.push_step(fetches, new_state)
        if mon_t0 is not None:
            monitor.record_step(
                "executor", time.perf_counter() - mon_t0,
                _batch_examples(block, feed_names, feed_vals),
                len(self._dispatch_queue), device=dev,
                warm=not cold, fingerprint=fp,
                extras=_sparse_step_extras(program, feed_names,
                                           feed_vals))
        # guardian hook LAST (after telemetry): a ladder decision raises
        # out of run() with this step's record already published.  One
        # module-global read when no guardian is installed.
        g = guardian.active()
        if g is not None:
            g.note_step("executor", step_idx, ok=ok_flag,
                        fetch_names=compiled.fetch_names, fetches=fetches,
                        feed=(feed_names, feed_vals), sync=return_numpy)
        elif ok_flag is not None:
            guardian.warn_unobserved_skip_guard(self)
        return fetches

    def cost_analysis(self, program=None, feed=None, fetch_list=None,
                      scope=None, compile_if_missing=True):
        """XLA compiled-module cost analysis for the step this
        (program, feed signature, fetch set) lowers to: exact flops /
        bytes-accessed per step from the compiler's own accounting (the
        `est_mfu` heuristic's ground truth; bench.py --exact_mfu).

        Served from the program-profile registry when the program was
        already compiled (the cold dispatch captured the analysis at
        zero extra cost) — *free* for warm programs.  Never-run programs
        fall back to one explicit lower+compile (and seed the registry
        so the next call is free); ``compile_if_missing=False`` returns
        None instead of paying that compile."""
        if program is None:
            program = default_main_program()
        feed = dict(feed or {})
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()
        fetch_names = [
            v.name if isinstance(v, Variable) else v for v in fetch_list
        ]
        feed_names = sorted(feed.keys())
        block = program.global_block()
        feed_vals = [_coerce_feed(block, n, feed[n]) for n in feed_names]
        feed_sig = tuple(
            (n, tuple(v.shape), str(v.dtype))
            for n, v in zip(feed_names, feed_vals)
        )
        fp = compile_cache.program_fingerprint(program)
        prof = program_profile.get(fp, feed_sig, kind="executor",
                                   fetch_names=tuple(fetch_names))
        if prof is not None and prof.cost:
            return dict(prof.cost)
        if not compile_if_missing:
            return None
        key = self._program_key(program, feed_sig, fetch_names, scope)
        compiled = self._cache.get(key)
        if compiled is None:
            state_names, writeback = self._analyze(
                program, feed_names, scope, fetch_names)
            compiled = self._lower(
                program, feed_names, state_names, writeback, fetch_names)
            self._cache[key] = compiled
        state_vals = [np.asarray(scope.var(n)) for n in compiled.state_in]
        rng = jax.random.key(
            0, impl="rbg" if flags.flag("fast_prng") else None)
        dev = self.place.jax_device()
        # lower on the executor's device so the executable is the one a
        # run() of this signature would build
        with jax.default_device(dev):
            cexec = compiled.fn.lower(feed_vals, state_vals, rng).compile()
        # seed the profile registry AND the entry's AOT-dispatch slot:
        # repeated cost_analysis calls are free, and a later run() of
        # the same signature dispatches through this executable instead
        # of paying a second backend compile (jax's AOT and jit call
        # paths share no compile cache)
        program_profile.store_compiled(fp, feed_sig, cexec,
                                       device=dev, kind="executor",
                                       fetch_names=tuple(fetch_names))
        compiled.aot[(feed_sig, getattr(dev, "id", 0))] = cexec
        ca = cexec.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return dict(ca)


def _check_finite(named_vals, context=None):
    """FLAGS_check_nan_inf parity (operator.cc:31,717): verify every
    floating output of the step; raise naming the FIRST bad variable and
    summarizing every other one found in the same scan (one host pass —
    the whole step already synced, so scanning to the end costs nothing
    and turns "loss is nan" into "loss, fc_0.w_0@GRAD, ... are nan").
    ``context`` (a callable, evaluated only on failure) adds the run_id
    / program fingerprint / step index so the raise correlates with the
    JSONL and trace records of the same step."""
    from .core import bfloat16

    bad_vars = []
    first_kind = None
    for name, v in named_vals:
        a = np.asarray(v)
        if bfloat16 is not None and a.dtype == bfloat16:
            a = a.astype(np.float32)  # np.isfinite lacks a bf16 loop
        if np.issubdtype(a.dtype, np.floating) and not np.isfinite(a).all():
            bad_vars.append(name)
            if first_kind is None:
                first_kind = "nan" if np.isnan(a).any() else "inf"
    if not bad_vars:
        return
    where = ""
    if context is not None:
        try:
            where = " [%s]" % (context() if callable(context)
                               else context)
        except Exception:  # noqa: BLE001 — the raise must land
            pass
    others = "" if len(bad_vars) == 1 else \
        " (+%d more non-finite: %s)" % (
            len(bad_vars) - 1, ", ".join(repr(n) for n in bad_vars[1:5]))
    raise RuntimeError(
        "check_nan_inf: variable %r contains %s after step%s%s "
        "(enable FLAGS_debug_nans to localize the producing op)"
        % (bad_vars[0], first_kind, others, where))


def _with_provenance(err, probe, step_idx):
    """Augment a check_nan_inf raise with op-level NaN provenance when
    the health probe is on: replay the stashed step off the hot path and
    name the first op whose output went non-finite.  The original error
    text is preserved; provenance failures never mask it."""
    if probe is None:
        return err
    from .monitor import health

    try:
        prov = health.nan_provenance(step_idx)
    except Exception:  # noqa: BLE001 — diagnostics must not mask the raise
        return err
    if not prov or not prov.get("found"):
        return err
    return RuntimeError(
        "%s; first non-finite op: %s -> %r (op #%d%s)"
        % (err, prov["op_type"], prov["out_var"], prov["op_index"],
           ", layer %s" % prov["layer"] if prov.get("layer") else ""))
