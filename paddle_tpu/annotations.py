"""API deprecation annotation (reference
python/paddle/fluid/annotations.py:1)."""

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(since, instead, extra_message=""):
    """Mark an API as deprecated since a version, pointing at the
    replacement.  Emits a DeprecationWarning once per call site (the
    reference prints to stderr on every call)."""

    def decorator(func):
        msg = "API %s is deprecated since %s. Please use %s instead." % (
            func.__name__, since, instead)
        if extra_message:
            msg += " " + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        if wrapper.__doc__:
            wrapper.__doc__ += "\n\n    " + msg
        else:
            wrapper.__doc__ = msg
        return wrapper

    return decorator
