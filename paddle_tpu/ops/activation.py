"""Activation ops — the reference's functor-based family
(``activation_op.cc``, ~25 activations + parameterized variants like
``leaky_relu``, ``elu``, ``brelu``, ``prelu_op.cc``, ``soft_relu``) —
TPU-native: one-liner jnp/lax bodies; XLA fuses them into producers, and
their vjp-derived gradients match the reference's analytic grad kernels.
"""

import jax
import jax.numpy as jnp

from ..registry import register_op, same_shape_infer, set_output, in_var


def _register_act(name, fn):
    register_op(
        name, ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
        compute=lambda ins, attrs, ctx, op_index: {
            "Out": fn(ins["X"][0], attrs)
        },
    )


_SIMPLE = {
    "relu": lambda x, a: jnp.maximum(x, 0),
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "exp": lambda x, a: jnp.exp(x),
    "log": lambda x, a: jnp.log(x),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "rsqrt": lambda x, a: jax.lax.rsqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "cos": lambda x, a: jnp.cos(x),
    "sin": lambda x, a: jnp.sin(x),
    "square": lambda x, a: x * x,
    "reciprocal": lambda x, a: 1.0 / x,
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: x / (1 + jnp.abs(x)),
    "relu6": lambda x, a: jnp.clip(x, 0, a.get("threshold", 6.0)),
    "leaky_relu": lambda x, a: jnp.where(x >= 0, x, a.get("alpha", 0.02) * x),
    "elu": lambda x, a: jnp.where(
        x >= 0, x, a.get("alpha", 1.0) * (jnp.exp(jnp.minimum(x, 0.0)) - 1)),
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "soft_relu": lambda x, a: jnp.log(
        1 + jnp.exp(jnp.clip(x, -a.get("threshold", 40.0),
                             a.get("threshold", 40.0)))),
    "pow": lambda x, a: jnp.power(x, a.get("factor", 1.0)),
    "stanh": lambda x, a: a.get("scale_b", 1.7159) * jnp.tanh(
        a.get("scale_a", 2.0 / 3.0) * x),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=False),
    "thresholded_relu": lambda x, a: jnp.where(
        x > a.get("threshold", 1.0), x, 0.0),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0),
    "softshrink": lambda x, a: jnp.where(
        x > a.get("lambda", 0.5), x - a.get("lambda", 0.5),
        jnp.where(x < -a.get("lambda", 0.5), x + a.get("lambda", 0.5), 0.0)),
}

for _name, _fn in _SIMPLE.items():
    _register_act(_name, _fn)


# -- prelu (per-channel learnable alpha; prelu_op.cc) -----------------------

def _prelu_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _prelu_compute(ins, attrs, ctx, op_index):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    elif mode == "element":
        a = alpha.reshape((1,) + x.shape[1:])
    else:
        a = alpha.reshape(())
    return {"Out": jnp.where(x >= 0, x, a * x)}


register_op("prelu", ["X", "Alpha"], ["Out"], infer=_prelu_infer,
            compute=_prelu_compute)


# -- softmax (softmax_op.cc: applied on the last dim) -----------------------

def _softmax_compute(ins, attrs, ctx, op_index):
    axis = attrs.get("axis", -1)
    return {"Out": jax.nn.softmax(ins["X"][0], axis=axis)}


register_op("softmax", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
            compute=_softmax_compute)


def _log_softmax_compute(ins, attrs, ctx, op_index):
    axis = attrs.get("axis", -1)
    return {"Out": jax.nn.log_softmax(ins["X"][0], axis=axis)}


register_op("log_softmax", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
            compute=_log_softmax_compute)


# -- maxout (maxout_op.cc) --------------------------------------------------

def _maxout_infer(op, block):
    x = in_var(op, block, "X")
    groups = op.attrs["groups"]
    n, c = x.shape[0], x.shape[1]
    set_output(op, block, "Out", (n, c // groups) + tuple(x.shape[2:]), x.dtype)


def _maxout_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    g = attrs["groups"]
    n, c = x.shape[0], x.shape[1]
    x = x.reshape((n, c // g, g) + x.shape[2:])
    return {"Out": jnp.max(x, axis=2)}


register_op("maxout", ["X"], ["Out"], infer=_maxout_infer,
            compute=_maxout_compute)
