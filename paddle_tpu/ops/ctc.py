"""CTC family: warpctc loss, ctc_align, edit_distance.

Parity: reference ``operators/warpctc_op.{cc,h}`` (dynloaded warp-ctc
library over LoD sequences), ``ctc_align_op.{cc,cu}`` (merge repeated
then drop blanks), ``edit_distance_op.{cc,cu}`` (Levenshtein over LoD
label pairs).

TPU-first redesign: no external warp-ctc — the CTC forward-backward is
the standard extended-label (blank-interleaved) alpha recursion in log
space as a ``lax.scan`` over time, ``vmap`` over the batch; gradients
fall out of auto-vjp of that recursion (warp-ctc's hand-written beta
pass is unnecessary under autodiff).  Sequences are padded ``[B, T, C]``
logits and ``[B, U]`` labels with explicit lengths.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var
from ..core import long_dtype

__all__ = []

_NEG_INF = -1e30


# -- warpctc ----------------------------------------------------------------

def _ctc_loss_single(logits, t_len, label, u_len, blank):
    """CTC NLL of one sequence: logits [T, C], label [U] int32."""
    t_max, _ = logits.shape
    u_max = label.shape[0]
    logp = jax.nn.log_softmax(logits, axis=-1)

    s_max = 2 * u_max + 1
    # extended label: blank, l1, blank, l2, ..., blank
    ext = jnp.full((s_max,), blank, dtype=jnp.int32)
    ext = ext.at[1::2].set(label.astype(jnp.int32))
    s_idx = jnp.arange(s_max)
    # skip-transition allowed at odd s (labels) when label != previous label
    prev_lbl = jnp.concatenate(
        [jnp.array([-1], jnp.int32), label[:-1].astype(jnp.int32)])
    can_skip = jnp.zeros((s_max,), bool).at[1::2].set(
        label.astype(jnp.int32) != prev_lbl)

    s_eff = 2 * u_len + 1                       # true extended length
    valid_s = s_idx < s_eff

    alpha0 = jnp.full((s_max,), _NEG_INF)
    alpha0 = alpha0.at[0].set(logp[0, blank])
    alpha0 = jnp.where((s_idx == 1) & (u_len > 0),
                       logp[0, ext[1]], alpha0)

    def step(alpha, inp):
        lp_t, valid_t = inp
        a1 = jnp.concatenate([jnp.array([_NEG_INF]), alpha[:-1]])
        a2 = jnp.concatenate([jnp.full((2,), _NEG_INF), alpha[:-2]])
        a2 = jnp.where(can_skip, a2, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        nxt = merged + lp_t[ext]
        nxt = jnp.where(valid_s, nxt, _NEG_INF)
        alpha = jnp.where(valid_t, nxt, alpha)
        return alpha, None

    t_valid = jnp.arange(1, t_max) < t_len
    alpha, _ = lax.scan(step, alpha0, (logp[1:], t_valid))
    final = jnp.logaddexp(alpha[jnp.maximum(s_eff - 1, 0)],
                          jnp.where(u_len > 0,
                                    alpha[jnp.maximum(s_eff - 2, 0)],
                                    _NEG_INF))
    return -final


def _warpctc_infer(op, block):
    x = in_var(op, block, "Logits")
    set_output(op, block, "Loss", (x.shape[0], 1), x.dtype)


def _warpctc_compute(ins, attrs, ctx, op_index):
    logits = ins["Logits"][0]                   # [B, T, C]
    logits_len = ins["LogitsLength"][0]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[:, :, 0]
    label_len = ins["LabelLength"][0]
    blank = int(attrs.get("blank", 0))
    loss = jax.vmap(_ctc_loss_single, in_axes=(0, 0, 0, 0, None))(
        logits.astype(jnp.float32), logits_len, label, label_len, blank)
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(logits_len, 1).astype(loss.dtype)
    return {"Loss": loss[:, None]}


register_op(
    "warpctc", ["Logits", "LogitsLength", "Label", "LabelLength"],
    ["Loss"],
    infer=_warpctc_infer, compute=_warpctc_compute,
    no_grad_inputs=("LogitsLength", "Label", "LabelLength"),
)


# -- ctc_align --------------------------------------------------------------

def _ctc_align_infer(op, block):
    x = in_var(op, block, "Input")
    set_output(op, block, "Output", x.shape, x.dtype, lod_level=1)
    set_output(op, block, "OutputLength", (x.shape[0],), "int32")


def _ctc_align_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]                          # [B, T] or [B, T, 1]
    squeeze = x.ndim == 3
    if squeeze:
        x = x[:, :, 0]
    length = ins["Length"][0]
    blank = int(attrs.get("blank", 0))
    merge = bool(attrs.get("merge_repeated", True))
    t_max = x.shape[1]
    valid = jnp.arange(t_max)[None, :] < length[:, None]
    prev = jnp.concatenate([jnp.full((x.shape[0], 1), -1, x.dtype),
                            x[:, :-1]], axis=1)
    keep = (x != blank) & valid
    if merge:
        keep = keep & (x != prev)
    # stable compaction: target position = exclusive cumsum of keep;
    # dropped tokens scatter to the out-of-bounds slot (mode="drop")
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    b_idx = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None], x.shape)
    out = jnp.zeros_like(x).at[
        b_idx, jnp.where(keep, pos, t_max)].set(x, mode="drop")
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    if squeeze:
        out = out[:, :, None]
    return {"Output": out, "OutputLength": new_len}


register_op(
    "ctc_align", ["Input", "Length"], ["Output", "OutputLength"],
    infer=_ctc_align_infer, compute=_ctc_align_compute, grad=None,
)


# -- edit_distance ----------------------------------------------------------

def _edit_distance_single(hyp, h_len, ref, r_len):
    """Levenshtein DP; returns distance at (h_len, r_len)."""
    u1 = hyp.shape[0]
    u2 = ref.shape[0]
    row0 = jnp.arange(u2 + 1, dtype=jnp.float32)

    def outer(row, inp):
        i, h_tok = inp

        def inner(left, inp2):
            j, up, upleft, r_tok = inp2
            cost = jnp.where(h_tok == r_tok, 0.0, 1.0)
            d = jnp.minimum(jnp.minimum(up + 1.0, left + 1.0),
                            upleft + cost)
            return d, d

        j_idx = jnp.arange(1, u2 + 1)
        _, rest = lax.scan(
            inner, i.astype(jnp.float32),
            (j_idx, row[1:], row[:-1], ref))
        new_row = jnp.concatenate([i.astype(jnp.float32)[None], rest])
        return new_row, new_row

    i_idx = jnp.arange(1, u1 + 1)
    _, rows = lax.scan(outer, row0, (i_idx, hyp))
    table = jnp.concatenate([row0[None], rows])   # [U1+1, U2+1]
    return table[h_len, r_len]


def _edit_distance_infer(op, block):
    h = in_var(op, block, "Hyps")
    set_output(op, block, "Out", (h.shape[0], 1), "float32")
    set_output(op, block, "SequenceNum", (1,), "int64")


def _edit_distance_compute(ins, attrs, ctx, op_index):
    hyps = ins["Hyps"][0]
    refs = ins["Refs"][0]
    if hyps.ndim == 3:
        hyps = hyps[:, :, 0]
    if refs.ndim == 3:
        refs = refs[:, :, 0]
    h_len = ins["HypsLength"][0]
    r_len = ins["RefsLength"][0]
    d = jax.vmap(_edit_distance_single)(hyps, h_len, refs, r_len)
    if attrs.get("normalized", True):
        d = d / jnp.maximum(r_len, 1).astype(d.dtype)
    n = jnp.asarray([hyps.shape[0]], dtype=long_dtype())
    return {"Out": d[:, None], "SequenceNum": n}


register_op(
    "edit_distance", ["Hyps", "HypsLength", "Refs", "RefsLength"],
    ["Out", "SequenceNum"],
    infer=_edit_distance_infer, compute=_edit_distance_compute, grad=None,
)
