"""Op library: importing this package registers every op with the registry.

The TPU-native analog of the reference's ``paddle/fluid/operators/``
(~314 registered op types): kernels are pure JAX functions that trace into
the program-level jit, with Pallas bodies for selected hot ops.
"""

from . import (  # noqa: F401
    activation,
    attention,
    control_flow,
    conv,
    creation,
    crf,
    ctc,
    detection,
    elementwise,
    fused_conv_bn,
    kv_cache,
    loss,
    manipulation,
    math,
    metric,
    norm,
    optimizer_ops,
    pipeline_region,
    pool,
    quantize,
    random,
    sampled_loss,
    reduction,
    rnn,
    selected_rows,
    sequence,
)
