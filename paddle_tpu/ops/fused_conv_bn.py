"""Fused conv+BN op family — targets of ``transpiler.fusion.fuse_conv_bn``.

The pass decomposes train-mode ``batch_norm`` ops and absorbs eligible
1x1 convolutions so each activation is touched the minimum number of
times (see ``ops/pallas/conv_bn.py`` for the kernel and the traffic
accounting):

* ``batch_stats``      — one-pass fp32 per-channel mean/var of a raw
                         activation (when no producer supplies stats).
* ``stats_finalize``   — mean/var from a producer kernel's fused
                         sum/sumsq outputs ([C] arithmetic, no
                         activation pass at all).
* ``bn_update_stats``  — the momentum moving-average update
                         (MeanOut/VarianceOut writeback contract of the
                         original batch_norm op).
* ``bn_apply``         — normalize(+act) from explicit batch stats, for
                         consumers that stay un-fused (3x3 conv inputs,
                         residual adds).
* ``bn_act_conv2d``    — normalize(+act) -> 1x1 conv -> output stats in
                         one Pallas kernel (XLA-composed fallback off
                         TPU / for unsupported shapes), with a
                         hand-fused single-kernel backward.

Gradient structure: BatchMean/BatchVar are explicit graph values, so
the BN three-term backward emerges from the chain
consumer -> stats_finalize -> producer sum/sumsq cotangents instead of
being hand-wired inside one op (reference
``batch_norm_op.cu.cc:1``'s fused kernel, re-derived for the
one-jaxpr world).

Parity: cuDNN fused conv+BN epilogues
(``paddle/fluid/operators/conv_cudnn_op.cu.cc:1``,
``batch_norm_op.cu.cc:1``).
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var

__all__ = []


def _to_3d(x):
    # NCHW -> [B, C, HW]: a free reshape — the kernels are NCHW-native
    # (channels are the contraction dim), so no transpose materializes
    b, c, h, w = x.shape
    return x.reshape(b, c, h * w)


# -- batch_stats ------------------------------------------------------------

def _c_axis(attrs, ndim):
    # channel axis under the op's data_layout (NHWC = trunk converted by
    # transpiler.layout.convert_to_nhwc)
    return ndim - 1 if attrs.get("data_layout", "NCHW") == "NHWC" else 1


def _batch_stats_infer(op, block):
    x = in_var(op, block, "X")
    c = x.shape[_c_axis(op.attrs, len(x.shape))]
    set_output(op, block, "BatchMean", (c,), "float32")
    set_output(op, block, "BatchVar", (c,), "float32")


def _batch_stats_compute(ins, attrs, ctx, op_index):
    from ..flags import flag
    from .norm import shifted_one_pass_stats

    x = ins["X"][0]
    ca = _c_axis(attrs, x.ndim)
    red = tuple(i for i in range(x.ndim) if i != ca)
    bshape = [1] * x.ndim
    bshape[ca] = x.shape[ca]
    xf = x.astype(jnp.float32)
    if flag("bn_two_pass"):
        # exact two-pass form (same escape hatch as ops/norm.py)
        mean = jnp.mean(xf, axis=red)
        var = jnp.mean(jnp.square(xf - mean.reshape(bshape)), axis=red)
        return {"BatchMean": mean, "BatchVar": var}
    # Shift is the BN's running mean, wired by the fusion pass
    shift = ins.get("Shift", [None])[0]
    mean, var = shifted_one_pass_stats(xf, shift, red, bshape)
    return {"BatchMean": mean, "BatchVar": var}


register_op("batch_stats", ["X", "Shift"], ["BatchMean", "BatchVar"],
            infer=_batch_stats_infer, compute=_batch_stats_compute,
            no_grad_inputs=("Shift",))


# -- stats_finalize ---------------------------------------------------------

def _stats_finalize_infer(op, block):
    s = in_var(op, block, "Sum")
    set_output(op, block, "BatchMean", s.shape, "float32")
    set_output(op, block, "BatchVar", s.shape, "float32")


def _stats_finalize_compute(ins, attrs, ctx, op_index):
    # sum/sumsq come from a producer kernel's fp32 epilogue, accumulated
    # SHIFTED by the consumer bn's running mean (sum(z-rm), sum((z-rm)^2)
    # — the same cancellation guard as ops/norm.py's shifted one-pass
    # variance).  When FLAGS_bn_two_pass demands exact numerics, the
    # fusion pass leaves the original batch_norm in place instead of
    # emitting this op, so the flag's contract holds on the fused path.
    s = ins["Sum"][0].astype(jnp.float32)
    ss = ins["SumSq"][0].astype(jnp.float32)
    shift = ins.get("Shift", [None])[0]
    ref = ins.get("CountFrom", [None])[0]
    if ref is not None:
        # per-channel element count from the referenced activation's
        # trace-time shape (the batch dim is -1 at transpile time)
        ca = _c_axis(attrs, ref.ndim)
        cnt = 1.0
        for i, d in enumerate(ref.shape):
            if i != ca:
                cnt *= d
    else:
        cnt = float(attrs["count"])
    m1 = s / cnt
    var = jnp.maximum(ss / cnt - jnp.square(m1), 0.0)
    mean = m1 + shift.astype(jnp.float32) if shift is not None else m1
    return {"BatchMean": mean, "BatchVar": var}


register_op("stats_finalize", ["Sum", "SumSq", "CountFrom", "Shift"],
            ["BatchMean", "BatchVar"],
            infer=_stats_finalize_infer, compute=_stats_finalize_compute,
            no_grad_inputs=("CountFrom", "Shift"))


# -- bn_update_stats --------------------------------------------------------

def _update_stats_infer(op, block):
    m = in_var(op, block, "Mean")
    set_output(op, block, "MeanOut", m.shape, m.dtype)
    set_output(op, block, "VarianceOut", m.shape, m.dtype)


def _update_stats_compute(ins, attrs, ctx, op_index):
    mean, var = ins["Mean"][0], ins["Variance"][0]
    bm, bv = ins["BatchMean"][0], ins["BatchVar"][0]
    mom = attrs.get("momentum", 0.9)
    return {"MeanOut": mom * mean + (1.0 - mom) * bm.astype(mean.dtype),
            "VarianceOut": mom * var + (1.0 - mom) * bv.astype(var.dtype)}


register_op("bn_update_stats", ["Mean", "Variance", "BatchMean", "BatchVar"],
            ["MeanOut", "VarianceOut"],
            infer=_update_stats_infer, compute=_update_stats_compute,
            grad=None,
            no_grad_inputs=("Mean", "Variance", "BatchMean", "BatchVar"))


# -- bn_apply ---------------------------------------------------------------

def _bn_apply_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Y", x.shape, x.dtype)


def _bn_apply_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    mean = ins["BatchMean"][0].astype(jnp.float32)
    var = ins["BatchVar"][0].astype(jnp.float32)
    gamma = ins["Scale"][0].astype(jnp.float32)
    beta = ins["Bias"][0].astype(jnp.float32)
    eps = attrs.get("epsilon", 1e-5)
    ca = _c_axis(attrs, x.ndim)
    bshape = [1] * x.ndim
    bshape[ca] = x.shape[ca]
    rstd = lax.rsqrt(var + eps)
    y = (x.astype(jnp.float32) - mean.reshape(bshape)) \
        * (rstd * gamma).reshape(bshape) + beta.reshape(bshape)
    if attrs.get("act", "") == "relu":
        y = jnp.maximum(y, 0.0)
    return {"Y": y.astype(x.dtype)}


register_op("bn_apply", ["X", "BatchMean", "BatchVar", "Scale", "Bias"],
            ["Y"], infer=_bn_apply_infer, compute=_bn_apply_compute)


# -- bn_act_conv2d ----------------------------------------------------------

def _bac_nhwc(attrs):
    return attrs.get("data_format", "NCHW") == "NHWC"


def _bac_infer(op, block):
    x = in_var(op, block, "X")
    w = in_var(op, block, "Filter")
    o = w.shape[0]
    if _bac_nhwc(op.attrs):
        out_shape = (x.shape[0], x.shape[1], x.shape[2], o)
    else:
        out_shape = (x.shape[0], o, x.shape[2], x.shape[3])
    set_output(op, block, "Out", out_shape, x.dtype)
    set_output(op, block, "SumOut", (o,), "float32")
    set_output(op, block, "SumSqOut", (o,), "float32")


def _bac_args(ins, attrs):
    x = ins["X"][0]
    filt = ins["Filter"][0]
    c = x.shape[3] if _bac_nhwc(attrs) else x.shape[1]
    o = filt.shape[0]
    apply_bn = bool(attrs.get("apply_bn", True))
    if apply_bn:
        mean = ins["BatchMean"][0].astype(jnp.float32)
        var = ins["BatchVar"][0].astype(jnp.float32)
        gamma = ins["Scale"][0].astype(jnp.float32)
        beta = ins["Bias"][0].astype(jnp.float32)
    else:
        mean = jnp.zeros((c,), jnp.float32)
        var = jnp.ones((c,), jnp.float32)
        gamma = jnp.ones((c,), jnp.float32)
        beta = jnp.zeros((c,), jnp.float32)
    w2 = filt.reshape(o, c).astype(x.dtype)
    shift = ins.get("StatsShift", [None])[0]
    shift = jnp.zeros((o,), jnp.float32) if shift is None \
        else jax.lax.stop_gradient(shift.astype(jnp.float32))
    return x, w2, mean, var, gamma, beta, shift, apply_bn


def _bac_compute(ins, attrs, ctx, op_index):
    from .pallas import conv_bn, interpret_mode
    x, w2, mean, var, gamma, beta, shift, apply_bn = _bac_args(ins, attrs)
    act = attrs.get("act", "")
    with_stats = bool(attrs.get("with_stats", True))
    eps = attrs.get("epsilon", 1e-5)
    if _bac_nhwc(attrs):
        # NHWC trunk: [B,H,W,C] -> [M,C] is free; one dense matmul
        b, h, wd, c = x.shape
        o = w2.shape[0]
        m = b * h * wd
        if conv_bn.supported(1, c, o, m, x.dtype):
            z2, s, ss = conv_bn.bn_act_matmul_nhwc(
                x.reshape(m, c), w2.T, mean, var, gamma, beta, shift,
                eps, act, apply_bn, with_stats, interpret_mode(ctx))
            return {"Out": z2.reshape(b, h, wd, o), "SumOut": s,
                    "SumSqOut": ss}
        z, s, ss = _bac_xla_fwd_nhwc(x, w2, mean, var, gamma, beta,
                                     shift, eps, act, apply_bn,
                                     with_stats)
        return {"Out": z, "SumOut": s, "SumSqOut": ss}
    b, c, h, wd = x.shape
    o = w2.shape[0]
    if conv_bn.supported(b, c, o, h * wd, x.dtype):
        z3, s, ss = conv_bn.bn_act_matmul(
            _to_3d(x), w2, mean, var, gamma, beta, shift, eps, act,
            apply_bn, with_stats, interpret_mode(ctx))
        return {"Out": z3.reshape(b, o, h, wd), "SumOut": s,
                "SumSqOut": ss}
    # XLA-composed fallback (same math, still one-pass stats)
    z, s, ss = _bac_xla_fwd(x, w2, mean, var, gamma, beta, shift, eps,
                            act, apply_bn, with_stats)
    return {"Out": z, "SumOut": s, "SumSqOut": ss}


def _bac_xla_fwd_nhwc(x, w2, mean, var, gamma, beta, shift, eps, act,
                      apply_bn, with_stats):
    b, h, wd, c = x.shape
    o = w2.shape[0]
    if apply_bn:
        rstd = lax.rsqrt(var + eps)
        xn = (x.astype(jnp.float32) - mean) * (rstd * gamma) + beta
        if act == "relu":
            xn = jnp.maximum(xn, 0.0)
        xn = xn.astype(x.dtype)
    else:
        xn = jnp.maximum(x, jnp.zeros_like(x)) if act == "relu" else x
    z2 = jax.lax.dot_general(
        xn.reshape(b * h * wd, c), w2.T, (((1,), (0,)), ((), ())),
        preferred_element_type=x.dtype)            # [M, O]
    z = z2.reshape(b, h, wd, o)
    if with_stats:
        zf = z2.astype(jnp.float32) - shift
        s = jnp.sum(zf, axis=0)
        ss = jnp.sum(zf * zf, axis=0)
    else:
        s = jnp.zeros((o,), jnp.float32)
        ss = jnp.zeros((o,), jnp.float32)
    return z, s, ss


def _bac_xla_fwd(x, w2, mean, var, gamma, beta, shift, eps, act, apply_bn,
                 with_stats):
    b, c, h, wd = x.shape
    o = w2.shape[0]
    if apply_bn:
        bshape = (1, c, 1, 1)
        rstd = lax.rsqrt(var + eps)
        xn = (x.astype(jnp.float32) - mean.reshape(bshape)) \
            * (rstd * gamma).reshape(bshape) + beta.reshape(bshape)
        if act == "relu":
            xn = jnp.maximum(xn, 0.0)
        xn = xn.astype(x.dtype)
    else:
        xn = jnp.maximum(x, jnp.zeros_like(x)) if act == "relu" else x
    # contraction over the channel dim, NCHW-native (no transposes)
    z3 = jax.lax.dot_general(
        w2, _to_3d(xn), (((1,), (1,)), ((), ())),
        preferred_element_type=x.dtype)            # [O, B, HW]
    z = jnp.swapaxes(z3, 0, 1).reshape(b, o, h, wd)
    if with_stats:
        zf = z3.astype(jnp.float32) - shift.reshape(o, 1, 1)
        s = jnp.sum(zf, axis=(1, 2))
        ss = jnp.sum(zf * zf, axis=(1, 2))
    else:
        s = jnp.zeros((o,), jnp.float32)
        ss = jnp.zeros((o,), jnp.float32)
    return z, s, ss


def _bac_grad_maker(op, no_grad_set):
    """Hand-fused backward consuming the saved forward output (the raw z
    the stats cotangents fold over) — avoids re-running the forward
    kernel the generic auto-vjp recompute would."""
    from ..framework import grad_var_name

    outs = {}
    for slot in ("X", "Filter", "BatchMean", "BatchVar", "Scale", "Bias"):
        names = op.inputs.get(slot, [])
        outs["GRAD::" + slot] = ["" if n in no_grad_set else grad_var_name(n)
                                 for n in names]
    if not any(n for ns in outs.values() for n in ns):
        return []
    g_inputs = {slot: list(op.inputs.get(slot, []))
                for slot in ("X", "Filter", "BatchMean", "BatchVar",
                             "Scale", "Bias", "StatsShift")}
    g_inputs["Out::Out"] = list(op.outputs["Out"])
    g_inputs["GRAD::Out"] = [grad_var_name(n) for n in op.outputs["Out"]]
    if op.attrs.get("with_stats", True):
        # stat cotangents exist only when the stats have a (diff)
        # consumer; a with_stats=False op's SumOut is dead zeros and
        # demanding its grad var would be a wiring error
        for slot in ("SumOut", "SumSqOut"):
            g_inputs["GRAD::" + slot] = [grad_var_name(n)
                                         for n in op.outputs[slot]]
    return [dict(type="bn_act_conv2d_grad", inputs=g_inputs, outputs=outs,
                 attrs=dict(op.attrs))]


def _bac_grad_infer(gop, block):
    for slot in ("X", "Filter", "BatchMean", "BatchVar", "Scale", "Bias"):
        names = gop.inputs.get(slot, [])
        gnames = gop.outputs.get("GRAD::" + slot, [])
        for n, g in zip(names, gnames):
            if not g:
                continue
            v = block._find_var_recursive(n)
            if v is not None:
                block.create_var(name=g, shape=v.shape, dtype=v.dtype,
                                 persistable=False)


def _bac_grad_compute(ins, attrs, ctx, op_index):
    from .pallas import conv_bn, interpret_mode
    x, w2, mean, var, gamma, beta, shift, apply_bn = _bac_args(ins, attrs)
    o = w2.shape[0]
    act = attrs.get("act", "")
    with_stats = bool(attrs.get("with_stats", True))
    eps = attrs.get("epsilon", 1e-5)
    filt = ins["Filter"][0]
    z4 = ins["Out::Out"][0]
    dz4 = ins["GRAD::Out"][0]
    dsum = ins.get("GRAD::SumOut", [None])[0]
    dsumsq = ins.get("GRAD::SumSqOut", [None])[0]
    have_stats_ct = dsum is not None or dsumsq is not None
    if dsum is None:
        dsum = jnp.zeros((o,), jnp.float32)
    if dsumsq is None:
        dsumsq = jnp.zeros((o,), jnp.float32)
    if dz4 is None:
        dz4 = jnp.zeros_like(z4)

    if _bac_nhwc(attrs):
        b, h, wd, c = x.shape
        m = b * h * wd
        if conv_bn.supported(1, c, o, m, x.dtype):
            rstd = lax.rsqrt(var + eps)
            dx2, dwT, dgamma, dbeta = conv_bn._bwd_call_nhwc(
                x.reshape(m, c), w2.T, z4.reshape(m, o),
                dz4.reshape(m, o).astype(x.dtype), dsum, dsumsq, mean,
                rstd, gamma, beta, shift, act, apply_bn,
                with_stats and have_stats_ct, interpret_mode(ctx))
            dx = dx2.reshape(b, h, wd, c)
            dw = dwT.T
            dmean, dvar = conv_bn.stats_grads(apply_bn, gamma, rstd,
                                              dgamma, dbeta)
        else:
            def fwd(x, w2, mean, var, gamma, beta):
                return _bac_xla_fwd_nhwc(x, w2, mean, var, gamma, beta,
                                         shift, eps, act, apply_bn,
                                         with_stats)

            _, vjp = jax.vjp(fwd, x, w2, mean, var, gamma, beta)
            dx, dw, dmean, dvar, dgamma, dbeta = vjp((dz4, dsum, dsumsq))
    else:
        b, c, h, wd = x.shape
        if conv_bn.supported(b, c, o, h * wd, x.dtype):
            rstd = lax.rsqrt(var + eps)
            dx3, dw, dgamma, dbeta = conv_bn._bwd_call(
                _to_3d(x), w2, _to_3d(z4), _to_3d(dz4).astype(x.dtype),
                dsum, dsumsq, mean, rstd, gamma, beta, shift, act,
                apply_bn, with_stats and have_stats_ct,
                interpret_mode(ctx))
            dx = dx3.reshape(b, c, h, wd)
            dmean, dvar = conv_bn.stats_grads(apply_bn, gamma, rstd,
                                              dgamma, dbeta)
        else:
            def fwd(x, w2, mean, var, gamma, beta):
                return _bac_xla_fwd(x, w2, mean, var, gamma, beta, shift,
                                    eps, act, apply_bn, with_stats)

            _, vjp = jax.vjp(fwd, x, w2, mean, var, gamma, beta)
            dx, dw, dmean, dvar, dgamma, dbeta = vjp(
                (dz4, dsum, dsumsq))
    dfilt = dw.reshape(o, c, 1, 1).astype(filt.dtype)
    out = {"GRAD::X": dx, "GRAD::Filter": dfilt}
    if apply_bn:
        sdt = ins["Scale"][0].dtype
        out["GRAD::BatchMean"] = dmean.astype(sdt)
        out["GRAD::BatchVar"] = dvar.astype(sdt)
        out["GRAD::Scale"] = dgamma.astype(sdt)
        out["GRAD::Bias"] = dbeta.astype(sdt)
    return out


register_op(
    "bn_act_conv2d",
    ["X", "Filter", "BatchMean", "BatchVar", "Scale", "Bias",
     "StatsShift"],
    ["Out", "SumOut", "SumSqOut"],
    infer=_bac_infer, compute=_bac_compute, grad=_bac_grad_maker,
    no_grad_inputs=("StatsShift",),
)

register_op(
    "bn_act_conv2d_grad",
    ["X", "Filter", "BatchMean", "BatchVar", "Scale", "Bias",
     "StatsShift", "Out::Out", "GRAD::Out", "GRAD::SumOut",
     "GRAD::SumSqOut"],
    ["GRAD::X", "GRAD::Filter", "GRAD::BatchMean", "GRAD::BatchVar",
     "GRAD::Scale", "GRAD::Bias"],
    infer=_bac_grad_infer, compute=_bac_grad_compute, grad=None,
)
