"""Linear-chain CRF ops over padded sequence batches.

Parity: reference ``operators/linear_chain_crf_op.{cc,h}`` (forward
algorithm over LoD sequences with a ``[D+2, D]`` transition parameter:
row 0 = start weights, row 1 = end weights, rows 2.. = tag->tag
transitions), ``crf_decoding_op.{cc,h}`` (Viterbi; with a Label input the
output becomes a per-position correctness mask,
``crf_decoding_op.h:61``), and ``chunk_eval_op.{cc,h}`` (chunk
precision/recall/F1 under IOB/IOE/IOBES/plain schemes).

TPU-first redesign:

* sequences are ``[B, T, D]`` padded batches + ``[B]`` lengths (the LoD
  replacement); the recursions are ``lax.scan`` over time, ``vmap`` over
  the batch — no per-sequence host loops;
* log-space forward recursion (logsumexp) instead of the reference's
  L1-renormalized exp-space alphas (linear_chain_crf_op.h:158) — same
  overflow safety, simpler and fusion-friendly on XLA;
* ``LogLikelihood`` output is the **negative** log-likelihood per
  sequence (cost, shape [B, 1]): its gradient is (marginal - onehot),
  exactly the reference backward (linear_chain_crf_op.h:295-305), and
  ``mean(cost)`` is directly minimizable as in the reference's
  label_semantic_roles book test;
* gradients come from auto-vjp of the forward recursion — no
  hand-written beta pass.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var
from ..core import long_dtype

__all__ = []


# -- linear_chain_crf -------------------------------------------------------

def _crf_nll_single(emission, length, transition, label):
    """NLL of one sequence.  emission [T, D], label [T] int, length scalar."""
    t_max, d = emission.shape
    start_w = transition[0]
    end_w = transition[1]
    trans = transition[2:]                      # [D, D] from -> to

    steps = jnp.arange(t_max)
    valid = steps < length                      # [T]
    last_idx = jnp.maximum(length - 1, 0)

    # ---- log partition via forward recursion -------------------------
    alpha0 = start_w + emission[0]

    def fwd(alpha, inp):
        e_t, valid_t = inp
        nxt = jax.nn.logsumexp(alpha[:, None] + trans, axis=0) + e_t
        alpha = jnp.where(valid_t, nxt, alpha)
        return alpha, None

    alpha, _ = lax.scan(fwd, alpha0, (emission[1:], valid[1:]))
    log_z = jax.nn.logsumexp(alpha + end_w)

    # ---- score of the gold path --------------------------------------
    lbl = label.astype(jnp.int32)
    emit_score = jnp.sum(
        jnp.where(valid, jnp.take_along_axis(
            emission, lbl[:, None], axis=1)[:, 0], 0.0))
    pair_valid = (steps[1:] < length)
    trans_score = jnp.sum(
        jnp.where(pair_valid, trans[lbl[:-1], lbl[1:]], 0.0))
    score = (start_w[lbl[0]] + emit_score + trans_score +
             end_w[lbl[last_idx]])
    return log_z - score


def _crf_infer(op, block):
    e = in_var(op, block, "Emission")
    set_output(op, block, "LogLikelihood", (e.shape[0], 1), e.dtype)


def _crf_compute(ins, attrs, ctx, op_index):
    emission = ins["Emission"][0]               # [B, T, D]
    length = ins["Length"][0]                   # [B]
    transition = ins["Transition"][0]           # [D+2, D]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[:, :, 0]
    nll = jax.vmap(_crf_nll_single, in_axes=(0, 0, None, 0))(
        emission, length, transition, label)
    return {"LogLikelihood": nll[:, None]}


register_op(
    "linear_chain_crf", ["Emission", "Length", "Transition", "Label"],
    ["LogLikelihood"],
    infer=_crf_infer, compute=_crf_compute,
    no_grad_inputs=("Length", "Label"),
)


# -- crf_decoding -----------------------------------------------------------

def _viterbi_path(emission, length, transition):
    """Correct backtracking: returns [T] int32 path (zeros past length)."""
    t_max, d = emission.shape
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    steps = jnp.arange(t_max)
    valid = steps < length

    v0 = start_w + emission[0]

    def fwd(v, inp):
        e_t, valid_t = inp
        scores = v[:, None] + trans
        best_prev = jnp.argmax(scores, axis=0).astype(jnp.int32)
        nxt = jnp.max(scores, axis=0) + e_t
        v_new = jnp.where(valid_t, nxt, v)
        bp = jnp.where(valid_t, best_prev, jnp.arange(d, dtype=jnp.int32))
        return v_new, bp

    v_last, bps = lax.scan(fwd, v0, (emission[1:], valid[1:]))
    last_tag = jnp.argmax(v_last + end_w).astype(jnp.int32)

    # walk backpointers from the last step down; bps[t-1] maps tag at t
    # to its best predecessor at t-1
    def back(tag, bp):
        prev = bp[tag]
        return prev, prev

    _, preds = lax.scan(back, last_tag, bps, reverse=True)  # [T-1]
    path = jnp.concatenate([preds, last_tag[None]])
    return jnp.where(valid, path, 0), valid


def _crf_decoding_infer(op, block):
    e = in_var(op, block, "Emission")
    set_output(op, block, "ViterbiPath", (e.shape[0], e.shape[1], 1),
               "int64", lod_level=1)


def _crf_decoding_compute(ins, attrs, ctx, op_index):
    emission = ins["Emission"][0]
    length = ins["Length"][0]
    transition = ins["Transition"][0]
    path, valid = jax.vmap(_viterbi_path, in_axes=(0, 0, None))(
        emission, length, transition)
    path = path.astype(long_dtype())
    labels = ins.get("Label", [None])
    label = labels[0] if labels else None
    if label is not None:
        if label.ndim == 3:
            label = label[:, :, 0]
        # reference crf_decoding_op.h:61 — with Label, emit the per-
        # position correctness mask instead of the path
        path = jnp.where(valid, (path == label.astype(long_dtype()))
                         .astype(long_dtype()), 0)
    return {"ViterbiPath": path[:, :, None]}


register_op(
    "crf_decoding", ["Emission", "Length", "Transition", "Label"],
    ["ViterbiPath"],
    infer=_crf_decoding_infer, compute=_crf_decoding_compute, grad=None,
)


# -- chunk_eval -------------------------------------------------------------

# scheme -> (num_tag_types, tag_begin, tag_inside, tag_end, tag_single),
# exactly chunk_eval_op.h:118-141
_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_end_pair(prev_tag, prev_type, tag, typ, other, tb, ti, te, ts):
    """Vectorized ChunkEnd (chunk_eval_op.h:84): does the open chunk end
    at ``prev``'s position given the next tag?  Where-cascade in the
    reference's return order (first matching clause wins)."""
    r = jnp.zeros_like(tag, dtype=bool)
    r = jnp.where(prev_tag == ts, True, r)
    r = jnp.where(prev_tag == te, True, r)
    r = jnp.where(prev_tag == ti, (tag == tb) | (tag == ts), r)
    r = jnp.where(prev_tag == tb, (tag == tb) | (tag == ts), r)
    r = jnp.where(typ != prev_type, True, r)
    r = jnp.where(typ == other, True, r)
    r = jnp.where(prev_type == other, False, r)
    return r


def _chunk_begin_pair(prev_tag, prev_type, tag, typ, other, tb, ti, te, ts):
    """Vectorized ChunkBegin (chunk_eval_op.h:96)."""
    r = jnp.zeros_like(tag, dtype=bool)
    r = jnp.where(tag == ts, True, r)
    r = jnp.where(tag == te, (prev_tag == te) | (prev_tag == ts), r)
    r = jnp.where(tag == ti, (prev_tag == te) | (prev_tag == ts), r)
    r = jnp.where(tag == tb, True, r)
    r = jnp.where(typ != prev_type, True, r)
    r = jnp.where(typ == other, False, r)
    r = jnp.where(prev_type == other, typ != other, r)
    return r


def _chunk_flags(tags, types, scheme, other):
    """Per-position (begin, end_at) flags reproducing the reference's
    GetSegments state machine (chunk_eval_op.h:41): a chunk starts where
    ChunkBegin(prev, cur) fires and ends at the last position before
    ChunkEnd(cur, next) fires (sequence end always closes).  Whenever
    ChunkBegin fires while a chunk is open, ChunkEnd fires too, so
    begins count chunks exactly."""
    _, tb, ti, te, ts = _SCHEMES[scheme]
    prev_tags = jnp.concatenate([jnp.array([-1], tags.dtype), tags[:-1]])
    prev_types = jnp.concatenate([jnp.array([other], types.dtype),
                                  types[:-1]])
    next_tags = jnp.concatenate([tags[1:], jnp.array([-1], tags.dtype)])
    next_types = jnp.concatenate([types[1:],
                                  jnp.array([other], types.dtype)])
    begin = _chunk_begin_pair(prev_tags, prev_types, tags, types, other,
                              tb, ti, te, ts)
    # end_at[i]: chunk (if open) closes at i — ChunkEnd evaluated on the
    # (i, i+1) pair; the virtual type=other tail closes any open chunk
    end_at = _chunk_end_pair(tags, types, next_tags, next_types, other,
                             tb, ti, te, ts)
    return begin, end_at


def _chunk_eval_compute(ins, attrs, ctx, op_index):
    inference = ins["Inference"][0]
    label = ins["Label"][0]
    length = ins["Length"][0]
    if inference.ndim == 3:
        inference = inference[:, :, 0]
    if label.ndim == 3:
        label = label[:, :, 0]
    num_chunk_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    num_tag_types = _SCHEMES[scheme][0]
    excluded = list(attrs.get("excluded_chunk_types", []) or [])
    other = num_chunk_types  # type id used for the Other/O tag

    t_max = inference.shape[1]
    valid = jnp.arange(t_max)[None, :] < length[:, None]

    def one_seq(inf, lab, val):
        def decomp(x):
            tag = x % num_tag_types
            typ = jnp.where(x >= num_tag_types * num_chunk_types,
                            other, x // num_tag_types)
            typ = jnp.where(val, typ, other)
            return tag.astype(jnp.int32), typ.astype(jnp.int32)

        itag, ityp = decomp(inf.astype(jnp.int32))
        ltag, ltyp = decomp(lab.astype(jnp.int32))
        ib, ie_at = _chunk_flags(itag, ityp, scheme, other)
        lb, le_at = _chunk_flags(ltag, ltyp, scheme, other)
        # excluded chunk types are dropped from all three counts
        # (chunk_eval_op.h excluded_chunk_types)
        for ex in excluded:
            ib = ib & (ityp != ex)
            lb = lb & (ltyp != ex)

        n_inf = jnp.sum((ib & val).astype(long_dtype()))
        n_lab = jnp.sum((lb & val).astype(long_dtype()))

        # a predicted chunk (start j) is correct iff the label also
        # starts a chunk at j with the same type and both chunks close
        # at the same position; first-end-at-or-after via reverse scan
        idx = jnp.arange(t_max)

        def first_end(end_at):
            def scan_fn(nxt, inp):
                i, e = inp
                cur = jnp.where(e, i, nxt)
                return cur, cur
            _, ne = lax.scan(scan_fn, t_max, (idx, end_at), reverse=True)
            return ne

        ie_pos = first_end(ie_at)
        le_pos = first_end(le_at)
        correct_start = ib & lb & val & (ityp == ltyp) & (ie_pos == le_pos)
        n_correct = jnp.sum(correct_start.astype(long_dtype()))
        return n_inf, n_lab, n_correct

    n_inf, n_lab, n_correct = jax.vmap(one_seq)(inference, label, valid)
    num_infer = jnp.sum(n_inf).reshape(1)
    num_label = jnp.sum(n_lab).reshape(1)
    num_correct = jnp.sum(n_correct).reshape(1)
    f = num_infer.astype(jnp.float32)
    l = num_label.astype(jnp.float32)
    c = num_correct.astype(jnp.float32)
    precision = jnp.where(f > 0, c / jnp.maximum(f, 1), 0.0)
    recall = jnp.where(l > 0, c / jnp.maximum(l, 1), 0.0)
    f1 = jnp.where(precision + recall > 0,
                   2 * precision * recall /
                   jnp.maximum(precision + recall, 1e-12), 0.0)
    return {"Precision": precision, "Recall": recall, "F1-Score": f1,
            "NumInferChunks": num_infer, "NumLabelChunks": num_label,
            "NumCorrectChunks": num_correct}


def _chunk_eval_infer(op, block):
    set_output(op, block, "Precision", (1,), "float32")
    set_output(op, block, "Recall", (1,), "float32")
    set_output(op, block, "F1-Score", (1,), "float32")
    set_output(op, block, "NumInferChunks", (1,), "int64")
    set_output(op, block, "NumLabelChunks", (1,), "int64")
    set_output(op, block, "NumCorrectChunks", (1,), "int64")


register_op(
    "chunk_eval", ["Inference", "Label", "Length"],
    ["Precision", "Recall", "F1-Score", "NumInferChunks", "NumLabelChunks",
     "NumCorrectChunks"],
    infer=_chunk_eval_infer, compute=_chunk_eval_compute, grad=None,
)
