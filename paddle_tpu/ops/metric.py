"""In-graph metric ops: accuracy, auc, mean_iou, precision/recall support.

Parity: reference ``accuracy_op.cc``, ``auc_op.cc``, ``mean_iou_op.cc`` —
metrics run inside the jitted step (no host round-trip), accumulation
states are persistable scope vars like the reference's evaluator states.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var
from ..core import long_dtype


def _accuracy_infer(op, block):
    set_output(op, block, "Accuracy", (1,), np.float32)
    set_output(op, block, "Correct", (1,), np.int32)
    set_output(op, block, "Total", (1,), np.int32)


def _accuracy_compute(ins, attrs, ctx, op_index):
    indices = ins["Indices"][0]  # [N, k] from top_k
    label = ins["Label"][0]      # [N, 1]
    hit = jnp.any(indices == label.astype(indices.dtype), axis=-1)
    correct = jnp.sum(hit.astype(jnp.int32)).reshape(1)
    total = jnp.asarray([indices.shape[0]], dtype=jnp.int32)
    acc = correct.astype(jnp.float32) / indices.shape[0]
    return {"Accuracy": acc, "Correct": correct, "Total": total}


register_op(
    "accuracy", ["Out", "Indices", "Label"],
    ["Accuracy", "Correct", "Total"],
    infer=_accuracy_infer, compute=_accuracy_compute, grad=None,
)


def _auc_infer(op, block):
    set_output(op, block, "AUC", (1,), np.float64)
    bins = op.attrs.get("num_thresholds", 4095) + 1
    set_output(op, block, "StatPosOut", (bins,), np.int64)
    set_output(op, block, "StatNegOut", (bins,), np.int64)


def _auc_compute(ins, attrs, ctx, op_index):
    """Streaming AUC via threshold-bucketed TP/FP histograms
    (auc_op.cc redesigned: vectorized bincount instead of loops)."""
    preds = ins["Predict"][0]  # [N, 2] binary probabilities
    label = ins["Label"][0].reshape(-1)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    n_bins = stat_pos.shape[0]
    p = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
    idx = jnp.clip((p * (n_bins - 1)).astype(jnp.int32), 0, n_bins - 1)
    pos = (label > 0).astype(long_dtype())
    stat_pos = stat_pos + jnp.zeros_like(stat_pos).at[idx].add(pos)
    stat_neg = stat_neg + jnp.zeros_like(stat_neg).at[idx].add(1 - pos)
    # integrate ROC from histograms (descending threshold)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1].astype(jnp.float64)
    tot_neg = fp[-1].astype(jnp.float64)
    tpr = tp.astype(jnp.float64) / jnp.maximum(tot_pos, 1)
    fpr = fp.astype(jnp.float64) / jnp.maximum(tot_neg, 1)
    auc = jnp.trapezoid(tpr, fpr).reshape(1)
    return {"AUC": auc, "StatPosOut": stat_pos, "StatNegOut": stat_neg}


register_op(
    "auc", ["Predict", "Label", "StatPos", "StatNeg"],
    ["AUC", "StatPosOut", "StatNegOut"],
    infer=_auc_infer, compute=_auc_compute, grad=None,
)


def _mean_iou_compute(ins, attrs, ctx, op_index):
    pred = ins["Predictions"][0].reshape(-1)
    label = ins["Labels"][0].reshape(-1)
    n = attrs["num_classes"]
    inter = jnp.zeros((n,), long_dtype()).at[
        jnp.where(pred == label, pred, n - 1)
    ].add((pred == label).astype(long_dtype()))
    pred_cnt = jnp.zeros((n,), long_dtype()).at[pred].add(1)
    label_cnt = jnp.zeros((n,), long_dtype()).at[label].add(1)
    union = pred_cnt + label_cnt - inter
    iou = inter.astype(jnp.float32) / jnp.maximum(union, 1).astype(jnp.float32)
    valid = (union > 0).astype(jnp.float32)
    mean_iou = (jnp.sum(iou * valid) / jnp.maximum(jnp.sum(valid), 1)).reshape(1)
    return {"OutMeanIou": mean_iou, "OutWrong": (label_cnt - inter).astype(jnp.int32),
            "OutCorrect": inter.astype(jnp.int32)}


register_op(
    "mean_iou", ["Predictions", "Labels"],
    ["OutMeanIou", "OutWrong", "OutCorrect"],
    infer=lambda op, block: (
        set_output(op, block, "OutMeanIou", (1,), np.float32),
        set_output(op, block, "OutWrong", (op.attrs["num_classes"],), np.int32),
        set_output(op, block, "OutCorrect", (op.attrs["num_classes"],), np.int32),
    ),
    compute=_mean_iou_compute, grad=None,
)


# -- precision_recall -------------------------------------------------------

def _pr_metrics(states):
    """ComputeMetrics (precision_recall_op.h:124): macro/micro P, R, F1
    from per-class [C, 4] TP/FP/TN/FN counts."""
    tp, fp, fn = states[:, 0], states[:, 1], states[:, 3]

    def calc(num, den_extra):
        any_ = (num > 0) | (den_extra > 0)
        return jnp.where(any_, num / jnp.maximum(num + den_extra, 1e-20),
                         1.0)

    prec = calc(tp, fp)
    rec = calc(tp, fn)
    macro_p = jnp.mean(prec)
    macro_r = jnp.mean(rec)

    def f1(p, r):
        return jnp.where((p > 0) | (r > 0),
                         2 * p * r / jnp.maximum(p + r, 1e-20), 0.0)

    t_tp, t_fp, t_fn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    micro_p = calc(t_tp, t_fp)
    micro_r = calc(t_tp, t_fn)
    return jnp.stack([macro_p, macro_r, f1(macro_p, macro_r),
                      micro_p, micro_r, f1(micro_p, micro_r)])


def _precision_recall_infer(op, block):
    c = int(op.attrs["class_number"])
    set_output(op, block, "BatchMetrics", (6,), "float32")
    set_output(op, block, "AccumMetrics", (6,), "float32")
    set_output(op, block, "AccumStatesInfo", (c, 4), "float32")


def _precision_recall_compute(ins, attrs, ctx, op_index):
    """Streaming multiclass precision/recall (precision_recall_op.h:54-98):
    per-sample TP/FP/TN/FN scatter, batch metrics from this batch's
    counts, accumulated metrics after merging StatesInfo."""
    ids = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    labels = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    ws = ins.get("Weights")
    w = ws[0].reshape(-1) if ws and ws[0] is not None else \
        jnp.ones(ids.shape, jnp.float32)
    c = int(attrs["class_number"])

    correct = ids == labels
    batch = jnp.zeros((c, 4), jnp.float32)
    # TP[idx] += w where correct
    batch = batch.at[ids, 0].add(jnp.where(correct, w, 0.0))
    # FP[idx] += w ; FN[label] += w where wrong
    batch = batch.at[ids, 1].add(jnp.where(correct, 0.0, w))
    batch = batch.at[labels, 3].add(jnp.where(correct, 0.0, w))
    # TN: every class gets +w per sample, minus the involved classes
    batch = batch.at[:, 2].add(jnp.sum(w))
    batch = batch.at[ids, 2].add(-w)
    batch = batch.at[labels, 2].add(jnp.where(correct, 0.0, -w))

    states = ins.get("StatesInfo")
    prev = states[0] if states and states[0] is not None else None
    accum = batch if prev is None else batch + prev
    return {"BatchMetrics": _pr_metrics(batch),
            "AccumMetrics": _pr_metrics(accum),
            "AccumStatesInfo": accum}


register_op(
    "precision_recall", ["MaxProbs", "Indices", "Labels", "Weights",
                         "StatesInfo"],
    ["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
    infer=_precision_recall_infer, compute=_precision_recall_compute,
    grad=None,
)


# -- positive_negative_pair (reference positive_negative_pair_op.cc) --------

def _pnp_infer(op, block):
    s = in_var(op, block, "Score")
    for slot in ("PositivePair", "NegativePair", "NeutralPair"):
        set_output(op, block, slot, (1,), s.dtype)


def _pnp_compute(ins, attrs, ctx, op_index):
    score, label, query = ins["Score"][0], ins["Label"][0], ins["QueryID"][0]
    col = attrs.get("column", 0)
    if col < 0:
        col += score.shape[1]
    s = score[:, col]
    lbl = label.reshape(-1)
    q = query.reshape(-1)
    w_in = ins.get("Weight")
    w = w_in[0].reshape(-1) if w_in and w_in[0] is not None \
        else jnp.ones_like(s)
    # all ordered pairs i<j within the same query whose labels differ;
    # O(B^2) pairwise mask — a metrics-only op, B is a minibatch
    same_q = q[:, None] == q[None, :]
    upper = jnp.arange(s.shape[0])[:, None] < jnp.arange(s.shape[0])[None, :]
    differ = lbl[:, None] != lbl[None, :]
    valid = same_q & upper & differ
    pair_w = 0.5 * (w[:, None] + w[None, :])
    tie = s[:, None] == s[None, :]
    # a tied pair counts as neutral AND negative: the reference kernel has
    # no else-if (positive_negative_pair_op.h — the tie falls through the
    # ternary into neg), and this op reproduces that behavior exactly
    agree = (s[:, None] - s[None, :]) * (lbl[:, None] - lbl[None, :]) > 0
    pos = jnp.sum(jnp.where(valid & agree, pair_w, 0.0))
    neg = jnp.sum(jnp.where(valid & ~agree, pair_w, 0.0))
    neu = jnp.sum(jnp.where(valid & tie, pair_w, 0.0))

    def acc(slot, v):
        a = ins.get(slot)
        return v + a[0].reshape(()) if a and a[0] is not None else v

    return {"PositivePair": acc("AccumulatePositivePair", pos)[None],
            "NegativePair": acc("AccumulateNegativePair", neg)[None],
            "NeutralPair": acc("AccumulateNeutralPair", neu)[None]}


register_op(
    "positive_negative_pair",
    ["Score", "Label", "QueryID", "AccumulatePositivePair",
     "AccumulateNegativePair", "AccumulateNeutralPair", "Weight"],
    ["PositivePair", "NegativePair", "NeutralPair"],
    infer=_pnp_infer, compute=_pnp_compute, grad=None,
)


# -- detection_map (reference detection_map_op.h) ---------------------------
# In-graph mAP so SSD eval runs inside the program like the reference.
# TPU redesign: padded [B, D, 6] detections + [B, G, 5|6] labels with
# length companions instead of LoD; the whole evaluation (greedy per-class
# matching, score-ordered PR curve, integral / 11-point AP) is traced.
# Streaming multi-batch accumulation (the reference's PosCount/TruePos/
# FalsePos recursion, dynamic-length state) stays HOST-side in
# ``metrics.DetectionMAP`` by design: the state is variable-length and
# branch-heavy, the wrong shape for XLA; this op evaluates one mini-batch
# (the reference's empty-PosCount path).

def _dmap_infer(op, block):
    c = int(op.attrs["class_num"])
    set_output(op, block, "MAP", (1,), "float32")
    set_output(op, block, "AccumPosCount", (c, 1), "int32")


def _dmap_match_image(dets, dlen, gts, glen, thresh, eval_difficult):
    """Per-image greedy matching (CalcTrueAndFalsePositive): dets
    [D, 6] (label, score, x1, y1, x2, y2), gts [G, 6] (label, x1..y2,
    difficult).  Returns (tp, fp, counted) [D] each."""
    d, g = dets.shape[0], gts.shape[0]
    det_valid = (jnp.arange(d) < dlen) & (dets[:, 0] >= 0)
    gt_valid = jnp.arange(g) < glen
    order = jnp.argsort(-dets[:, 1])          # score desc
    sdets = dets[order]
    svalid = det_valid[order]

    # det boxes are clipped to [0, 1] before overlap (ClipBBox); shared
    # pairwise-IoU kernel (clamped intersection = 0 for disjoint boxes,
    # matching JaccardOverlap)
    from .detection import _iou_matrix

    box = jnp.clip(sdets[:, 2:6], 0.0, 1.0)
    gbox = gts[:, 1:5]
    iou = _iou_matrix(box, gbox)
    same_cls = sdets[:, 0, None] == gts[None, :, 0]
    iou = jnp.where(same_cls & gt_valid[None, :], iou, -1.0)

    difficult = gts[:, 5] > 0

    def body(i, carry):
        visited, tp, fp, counted = carry
        ov = iou[i]
        max_ov = jnp.max(ov)
        max_idx = jnp.argmax(ov)
        matched = max_ov > thresh
        diff_skip = (~eval_difficult) & difficult[max_idx] & matched
        fresh = matched & ~visited[max_idx] & ~diff_skip
        is_tp = fresh
        is_fp = ~diff_skip & ~fresh
        ok = svalid[i]
        visited = visited.at[max_idx].set(
            visited[max_idx] | (fresh & ok))
        tp = tp.at[i].set(is_tp & ok)
        fp = fp.at[i].set(is_fp & ok)
        counted = counted.at[i].set(ok & ~diff_skip)
        return visited, tp, fp, counted

    z = jnp.zeros((d,), bool)
    _, tp, fp, counted = lax.fori_loop(
        0, d, body, (jnp.zeros((g,), bool), z, z, z))
    # undo the score sort so outputs align with input rows
    inv = jnp.argsort(order)
    return tp[inv], fp[inv], counted[inv]


def _dmap_compute(ins, attrs, ctx, op_index):
    dets = ins["DetectRes"][0]                # [B, D, 6]
    labels = ins["Label"][0]                  # [B, G, 5|6]
    c = int(attrs["class_num"])
    bg = int(attrs.get("background_label", 0))
    thresh = float(attrs.get("overlap_threshold", 0.5))
    eval_diff = bool(attrs.get("evaluate_difficult", True))
    ap_type = attrs.get("ap_type", "integral")
    if ap_type not in ("integral", "11point"):
        raise ValueError("detection_map: ap_type must be integral or "
                         "11point, got %r" % ap_type)
    b, d = dets.shape[:2]
    g = labels.shape[1]
    if labels.shape[-1] == 5:                 # no difficult column
        labels = jnp.concatenate(
            [labels, jnp.zeros(labels.shape[:-1] + (1,), labels.dtype)],
            axis=-1)
    dl = ins.get("DetectResLength")
    dlen = dl[0] if dl and dl[0] is not None else \
        jnp.full((b,), d, jnp.int32)
    gl = ins.get("GtLength")
    glen = gl[0] if gl and gl[0] is not None else \
        jnp.full((b,), g, jnp.int32)

    gt_valid = jnp.arange(g)[None, :] < glen[:, None]
    gt_counted = gt_valid if eval_diff else gt_valid & (labels[..., 5] <= 0)
    cls_ids = jnp.arange(c, dtype=labels.dtype)
    pos_count = jnp.sum(
        (labels[:, :, 0][None] == cls_ids[:, None, None])
        & gt_counted[None], axis=(1, 2))      # [C]

    tp, fp, counted = jax.vmap(
        lambda dd, dn, gg, gn: _dmap_match_image(
            dd, dn, gg, gn, thresh, jnp.asarray(eval_diff)))(
        dets, dlen, labels, glen)
    scores = dets[..., 1].reshape(-1)
    det_cls = dets[..., 0].reshape(-1)
    tp = tp.reshape(-1)
    fp = fp.reshape(-1)
    counted = counted.reshape(-1)

    order = jnp.argsort(-scores)              # global score-desc order
    s_cls = det_cls[order]
    s_tp = tp[order].astype(jnp.float32)
    s_fp = fp[order].astype(jnp.float32)
    s_cnt = counted[order]

    def ap_for_class(cid, npos):
        mask = s_cnt & (s_cls == cid.astype(s_cls.dtype))
        tpk = jnp.where(mask, s_tp, 0.0)
        fpk = jnp.where(mask, s_fp, 0.0)
        tp_cum = jnp.cumsum(tpk)
        fp_cum = jnp.cumsum(fpk)
        denom = jnp.maximum(tp_cum + fp_cum, 1.0)
        precision = tp_cum / denom
        recall = tp_cum / jnp.maximum(npos.astype(jnp.float32), 1.0)
        if ap_type == "integral":
            # recall moves only at TP rows: each contributes
            # precision * 1/npos (CalcMAP kIntegral)
            return jnp.sum(jnp.where(mask & (tpk > 0), precision, 0.0)
                           / jnp.maximum(npos.astype(jnp.float32), 1.0))
        # 11point: interpolated max precision at recall >= j/10
        pts = jnp.arange(11, dtype=jnp.float32) / 10.0
        interp = jnp.max(
            jnp.where(mask[None, :] & (recall[None, :] >= pts[:, None]),
                      precision[None, :], 0.0), axis=1)
        return jnp.sum(interp) / 11.0

    aps = jax.vmap(ap_for_class)(jnp.arange(c), pos_count)
    # reference CalcMAP: a class contributes only if it has positives,
    # appears among the detections (true_pos.find == end -> skipped,
    # detection_map_op.h:423), and — a reference quirk — its positive
    # COUNT differs from background_label (with the default bg=0 this
    # reduces to "has positives")
    has_det = jax.vmap(
        lambda cid: jnp.any(s_cnt & (s_cls == cid.astype(s_cls.dtype))))(
        jnp.arange(c))
    contributing = (pos_count > 0) & (pos_count != bg) & has_det
    n = jnp.sum(contributing.astype(jnp.int32))
    mean_ap = jnp.sum(jnp.where(contributing, aps, 0.0)) / \
        jnp.maximum(n, 1).astype(jnp.float32)
    return {"MAP": mean_ap[None].astype(jnp.float32),
            "AccumPosCount": pos_count[:, None].astype(jnp.int32)}


register_op(
    "detection_map",
    ["DetectRes", "DetectResLength", "Label", "GtLength"],
    ["MAP", "AccumPosCount"],
    infer=_dmap_infer, compute=_dmap_compute, grad=None,
)
