"""In-graph metric ops: accuracy, auc, mean_iou, precision/recall support.

Parity: reference ``accuracy_op.cc``, ``auc_op.cc``, ``mean_iou_op.cc`` —
metrics run inside the jitted step (no host round-trip), accumulation
states are persistable scope vars like the reference's evaluator states.
"""

import numpy as np

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var
from ..core import long_dtype


def _accuracy_infer(op, block):
    set_output(op, block, "Accuracy", (1,), np.float32)
    set_output(op, block, "Correct", (1,), np.int32)
    set_output(op, block, "Total", (1,), np.int32)


def _accuracy_compute(ins, attrs, ctx, op_index):
    indices = ins["Indices"][0]  # [N, k] from top_k
    label = ins["Label"][0]      # [N, 1]
    hit = jnp.any(indices == label.astype(indices.dtype), axis=-1)
    correct = jnp.sum(hit.astype(jnp.int32)).reshape(1)
    total = jnp.asarray([indices.shape[0]], dtype=jnp.int32)
    acc = correct.astype(jnp.float32) / indices.shape[0]
    return {"Accuracy": acc, "Correct": correct, "Total": total}


register_op(
    "accuracy", ["Out", "Indices", "Label"],
    ["Accuracy", "Correct", "Total"],
    infer=_accuracy_infer, compute=_accuracy_compute, grad=None,
)


def _auc_infer(op, block):
    set_output(op, block, "AUC", (1,), np.float64)
    bins = op.attrs.get("num_thresholds", 4095) + 1
    set_output(op, block, "StatPosOut", (bins,), np.int64)
    set_output(op, block, "StatNegOut", (bins,), np.int64)


def _auc_compute(ins, attrs, ctx, op_index):
    """Streaming AUC via threshold-bucketed TP/FP histograms
    (auc_op.cc redesigned: vectorized bincount instead of loops)."""
    preds = ins["Predict"][0]  # [N, 2] binary probabilities
    label = ins["Label"][0].reshape(-1)
    stat_pos = ins["StatPos"][0]
    stat_neg = ins["StatNeg"][0]
    n_bins = stat_pos.shape[0]
    p = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
    idx = jnp.clip((p * (n_bins - 1)).astype(jnp.int32), 0, n_bins - 1)
    pos = (label > 0).astype(long_dtype())
    stat_pos = stat_pos + jnp.zeros_like(stat_pos).at[idx].add(pos)
    stat_neg = stat_neg + jnp.zeros_like(stat_neg).at[idx].add(1 - pos)
    # integrate ROC from histograms (descending threshold)
    tp = jnp.cumsum(stat_pos[::-1])
    fp = jnp.cumsum(stat_neg[::-1])
    tot_pos = tp[-1].astype(jnp.float64)
    tot_neg = fp[-1].astype(jnp.float64)
    tpr = tp.astype(jnp.float64) / jnp.maximum(tot_pos, 1)
    fpr = fp.astype(jnp.float64) / jnp.maximum(tot_neg, 1)
    auc = jnp.trapezoid(tpr, fpr).reshape(1)
    return {"AUC": auc, "StatPosOut": stat_pos, "StatNegOut": stat_neg}


register_op(
    "auc", ["Predict", "Label", "StatPos", "StatNeg"],
    ["AUC", "StatPosOut", "StatNegOut"],
    infer=_auc_infer, compute=_auc_compute, grad=None,
)


def _mean_iou_compute(ins, attrs, ctx, op_index):
    pred = ins["Predictions"][0].reshape(-1)
    label = ins["Labels"][0].reshape(-1)
    n = attrs["num_classes"]
    inter = jnp.zeros((n,), long_dtype()).at[
        jnp.where(pred == label, pred, n - 1)
    ].add((pred == label).astype(long_dtype()))
    pred_cnt = jnp.zeros((n,), long_dtype()).at[pred].add(1)
    label_cnt = jnp.zeros((n,), long_dtype()).at[label].add(1)
    union = pred_cnt + label_cnt - inter
    iou = inter.astype(jnp.float32) / jnp.maximum(union, 1).astype(jnp.float32)
    valid = (union > 0).astype(jnp.float32)
    mean_iou = (jnp.sum(iou * valid) / jnp.maximum(jnp.sum(valid), 1)).reshape(1)
    return {"OutMeanIou": mean_iou, "OutWrong": (label_cnt - inter).astype(jnp.int32),
            "OutCorrect": inter.astype(jnp.int32)}


register_op(
    "mean_iou", ["Predictions", "Labels"],
    ["OutMeanIou", "OutWrong", "OutCorrect"],
    infer=lambda op, block: (
        set_output(op, block, "OutMeanIou", (1,), np.float32),
        set_output(op, block, "OutWrong", (op.attrs["num_classes"],), np.int32),
        set_output(op, block, "OutCorrect", (op.attrs["num_classes"],), np.int32),
    ),
    compute=_mean_iou_compute, grad=None,
)


# -- precision_recall -------------------------------------------------------

def _pr_metrics(states):
    """ComputeMetrics (precision_recall_op.h:124): macro/micro P, R, F1
    from per-class [C, 4] TP/FP/TN/FN counts."""
    tp, fp, fn = states[:, 0], states[:, 1], states[:, 3]

    def calc(num, den_extra):
        any_ = (num > 0) | (den_extra > 0)
        return jnp.where(any_, num / jnp.maximum(num + den_extra, 1e-20),
                         1.0)

    prec = calc(tp, fp)
    rec = calc(tp, fn)
    macro_p = jnp.mean(prec)
    macro_r = jnp.mean(rec)

    def f1(p, r):
        return jnp.where((p > 0) | (r > 0),
                         2 * p * r / jnp.maximum(p + r, 1e-20), 0.0)

    t_tp, t_fp, t_fn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    micro_p = calc(t_tp, t_fp)
    micro_r = calc(t_tp, t_fn)
    return jnp.stack([macro_p, macro_r, f1(macro_p, macro_r),
                      micro_p, micro_r, f1(micro_p, micro_r)])


def _precision_recall_infer(op, block):
    c = int(op.attrs["class_number"])
    set_output(op, block, "BatchMetrics", (6,), "float32")
    set_output(op, block, "AccumMetrics", (6,), "float32")
    set_output(op, block, "AccumStatesInfo", (c, 4), "float32")


def _precision_recall_compute(ins, attrs, ctx, op_index):
    """Streaming multiclass precision/recall (precision_recall_op.h:54-98):
    per-sample TP/FP/TN/FN scatter, batch metrics from this batch's
    counts, accumulated metrics after merging StatesInfo."""
    ids = ins["Indices"][0].reshape(-1).astype(jnp.int32)
    labels = ins["Labels"][0].reshape(-1).astype(jnp.int32)
    ws = ins.get("Weights")
    w = ws[0].reshape(-1) if ws and ws[0] is not None else \
        jnp.ones(ids.shape, jnp.float32)
    c = int(attrs["class_number"])

    correct = ids == labels
    batch = jnp.zeros((c, 4), jnp.float32)
    # TP[idx] += w where correct
    batch = batch.at[ids, 0].add(jnp.where(correct, w, 0.0))
    # FP[idx] += w ; FN[label] += w where wrong
    batch = batch.at[ids, 1].add(jnp.where(correct, 0.0, w))
    batch = batch.at[labels, 3].add(jnp.where(correct, 0.0, w))
    # TN: every class gets +w per sample, minus the involved classes
    batch = batch.at[:, 2].add(jnp.sum(w))
    batch = batch.at[ids, 2].add(-w)
    batch = batch.at[labels, 2].add(jnp.where(correct, 0.0, -w))

    states = ins.get("StatesInfo")
    prev = states[0] if states and states[0] is not None else None
    accum = batch if prev is None else batch + prev
    return {"BatchMetrics": _pr_metrics(batch),
            "AccumMetrics": _pr_metrics(accum),
            "AccumStatesInfo": accum}


register_op(
    "precision_recall", ["MaxProbs", "Indices", "Labels", "Weights",
                         "StatesInfo"],
    ["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
    infer=_precision_recall_infer, compute=_precision_recall_compute,
    grad=None,
)


# -- positive_negative_pair (reference positive_negative_pair_op.cc) --------

def _pnp_infer(op, block):
    s = in_var(op, block, "Score")
    for slot in ("PositivePair", "NegativePair", "NeutralPair"):
        set_output(op, block, slot, (1,), s.dtype)


def _pnp_compute(ins, attrs, ctx, op_index):
    score, label, query = ins["Score"][0], ins["Label"][0], ins["QueryID"][0]
    col = attrs.get("column", 0)
    if col < 0:
        col += score.shape[1]
    s = score[:, col]
    lbl = label.reshape(-1)
    q = query.reshape(-1)
    w_in = ins.get("Weight")
    w = w_in[0].reshape(-1) if w_in and w_in[0] is not None \
        else jnp.ones_like(s)
    # all ordered pairs i<j within the same query whose labels differ;
    # O(B^2) pairwise mask — a metrics-only op, B is a minibatch
    same_q = q[:, None] == q[None, :]
    upper = jnp.arange(s.shape[0])[:, None] < jnp.arange(s.shape[0])[None, :]
    differ = lbl[:, None] != lbl[None, :]
    valid = same_q & upper & differ
    pair_w = 0.5 * (w[:, None] + w[None, :])
    tie = s[:, None] == s[None, :]
    # a tied pair counts as neutral AND negative: the reference kernel has
    # no else-if (positive_negative_pair_op.h — the tie falls through the
    # ternary into neg), and this op reproduces that behavior exactly
    agree = (s[:, None] - s[None, :]) * (lbl[:, None] - lbl[None, :]) > 0
    pos = jnp.sum(jnp.where(valid & agree, pair_w, 0.0))
    neg = jnp.sum(jnp.where(valid & ~agree, pair_w, 0.0))
    neu = jnp.sum(jnp.where(valid & tie, pair_w, 0.0))

    def acc(slot, v):
        a = ins.get(slot)
        return v + a[0].reshape(()) if a and a[0] is not None else v

    return {"PositivePair": acc("AccumulatePositivePair", pos)[None],
            "NegativePair": acc("AccumulateNegativePair", neg)[None],
            "NeutralPair": acc("AccumulateNeutralPair", neu)[None]}


register_op(
    "positive_negative_pair",
    ["Score", "Label", "QueryID", "AccumulatePositivePair",
     "AccumulateNegativePair", "AccumulateNeutralPair", "Weight"],
    ["PositivePair", "NegativePair", "NeutralPair"],
    infer=_pnp_infer, compute=_pnp_compute, grad=None,
)
