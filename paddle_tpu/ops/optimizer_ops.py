"""Optimizer update ops — one op per optimizer family, dense kernels.

Parity: reference ``sgd_op.cc``, ``momentum_op.cc``, ``adam_op.cc``,
``adagrad_op.cc``, ``adamax_op.cc``, ``adadelta_op.cc``, ``rmsprop_op.cc``,
``ftrl_op.cc``, ``decayed_adagrad_op.cc``, ``proximal_gd_op.cc``,
``proximal_adagrad_op.cc`` — TPU-native: pure functional updates traced into
the same jitted step as fwd/bwd (the whole train step is one HLO module);
"in-place" parameter update is achieved by XLA buffer donation in the
executor, matching the reference's Param==ParamOut aliasing convention.
Sparse (SelectedRows) gradient variants use segment-sum scatter updates —
see ``paddle_tpu/ops/selected_rows.py``.
"""

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var


def _mirror_infer(*pairs):
    """infer fn mapping input slot -> output slot with same shape/dtype."""

    def infer(op, block):
        for in_slot, out_slot in pairs:
            v = in_var(op, block, in_slot)
            if v is not None and out_slot in op.outputs:
                set_output(op, block, out_slot, v.shape, v.dtype)

    return infer


def _maybe_sharded_rows(ctx, slots, tables, sr, scalars, row_update):
    """Route a lazy SelectedRows update through the mesh's row-sharded
    lowering when the param (and every row-wise slot var) is dim-0
    sharded on the trace's mesh: ids+values exchange over the batch
    axes, each shard updates only its local rows
    (``parallel.embedding.sharded_sparse_update``).  Returns the updated
    tables, or None -> caller runs ``row_update`` unsharded."""
    if ctx is None or getattr(ctx, "mesh", None) is None \
            or getattr(ctx, "op", None) is None \
            or not getattr(ctx, "state_specs", None):
        return None
    from ..parallel.embedding import sharded_sparse_update

    names = [ctx.op.inputs[s][0] for s in slots]
    return sharded_sparse_update(ctx, names, tables, sr, scalars,
                                 row_update)


def _sgd_rows_update(sr, lr, p):
    # sparse kernel (sgd_op.cc SelectedRows path): scatter-add only the
    # touched rows; duplicates sum naturally, sentinel rows (height,
    # from merged/clipped grads or foreign shard rows) drop
    lr = lr.astype(p.dtype)
    return (p.at[sr.rows].add(-lr * sr.values.astype(p.dtype),
                              mode="drop"),)


def _sgd_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows

    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    if isinstance(g, SelectedRows):
        out = _maybe_sharded_rows(ctx, ("Param",), (p,), g, lr,
                                  _sgd_rows_update)
        if out is None:
            out = _sgd_rows_update(g, lr, p)
        return {"ParamOut": out[0]}
    return {"ParamOut": p - lr.astype(p.dtype) * g.astype(p.dtype)}


register_op(
    "sgd", ["Param", "Grad", "LearningRate"], ["ParamOut"],
    infer=_mirror_infer(("Param", "ParamOut")), compute=_sgd_compute,
    grad=None,
)


def _momentum_rows_update(attrs):
    from .selected_rows import merge_rows, scatter_update_rows

    mu = attrs["mu"]
    nesterov = attrs.get("use_nesterov", False)

    def upd(sr, lr, p, v):
        # lazy sparse kernel: only touched rows' velocity/param move
        lr = lr.astype(p.dtype)
        uniq, gm, valid = merge_rows(sr)
        safe = jnp.where(valid, uniq, 0)
        v_r, p_r = v[safe], p[safe]
        v_new = mu * v_r + gm
        if nesterov:
            p_new = p_r - (gm + mu * v_new) * lr
        else:
            p_new = p_r - lr * v_new
        return (scatter_update_rows(p, uniq, valid, p_new, p_r),
                scatter_update_rows(v, uniq, valid, v_new, v_r))

    return upd


def _momentum_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows

    p, g, v = ins["Param"][0], ins["Grad"][0], ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs["mu"]
    if isinstance(g, SelectedRows):
        upd = _momentum_rows_update(attrs)
        out = _maybe_sharded_rows(ctx, ("Param", "Velocity"), (p, v), g,
                                  lr, upd)
        if out is None:
            out = upd(g, lr, p, v)
        return {"ParamOut": out[0], "VelocityOut": out[1]}
    lr = lr.astype(p.dtype)
    v_out = mu * v + g
    if attrs.get("use_nesterov", False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": p_out, "VelocityOut": v_out}


register_op(
    "momentum", ["Param", "Grad", "Velocity", "LearningRate"],
    ["ParamOut", "VelocityOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Velocity", "VelocityOut")),
    compute=_momentum_compute, grad=None,
)


def _adam_rows_update(attrs):
    from .selected_rows import merge_rows, scatter_update_rows

    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)

    def upd(sr, lr_t, p, m1, m2):
        # lazy adam (adam_op.cc SelectedRows kernel): untouched rows'
        # moments and params are bit-identical across the step
        lr_t = lr_t.astype(p.dtype)
        uniq, gm, valid = merge_rows(sr)
        safe = jnp.where(valid, uniq, 0)
        m1_r, m2_r, p_r = m1[safe], m2[safe], p[safe]
        m1_new = b1 * m1_r + (1 - b1) * gm
        m2_new = b2 * m2_r + (1 - b2) * gm * gm
        p_new = p_r - lr_t * m1_new / (jnp.sqrt(m2_new) + eps)
        return (scatter_update_rows(p, uniq, valid, p_new, p_r),
                scatter_update_rows(m1, uniq, valid, m1_new, m1_r),
                scatter_update_rows(m2, uniq, valid, m2_new, m2_r))

    return upd


def _adam_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows

    p, g = ins["Param"][0], ins["Grad"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SelectedRows):
        upd = _adam_rows_update(attrs)
        out = _maybe_sharded_rows(
            ctx, ("Param", "Moment1", "Moment2"), (p, m1, m2), g, lr_t,
            upd)
        if out is None:
            out = upd(g, lr_t, p, m1, m2)
        return {"ParamOut": out[0], "Moment1Out": out[1],
                "Moment2Out": out[2]}
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * g * g
    p_out = p - lr_t * m1_out / (jnp.sqrt(m2_out) + eps)
    return {"ParamOut": p_out, "Moment1Out": m1_out, "Moment2Out": m2_out}


register_op(
    "adam",
    ["Param", "Grad", "LearningRate", "Moment1", "Moment2", "Beta1Pow",
     "Beta2Pow"],
    ["ParamOut", "Moment1Out", "Moment2Out"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Moment1", "Moment1Out"),
                        ("Moment2", "Moment2Out")),
    compute=_adam_compute, grad=None,
)


def _adagrad_rows_update(attrs):
    from .selected_rows import merge_rows, scatter_update_rows

    eps = attrs.get("epsilon", 1e-6)

    def upd(sr, lr, p, mom):
        lr = lr.astype(p.dtype)
        uniq, gm, valid = merge_rows(sr)
        safe = jnp.where(valid, uniq, 0)
        mom_r, p_r = mom[safe], p[safe]
        mom_new = mom_r + gm * gm
        p_new = p_r - lr * gm / (jnp.sqrt(mom_new) + eps)
        return (scatter_update_rows(p, uniq, valid, p_new, p_r),
                scatter_update_rows(mom, uniq, valid, mom_new, mom_r))

    return upd


def _adagrad_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows

    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0]
    eps = attrs.get("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        upd = _adagrad_rows_update(attrs)
        out = _maybe_sharded_rows(ctx, ("Param", "Moment"), (p, mom), g,
                                  lr, upd)
        if out is None:
            out = upd(g, lr, p, mom)
        return {"ParamOut": out[0], "MomentOut": out[1]}
    lr = lr.astype(p.dtype)
    mom_out = mom + g * g
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": p_out, "MomentOut": mom_out}


register_op(
    "adagrad", ["Param", "Grad", "Moment", "LearningRate"],
    ["ParamOut", "MomentOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Moment", "MomentOut")),
    compute=_adagrad_compute, grad=None,
)


def _adamax_compute(ins, attrs, ctx, op_index):
    p, g = ins["Param"][0], ins["Grad"][0]
    m, inf_norm = ins["Moment"][0], ins["InfNorm"][0]
    b1p = ins["Beta1Pow"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    b1, b2 = attrs.get("beta1", 0.9), attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g) + eps)
    lr_t = lr / (1 - b1p)
    p_out = p - lr_t * m_out / inf_out
    return {"ParamOut": p_out, "MomentOut": m_out, "InfNormOut": inf_out}


register_op(
    "adamax",
    ["Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"],
    ["ParamOut", "MomentOut", "InfNormOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Moment", "MomentOut"),
                        ("InfNorm", "InfNormOut")),
    compute=_adamax_compute, grad=None,
)


def _adadelta_compute(ins, attrs, ctx, op_index):
    p, g = ins["Param"][0], ins["Grad"][0]
    avg_sq_g, avg_sq_u = ins["AvgSquaredGrad"][0], ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * g * g
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * update * update
    return {"ParamOut": p + update, "AvgSquaredGradOut": g2,
            "AvgSquaredUpdateOut": u2}


register_op(
    "adadelta", ["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
    ["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
    infer=_mirror_infer(("Param", "ParamOut"),
                        ("AvgSquaredGrad", "AvgSquaredGradOut"),
                        ("AvgSquaredUpdate", "AvgSquaredUpdateOut")),
    compute=_adadelta_compute, grad=None,
)


def _rmsprop_compute(ins, attrs, ctx, op_index):
    p, g = ins["Param"][0], ins["Grad"][0]
    ms, mom = ins["MeanSquare"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    rho = attrs.get("decay", 0.9)
    eps = attrs.get("epsilon", 1e-10)
    momentum = attrs.get("momentum", 0.0)
    ms_out = rho * ms + (1 - rho) * g * g
    if attrs.get("centered", False):
        mg = ins["MeanGrad"][0]
        mg_out = rho * mg + (1 - rho) * g
        mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out - mg_out * mg_out + eps)
        return {"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
                "MomentOut": mom_out, "MeanGradOut": mg_out}
    mom_out = momentum * mom + lr * g / jnp.sqrt(ms_out + eps)
    return {"ParamOut": p - mom_out, "MeanSquareOut": ms_out,
            "MomentOut": mom_out}


register_op(
    "rmsprop",
    ["Param", "Grad", "MeanSquare", "MeanGrad", "Moment", "LearningRate"],
    ["ParamOut", "MeanSquareOut", "MomentOut", "MeanGradOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("MeanSquare", "MeanSquareOut"),
                        ("Moment", "MomentOut"), ("MeanGrad", "MeanGradOut")),
    compute=_rmsprop_compute, grad=None,
)


def _decayed_adagrad_compute(ins, attrs, ctx, op_index):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    mom_out = decay * mom + (1 - decay) * g * g
    p_out = p - lr * g / (jnp.sqrt(mom_out) + eps)
    return {"ParamOut": p_out, "MomentOut": mom_out}


register_op(
    "decayed_adagrad", ["Param", "Grad", "Moment", "LearningRate"],
    ["ParamOut", "MomentOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Moment", "MomentOut")),
    compute=_decayed_adagrad_compute, grad=None,
)


def _ftrl_compute(ins, attrs, ctx, op_index):
    p, g = ins["Param"][0], ins["Grad"][0]
    sq_accum, lin_accum = ins["SquaredAccumulator"][0], ins["LinearAccumulator"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    new_accum = sq_accum + g * g
    if lr_power == -0.5:
        lin_out = lin_accum + g - (jnp.sqrt(new_accum) - jnp.sqrt(sq_accum)) / lr * p
    else:
        lin_out = lin_accum + g - (
            jnp.power(new_accum, -lr_power) - jnp.power(sq_accum, -lr_power)
        ) / lr * p
    x = l1 * jnp.sign(lin_out) - lin_out
    if lr_power == -0.5:
        y = jnp.sqrt(new_accum) / lr + 2 * l2
    else:
        y = jnp.power(new_accum, -lr_power) / lr + 2 * l2
    p_out = jnp.where(jnp.abs(lin_out) > l1, x / y, jnp.zeros_like(p))
    return {"ParamOut": p_out, "SquaredAccumOut": new_accum,
            "LinearAccumOut": lin_out}


register_op(
    "ftrl",
    ["Param", "SquaredAccumulator", "LinearAccumulator", "Grad",
     "LearningRate"],
    ["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
    infer=_mirror_infer(("Param", "ParamOut"),
                        ("SquaredAccumulator", "SquaredAccumOut"),
                        ("LinearAccumulator", "LinearAccumOut")),
    compute=_ftrl_compute, grad=None,
)


def _proximal_gd_compute(ins, attrs, ctx, op_index):
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    prox = p - lr * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) / (
        1.0 + lr * l2
    )
    return {"ParamOut": p_out}


register_op(
    "proximal_gd", ["Param", "Grad", "LearningRate"], ["ParamOut"],
    infer=_mirror_infer(("Param", "ParamOut")), compute=_proximal_gd_compute,
    grad=None,
)


def _proximal_adagrad_compute(ins, attrs, ctx, op_index):
    p, g, mom = ins["Param"][0], ins["Grad"][0], ins["Moment"][0]
    lr = ins["LearningRate"][0].astype(p.dtype)
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    mom_out = mom + g * g
    lr_t = lr / jnp.sqrt(mom_out)
    prox = p - lr_t * g
    p_out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_t * l1, 0.0) / (
        1.0 + lr_t * l2
    )
    return {"ParamOut": p_out, "MomentOut": mom_out}


register_op(
    "proximal_adagrad", ["Param", "Moment", "Grad", "LearningRate"],
    ["ParamOut", "MomentOut"],
    infer=_mirror_infer(("Param", "ParamOut"), ("Moment", "MomentOut")),
    compute=_proximal_adagrad_compute, grad=None,
)


# -- average_accumulates (reference average_accumulates_op.h) ---------------
# Drives ModelAverage: three staggered sum buffers avoid precision loss over
# long runs; window restarts keep a bounded trailing average.

_K_MAX_NUM_ACCUMULATES = 16384


def _avg_acc_compute(ins, attrs, ctx, op_index):
    param = ins["param"][0]
    s1, s2, s3 = ins["in_sum_1"][0], ins["in_sum_2"][0], ins["in_sum_3"][0]
    num_acc = ins["in_num_accumulates"][0]
    old_num_acc = ins["in_old_num_accumulates"][0]
    num_upd = ins["in_num_updates"][0]
    avg_window = attrs.get("average_window", 0.0)
    max_w = attrs["max_average_window"]
    min_w = attrs.get("min_average_window", 10000)

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    out1 = s1 + param
    out2, out3 = s2, s3

    # roll sum_1 into sum_2 every kMax updates (precision guard); the
    # reference rolls the *pre-update* buffers (average_accumulates_op.h)
    roll = (num_upd % _K_MAX_NUM_ACCUMULATES) == 0
    out2 = jnp.where(roll, s2 + s1, out2)
    out1 = jnp.where(roll, jnp.zeros_like(out1), out1)

    # restart the window once it exceeds min(max_w, num_upd * avg_window)
    limit = jnp.minimum(
        jnp.asarray(max_w, num_acc.dtype),
        (num_upd.astype(jnp.float32) * avg_window).astype(num_acc.dtype))
    done = (num_acc >= min_w) & (num_acc >= limit)
    out3 = jnp.where(done, s1 + s2, out3)
    out1 = jnp.where(done, jnp.zeros_like(out1), out1)
    out2 = jnp.where(done, jnp.zeros_like(out2), out2)
    old_num_acc = jnp.where(done, num_acc, old_num_acc)
    num_acc = jnp.where(done, jnp.zeros_like(num_acc), num_acc)

    return {"out_sum_1": out1, "out_sum_2": out2, "out_sum_3": out3,
            "out_num_accumulates": num_acc,
            "out_old_num_accumulates": old_num_acc,
            "out_num_updates": num_upd}


register_op(
    "average_accumulates",
    ["param", "in_sum_1", "in_sum_2", "in_sum_3", "in_num_accumulates",
     "in_old_num_accumulates", "in_num_updates"],
    ["out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
     "out_old_num_accumulates", "out_num_updates"],
    infer=_mirror_infer(
        ("in_sum_1", "out_sum_1"), ("in_sum_2", "out_sum_2"),
        ("in_sum_3", "out_sum_3"),
        ("in_num_accumulates", "out_num_accumulates"),
        ("in_old_num_accumulates", "out_old_num_accumulates"),
        ("in_num_updates", "out_num_updates")),
    compute=_avg_acc_compute, grad=None,
)
