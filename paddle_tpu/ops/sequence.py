"""Sequence ops over padded batches with explicit lengths.

Parity: the reference's LoD sequence family
(``paddle/fluid/operators/sequence_*_op.cc``, ``math/sequence_pooling.cc``,
``row_conv_op.cc``, ``sequence_conv_op.cc`` + ``math/im2sequence``) —
re-designed for XLA's static shapes: a "sequence batch" is a dense
``[batch, time, ...]`` array plus an int32 ``[batch]`` length vector
(SURVEY.md §5 long-context: segment/mask-based packing instead of LoD
offset vectors).  Every op takes the lengths through a ``Length`` slot
(wired automatically by the layer wrappers from the ``<name>@LEN``
companion var created by ``layers.data(lod_level>=1)``).

Masked positions (t >= length) are zeros on output; gradients through
auto-vjp respect the mask because it is part of the traced math.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var
from ..core import long_dtype

__all__ = []


def _time_mask(length, t, extra_dims=0):
    """[B, T] (+ extra trailing singleton dims) validity mask."""
    m = jnp.arange(t)[None, :] < length[:, None]
    return m.reshape(m.shape + (1,) * extra_dims)


# -- sequence_mask ----------------------------------------------------------

def _seq_mask_infer(op, block):
    x = in_var(op, block, "X")
    maxlen = op.attrs.get("maxlen", -1)
    t = maxlen if maxlen > 0 else -1
    set_output(op, block, "Y", tuple(x.shape) + (t,),
               op.attrs.get("out_dtype", "float32"))


def _seq_mask_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    maxlen = attrs.get("maxlen", -1)
    if maxlen <= 0:
        raise ValueError(
            "sequence_mask needs a static maxlen under XLA (got %r)" % maxlen)
    dtype = attrs.get("out_dtype", "float32")
    mask = jnp.arange(maxlen)[None, :] < x[..., None]
    return {"Y": mask.astype(dtype)}


register_op("sequence_mask", ["X"], ["Y"], infer=_seq_mask_infer,
            compute=_seq_mask_compute, grad=None)


# -- sequence_pool ----------------------------------------------------------

def _seq_pool_infer(op, block):
    x = in_var(op, block, "X")
    out_shape = (x.shape[0],) + tuple(x.shape[2:])
    set_output(op, block, "Out", out_shape, x.dtype)
    set_output(op, block, "MaxIndex", out_shape, "int32")


def _seq_pool_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    t = x.shape[1]
    mask = _time_mask(length, t, x.ndim - 2)
    denom = jnp.maximum(length, 1).astype(x.dtype)
    denom = denom.reshape((-1,) + (1,) * (x.ndim - 2))
    idx = None
    if ptype == "AVERAGE":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / denom
    elif ptype == "SUM":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1)
    elif ptype == "SQRT":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / jnp.sqrt(denom)
    elif ptype == "MAX":
        neg = (jnp.finfo(x.dtype).min
               if jnp.issubdtype(x.dtype, jnp.floating)
               else jnp.iinfo(x.dtype).min)
        masked = jnp.where(mask, x, neg)
        out = jnp.max(masked, axis=1)
        idx = jnp.argmax(masked, axis=1).astype(jnp.int32)
        # empty sequences pool to 0
        valid0 = (length > 0).reshape(denom.shape)
        out = jnp.where(valid0, out, 0)
    elif ptype == "LAST":
        last = jnp.maximum(length - 1, 0)
        out = jnp.take_along_axis(
            x, last.reshape((-1, 1) + (1,) * (x.ndim - 2)).astype(jnp.int32),
            axis=1).squeeze(1)
        out = jnp.where((length > 0).reshape(denom.shape), out, 0)
    elif ptype == "FIRST":
        out = jnp.where((length > 0).reshape(denom.shape), x[:, 0], 0)
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    res = {"Out": out}
    if idx is not None:
        res["MaxIndex"] = idx
    return res


register_op("sequence_pool", ["X", "Length"], ["Out", "MaxIndex"],
            infer=_seq_pool_infer, compute=_seq_pool_compute,
            no_grad_inputs=("Length",))


# -- sequence_softmax -------------------------------------------------------

def _seq_softmax_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    t = x.shape[1]
    extra = x.ndim - 2
    mask = _time_mask(length, t, extra)
    neg = (jnp.finfo(x.dtype).min
           if jnp.issubdtype(x.dtype, jnp.floating)
           else jnp.iinfo(x.dtype).min)
    logits = jnp.where(mask, x, neg)
    sm = jax.nn.softmax(logits, axis=1)
    return {"Out": jnp.where(mask, sm, 0)}


register_op(
    "sequence_softmax", ["X", "Length"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_seq_softmax_compute, no_grad_inputs=("Length",),
)


# -- sequence_expand --------------------------------------------------------

def _seq_expand_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    set_output(op, block, "Out",
               (x.shape[0], y.shape[1]) + tuple(x.shape[1:]), x.dtype)


def _seq_expand_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]               # [B, ...] one row per sequence
    y = ins["Y"][0]               # [B, T, ...] provides the time extent
    length = ins["Length"][0]     # lengths of y
    t = y.shape[1]
    expanded = jnp.broadcast_to(
        x[:, None], (x.shape[0], t) + x.shape[1:])
    mask = _time_mask(length, t, expanded.ndim - 2)
    return {"Out": jnp.where(mask, expanded, 0)}


register_op("sequence_expand", ["X", "Y", "Length"], ["Out"],
            infer=_seq_expand_infer, compute=_seq_expand_compute,
            no_grad_inputs=("Y", "Length"))


# -- sequence_concat (along time) -------------------------------------------

def _seq_concat_infer(op, block):
    xs = [block._find_var_recursive(n) for n in op.inputs["X"]]
    dims = [v.shape[1] for v in xs]
    # any dynamic time dim makes the concat time dim dynamic
    t = -1 if any(d is None or d < 0 for d in dims) else sum(dims)
    set_output(op, block, "Out", (xs[0].shape[0], t) + tuple(xs[0].shape[2:]),
               xs[0].dtype)
    set_output(op, block, "OutLength", (xs[0].shape[0],), "int32")


def _seq_concat_compute(ins, attrs, ctx, op_index):
    xs = ins["X"]
    lens = ins["Length"]
    b = xs[0].shape[0]
    total_t = sum(x.shape[1] for x in xs)
    out_len = sum(lens)
    # scatter each sequence's valid prefix at its running offset
    out = jnp.zeros((b, total_t) + xs[0].shape[2:], xs[0].dtype)
    offset = jnp.zeros((b,), jnp.int32)
    for x, ln in zip(xs, lens):
        t = x.shape[1]
        pos = offset[:, None] + jnp.arange(t)[None, :]          # [B, T_i]
        valid = jnp.arange(t)[None, :] < ln[:, None]
        pos = jnp.where(valid, pos, total_t)  # out-of-range drops
        bidx = jnp.broadcast_to(jnp.arange(b)[:, None], pos.shape)
        out = out.at[bidx, pos].add(
            jnp.where(valid.reshape(valid.shape + (1,) * (x.ndim - 2)),
                      x, 0),
            mode="drop")
        offset = offset + ln
    return {"Out": out, "OutLength": out_len.astype(jnp.int32)}


register_op("sequence_concat", ["X", "Length"], ["Out", "OutLength"],
            infer=_seq_concat_infer, compute=_seq_concat_compute,
            no_grad_inputs=("Length",))


# -- sequence_reverse -------------------------------------------------------

def _seq_reverse_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    t = x.shape[1]
    # index t -> len-1-t for valid positions, identity elsewhere
    ar = jnp.arange(t)[None, :]
    idx = jnp.where(ar < length[:, None], length[:, None] - 1 - ar, ar)
    idx = idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32)
    out = jnp.take_along_axis(x, jnp.broadcast_to(idx, x.shape), axis=1)
    return {"Out": out}


register_op(
    "sequence_reverse", ["X", "Length"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_seq_reverse_compute, no_grad_inputs=("Length",),
)


# -- sequence_conv (context-window fc, sequence_conv_op.cc) -----------------

def _seq_conv_infer(op, block):
    x = in_var(op, block, "X")
    w = in_var(op, block, "Filter")   # [ctx * D, out]
    set_output(op, block, "Out", (x.shape[0], x.shape[1], w.shape[1]),
               x.dtype)


def _seq_conv_compute(ins, attrs, ctx, op_index):
    x, w = ins["X"][0], ins["Filter"][0]
    length = ins["Length"][0]
    ctx_len = attrs.get("contextLength", 3)
    ctx_start = attrs.get("contextStart", -((ctx_len - 1) // 2))
    b, t, d = x.shape
    mask = _time_mask(length, t, 1)
    xm = jnp.where(mask, x, 0)
    # gather the context window per step: rows [t+ctx_start, ...]
    cols = []
    for j in range(ctx_len):
        shift = ctx_start + j
        rolled = jnp.roll(xm, -shift, axis=1)
        ar = jnp.arange(t)
        valid = (ar + shift >= 0) & (ar + shift < t)
        cols.append(jnp.where(valid[None, :, None], rolled, 0))
    ctx_mat = jnp.concatenate(cols, axis=-1)          # [B, T, ctx*D]
    out = jnp.einsum("btc,co->bto", ctx_mat, w)
    return {"Out": jnp.where(mask, out, 0)}


register_op("sequence_conv", ["X", "Filter", "Length"], ["Out"],
            infer=_seq_conv_infer, compute=_seq_conv_compute,
            no_grad_inputs=("Length",))


# -- row_conv (lookahead conv, row_conv_op.cc) ------------------------------

def _row_conv_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _row_conv_compute(ins, attrs, ctx, op_index):
    x, w = ins["X"][0], ins["Filter"][0]   # x [B,T,D], w [k, D]
    length = ins["Length"][0]
    k = w.shape[0]
    t = x.shape[1]
    mask = _time_mask(length, t, 1)
    xm = jnp.where(mask, x, 0)
    out = jnp.zeros_like(x)
    for j in range(k):
        rolled = jnp.roll(xm, -j, axis=1)
        valid = (jnp.arange(t) + j < t)
        out = out + jnp.where(valid[None, :, None], rolled, 0) * w[j][None,
                                                                     None]
    return {"Out": jnp.where(mask, out, 0)}


register_op("row_conv", ["X", "Filter", "Length"], ["Out"],
            infer=_row_conv_infer, compute=_row_conv_compute,
            no_grad_inputs=("Length",))


# -- sequence_erase (drop tokens, int sequences) ----------------------------

def _seq_erase_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    squeeze = x.ndim == 3 and x.shape[-1] == 1   # [B,T,1] id layout
    if squeeze:
        x = x[..., 0]
    tokens = attrs.get("tokens", [])
    t = x.shape[1]
    keep = _time_mask(length, t)
    for tok in tokens:
        keep = keep & (x != tok)
    # stable-compact the kept tokens to the left
    new_pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    new_pos = jnp.where(keep, new_pos, t)
    out = jnp.zeros_like(x)
    bidx = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None], x.shape)
    out = out.at[bidx, new_pos].add(jnp.where(keep, x, 0), mode="drop")
    new_len = jnp.sum(keep.astype(jnp.int32), axis=1)
    if squeeze:
        out = out[..., None]
    return {"Out": out, "OutLength": new_len}


register_op(
    "sequence_erase", ["X", "Length"], ["Out", "OutLength"],
    infer=lambda op, block: (
        set_output(op, block, "Out", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
        set_output(op, block, "OutLength",
                   (in_var(op, block, "X").shape[0],), "int32"),
    ),
    compute=_seq_erase_compute, grad=None,
)


# -- sequence_enumerate (win_size n-grams of int ids) -----------------------

def _seq_enum_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out",
               tuple(x.shape[:2]) + (op.attrs.get("win_size", 2),), x.dtype)


def _seq_enum_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    if x.ndim == 3 and x.shape[-1] == 1:          # [B,T,1] id layout
        x = x[..., 0]
    win = attrs.get("win_size", 2)
    pad = attrs.get("pad_value", 0)
    t = x.shape[1]
    outs = []
    for j in range(win):
        rolled = jnp.roll(x, -j, axis=1)
        valid = (jnp.arange(t)[None, :] + j) < length[:, None]
        outs.append(jnp.where(valid, rolled, pad))
    return {"Out": jnp.stack(outs, axis=-1)}


register_op("sequence_enumerate", ["X", "Length"], ["Out"],
            infer=_seq_enum_infer, compute=_seq_enum_compute, grad=None)


# -- sequence_slice / sequence_reshape: geometric utilities -----------------

def _seq_slice_compute(ins, attrs, ctx, op_index):
    x, length = ins["X"][0], ins["Length"][0]
    offset, size = ins["Offset"][0], ins["Size"][0]
    t = x.shape[1]
    off = offset.reshape(-1).astype(jnp.int32)
    sz = size.reshape(-1).astype(jnp.int32)
    ar = jnp.arange(t)[None, :]
    idx = (off[:, None] + ar)
    valid = ar < sz[:, None]
    idx = jnp.clip(idx, 0, t - 1)
    gathered = jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)).astype(jnp.int32),
        axis=1)
    mask = valid.reshape(valid.shape + (1,) * (x.ndim - 2))
    return {"Out": jnp.where(mask, gathered, 0), "OutLength": sz}


register_op(
    "sequence_slice", ["X", "Offset", "Size", "Length"],
    ["Out", "OutLength"],
    infer=lambda op, block: (
        set_output(op, block, "Out", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
        set_output(op, block, "OutLength",
                   (in_var(op, block, "X").shape[0],), "int32"),
    ),
    compute=_seq_slice_compute,
    no_grad_inputs=("Offset", "Size", "Length"),
)


# -- causal_mask (decoder self-attention bias; transformer support) ---------

def _causal_mask_infer(op, block):
    t = op.attrs.get("seq_len", -1)
    if op.inputs.get("Ref"):
        ref = in_var(op, block, "Ref")
        t = ref.shape[1]
    set_output(op, block, "Out", (t, t), op.attrs.get("dtype", "float32"))


def _causal_mask_compute(ins, attrs, ctx, op_index):
    ref = ins.get("Ref", [None])[0]
    t = ref.shape[1] if ref is not None else attrs["seq_len"]
    neg = attrs.get("mask_value", -1e9)
    m = jnp.triu(jnp.full((t, t), neg, attrs.get("dtype", "float32")), k=1)
    return {"Out": m}


register_op("causal_mask", ["Ref"], ["Out"], infer=_causal_mask_infer,
            compute=_causal_mask_compute, grad=None)


# -- padding_attn_bias ([B] lengths + Ref[B,T,...] -> [B,1,1,T] bias) -------

def _pad_bias_infer(op, block):
    ref = in_var(op, block, "Ref")
    set_output(op, block, "Out", (ref.shape[0], 1, 1, ref.shape[1]),
               op.attrs.get("dtype", "float32"))


def _pad_bias_compute(ins, attrs, ctx, op_index):
    length, ref = ins["Length"][0], ins["Ref"][0]
    t = ref.shape[1]
    neg = attrs.get("mask_value", -1e9)
    valid = jnp.arange(t)[None, :] < length[:, None]
    bias = jnp.where(valid, 0.0, neg).astype(attrs.get("dtype", "float32"))
    return {"Out": bias[:, None, None, :]}


register_op("padding_attn_bias", ["Length", "Ref"], ["Out"],
            infer=_pad_bias_infer, compute=_pad_bias_compute, grad=None)


# -- add_position_encoding (X[B,T,D] + Table[:T]; transformer support) ------

def _add_pos_enc_compute(ins, attrs, ctx, op_index):
    x, table = ins["X"][0], ins["Table"][0]
    t = x.shape[1]
    return {"Out": x + table[:t][None]}


register_op(
    "add_position_encoding", ["X", "Table"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_add_pos_enc_compute, no_grad_inputs=("Table",),
)


# -- padding_mask ([B] lengths + Ref[B,T,...] -> [B,T] 0/1) -----------------

def _padding_mask_infer(op, block):
    ref = in_var(op, block, "Ref")
    set_output(op, block, "Out", (ref.shape[0], ref.shape[1]),
               op.attrs.get("dtype", "float32"))


def _padding_mask_compute(ins, attrs, ctx, op_index):
    length, ref = ins["Length"][0], ins["Ref"][0]
    t = ref.shape[1]
    valid = jnp.arange(t)[None, :] < length[:, None]
    return {"Out": valid.astype(attrs.get("dtype", "float32"))}


register_op("padding_mask", ["Length", "Ref"], ["Out"],
            infer=_padding_mask_infer, compute=_padding_mask_compute,
            grad=None)


# -- sequence_pad (reference sequence_pad_op.cc: LoD seq -> padded dense) ----

def _seq_pad_infer(op, block):
    x = in_var(op, block, "X")
    maxlen = op.attrs.get("padded_length", -1)
    t = maxlen if maxlen and maxlen > 0 else x.shape[1]
    set_output(op, block, "Out", (x.shape[0], t) + tuple(x.shape[2:]),
               x.dtype)
    set_output(op, block, "SeqLength", (x.shape[0],), "int64")


def _seq_pad_compute(ins, attrs, ctx, op_index):
    """Our sequences are already padded arrays; padding re-materializes
    the tail with ``pad_value`` and (optionally) re-times to
    ``padded_length`` (sequence_pad_op.cc contract: output is dense,
    plus the original lengths)."""
    x, length = ins["X"][0], ins["Length"][0]
    pad_value = ins["PadValue"][0] if ins.get("PadValue") and \
        ins["PadValue"][0] is not None else jnp.zeros((), x.dtype)
    t_in = x.shape[1]
    target = int(attrs.get("padded_length", -1))
    if target <= 0:
        target = t_in
    if target > t_in:
        pad_widths = [(0, 0), (0, target - t_in)] + \
            [(0, 0)] * (x.ndim - 2)
        x = jnp.pad(x, pad_widths)
    elif target < t_in:
        x = x[:, :target]
    mask = _time_mask(length, target, x.ndim - 2)
    out = jnp.where(mask, x, jnp.asarray(pad_value, x.dtype))
    return {"Out": out, "SeqLength": length.astype(long_dtype())}


register_op("sequence_pad", ["X", "Length", "PadValue"],
            ["Out", "SeqLength"],
            infer=_seq_pad_infer, compute=_seq_pad_compute,
            no_grad_inputs=("Length", "PadValue"))


# -- sequence_unpad (reference sequence_unpad_op.cc: dense -> LoD seq) -------

def _seq_unpad_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype, lod_level=1)


def _seq_unpad_compute(ins, attrs, ctx, op_index):
    """Dense [B,T,...] + lengths -> padded-sequence representation: the
    data is unchanged, the tail is zeroed so downstream masked ops see
    canonical padding."""
    x, length = ins["X"][0], ins["Length"][0]
    mask = _time_mask(length, x.shape[1], x.ndim - 2)
    return {"Out": jnp.where(mask, x, 0), "OutLength":
            length.astype(jnp.int32)}


register_op("sequence_unpad", ["X", "Length"], ["Out", "OutLength"],
            infer=_seq_unpad_infer, compute=_seq_unpad_compute,
            no_grad_inputs=("Length",))


# -- sequence_reshape (reference sequence_reshape_op.cc) ---------------------

def _seq_reshape_infer(op, block):
    x = in_var(op, block, "X")
    new_dim = int(op.attrs["new_dim"])
    d = x.shape[-1]
    t = x.shape[1]
    if d not in (-1, None) and t not in (-1, None) and \
            (t * d) % new_dim != 0:
        raise ValueError(
            "sequence_reshape: T*D = %d*%d is not divisible by new_dim %d "
            "(reference sequence_reshape_op.cc enforces divisibility)"
            % (t, d, new_dim))
    new_t = -1 if t in (-1, None) or d in (-1, None) \
        else (t * d) // new_dim
    set_output(op, block, "Out", (x.shape[0], new_t, new_dim), x.dtype,
               lod_level=1)


def _seq_reshape_compute(ins, attrs, ctx, op_index):
    """Per-sequence reshape: each sequence's len*D elements re-chunk to
    rows of ``new_dim`` (len*D must divide).  On padded batches this is
    a plain reshape because sequences are time-contiguous and the tail
    is zeros."""
    x, length = ins["X"][0], ins["Length"][0]
    b, t, d = x.shape
    new_dim = int(attrs["new_dim"])
    out = x.reshape(b, (t * d) // new_dim, new_dim)
    new_len = (length * d) // new_dim
    return {"Out": out, "OutLength": new_len.astype(jnp.int32)}


register_op("sequence_reshape", ["X", "Length"], ["Out", "OutLength"],
            infer=_seq_reshape_infer, compute=_seq_reshape_compute,
            no_grad_inputs=("Length",))


# -- sequence_expand_as (reference sequence_expand_as_op.cc) -----------------

def _seq_expand_as_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    set_output(op, block, "Out", (x.shape[0], y.shape[1]) +
               tuple(x.shape[1:]), x.dtype, lod_level=1)


def _seq_expand_as_compute(ins, attrs, ctx, op_index):
    """Row i of X repeats to Y's sequence-i length: [B, D] + Y lengths
    -> [B, Ty, D] (zeros past each length)."""
    x = ins["X"][0]
    y_len = ins["YLength"][0]
    t = ins["Y"][0].shape[1]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    mask = _time_mask(y_len, t, out.ndim - 2)
    return {"Out": jnp.where(mask, out, 0),
            "OutLength": y_len.astype(jnp.int32)}


register_op("sequence_expand_as", ["X", "Y", "YLength"],
            ["Out", "OutLength"],
            infer=_seq_expand_as_infer, compute=_seq_expand_as_compute,
            no_grad_inputs=("Y", "YLength"))


# -- sequence_scatter (reference sequence_scatter_op.cc) ---------------------

def _seq_scatter_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _seq_scatter_compute(ins, attrs, ctx, op_index):
    """out[b, ids[b, u]] += updates[b, u] for u < len(b): per-sequence
    scatter-add of update sequences into dense rows (the reference adds
    sequence i's updates into X row i)."""
    x = ins["X"][0]                               # [B, D]
    ids = ins["Ids"][0]
    upd = ins["Updates"][0]
    if ids.ndim == 3:
        ids = ids[:, :, 0]
    if upd.ndim == 3:
        upd = upd[:, :, 0]
    length = ins["Length"][0]
    u_max = ids.shape[1]
    valid = jnp.arange(u_max)[None, :] < length[:, None]
    b_idx = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None], ids.shape)
    safe_ids = jnp.where(valid, ids, x.shape[-1])   # OOB -> dropped
    return {"Out": x.at[b_idx, safe_ids].add(
        jnp.where(valid, upd, 0).astype(x.dtype), mode="drop")}


register_op("sequence_scatter", ["X", "Ids", "Updates", "Length"],
            ["Out"],
            infer=_seq_scatter_infer, compute=_seq_scatter_compute,
            no_grad_inputs=("Ids", "Length"))


# -- im2sequence (reference im2sequence_op.cc / math/im2col) -----------------

def _im2sequence_infer(op, block):
    x = in_var(op, block, "X")
    b, c, h, w = x.shape
    kh, kw = op.attrs["kernels"]
    sh, sw = op.attrs.get("strides", [1, 1])
    p = op.attrs.get("paddings", [0, 0, 0, 0])
    if h in (-1, None) or w in (-1, None):
        t = -1
    else:
        oh = (h + p[0] + p[2] - kh) // sh + 1
        ow = (w + p[1] + p[3] - kw) // sw + 1
        t = oh * ow
    d = None if c in (-1, None) else c * kh * kw
    set_output(op, block, "Out", (b, t, d), x.dtype, lod_level=1)


def _im2sequence_compute(ins, attrs, ctx, op_index):
    """[B, C, H, W] -> [B, oh*ow, C*kh*kw] patch sequence; every batch
    item has the same length oh*ow (im2sequence_op.cc semantics)."""
    x = ins["X"][0]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw),
        [(p[0], p[2]), (p[1], p[3])],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    b, d, oh, ow = patches.shape
    out = patches.reshape(b, d, oh * ow).transpose(0, 2, 1)
    lengths = jnp.full((b,), oh * ow, jnp.int32)
    return {"Out": out, "OutLength": lengths}


register_op("im2sequence", ["X"], ["Out", "OutLength"],
            infer=_im2sequence_infer, compute=_im2sequence_compute)


# -- lod_reset (reference lod_reset_op.cc) ----------------------------------
# In the padded-batch representation "resetting the LoD" keeps the data and
# replaces the length companion: the target level-0 offsets (from Y's data
# or attr target_lod) become a fresh [B] length vector.

def _lod_reset_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype, lod_level=1)
    set_output(op, block, "Length", (x.shape[0],), "int64")


def _lod_reset_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    y = ins.get("Y")
    if y and y[0] is not None:
        offsets = y[0].reshape(-1)
        lengths = (offsets[1:] - offsets[:-1]).astype(long_dtype())
    else:
        tl = attrs.get("target_lod")
        if not tl:
            raise ValueError(
                "lod_reset needs input Y or attr target_lod "
                "(lod_reset_op.cc contract)")
        lengths = jnp.asarray(
            [tl[i + 1] - tl[i] for i in range(len(tl) - 1)],
            dtype=long_dtype())
    if lengths.shape[0] != x.shape[0]:
        raise ValueError(
            "lod_reset: %d target sequences but the padded batch has %d "
            "rows; the padded representation cannot change the sequence "
            "count" % (lengths.shape[0], x.shape[0]))
    return {"Out": x, "Length": lengths}


register_op("lod_reset", ["X", "Y"], ["Out", "Length"],
            infer=_lod_reset_infer, compute=_lod_reset_compute,
            no_grad_inputs=("Y",))


# ---- rank-table family (reference lod_rank_table_op.cc:1,
# max_sequence_len_op.cc:1, reorder_lod_tensor_by_rank_op.cc:1) ----------
#
# The reference builds a LoDRankTable (sequence indices sorted by length,
# descending, stable) to drive length-bucketed DynamicRNN batching and
# in-graph reorders.  On the padded [B, T, ...]+@LEN design the table is
# an ordinary [B, 2] int64 tensor of (index, length) rows, reorders are
# batch gathers, and the shrinking-step-batch machinery
# (lod_tensor_to_array_op.cc) is absorbed by lax.scan RNNs + host-side
# bucket_by_length (reader/decorator.py) — scan steps are masked, not
# shrunk, because XLA wants static shapes.

def _lod_rank_table_infer(op, block):
    ln = in_var(op, block, "Length")
    set_output(op, block, "Out", (ln.shape[0], 2), "int64")


def _lod_rank_table_compute(ins, attrs, ctx, op_index):
    lens = ins["Length"][0].reshape(-1).astype(long_dtype())
    # stable argsort on negated lengths = descending, ties in input order
    order = jnp.argsort(-lens, stable=True)
    return {"Out": jnp.stack([order.astype(long_dtype()), lens[order]],
                             axis=1)}


register_op("lod_rank_table", ["Length"], ["Out"],
            infer=_lod_rank_table_infer, compute=_lod_rank_table_compute,
            grad=None)


def _max_sequence_len_infer(op, block):
    set_output(op, block, "Out", (), "int64")


def _max_sequence_len_compute(ins, attrs, ctx, op_index):
    table = ins["RankTable"][0]
    return {"Out": table[0, 1]}


register_op("max_sequence_len", ["RankTable"], ["Out"],
            infer=_max_sequence_len_infer,
            compute=_max_sequence_len_compute, grad=None)


def _reorder_by_rank_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype,
               lod_level=getattr(x, "lod_level", 0))
    set_output(op, block, "OutLength", (x.shape[0],), "int64")


def _reorder_by_rank_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    table = ins["RankTable"][0]
    idx = table[:, 0]
    return {"Out": jnp.take(x, idx, axis=0), "OutLength": table[:, 1]}


register_op("reorder_lod_tensor_by_rank", ["X", "RankTable"],
            ["Out", "OutLength"], infer=_reorder_by_rank_infer,
            compute=_reorder_by_rank_compute,
            no_grad_inputs=("RankTable",))


def _lod_tensor_to_array_infer(op, block):
    x = in_var(op, block, "X")
    t = x.shape[1] if len(x.shape) > 1 else -1
    b = x.shape[0]
    set_output(op, block, "Out", (t, b) + tuple(x.shape[2:]), x.dtype)
    set_output(op, block, "OutLength", (b,), "int64")


def _lod_tensor_to_array_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    table = ins["RankTable"][0]
    ro = jnp.take(x, table[:, 0], axis=0)          # rank order, [B, T, ...]
    return {"Out": jnp.swapaxes(ro, 0, 1),         # time-major [T, B, ...]
            "OutLength": table[:, 1]}


register_op(
    "lod_tensor_to_array", ["X", "RankTable"], ["Out", "OutLength"],
    infer=_lod_tensor_to_array_infer, compute=_lod_tensor_to_array_compute,
    no_grad_inputs=("RankTable",),
    doc="""[B, T, ...] -> time-major step batches [T, B, ...] in rank
    order (reference lod_tensor_to_array_op.cc:1).  The reference's
    per-step SHRINKING batches (step t keeps only sequences longer than
    t) are a dynamic-shape device; XLA wants static shapes, so steps
    stay full-width and downstream scan ops mask via OutLength — same
    convergence, MXU-friendly tiles (SURVEY §5).""")


def _array_to_lod_tensor_infer(op, block):
    x = in_var(op, block, "X")
    b = x.shape[1] if len(x.shape) > 1 else -1
    t = x.shape[0]
    set_output(op, block, "Out", (b, t) + tuple(x.shape[2:]), x.dtype,
               lod_level=1)
    set_output(op, block, "OutLength", (b,), "int64")


def _array_to_lod_tensor_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]                                # [T, B, ...] rank order
    table = ins["RankTable"][0]
    inv = jnp.argsort(table[:, 0])                 # undo the rank permute
    bt = jnp.swapaxes(x, 0, 1)                     # [B, T, ...]
    return {"Out": jnp.take(bt, inv, axis=0),
            "OutLength": jnp.take(table[:, 1], inv, axis=0)}


register_op(
    "array_to_lod_tensor", ["X", "RankTable"], ["Out", "OutLength"],
    infer=_array_to_lod_tensor_infer, compute=_array_to_lod_tensor_compute,
    no_grad_inputs=("RankTable",),
    doc="""Inverse of lod_tensor_to_array: time-major rank-ordered step
    batches back to the original [B, T, ...] batch order with the
    original @LEN companion (reference array_to_lod_tensor_op.cc:1).""")
