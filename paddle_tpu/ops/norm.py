"""Normalization ops: batch_norm, layer_norm, lrn, norm (L2), group_norm.

Parity: reference ``paddle/fluid/operators/batch_norm_op.{cc,cu.cc}``
(train/infer modes, momentum moving stats, NCHW/NHWC data_layout),
``layer_norm_op.cc`` (begin_norm_axis), ``lrn_op.cc``, ``norm_op.cc`` —
TPU-native: each is a handful of jnp reductions that XLA fuses into one
kernel; gradients via auto-vjp reproduce the saved-stat backward the
reference hand-writes (vjp through rsqrt of the saved variance).

batch_norm's moving-average update is part of the same traced program, so
MeanOut/VarianceOut write back to the persistable stat vars in the scope
(the reference does this in-place through the same-name output trick,
python/paddle/fluid/layers/nn.py batch_norm).
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var

__all__ = []


# -- batch_norm -------------------------------------------------------------

def _bn_infer(op, block):
    x = in_var(op, block, "X")
    c = x.shape[1] if op.attrs.get("data_layout", "NCHW") == "NCHW" \
        else x.shape[-1]
    set_output(op, block, "Y", x.shape, x.dtype)
    set_output(op, block, "MeanOut", (c,), x.dtype)
    set_output(op, block, "VarianceOut", (c,), x.dtype)
    set_output(op, block, "SavedMean", (c,), x.dtype)
    set_output(op, block, "SavedVariance", (c,), x.dtype)


def shifted_one_pass_stats(xf, shift, red_axes, bshape=None):
    """Per-channel (mean, var) in ONE fused HBM pass: both reductions of
    E[(x-c)^2]-(E[x-c])^2 are independent so XLA fuses them (the
    two-pass exact form needs a second full read after the mean
    barrier).  ``shift`` (fp32 [C] or None) — typically the running mean
    — kills the catastrophic cancellation of the naive E[x^2]-E[x]^2
    whenever it tracks the batch mean.  Clamped at 0.  Shared by
    batch_norm and the fused-conv-BN decomposition (transpiler.fusion)
    so the two paths cannot drift numerically."""
    if shift is not None:
        s32 = shift.astype(jnp.float32)
        if bshape is None:
            bshape = [1] * xf.ndim
            c_axis = [i for i in range(xf.ndim) if i not in red_axes][0]
            bshape[c_axis] = xf.shape[c_axis]
        xs = xf - s32.reshape(bshape)
    else:
        s32 = 0.0
        xs = xf
    m1 = jnp.mean(xs, axis=red_axes)
    var = jnp.maximum(jnp.mean(jnp.square(xs), axis=red_axes)
                      - jnp.square(m1), 0.0)
    return m1 + s32, var


def _bn_axes(x, attrs):
    """(c_axis, reduction axes, broadcast shape) for a BN input under the
    op's data_layout — shared by forward and the fused backward so the
    two can never disagree on reduction axes."""
    layout = attrs.get("data_layout", "NCHW")
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    red_axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]
    return c_axis, red_axes, bshape


def _bn_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    eps = attrs.get("epsilon", 1e-5)
    momentum = attrs.get("momentum", 0.9)
    is_test = attrs.get("is_test", False) or attrs.get("use_global_stats",
                                                       False)
    c_axis, red_axes, bshape = _bn_axes(x, attrs)

    # statistics accumulate in fp32 INSIDE the kernel regardless of the
    # activation dtype, so bf16 activations flow through unconverted (the
    # op is AMP-gray: blacklisting it would cost two full-activation cast
    # passes around every conv) while running stats stay accurate.  XLA
    # fuses the f32 cast into the reduction — no fp32 materialization.
    xf = x.astype(jnp.float32)
    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        from ..flags import flag
        if flag("bn_two_pass"):
            # two-pass variance: E[(x-mean)^2] — exact but costs a second
            # full read of the activation (the mean must finish first, so
            # XLA cannot fuse the two reductions into one pass)
            use_mean = jnp.mean(xf, axis=red_axes)
            use_var = jnp.mean(
                jnp.square(xf - use_mean.reshape(bshape)), axis=red_axes
            )
        else:
            # one-pass variance shifted by the running mean (cuDNN's
            # form) — measured ~8% off a ResNet-50 step on a v5e vs the
            # two-pass form; FLAGS_bn_two_pass restores the exact form
            use_mean, use_var = shifted_one_pass_stats(
                xf, mean, red_axes, bshape)
        mean_out = momentum * mean + (1.0 - momentum) * use_mean
        var_out = momentum * var + (1.0 - momentum) * use_var
        saved_mean = use_mean
        saved_var = use_var

    inv_std = lax.rsqrt(use_var.astype(jnp.float32) + eps)
    y = (xf - use_mean.reshape(bshape).astype(jnp.float32)) * \
        (inv_std * scale.astype(jnp.float32)).reshape(bshape) + \
        bias.astype(jnp.float32).reshape(bshape)
    return {"Y": y.astype(x.dtype), "MeanOut": mean_out,
            "VarianceOut": var_out, "SavedMean": saved_mean,
            "SavedVariance": saved_var}


def _bn_grad_maker(op, no_grad_set):
    """Hand-written fused BN backward (reference ``batch_norm_op.cu``'s
    three-term kernel) instead of the generic vjp: differentiating the
    recomputed two-pass variance costs ~2x the activation traffic of the
    closed-form dx/dgamma/dbeta."""
    from ..framework import grad_var_name

    x = op.inputs["X"][0]
    outs = {}
    for slot, names in (("GRAD::X", op.inputs["X"]),
                        ("GRAD::Scale", op.inputs["Scale"]),
                        ("GRAD::Bias", op.inputs["Bias"])):
        outs[slot] = ["" if n in no_grad_set else grad_var_name(n)
                      for n in names]
    if not any(n for ns in outs.values() for n in ns):
        return []
    return [dict(
        type="batch_norm_grad",
        inputs={"X": [x], "Scale": op.inputs["Scale"],
                "Out::SavedMean": op.outputs["SavedMean"],
                "Out::SavedVariance": op.outputs["SavedVariance"],
                "GRAD::Y": [grad_var_name(op.outputs["Y"][0])]},
        outputs=outs,
        attrs=dict(op.attrs),
    )]


def _bn_grad_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale = ins["Scale"][0]
    mean = ins["Out::SavedMean"][0]
    var = ins["Out::SavedVariance"][0]
    dy = ins["GRAD::Y"][0]
    eps = attrs.get("epsilon", 1e-5)
    c_axis, red, bshape = _bn_axes(x, attrs)
    n = 1
    for i in red:
        n *= x.shape[i]

    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    mu = mean.astype(jnp.float32).reshape(bshape)
    rstd = lax.rsqrt(var.astype(jnp.float32) + eps).reshape(bshape)
    xhat = (xf - mu) * rstd
    dbeta = jnp.sum(dyf, axis=red)
    dgamma = jnp.sum(dyf * xhat, axis=red)
    g = scale.astype(jnp.float32).reshape(bshape) * rstd
    if attrs.get("is_test", False) or attrs.get("use_global_stats", False):
        # running stats are constants w.r.t. x
        dx = g * dyf
    else:
        # classic fused form: dx = g*(dy - mean(dy) - xhat*mean(dy*xhat))
        dx = g * (dyf - (dbeta / n).reshape(bshape)
                  - xhat * (dgamma / n).reshape(bshape))
    return {"GRAD::X": dx.astype(x.dtype),
            "GRAD::Scale": dgamma.astype(scale.dtype),
            "GRAD::Bias": dbeta.astype(scale.dtype)}


register_op(
    "batch_norm", ["X", "Scale", "Bias", "Mean", "Variance"],
    ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    infer=_bn_infer, compute=_bn_compute, grad=_bn_grad_maker,
    no_grad_inputs=("Mean", "Variance"),
)

def _bn_grad_infer(gop, block):
    x = in_var(gop, block, "X")
    scale = in_var(gop, block, "Scale")
    for slot, ref in (("GRAD::X", x), ("GRAD::Scale", scale),
                      ("GRAD::Bias", scale)):
        for name in gop.outputs.get(slot, []):
            if name:
                block.create_var(name=name, shape=ref.shape,
                                 dtype=ref.dtype, persistable=False)


register_op(
    "batch_norm_grad",
    ["X", "Scale", "Out::SavedMean", "Out::SavedVariance", "GRAD::Y"],
    ["GRAD::X", "GRAD::Scale", "GRAD::Bias"],
    infer=_bn_grad_infer, compute=_bn_grad_compute, grad=None,
)


# -- layer_norm -------------------------------------------------------------

def _ln_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("begin_norm_axis", 1)
    rows = x.shape[:axis]
    set_output(op, block, "Y", x.shape, x.dtype)
    set_output(op, block, "Mean", rows, x.dtype)
    set_output(op, block, "Variance", rows, x.dtype)


def _ln_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"][0] is not None \
        else None
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None \
        else None
    axis = attrs.get("begin_norm_axis", 1)
    eps = attrs.get("epsilon", 1e-5)
    if scale is not None and bias is not None and scale.ndim == 1:
        from ..flags import flag
        if flag("pallas_kernels"):
            # opt-in hand-tiled kernel (ops/pallas/layer_norm.py)
            from .pallas import interpret_mode, layer_norm as pln
            d = int(np.prod(x.shape[axis:]))
            flat = x.reshape(-1, d)
            y = pln.layer_norm(flat, scale.reshape(d), bias.reshape(d),
                               float(eps), interpret_mode(ctx))
            # Mean/Variance side outputs recomputed cheaply (fetch-only
            # parity outputs; XLA dead-code-eliminates them when unused)
            red = tuple(range(axis, x.ndim))
            mean = jnp.mean(x, axis=red)
            var = jnp.mean(jnp.square(
                x - mean.reshape(mean.shape + (1,) * (x.ndim - axis))),
                axis=red)
            return {"Y": y.reshape(x.shape), "Mean": mean,
                    "Variance": var}
    # statistics in fp32 regardless of activation dtype (AMP-gray op:
    # bf16 activations pass through; XLA fuses the casts into the
    # reduction/normalize chain)
    red = tuple(range(axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=red, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(
            (1,) * axis + x.shape[axis:])
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(
            (1,) * axis + x.shape[axis:])
    return {"Y": y.astype(x.dtype), "Mean": mean.reshape(x.shape[:axis]),
            "Variance": var.reshape(x.shape[:axis])}


register_op(
    "layer_norm", ["X", "Scale", "Bias"], ["Y", "Mean", "Variance"],
    infer=_ln_infer, compute=_ln_compute,
)


# -- group_norm (parity extension; reference gained it right after 0.15) ----

def _gn_infer(op, block):
    x = in_var(op, block, "X")
    g = op.attrs.get("groups", 1)
    set_output(op, block, "Y", x.shape, x.dtype)
    set_output(op, block, "Mean", (x.shape[0], g), x.dtype)
    set_output(op, block, "Variance", (x.shape[0], g), x.dtype)


def _gn_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale = ins["Scale"][0] if ins.get("Scale") and ins["Scale"][0] is not None \
        else None
    bias = ins["Bias"][0] if ins.get("Bias") and ins["Bias"][0] is not None \
        else None
    g = attrs.get("groups", 1)
    eps = attrs.get("epsilon", 1e-5)
    if attrs.get("data_layout", "NCHW") == "NHWC":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    red = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=red, keepdims=True)
    y = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if scale is not None:
        y = y * scale.reshape(bshape)
    if bias is not None:
        y = y + bias.reshape(bshape)
    if attrs.get("data_layout", "NCHW") == "NHWC":
        y = jnp.moveaxis(y, 1, -1)
    return {"Y": y, "Mean": mean.reshape(n, g), "Variance": var.reshape(n, g)}


register_op(
    "group_norm", ["X", "Scale", "Bias"], ["Y", "Mean", "Variance"],
    infer=_gn_infer, compute=_gn_compute,
)


# -- lrn (local response normalization across channels) ---------------------

def _lrn_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "MidOut", x.shape, x.dtype)


def _lrn_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]  # NCHW
    n = attrs.get("n", 5)
    k = attrs.get("k", 2.0)
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    half = n // 2
    sq = jnp.square(x)
    # sliding window sum over the channel axis
    window_sum = lax.reduce_window(
        sq, 0.0, lax.add, (1, n, 1, 1), (1, 1, 1, 1),
        [(0, 0), (half, n - 1 - half), (0, 0), (0, 0)],
    )
    mid = k + alpha * window_sum
    return {"Out": x * jnp.power(mid, -beta), "MidOut": mid}


register_op("lrn", ["X"], ["Out", "MidOut"],
            infer=_lrn_infer, compute=_lrn_compute)


# -- norm (L2 normalize along axis; norm_op.cc) -----------------------------

def _norm_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("axis", 1)
    nshape = list(x.shape)
    nshape[axis] = 1
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "Norm", tuple(nshape), x.dtype)


def _norm_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


register_op("norm", ["X"], ["Out", "Norm"],
            infer=_norm_infer, compute=_norm_compute)


# -- bilinear_interp (align_corners=True era semantics) ---------------------

def _interp_infer(op, block):
    x = in_var(op, block, "X")
    oh = op.attrs.get("out_h", -1)
    ow = op.attrs.get("out_w", -1)
    set_output(op, block, "Out", (x.shape[0], x.shape[1], oh, ow), x.dtype)


def _bilinear_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]  # NCHW
    if ins.get("OutSize") and ins["OutSize"][0] is not None:
        raise NotImplementedError(
            "dynamic OutSize needs static shapes under XLA; set out_h/out_w"
        )
    oh, ow = attrs["out_h"], attrs["out_w"]
    n, c, h, w = x.shape
    # align_corners=True ratios (reference bilinear_interp_op.cc at 0.15)
    rh = (h - 1.0) / (oh - 1.0) if oh > 1 else 0.0
    rw = (w - 1.0) / (ow - 1.0) if ow > 1 else 0.0
    ys = jnp.arange(oh) * rh
    xs = jnp.arange(ow) * rw
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0).astype(x.dtype)
    wx = (xs - x0).astype(x.dtype)
    top = x[:, :, y0, :][:, :, :, x0] * (1 - wx) + \
        x[:, :, y0, :][:, :, :, x1] * wx
    bot = x[:, :, y1, :][:, :, :, x0] * (1 - wx) + \
        x[:, :, y1, :][:, :, :, x1] * wx
    out = top * (1 - wy)[None, None, :, None] + bot * wy[None, None, :, None]
    return {"Out": out}


register_op("bilinear_interp", ["X", "OutSize"], ["Out"],
            infer=_interp_infer, compute=_bilinear_compute,
            no_grad_inputs=("OutSize",))


def _nearest_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    oh, ow = attrs["out_h"], attrs["out_w"]
    n, c, h, w = x.shape
    rh = (h - 1.0) / (oh - 1.0) if oh > 1 else 0.0
    rw = (w - 1.0) / (ow - 1.0) if ow > 1 else 0.0
    ys = jnp.round(jnp.arange(oh) * rh).astype(jnp.int32)
    xs = jnp.round(jnp.arange(ow) * rw).astype(jnp.int32)
    return {"Out": x[:, :, ys, :][:, :, :, xs]}


register_op("nearest_interp", ["X", "OutSize"], ["Out"],
            infer=_interp_infer, compute=_nearest_compute,
            no_grad_inputs=("OutSize",))
