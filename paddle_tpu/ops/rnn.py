"""Recurrent ops: dynamic_lstm, dynamic_lstmp, dynamic_gru, lstm_unit,
gru_unit.

Parity: reference ``lstm_op.cc`` / ``lstmp_op.cc`` / ``gru_op.cc`` /
``lstm_unit_op.cc`` / ``gru_unit_op.cc`` (+ ``math/lstm_compute``,
``math/gru_compute``, ``math/sequence2batch`` batch reordering) —
TPU-native: one ``lax.scan`` over the time axis of the padded batch; the
per-step compute is a single fused gate matmul on the MXU.  The
reference's LoD->batch reordering machinery (sequence2batch.cc) is
unnecessary: masking freezes finished sequences' carry instead.

Gate layouts follow the reference: LSTM projections are ``[B, T, 4H]``
with gate order (c, i, f, o) as documented in lstm_op.cc
(Weight = {W_ch, W_ih, W_fh, W_oh}, Bias = {b_c, b_i, b_f, b_o}); GRU is
``[B, T, 3H]`` with (u, r, c).  Peephole weights live in the 7H-wide Bias
(lstm_op.cc use_peepholes).
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var

__all__ = []

_ACT = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda v: v,
}


def _lstm_infer(op, block):
    x = in_var(op, block, "Input")     # [B, T, 4H]
    h = x.shape[2] // 4
    set_output(op, block, "Hidden", (x.shape[0], x.shape[1], h), x.dtype)
    set_output(op, block, "Cell", (x.shape[0], x.shape[1], h), x.dtype)


def _lstm_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]                      # [B, T, 4H] (x @ W_x + b_x)
    w = ins["Weight"][0]                     # [H, 4H] recurrent
    bias = ins["Bias"][0]                    # [1, 4H] or [1, 7H] peepholes
    length = ins["Length"][0]
    h0 = ins.get("H0", [None])[0]
    c0 = ins.get("C0", [None])[0]
    b, t, h4 = x.shape
    h = h4 // 4
    use_peep = attrs.get("use_peepholes", True) and bias.shape[-1] == 7 * h
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    reverse = attrs.get("is_reverse", False)

    gb = bias[..., :4 * h].reshape(4 * h)
    if use_peep:
        w_ic = bias[..., 4 * h:5 * h].reshape(h)
        w_fc = bias[..., 5 * h:6 * h].reshape(h)
        w_oc = bias[..., 6 * h:7 * h].reshape(h)

    xs = jnp.swapaxes(x, 0, 1)               # [T, B, 4H]
    if reverse:
        xs = xs[::-1]
    steps = jnp.arange(t)
    if reverse:
        steps = steps[::-1]

    # the recurrence follows the INPUT's precision: under AMP the
    # pre-projected x is bf16 while the gray lstm op's weight stays
    # fp32 master — casting w/bias down keeps the whole scan (gates,
    # [B,T,H] outputs, MXU steps) on the bf16 path instead of silently
    # promoting the carry to fp32 mid-scan (a scan dtype error)
    dt = x.dtype
    w = w.astype(dt)
    gb = gb.astype(dt)
    if use_peep:
        w_ic, w_fc, w_oc = (v.astype(dt) for v in (w_ic, w_fc, w_oc))
    h_prev0 = h0.astype(dt) if h0 is not None else jnp.zeros((b, h), dt)
    c_prev0 = c0.astype(dt) if c0 is not None else jnp.zeros((b, h), dt)

    def step(carry, inp):
        h_prev, c_prev = carry
        xt, tidx = inp
        gates = (xt + h_prev @ w + gb).astype(dt)
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            i = gate_act(gi + c_prev * w_ic)
            f = gate_act(gf + c_prev * w_fc)
        else:
            i = gate_act(gi)
            f = gate_act(gf)
        c = f * c_prev + i * cand_act(gc)
        if use_peep:
            o = gate_act(go + c * w_oc)
        else:
            o = gate_act(go)
        hh = o * cell_act(c)
        valid = (tidx < length)[:, None]
        c = jnp.where(valid, c, c_prev)
        hh_keep = jnp.where(valid, hh, 0)
        h_new = jnp.where(valid, hh, h_prev)
        return (h_new, c), (hh_keep, jnp.where(valid, c, 0))

    (_, _), (hs, cs) = lax.scan(step, (h_prev0, c_prev0), (xs, steps))
    if reverse:
        hs, cs = hs[::-1], cs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


register_op(
    "lstm", ["Input", "Weight", "Bias", "Length", "H0", "C0"],
    ["Hidden", "Cell"], infer=_lstm_infer, compute=_lstm_compute,
    no_grad_inputs=("Length",),
)


# -- dynamic_lstmp (lstm with projection, lstmp_op.cc) ----------------------

def _lstmp_infer(op, block):
    x = in_var(op, block, "Input")
    w_proj = in_var(op, block, "ProjWeight")  # [H, P]
    p = w_proj.shape[1]
    h = x.shape[2] // 4
    set_output(op, block, "Projection", (x.shape[0], x.shape[1], p), x.dtype)
    set_output(op, block, "Cell", (x.shape[0], x.shape[1], h), x.dtype)


def _lstmp_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]
    w = ins["Weight"][0]                     # [P, 4H]
    w_proj = ins["ProjWeight"][0]            # [H, P]
    bias = ins["Bias"][0]
    length = ins["Length"][0]
    b, t, h4 = x.shape
    h = h4 // 4
    p = w_proj.shape[1]
    use_peep = attrs.get("use_peepholes", True) and bias.shape[-1] == 7 * h
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cell_act = _ACT[attrs.get("cell_activation", "tanh")]
    cand_act = _ACT[attrs.get("candidate_activation", "tanh")]
    proj_act = _ACT[attrs.get("proj_activation", "tanh")]
    reverse = attrs.get("is_reverse", False)

    gb = bias[..., :4 * h].reshape(4 * h)
    if use_peep:
        w_ic = bias[..., 4 * h:5 * h].reshape(h)
        w_fc = bias[..., 5 * h:6 * h].reshape(h)
        w_oc = bias[..., 6 * h:7 * h].reshape(h)

    xs = jnp.swapaxes(x, 0, 1)
    steps = jnp.arange(t)
    if reverse:
        xs, steps = xs[::-1], steps[::-1]

    def step(carry, inp):
        r_prev, c_prev = carry
        xt, tidx = inp
        gates = (xt + r_prev @ w + gb).astype(dt)
        gc, gi, gf, go = jnp.split(gates, 4, axis=-1)
        if use_peep:
            i = gate_act(gi + c_prev * w_ic)
            f = gate_act(gf + c_prev * w_fc)
        else:
            i, f = gate_act(gi), gate_act(gf)
        c = f * c_prev + i * cand_act(gc)
        o = gate_act(go + c * w_oc) if use_peep else gate_act(go)
        hh = o * cell_act(c)
        r = proj_act(hh @ w_proj)
        valid = (tidx < length)[:, None]
        c = jnp.where(valid, c, c_prev)
        r_new = jnp.where(valid, r, r_prev)
        return (r_new, c), (jnp.where(valid, r, 0), jnp.where(valid, c, 0))

    dt = x.dtype
    w = w.astype(dt)
    w_proj = w_proj.astype(dt)
    gb = gb.astype(dt)
    if use_peep:
        w_ic, w_fc, w_oc = (v.astype(dt) for v in (w_ic, w_fc, w_oc))
    init = (jnp.zeros((b, p), dt), jnp.zeros((b, h), dt))
    _, (rs, cs) = lax.scan(step, init, (xs, steps))
    if reverse:
        rs, cs = rs[::-1], cs[::-1]
    return {"Projection": jnp.swapaxes(rs, 0, 1),
            "Cell": jnp.swapaxes(cs, 0, 1)}


register_op(
    "lstmp", ["Input", "Weight", "ProjWeight", "Bias", "Length"],
    ["Projection", "Cell"], infer=_lstmp_infer, compute=_lstmp_compute,
    no_grad_inputs=("Length",),
)


# -- dynamic_gru (gru_op.cc) ------------------------------------------------

def _gru_infer(op, block):
    x = in_var(op, block, "Input")     # [B, T, 3H]
    h = x.shape[2] // 3
    set_output(op, block, "Hidden", (x.shape[0], x.shape[1], h), x.dtype)


def _gru_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]                     # [B, T, 3H] = x@W_x + b
    w = ins["Weight"][0]                    # [H, 3H]: [W_u, W_r | W_c]
    length = ins["Length"][0]
    h0 = ins.get("H0", [None])[0]
    b, t, h3 = x.shape
    h = h3 // 3
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    reverse = attrs.get("is_reverse", False)
    w_g = w[:, :2 * h]                      # update+reset recurrent
    w_c = w[:, 2 * h:]                      # candidate recurrent

    xs = jnp.swapaxes(x, 0, 1)
    if reverse:
        xs = xs[::-1]
    steps = jnp.arange(t)
    if reverse:
        steps = steps[::-1]
    dt = x.dtype
    w_g = w_g.astype(dt)
    w_c = w_c.astype(dt)
    h_prev0 = h0.astype(dt) if h0 is not None else jnp.zeros((b, h), dt)

    def step(h_prev, inp):
        xt, tidx = inp
        xg, xc = xt[:, :2 * h], xt[:, 2 * h:]
        g = gate_act(xg + h_prev @ w_g)
        u, r = g[:, :h], g[:, h:]
        c = cand_act(xc + (r * h_prev) @ w_c)
        # reference gru kernel (math/detail/gru_kernel.h:62):
        # h = (1 - u) * h_prev + u * c
        hh = ((1.0 - u) * h_prev + u * c).astype(dt)
        valid = (tidx < length)[:, None]
        h_new = jnp.where(valid, hh, h_prev)
        return h_new, jnp.where(valid, hh, 0)

    _, hs = lax.scan(step, h_prev0, (xs, steps))
    if reverse:
        hs = hs[::-1]
    return {"Hidden": jnp.swapaxes(hs, 0, 1)}


register_op(
    "gru", ["Input", "Weight", "Length", "H0"], ["Hidden"],
    infer=_gru_infer, compute=_gru_compute, no_grad_inputs=("Length",),
)


# -- single-step units (lstm_unit_op.cc / gru_unit_op.cc) -------------------

def _lstm_unit_infer(op, block):
    x = in_var(op, block, "X")         # [B, 4H]
    h = x.shape[-1] // 4
    set_output(op, block, "H", (x.shape[0], h), x.dtype)
    set_output(op, block, "C", (x.shape[0], h), x.dtype)


def _lstm_unit_compute(ins, attrs, ctx, op_index):
    x, c_prev = ins["X"][0], ins["C_prev"][0]
    forget_bias = attrs.get("forget_bias", 0.0)
    h = x.shape[-1] // 4
    gi, gc, gf, go = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    c = f * c_prev + i * jnp.tanh(gc)
    o = jax.nn.sigmoid(go)
    return {"H": o * jnp.tanh(c), "C": c}


register_op("lstm_unit", ["X", "C_prev"], ["H", "C"],
            infer=_lstm_unit_infer, compute=_lstm_unit_compute)


def _gru_unit_infer(op, block):
    x = in_var(op, block, "Input")     # [B, 3H]
    h = x.shape[-1] // 3
    set_output(op, block, "Hidden", (x.shape[0], h), x.dtype)
    set_output(op, block, "Gate", (x.shape[0], 3 * h), x.dtype)
    set_output(op, block, "ResetHiddenPrev", (x.shape[0], h), x.dtype)


def _gru_unit_compute(ins, attrs, ctx, op_index):
    x, h_prev, w = ins["Input"][0], ins["HiddenPrev"][0], ins["Weight"][0]
    bias = ins.get("Bias", [None])[0]
    h = x.shape[-1] // 3
    if bias is not None:
        x = x + bias.reshape(-1)
    gate_act = _ACT[attrs.get("gate_activation", "sigmoid")]
    cand_act = _ACT[attrs.get("activation", "tanh")]
    xg, xc = x[:, :2 * h], x[:, 2 * h:]
    g = gate_act(xg + h_prev @ w[:, :2 * h])
    u, r = g[:, :h], g[:, h:]
    rhp = r * h_prev
    c = cand_act(xc + rhp @ w[:, 2 * h:])
    # gru_unit_op.h:116: h = (1 - u) * h_prev + u * c
    hh = (1.0 - u) * h_prev + u * c
    return {"Hidden": hh, "Gate": jnp.concatenate([g, c], axis=-1),
            "ResetHiddenPrev": rhp}


register_op("gru_unit", ["Input", "HiddenPrev", "Weight", "Bias"],
            ["Hidden", "Gate", "ResetHiddenPrev"],
            infer=_gru_unit_infer, compute=_gru_unit_compute)
