"""Loss ops.

Parity: reference ``cross_entropy_op.cc``,
``softmax_with_cross_entropy_op.cc`` (the fused hot op named in the north
star), ``sigmoid_cross_entropy_with_logits_op.cc``, ``huber_loss_op.cc``,
``smooth_l1_loss_op.cc``, ``hinge_loss_op.cc``, ``log_loss_op.cc``,
``rank_loss_op.cc``, ``margin_rank_loss_op.cc`` — TPU-native: the fused
softmax+CE is written as logsumexp-based log-softmax so its vjp is exactly
the numerically-stable ``softmax - onehot`` kernel the reference hand-writes.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register_op, set_output, in_var, same_shape_infer


def _rowwise_out_infer(op, block, x_slot="X"):
    x = in_var(op, block, x_slot)
    set_output(op, block, "Out" if "Out" in op.outputs else "Loss",
               tuple(x.shape[:-1]) + (1,), x.dtype)


# -- cross_entropy (takes probabilities; cross_entropy_op.cc) ---------------

def _cross_entropy_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Y", tuple(x.shape[:-1]) + (1,), x.dtype)


def _cross_entropy_compute(ins, attrs, ctx, op_index):
    x, label = ins["X"][0], ins["Label"][0]
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * jnp.log(x), axis=-1, keepdims=True)
    else:
        idx = label.reshape(label.shape[:-1] + (1,)) if label.shape[-1] == 1 \
            else label[..., None]
        picked = jnp.take_along_axis(x, idx.astype(jnp.int32), axis=-1)
        loss = -jnp.log(picked)
        loss = loss.reshape(x.shape[:-1] + (1,))
    return {"Y": loss}


register_op(
    "cross_entropy", ["X", "Label"], ["Y"], infer=_cross_entropy_infer,
    compute=_cross_entropy_compute, no_grad_inputs=("Label",),
)


# -- softmax_with_cross_entropy (fused; the hot op) -------------------------

def _swce_infer(op, block):
    logits = in_var(op, block, "Logits")
    set_output(op, block, "Softmax", logits.shape, logits.dtype)
    set_output(op, block, "Loss", tuple(logits.shape[:-1]) + (1,), logits.dtype)


def _swce_compute(ins, attrs, ctx, op_index):
    logits, label = ins["Logits"][0], ins["Label"][0]
    eps = float(attrs.get("label_smooth_eps", 0.0))
    if not attrs.get("soft_label", False) and \
            attrs.get("ignore_index", -100) == -100:
        # hand-tiled kernel covers both the plain and the fused
        # label-smoothing loss (ops/pallas/softmax_xent.py); no ignore
        # mask there (-100 sentinel = none, matching the sigmoid variant)
        from ..flags import flag
        if flag("pallas_kernels"):
            from .pallas import interpret_mode, softmax_xent as px
            flat = logits.reshape(-1, logits.shape[-1])
            lbl = label.reshape(-1)
            loss, softmax = px.softmax_xent(flat, lbl, interpret_mode(ctx),
                                            eps)
            return {"Softmax": softmax.reshape(logits.shape),
                    "Loss": loss.reshape(logits.shape[:-1] + (1,))}
    if eps and not attrs.get("soft_label", False):
        # fused uniform label smoothing: target = (1-eps)*onehot + eps/C;
        # loss = (1-eps)*nll + eps*(lse - mean(logits)).  Keeps the [N, C]
        # soft-label tensor out of HBM (vs one_hot + label_smooth +
        # soft_label CE, which materializes it three times).
        lse = jax.scipy.special.logsumexp(logits, axis=-1, keepdims=True)
        log_sm = logits - lse
        idx = label if label.shape[-1] == 1 else label[..., None]
        picked = jnp.take_along_axis(log_sm, idx.astype(jnp.int32), axis=-1)
        uniform = lse[..., 0:1] - jnp.mean(logits, axis=-1, keepdims=True)
        loss = (1.0 - eps) * -picked + eps * uniform
        ignore = attrs.get("ignore_index", -100)
        if ignore != -100:
            loss = jnp.where(idx == ignore, 0.0, loss)
        return {"Softmax": jnp.exp(log_sm), "Loss": loss}
    log_sm = jax.nn.log_softmax(logits, axis=-1)
    softmax = jnp.exp(log_sm)
    if attrs.get("soft_label", False):
        loss = -jnp.sum(label * log_sm, axis=-1, keepdims=True)
    else:
        idx = label if label.shape[-1] == 1 else label[..., None]
        picked = jnp.take_along_axis(log_sm, idx.astype(jnp.int32), axis=-1)
        ignore = attrs.get("ignore_index", -100)
        loss = -picked
        if ignore != -100:
            # any index (including negative ones like -1) may be ignored;
            # -100 is the "none" sentinel (matches the sigmoid variant).
            # Negative ignored labels wrap through take_along_axis but the
            # picked value is discarded by this mask, so the loss is exact.
            loss = jnp.where(idx == ignore, 0.0, loss)
    return {"Softmax": softmax, "Loss": loss}


register_op(
    "softmax_with_cross_entropy", ["Logits", "Label"], ["Softmax", "Loss"],
    infer=_swce_infer, compute=_swce_compute, no_grad_inputs=("Label",),
)


# -- sigmoid_cross_entropy_with_logits --------------------------------------

def _scewl_compute(ins, attrs, ctx, op_index):
    x, label = ins["X"][0], ins["Label"][0]
    # stable: max(x,0) - x*z + log(1+exp(-|x|))
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ignore = attrs.get("ignore_index", -100)
    if ignore != -100:
        loss = jnp.where(label == ignore, 0.0, loss)
    return {"Out": loss}


register_op(
    "sigmoid_cross_entropy_with_logits", ["X", "Label"], ["Out"],
    infer=same_shape_infer("X", "Out"), compute=_scewl_compute,
    no_grad_inputs=("Label",),
)


# -- huber / smooth_l1 / hinge / log_loss / rank losses ---------------------

def _huber_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Residual", x.shape, x.dtype)
    set_output(op, block, "Out", x.shape, x.dtype)


def _huber_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    d = attrs.get("delta", 1.0)
    r = y - x
    loss = jnp.where(jnp.abs(r) <= d, 0.5 * r * r, d * (jnp.abs(r) - 0.5 * d))
    return {"Residual": r, "Out": loss}


register_op("huber_loss", ["X", "Y"], ["Residual", "Out"],
            infer=_huber_infer, compute=_huber_compute,
            no_grad_inputs=("Y",))


def _smooth_l1_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Diff", x.shape, x.dtype)
    set_output(op, block, "Out", (x.shape[0], 1), x.dtype)


def _smooth_l1_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    sigma = attrs.get("sigma", 1.0)
    s2 = sigma * sigma
    diff = x - y
    if ins.get("InsideWeight") and ins["InsideWeight"][0] is not None:
        diff = diff * ins["InsideWeight"][0]
    ad = jnp.abs(diff)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * diff * diff, ad - 0.5 / s2)
    if ins.get("OutsideWeight") and ins["OutsideWeight"][0] is not None:
        loss = loss * ins["OutsideWeight"][0]
    out = jnp.sum(loss.reshape(x.shape[0], -1), axis=1, keepdims=True)
    return {"Diff": diff, "Out": out}


register_op(
    "smooth_l1_loss", ["X", "Y", "InsideWeight", "OutsideWeight"],
    ["Diff", "Out"], infer=_smooth_l1_infer, compute=_smooth_l1_compute,
    no_grad_inputs=("Y", "InsideWeight", "OutsideWeight"),
)


def _hinge_compute(ins, attrs, ctx, op_index):
    logits, labels = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": jnp.maximum(1.0 - (2.0 * labels - 1.0) * logits, 0.0)}


register_op("hinge_loss", ["Logits", "Labels"], ["Loss"],
            infer=lambda op, block: set_output(
                op, block, "Loss", in_var(op, block, "Logits").shape,
                in_var(op, block, "Logits").dtype),
            compute=_hinge_compute, no_grad_inputs=("Labels",))


def _log_loss_compute(ins, attrs, ctx, op_index):
    p, label = ins["Predicted"][0], ins["Labels"][0]
    eps = attrs.get("epsilon", 1e-4)
    loss = -label * jnp.log(p + eps) - (1 - label) * jnp.log(1 - p + eps)
    return {"Loss": loss}


register_op("log_loss", ["Predicted", "Labels"], ["Loss"],
            infer=lambda op, block: set_output(
                op, block, "Loss", in_var(op, block, "Predicted").shape,
                in_var(op, block, "Predicted").dtype),
            compute=_log_loss_compute, no_grad_inputs=("Labels",))


def _rank_loss_compute(ins, attrs, ctx, op_index):
    label, left, right = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = left - right
    return {"Out": jnp.log1p(jnp.exp(d)) - label * d}


register_op("rank_loss", ["Label", "Left", "Right"], ["Out"],
            infer=lambda op, block: set_output(
                op, block, "Out", in_var(op, block, "Left").shape,
                in_var(op, block, "Left").dtype),
            compute=_rank_loss_compute, no_grad_inputs=("Label",))


def _margin_rank_loss_compute(ins, attrs, ctx, op_index):
    label, x1, x2 = ins["Label"][0], ins["X1"][0], ins["X2"][0]
    margin = attrs.get("margin", 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    act = (out > 0).astype(x1.dtype)
    return {"Out": out, "Activated": act}


register_op(
    "margin_rank_loss", ["Label", "X1", "X2"], ["Out", "Activated"],
    infer=lambda op, block: (
        set_output(op, block, "Out", in_var(op, block, "X1").shape,
                   in_var(op, block, "X1").dtype),
        set_output(op, block, "Activated", in_var(op, block, "X1").shape,
                   in_var(op, block, "X1").dtype),
    ),
    compute=_margin_rank_loss_compute, no_grad_inputs=("Label",),
)


# -- modified_huber_loss (reference modified_huber_loss_op.cc) --------------

def _mhl_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "IntermediateVal", x.shape, x.dtype)
    set_output(op, block, "Out", x.shape, x.dtype)


def _mhl_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]  # y in {0, 1}
    inter = x * (2.0 * y - 1.0)      # x * y' with y' in {-1, +1}
    loss = jnp.where(inter < -1.0, -4.0 * inter,
                     jnp.where(inter < 1.0, (1.0 - inter) ** 2, 0.0))
    return {"IntermediateVal": inter, "Out": loss}


register_op("modified_huber_loss", ["X", "Y"], ["IntermediateVal", "Out"],
            infer=_mhl_infer, compute=_mhl_compute, no_grad_inputs=("Y",))


# -- lambda_cost: LambdaRank listwise cost (v1 legacy LambdaCost layer,
# reference legacy/gserver/layers/CostLayer.cpp LambdaCost) --------------

def _lambda_cost_infer(op, block):
    x = in_var(op, block, "Score")
    set_output(op, block, "Out", (x.shape[0], 1), x.dtype)


def _lambda_cost_compute(ins, attrs, ctx, op_index):
    """Per-list LambdaRank: for each document pair (i, j) with
    rel_i > rel_j, loss += |deltaNDCG_ij| * log(1 + exp(-(s_i - s_j))).
    Scores/relevances are padded [B, T, 1]; Length masks the pad.
    deltaNDCG swaps positions i,j in the DCG of the model's ranking,
    normalized by the ideal DCG over the top ``ndcg_num``."""
    score = ins["Score"][0].reshape(ins["Score"][0].shape[0], -1)
    rel = ins["Rel"][0].reshape(score.shape).astype(score.dtype)
    length = ins.get("Length", [None])[0]
    b, t = score.shape
    ndcg_num = int(attrs.get("ndcg_num", 5))
    pos = jnp.arange(t)
    valid = (jnp.ones((b, t), bool) if length is None
             else pos[None, :] < length.reshape(b, 1))
    neg_inf = jnp.asarray(-1e9, score.dtype)
    s = jnp.where(valid, score, neg_inf)
    r = jnp.where(valid, rel, 0.0)

    # rank of each doc under the model scores (0 = best)
    order = jnp.argsort(-s, axis=1)
    rank = jnp.argsort(order, axis=1)
    disc = 1.0 / jnp.log2(2.0 + rank.astype(score.dtype))   # [B, T]
    gain = (2.0 ** r - 1.0)
    # ideal DCG over the top ndcg_num of the TRUE relevances
    r_sorted = -jnp.sort(-r, axis=1)
    ideal_disc = 1.0 / jnp.log2(2.0 + jnp.arange(t, dtype=score.dtype))
    topk_mask = (jnp.arange(t) < ndcg_num).astype(score.dtype)
    idcg = jnp.sum((2.0 ** r_sorted - 1.0) * ideal_disc * topk_mask,
                   axis=1, keepdims=True)
    idcg = jnp.maximum(idcg, 1e-8)

    # |deltaNDCG| of swapping i and j = |g_i - g_j| * |d_i - d_j| / idcg
    dg = jnp.abs(gain[:, :, None] - gain[:, None, :])
    dd = jnp.abs(disc[:, :, None] - disc[:, None, :])
    delta = dg * dd / idcg[:, :, None]

    diff = score[:, :, None] - score[:, None, :]
    pair_loss = jnp.log1p(jnp.exp(-jnp.clip(diff, -30.0, 30.0)))
    better = (rel[:, :, None] > rel[:, None, :]) & \
        valid[:, :, None] & valid[:, None, :]
    out = jnp.sum(jnp.where(better, delta * pair_loss, 0.0), axis=(1, 2))
    return {"Out": out.reshape(b, 1)}


register_op("lambda_cost", ["Score", "Rel", "Length"], ["Out"],
            infer=_lambda_cost_infer, compute=_lambda_cost_compute,
            no_grad_inputs=("Rel", "Length"))
