"""Fake quantization ops for quantization-aware training.

Parity: reference ``operators/fake_quantize_op.cc`` (fake_quantize_abs_max,
fake_quantize_range_abs_max) and ``operators/fake_dequantize_op.cc``
(fake_dequantize_max_abs).  Quantize-dequantize in one op ("fake"): the
tensor stays float but carries int8-grid rounding error, so training
learns quantization-robust weights.

TPU-first notes: gradients use the straight-through estimator (identity
through the rounding), implemented as a custom grad instead of the
reference's GradOpDescMaker pair; the range_abs_max sliding window
collapses to a running max state var (window bookkeeping is host-side
bookkeeping the XLA graph does not need — the max over the window is
what the quantizer consumes).
"""

import numpy as np

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var
from ..framework import grad_var_name

__all__ = []


def _quant_range(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


def _abs_max_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "OutScale", (1,), x.dtype)


def _abs_max_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    rng = _quant_range(attrs.get("bit_length", 8))
    scale = jnp.max(jnp.abs(x)).reshape(1)
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(x / scale * rng)
    q = jnp.clip(q, -rng, rng)
    return {"Out": q * scale / rng, "OutScale": scale}


def _ste_grad_infer(op, block):
    g = in_var(op, block, "GRAD::Out")
    set_output(op, block, "GRAD::X", g.shape, g.dtype)


register_op(
    "ste_identity_grad", ["GRAD::Out"], ["GRAD::X"],
    infer=_ste_grad_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "GRAD::X": ins["GRAD::Out"][0]},
    grad=None,
)


def _quant_grad_maker(op, no_grad_set):
    """Straight-through estimator: dL/dX = dL/dOut (identity through
    the rounding), the standard QAT gradient."""
    x_name = op.inputs["X"][0]
    if x_name in no_grad_set:
        return []
    out_name = op.outputs["Out"][0]
    return [{
        "type": "ste_identity_grad",
        "inputs": {"GRAD::Out": [grad_var_name(out_name)]},
        "outputs": {"GRAD::X": [grad_var_name(x_name)]},
        "attrs": {},
    }]


register_op(
    "fake_quantize_abs_max", ["X"], ["Out", "OutScale"],
    infer=_abs_max_infer, compute=_abs_max_compute,
    grad=_quant_grad_maker,
)


def _range_abs_max_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "OutScale", (1,), x.dtype)


def _range_abs_max_compute(ins, attrs, ctx, op_index):
    """Running-max variant: in training the scale is
    max(current |x|_max, InScale) — the monotone envelope of the
    reference's window max; in test mode InScale is used as-is."""
    x = ins["X"][0]
    in_scales = ins.get("InScale")
    in_scale = in_scales[0] if in_scales and in_scales[0] is not None \
        else jnp.zeros((1,), x.dtype)
    rng = _quant_range(attrs.get("bit_length", 8))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = jnp.maximum(in_scale.reshape(1), 1e-12)
    else:
        cur = jnp.max(jnp.abs(x)).reshape(1)
        scale = jnp.maximum(jnp.maximum(cur, in_scale.reshape(1)), 1e-12)
    q = jnp.clip(jnp.round(x / scale * rng), -rng, rng)
    return {"Out": q * scale / rng, "OutScale": scale}


register_op(
    "fake_quantize_range_abs_max", ["X", "InScale"], ["Out", "OutScale"],
    infer=_range_abs_max_infer, compute=_range_abs_max_compute,
    grad=_quant_grad_maker, no_grad_inputs=("InScale",),
)


def _dequant_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _dequant_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale = ins["Scale"][0]
    max_range = float(attrs["max_range"])
    return {"Out": x * scale.reshape(()) / max_range}


register_op(
    "fake_dequantize_max_abs", ["X", "Scale"], ["Out"],
    infer=_dequant_infer, compute=_dequant_compute,
    no_grad_inputs=("Scale",),
)
