"""Fake quantization ops (QAT) and real int8 execution.

Parity: reference ``operators/fake_quantize_op.cc`` (fake_quantize_abs_max,
fake_quantize_range_abs_max) and ``operators/fake_dequantize_op.cc``
(fake_dequantize_max_abs).  Quantize-dequantize in one op ("fake"): the
tensor stays float but carries int8-grid rounding error, so training
learns quantization-robust weights.

TPU-first notes: gradients use the straight-through estimator (identity
through the rounding), implemented as a custom grad instead of the
reference's GradOpDescMaker pair; the range_abs_max sliding window
collapses to a running max state var (window bookkeeping is host-side
bookkeeping the XLA graph does not need — the max over the window is
what the quantizer consumes).

Real execution (ISSUE 14): ``dequant_matmul`` is the inference-side op
the ``quantize_inference`` program pass rewrites matmul/mul/FC weights
into — int8 weights with per-output-channel dequant scales, executed as
a fused dequant-matmul.  Two modes:

* ``weight_only`` — weights dequantize into the f32 accumulator feeding
  the dot (int8 values are exact in f32); activations keep their dtype.
* ``dynamic`` — activations additionally quantize to int8 (per-row
  abs-max grid, or a trained QAT ``XScale`` when the pass found one) and
  the dot runs int8 x int8 with an int32 accumulator.

The kernel per shape is the Pallas fused kernel
(``ops/pallas/quant_matmul.py``) or the XLA ``dot_general`` fallback,
chosen like ``fused_attention`` chooses: a tuned per-shape ruling in the
autotune decision table wins unless the operator pinned
``FLAGS_pallas_kernels``.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var
from ..framework import grad_var_name
from .math import _flatten_to_2d

__all__ = []


def _quant_range(bit_length):
    return float((1 << (int(bit_length) - 1)) - 1)


def _abs_max_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    axis = op.attrs.get("quant_axis", -1)
    scale_shape = (x.shape[axis],) if axis is not None and axis >= 0 \
        else (1,)
    set_output(op, block, "OutScale", scale_shape, x.dtype)


def _abs_max_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    rng = _quant_range(attrs.get("bit_length", 8))
    axis = attrs.get("quant_axis", -1)
    if axis is not None and axis >= 0:
        # per-channel grid along ``axis`` (conv filters axis 0, fc/mul
        # weights their output axis): one abs-max per channel, so a wide
        # FC layer's small-magnitude columns stop being over-clipped by
        # the single per-tensor max — the same grid the inference-side
        # quantize_inference pass deploys
        red = tuple(i for i in range(x.ndim) if i != axis)
        scale = jnp.max(jnp.abs(x), axis=red)
        scale = jnp.maximum(scale, 1e-12)
        bshape = [1] * x.ndim
        bshape[axis] = scale.shape[0]
        sb = scale.reshape(bshape)
        q = jnp.clip(jnp.round(x / sb * rng), -rng, rng)
        return {"Out": q * sb / rng, "OutScale": scale}
    scale = jnp.max(jnp.abs(x)).reshape(1)
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.round(x / scale * rng)
    q = jnp.clip(q, -rng, rng)
    return {"Out": q * scale / rng, "OutScale": scale}


def _ste_grad_infer(op, block):
    g = in_var(op, block, "GRAD::Out")
    set_output(op, block, "GRAD::X", g.shape, g.dtype)


register_op(
    "ste_identity_grad", ["GRAD::Out"], ["GRAD::X"],
    infer=_ste_grad_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "GRAD::X": ins["GRAD::Out"][0]},
    grad=None,
)


def _quant_grad_maker(op, no_grad_set):
    """Straight-through estimator: dL/dX = dL/dOut (identity through
    the rounding), the standard QAT gradient."""
    x_name = op.inputs["X"][0]
    if x_name in no_grad_set:
        return []
    out_name = op.outputs["Out"][0]
    return [{
        "type": "ste_identity_grad",
        "inputs": {"GRAD::Out": [grad_var_name(out_name)]},
        "outputs": {"GRAD::X": [grad_var_name(x_name)]},
        "attrs": {},
    }]


register_op(
    "fake_quantize_abs_max", ["X"], ["Out", "OutScale"],
    infer=_abs_max_infer, compute=_abs_max_compute,
    grad=_quant_grad_maker,
)


def _range_abs_max_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "OutScale", (1,), x.dtype)


def _range_abs_max_compute(ins, attrs, ctx, op_index):
    """Running-max variant: in training the scale is
    max(current |x|_max, InScale) — the monotone envelope of the
    reference's window max; in test mode InScale is used as-is."""
    x = ins["X"][0]
    in_scales = ins.get("InScale")
    in_scale = in_scales[0] if in_scales and in_scales[0] is not None \
        else jnp.zeros((1,), x.dtype)
    rng = _quant_range(attrs.get("bit_length", 8))
    if attrs.get("is_test", False) or ctx.is_test:
        scale = jnp.maximum(in_scale.reshape(1), 1e-12)
    else:
        cur = jnp.max(jnp.abs(x)).reshape(1)
        scale = jnp.maximum(jnp.maximum(cur, in_scale.reshape(1)), 1e-12)
    q = jnp.clip(jnp.round(x / scale * rng), -rng, rng)
    return {"Out": q * scale / rng, "OutScale": scale}


register_op(
    "fake_quantize_range_abs_max", ["X", "InScale"], ["Out", "OutScale"],
    infer=_range_abs_max_infer, compute=_range_abs_max_compute,
    grad=_quant_grad_maker, no_grad_inputs=("InScale",),
)


def _dequant_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _dequant_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    scale = ins["Scale"][0]
    max_range = float(attrs["max_range"])
    return {"Out": x * scale.reshape(()) / max_range}


register_op(
    "fake_dequantize_max_abs", ["X", "Scale"], ["Out"],
    infer=_dequant_infer, compute=_dequant_compute,
    no_grad_inputs=("Scale",),
)


# ---------------------------------------------------------------------------
# real int8 execution: fused dequant-matmul (ISSUE 14)
# ---------------------------------------------------------------------------

def xla_dequant_matmul(x2, qw, scale, mode="weight_only", xscale=None,
                       bit_length=8):
    """XLA fallback for the fused dequant-matmul: ``x2`` [M, K] float,
    ``qw`` [K, N] int8, ``scale`` [N] f32 dequant multipliers
    (``w ~= qw * scale``).  ``weight_only`` dequantizes into the f32
    accumulator (int8 values are exact in f32; one GEMM, scale applied
    per output channel); ``dynamic`` quantizes activations to int8 too
    (per-row abs-max grid, or the trained ``xscale`` envelope when QAT
    calibration exists) and accumulates the int8 x int8 dot in int32
    via ``preferred_element_type``."""
    scale = scale.astype(jnp.float32)
    if mode == "weight_only":
        acc = jnp.matmul(x2.astype(jnp.float32), qw.astype(jnp.float32),
                         preferred_element_type=jnp.float32)
        return acc * scale
    if mode != "dynamic":
        raise ValueError("unknown dequant_matmul mode %r" % mode)
    rng = _quant_range(bit_length)
    xf = x2.astype(jnp.float32)
    if xscale is not None:
        # trained QAT running abs-max envelope -> static activation grid
        sx = jnp.maximum(xscale.astype(jnp.float32).reshape(()),
                         1e-12) / rng
    else:
        sx = jnp.maximum(jnp.max(jnp.abs(xf), axis=1, keepdims=True),
                         1e-12) / rng
    qx = jnp.clip(jnp.round(xf / sx), -rng, rng).astype(jnp.int8)
    acc = lax.dot_general(qx, qw, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * sx * scale


def _dequant_matmul_infer(op, block):
    x = in_var(op, block, "X")
    qw = in_var(op, block, "QWeight")
    xnc = op.attrs.get("x_num_col_dims", 1)
    out_shape = tuple(x.shape[:xnc]) + (qw.shape[-1],)
    set_output(op, block, "Out", out_shape, x.dtype)


def _dequant_matmul_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    qw = ins["QWeight"][0]
    scale = ins["Scale"][0]
    xscales = ins.get("XScale")
    xscale = xscales[0] if xscales else None
    xnc = attrs.get("x_num_col_dims", 1)
    mode = attrs.get("mode", "weight_only")
    bits = attrs.get("bit_length", 8)
    x2 = _flatten_to_2d(x, xnc)
    m, k = x2.shape
    n = qw.shape[-1]

    from .. import autotune
    from ..flags import flag
    from .pallas import interpret_mode
    from .pallas import quant_matmul as qm

    # kernel selection mirrors fused_attention: a tuned per-shape ruling
    # from the autotune decision table wins, unless the operator PINNED
    # FLAGS_pallas_kernels (then quant_kernel_choice returns None and
    # the flag rules); supported() still gates either way
    choice = autotune.quant_kernel_choice(m, k, n, x.dtype, mode)
    use_pallas = flag("pallas_kernels") if choice is None else choice
    if use_pallas and xscale is None and qm.supported(m, k, n, x.dtype):
        acc = qm.dequant_matmul(x2, qw, scale, mode=mode,
                                bit_length=bits,
                                interpret=interpret_mode(ctx))
    else:
        acc = xla_dequant_matmul(x2, qw, scale, mode=mode, xscale=xscale,
                                 bit_length=bits)
    out = acc.astype(x.dtype).reshape(tuple(x.shape[:xnc]) + (n,))
    return {"Out": out}


register_op(
    "dequant_matmul", ["X", "QWeight", "Scale", "XScale"], ["Out"],
    infer=_dequant_matmul_infer, compute=_dequant_matmul_compute,
    grad=None, no_grad_inputs=("QWeight", "Scale", "XScale"),
)
