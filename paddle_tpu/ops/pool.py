"""Pooling ops: pool2d, pool3d (max/avg, global, adaptive, ceil_mode).

Parity: reference ``paddle/fluid/operators/pool_op.cc`` (+
``pool_cudnn_op.cu.cc``, ``math/pooling.{cc,cu}``), ``spp_op.cc`` — the
TPU-native kernel is one ``lax.reduce_window`` (XLA pools natively; the
avg-pool ``exclusive`` mode divides by a second reduce_window over ones,
matching the reference's exclude-padding counting).  Gradients come from
auto-vjp (XLA emits select-and-scatter for max pool).
"""

import numpy as np
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var, int_list

__all__ = []



def _pool_out_dim(in_size, k, pad, stride, ceil_mode):
    if in_size is None or in_size < 0:
        return -1
    if ceil_mode:
        return -(-(in_size + 2 * pad - k) // stride) + 1
    return (in_size + 2 * pad - k) // stride + 1


def _pool_infer_nd(nd):
    def infer(op, block):
        x = in_var(op, block, "X")
        attrs = op.attrs
        nhwc = attrs.get("data_format", "NCHW") == "NHWC" and nd == 2
        sp0 = 1 if nhwc else 2
        if attrs.get("global_pooling", False):
            spatial = [1] * nd
        elif attrs.get("adaptive", False):
            spatial = int_list(attrs.get("ksize"), nd)
        else:
            ks = int_list(attrs.get("ksize"), nd)
            strides = int_list(attrs.get("strides", 1), nd)
            pads = int_list(attrs.get("paddings", 0), nd)
            ceil = attrs.get("ceil_mode", False)
            spatial = [
                _pool_out_dim(x.shape[sp0 + i], ks[i], pads[i], strides[i],
                              ceil)
                for i in range(nd)
            ]
        if nhwc:
            shape = (x.shape[0],) + tuple(spatial) + (x.shape[3],)
        else:
            shape = tuple(x.shape[:2]) + tuple(spatial)
        set_output(op, block, "Out", shape, x.dtype)
    return infer


def _adaptive_pool(x, out_sizes, nd, is_max, sp0=2):
    """Adaptive pooling: output cell i covers [floor(i*L/out), ceil((i+1)*L/out))."""
    # pool one spatial axis at a time with static window boundaries
    for d in range(nd):
        axis = sp0 + d
        in_size, out_size = x.shape[axis], out_sizes[d]
        starts = [(i * in_size) // out_size for i in range(out_size)]
        ends = [-(-((i + 1) * in_size) // out_size) for i in range(out_size)]
        pieces = []
        for s, e in zip(starts, ends):
            sl = lax.slice_in_dim(x, s, e, axis=axis)
            red = (jnp.max if is_max else jnp.mean)(sl, axis=axis,
                                                   keepdims=True)
            pieces.append(red)
        x = jnp.concatenate(pieces, axis=axis)
    return x


def _pool_compute_nd(nd):
    def compute(ins, attrs, ctx, op_index):
        x = ins["X"][0]
        is_max = attrs.get("pooling_type", "max") == "max"
        # NHWC (transpiler.layout trunk layout): spatial dims sit at
        # 1..nd and the window/stride tuples carry the channel 1 last
        nhwc = attrs.get("data_format", "NCHW") == "NHWC" and nd == 2
        sp0 = 1 if nhwc else 2
        spatial_axes = tuple(range(sp0, sp0 + nd))
        if attrs.get("global_pooling", False):
            out = (jnp.max if is_max else jnp.mean)(x, axis=spatial_axes,
                                                    keepdims=True)
            return {"Out": out}
        if attrs.get("adaptive", False):
            return {"Out": _adaptive_pool(x, int_list(attrs.get("ksize"), nd),
                                          nd, is_max, sp0=sp0)}

        ks = int_list(attrs.get("ksize"), nd)
        strides = int_list(attrs.get("strides", 1), nd)
        pads = int_list(attrs.get("paddings", 0), nd)
        ceil = attrs.get("ceil_mode", False)
        # explicit (lo, hi) padding; ceil_mode extends hi so the last window
        # fits (reference math/pooling.cc ceil semantics)
        sp_pad = []
        for i in range(nd):
            in_size = x.shape[sp0 + i]
            out_size = _pool_out_dim(in_size, ks[i], pads[i], strides[i], ceil)
            needed = (out_size - 1) * strides[i] + ks[i]
            hi = max(needed - in_size - pads[i], pads[i])
            sp_pad.append((pads[i], hi))
        if nhwc:
            pad_cfg = [(0, 0)] + sp_pad + [(0, 0)]
            window = (1,) + tuple(ks) + (1,)
            stride = (1,) + tuple(strides) + (1,)
        else:
            pad_cfg = [(0, 0), (0, 0)] + sp_pad
            window = (1, 1) + tuple(ks)
            stride = (1, 1) + tuple(strides)
        if is_max:
            init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
                jnp.iinfo(x.dtype).min
            out = lax.reduce_window(x, init, lax.max, window, stride, pad_cfg)
        else:
            summed = lax.reduce_window(x, 0.0, lax.add, window, stride,
                                       pad_cfg)
            if attrs.get("exclusive", True):
                ones = jnp.ones(tuple(x.shape[a] for a in spatial_axes),
                                x.dtype)
                cnt = lax.reduce_window(
                    ones, 0.0, lax.add, tuple(ks), tuple(strides), sp_pad
                )
                # ceil_mode can create windows lying wholly in the extension
                # padding (cnt == 0); the reference clamps window extents so
                # the divisor is always >= 1 (math/pooling.cc).
                cnt = jnp.maximum(cnt, 1.0)
                out = summed / (cnt[None, ..., None] if nhwc
                                else cnt[None, None])
            else:
                out = summed / float(int(np.prod(ks)))
        return {"Out": out}
    return compute


register_op("pool2d", ["X"], ["Out"],
            infer=_pool_infer_nd(2), compute=_pool_compute_nd(2))
register_op("pool3d", ["X"], ["Out"],
            infer=_pool_infer_nd(3), compute=_pool_compute_nd(3))


# -- pool2d with argmax index (pool_with_index_op.cc) -----------------------

def _pool_idx_infer_nd(nd):
    def infer(op, block):
        x = in_var(op, block, "X")
        ks = int_list(op.attrs.get("ksize"), nd)
        if op.attrs.get("global_pooling", False):
            spatial = [1] * nd
        else:
            strides = int_list(op.attrs.get("strides", 1), nd)
            pads = int_list(op.attrs.get("paddings", 0), nd)
            spatial = [
                _pool_out_dim(x.shape[2 + i], ks[i], pads[i], strides[i],
                              False)
                for i in range(nd)
            ]
        shape = tuple(x.shape[:2]) + tuple(spatial)
        set_output(op, block, "Out", shape, x.dtype)
        set_output(op, block, "Mask", shape, "int32")
    return infer


def _pool_idx_compute_nd(nd):
    def compute(ins, attrs, ctx, op_index):
        x = ins["X"][0]
        ks = int_list(attrs.get("ksize"), nd)
        if attrs.get("global_pooling", False):
            ks = list(x.shape[2:])
            strides, pads = ks, [0] * nd
        else:
            strides = int_list(attrs.get("strides", 1), nd)
            pads = int_list(attrs.get("paddings", 0), nd)
        spatial = x.shape[2:]
        # index map of flattened spatial positions, padded with -1
        flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(
            (1, 1) + tuple(spatial))
        flat_idx = jnp.broadcast_to(flat_idx, x.shape)
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        pad_cfg = [(0, 0), (0, 0)] + [(p, p) for p in pads]
        window = (1, 1) + tuple(ks)
        stride = (1, 1) + tuple(strides)

        def reducer(a, b):
            av, ai = a
            bv, bi = b
            take_b = bv > av
            return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

        out, mask = lax.reduce_window(
            (x, flat_idx),
            (jnp.asarray(neg, x.dtype), jnp.asarray(-1, jnp.int32)),
            reducer, window, stride, pad_cfg,
        )
        return {"Out": out, "Mask": mask}
    return compute


def _pool_idx_grad(op, no_grad_set):
    from ..framework import grad_var_name
    x = op.inputs["X"][0]
    if x in no_grad_set:
        return []
    return [dict(
        type="max_pool_with_index_grad",
        inputs={"X": [x], "Mask": list(op.outputs["Mask"]),
                "GRAD::Out": [grad_var_name(op.outputs["Out"][0])]},
        outputs={"GRAD::X": [grad_var_name(x)]},
        attrs=dict(op.attrs),
    )]


def _pool_idx_grad_infer(gop, block):
    x = in_var(gop, block, "X")
    set_output(gop, block, "GRAD::X", x.shape, x.dtype)


def _pool_idx_grad_compute(ins, attrs, ctx, op_index):
    x, mask, og = ins["X"][0], ins["Mask"][0], ins["GRAD::Out"][0]
    n, c = x.shape[:2]
    flat = jnp.zeros((n, c, int(np.prod(x.shape[2:]))), x.dtype)
    m = mask.reshape(n, c, -1)
    g = og.reshape(n, c, -1)
    valid = m >= 0
    m_safe = jnp.where(valid, m, 0)
    contrib = jnp.where(valid, g, 0)
    flat = flat.at[
        jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None], m_safe
    ].add(contrib)
    return {"GRAD::X": flat.reshape(x.shape)}


register_op("max_pool2d_with_index", ["X"], ["Out", "Mask"],
            infer=_pool_idx_infer_nd(2), compute=_pool_idx_compute_nd(2),
            grad=_pool_idx_grad)
register_op("max_pool3d_with_index", ["X"], ["Out", "Mask"],
            infer=_pool_idx_infer_nd(3), compute=_pool_idx_compute_nd(3),
            grad=_pool_idx_grad)
register_op("max_pool_with_index_grad", ["X", "Mask", "GRAD::Out"],
            ["GRAD::X"], infer=_pool_idx_grad_infer,
            compute=_pool_idx_grad_compute, grad=None)


# -- spp (spatial pyramid pooling, reference spp_op.cc) ---------------------

def _spp_infer(op, block):
    x = in_var(op, block, "X")
    levels = int(op.attrs.get("pyramid_height", 1))
    c = x.shape[1]
    d = None if c in (None, -1) else \
        c * sum(4 ** l for l in range(levels))
    set_output(op, block, "Out", (x.shape[0], d), x.dtype)


def _spp_compute(ins, attrs, ctx, op_index):
    """Concat adaptive 2^l x 2^l poolings of each level, flattened
    (spp_op.cc: per-level adaptive kernel/stride/pad then concat)."""
    x = ins["X"][0]                                # [N, C, H, W]
    levels = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    n = x.shape[0]
    outs = []
    for l in range(levels):
        bins = 2 ** l
        pooled = _adaptive_pool(x, (bins, bins), 2, ptype == "max")
        outs.append(pooled.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=1)}


register_op("spp", ["X"], ["Out"], infer=_spp_infer,
            compute=_spp_compute)


# -- unpool (max unpooling with indices, reference unpool_op.cc) ------------

def _unpool_out_hw(shape, attrs):
    ks = attrs.get("ksize", [2, 2])
    st = attrs.get("strides", ks)
    pads = attrs.get("paddings", [0, 0])
    dims = []
    for i in range(2):
        d = shape[2 + i]
        dims.append(None if d in (None, -1)
                    else (d - 1) * st[i] - 2 * pads[i] + ks[i])
    return dims


def _unpool_infer(op, block):
    x = in_var(op, block, "X")
    h, w = _unpool_out_hw(x.shape, op.attrs)
    set_output(op, block, "Out", (x.shape[0], x.shape[1], h, w), x.dtype)


def _unpool_compute(ins, attrs, ctx, op_index):
    """Scatter pooled values back to their argmax positions (Indices
    from max_pool2d_with_index, flattened H*W offsets)."""
    x = ins["X"][0]                                # [N, C, h, w]
    idx = ins["Indices"][0].astype(jnp.int32)
    n, c, h, w = x.shape
    oh, ow = _unpool_out_hw(x.shape, attrs)
    flat_out = jnp.zeros((n, c, oh * ow), x.dtype)
    out = flat_out.at[
        jnp.arange(n)[:, None, None],
        jnp.arange(c)[None, :, None],
        idx.reshape(n, c, -1)].set(x.reshape(n, c, -1), mode="drop")
    return {"Out": out.reshape(n, c, oh, ow)}


register_op("unpool", ["X", "Indices"], ["Out"],
            infer=_unpool_infer, compute=_unpool_compute,
            no_grad_inputs=("Indices",))
