"""Convolution ops: conv2d, conv3d, conv2d_transpose, depthwise (groups).

Parity: reference ``paddle/fluid/operators/conv_op.cc`` (+ cuDNN kernel
``conv_cudnn_op.cu.cc``, ``math/im2col``), ``conv_transpose_op.cc``,
``math/depthwise_conv.cu`` — TPU-native: one ``lax.conv_general_dilated``
per op; XLA lowers it straight onto the MXU (no im2col materialization,
no per-library kernel dispatch).  Layouts follow the reference's NCHW/OIHW
API contract; XLA's layout assignment re-tiles internally for the MXU.

Gradients come from the registry's auto-vjp maker — the conv transpose /
filter-grad convs the reference hand-registers (conv2d_grad) are exactly
what ``jax.vjp`` of ``conv_general_dilated`` emits.
"""

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var, int_list

__all__ = []



def _conv_out_dim(in_size, k, pad, stride, dilation):
    if in_size is None or in_size < 0:
        return -1
    eff_k = dilation * (k - 1) + 1
    return (in_size + 2 * pad - eff_k) // stride + 1


def _conv_infer_nd(nd):
    def infer(op, block):
        x = in_var(op, block, "Input")
        w = in_var(op, block, "Filter")
        strides = int_list(op.attrs.get("strides", 1), nd)
        pads = int_list(op.attrs.get("paddings", 0), nd)
        dils = int_list(op.attrs.get("dilations", 1), nd)
        nhwc = op.attrs.get("data_format", "NCHW") == "NHWC" and nd == 2
        out_c = w.shape[0]
        sp0 = 1 if nhwc else 2
        spatial = [
            _conv_out_dim(x.shape[sp0 + i], w.shape[2 + i], pads[i],
                          strides[i], dils[i])
            for i in range(nd)
        ]
        if nhwc:
            set_output(op, block, "Output",
                       (x.shape[0], *spatial, out_c), x.dtype)
        else:
            set_output(op, block, "Output",
                       (x.shape[0], out_c, *spatial), x.dtype)
    return infer


def _conv_compute_nd(nd):
    def compute(ins, attrs, ctx, op_index):
        x, w = ins["Input"][0], ins["Filter"][0]
        # NHWC (transpiler.layout.convert_to_nhwc trunk layout): the
        # activation is feature-last; the filter STAYS OIHW in the
        # program (checkpoint/API parity) and transposes to HWIO here —
        # an O(C*O*k*k)-byte shuffle XLA schedules off the critical
        # path, vs. the O(B*H*W*C) activation transposes the NCHW
        # boundary form would materialize.
        nhwc = attrs.get("data_format", "NCHW") == "NHWC" and nd == 2
        if nhwc:
            dn = ("NHWC", "HWIO", "NHWC")
            w = jnp.transpose(w, (2, 3, 1, 0))
        else:
            dn = ("NCHW", "OIHW", "NCHW") if nd == 2 \
                else ("NCDHW", "OIDHW", "NCDHW")
        strides = int_list(attrs.get("strides", 1), nd)
        pads = int_list(attrs.get("paddings", 0), nd)
        dils = int_list(attrs.get("dilations", 1), nd)
        groups = attrs.get("groups", 1) or 1
        out = lax.conv_general_dilated(
            x, w,
            window_strides=strides,
            padding=[(p, p) for p in pads],
            rhs_dilation=dils,
            dimension_numbers=dn,
            feature_group_count=groups,
        )
        return {"Output": out}
    return compute


register_op("conv2d", ["Input", "Filter"], ["Output"],
            infer=_conv_infer_nd(2), compute=_conv_compute_nd(2))
register_op("conv3d", ["Input", "Filter"], ["Output"],
            infer=_conv_infer_nd(3), compute=_conv_compute_nd(3))
# depthwise_conv2d is conv2d with groups == in_channels; separate op type
# for API parity with the reference's registration
register_op("depthwise_conv2d", ["Input", "Filter"], ["Output"],
            infer=_conv_infer_nd(2), compute=_conv_compute_nd(2))


# -- conv2d_transpose -------------------------------------------------------


def _convt_infer_nd(nd):
    def infer(op, block):
        x = in_var(op, block, "Input")
        w = in_var(op, block, "Filter")  # [in_c, out_c/groups, *k]
        strides = int_list(op.attrs.get("strides", 1), nd)
        pads = int_list(op.attrs.get("paddings", 0), nd)
        dils = int_list(op.attrs.get("dilations", 1), nd)
        groups = op.attrs.get("groups", 1) or 1
        out_c = w.shape[1] * groups
        spatial = []
        for i in range(nd):
            if x.shape[2 + i] is None or x.shape[2 + i] < 0:
                spatial.append(-1)
            else:
                spatial.append(
                    (x.shape[2 + i] - 1) * strides[i] - 2 * pads[i]
                    + dils[i] * (w.shape[2 + i] - 1) + 1
                )
        set_output(op, block, "Output", (x.shape[0], out_c, *spatial),
                   x.dtype)
    return infer


def _convt_compute_nd(nd):
    dn = ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCDHW", "OIDHW", "NCDHW")
    spatial_axes = tuple(range(2, 2 + nd))

    def compute(ins, attrs, ctx, op_index):
        x, w = ins["Input"][0], ins["Filter"][0]
        strides = int_list(attrs.get("strides", 1), nd)
        pads = int_list(attrs.get("paddings", 0), nd)
        dils = int_list(attrs.get("dilations", 1), nd)
        groups = attrs.get("groups", 1) or 1

        def one_group(xg, wg):
            # wg: [in_c/g, out_c/g, *k] -> rotate spatially, swap I/O
            wt = jnp.flip(wg, axis=spatial_axes).transpose(
                (1, 0) + spatial_axes)
            k = [wt.shape[2 + i] for i in range(nd)]
            pad = [
                (dils[i] * (k[i] - 1) - pads[i],
                 dils[i] * (k[i] - 1) - pads[i])
                for i in range(nd)
            ]
            return lax.conv_general_dilated(
                xg, wt,
                window_strides=[1] * nd,
                padding=pad,
                lhs_dilation=strides,
                rhs_dilation=dils,
                dimension_numbers=dn,
            )

        if groups == 1:
            out = one_group(x, w)
        else:
            xs = jnp.split(x, groups, axis=1)
            ws = jnp.split(w, groups, axis=0)
            out = jnp.concatenate(
                [one_group(xg, wg) for xg, wg in zip(xs, ws)], axis=1
            )
        return {"Output": out}
    return compute


register_op("conv2d_transpose", ["Input", "Filter"], ["Output"],
            infer=_convt_infer_nd(2), compute=_convt_compute_nd(2))
register_op("conv3d_transpose", ["Input", "Filter"], ["Output"],
            infer=_convt_infer_nd(3), compute=_convt_compute_nd(3))
# depthwise transpose = grouped transpose; separate type for registration
# parity (reference conv_transpose_op.cc:335)
register_op("depthwise_conv2d_transpose", ["Input", "Filter"], ["Output"],
            infer=_convt_infer_nd(2), compute=_convt_compute_nd(2))


# -- conv_shift (circular 1-D correlation, conv_shift_op.cc) ----------------

def _conv_shift_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _conv_shift_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]  # x: [B, M], y: [B, N] (N odd, N<=M)
    m, n = x.shape[1], y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(n)[None, :] - half) % m
    # out[b, i] = sum_j x[b, (i+j-half) % m] * y[b, j]
    gathered = x[:, idx]                      # [B, M, N]
    out = jnp.einsum("bmn,bn->bm", gathered, y)
    return {"Out": out}


register_op("conv_shift", ["X", "Y"], ["Out"],
            infer=_conv_shift_infer, compute=_conv_shift_compute)
