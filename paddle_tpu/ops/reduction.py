"""Reduction ops: reduce_{sum,mean,max,min,prod}, argmax/argmin, cumsum.

Parity: reference ``reduce_*_op.cc``, ``arg_max_op.cc``, ``arg_min_op.cc``,
``cumsum_op.cc`` — TPU-native jnp reductions (XLA lowers to tree reductions
on the VPU; deterministic by construction, the analog of
FLAGS_cpu_deterministic).
"""

import numpy as np

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var
from ..core import long_dtype


def _reduce_infer(op, block):
    x = in_var(op, block, "X")
    dims = op.attrs.get("dim", [0])
    keep = op.attrs.get("keep_dim", False)
    if op.attrs.get("reduce_all", False):
        out = (1,) if not keep else (1,) * len(x.shape)
    else:
        dims = [d % len(x.shape) for d in dims]
        if keep:
            out = tuple(1 if i in dims else s for i, s in enumerate(x.shape))
        else:
            out = tuple(s for i, s in enumerate(x.shape) if i not in dims)
            if not out:
                out = (1,)
    set_output(op, block, "Out", out, x.dtype)


def _make_reduce(name, fn):
    def compute(ins, attrs, ctx, op_index):
        x = ins["X"][0]
        keep = attrs.get("keep_dim", False)
        if attrs.get("reduce_all", False):
            out = fn(x, axis=None, keepdims=keep)
            if not keep:
                out = out.reshape(1)
            return {"Out": out}
        dims = tuple(d % x.ndim for d in attrs.get("dim", [0]))
        out = fn(x, axis=dims, keepdims=keep)
        if out.ndim == 0:
            out = out.reshape(1)
        return {"Out": out}

    register_op(name, ["X"], ["Out"], infer=_reduce_infer, compute=compute)


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", jnp.max)
_make_reduce("reduce_min", jnp.min)
_make_reduce("reduce_prod", jnp.prod)


def _arg_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("axis", 0) % len(x.shape)
    out = tuple(s for i, s in enumerate(x.shape) if i != axis)
    set_output(op, block, "Out", out or (1,), np.int64)


def _make_arg(name, fn):
    register_op(
        name, ["X"], ["Out"], infer=_arg_infer,
        compute=lambda ins, attrs, ctx, op_index: {
            "Out": fn(ins["X"][0], axis=attrs.get("axis", 0)).astype(long_dtype())
        },
        grad=None,
    )


_make_arg("arg_max", jnp.argmax)
_make_arg("arg_min", jnp.argmin)


def _cumsum_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    if attrs.get("reverse", False):
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("exclusive", False):
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        out = jnp.pad(out, pad)[
            tuple(slice(0, s) if i == axis % x.ndim else slice(None)
                  for i, s in enumerate(x.shape))
        ]
    if attrs.get("reverse", False):
        out = jnp.flip(out, axis=axis)
    return {"Out": out}


register_op(
    "cumsum", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_cumsum_compute,
)
