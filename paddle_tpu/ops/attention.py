"""Fused attention op — the single-chip flash-attention surface.

Capability parity target: the reference's only attention implementation,
``nets.scaled_dot_product_attention`` (``python/paddle/fluid/nets.py:323``) —
batched QK^T, softmax, optional dropout on the weights, PV.  TPU-first
redesign: one op whose kernel never materializes the [B, H, Tq, Tk] score
matrix.  Under ``FLAGS_pallas_kernels`` it runs the hand-tiled blockwise
kernel (``ops/pallas/flash_attention.py``); otherwise an XLA fallback with
identical semantics (same structural masks, same counter-hash dropout mask),
so the flag changes schedule, not math.

Masking is structural: an optional per-batch valid-key count ``KLen`` [B]
(the ``<name>@LEN`` companion of the key sequence) and a ``causal`` attr —
the two shapes every Transformer mask reduces to.  ``causal`` with
``Tq == Tk`` is aligned self-attention (query i sees keys <= i); with
``Tq < Tk`` the queries are the *suffix* of the valid keys — query i sits
at global position ``klen - Tq + i`` — which is the single-token /
chunked KV-cache decode shape the serving engine drives.  Eval-time dropout follows
the reference's ``downgrade_in_infer``: weights scale by (1 - p), which
commutes with the PV matmul into a single output scale.
"""

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var


def _fused_attention_infer(op, block):
    q = in_var(op, block, "Q")
    k = in_var(op, block, "K")
    v = in_var(op, block, "V")
    if len(q.shape) != 4 or len(k.shape) != 4 or len(v.shape) != 4:
        raise ValueError(
            "fused_attention expects [B, H, T, D] Q/K/V, got %s/%s/%s"
            % (q.shape, k.shape, v.shape))
    if q.shape[3] != k.shape[3]:
        raise ValueError(
            "fused_attention Q/K head dims disagree: %s vs %s"
            % (q.shape, k.shape))
    if v.shape[2] != k.shape[2] or v.shape[3] != q.shape[3]:
        raise ValueError(
            "fused_attention V must be [B, H, Tk, D] matching K's length "
            "and Q's head dim: got Q %s, K %s, V %s"
            % (q.shape, k.shape, v.shape))
    if op.attrs.get("causal", False) and q.shape[2] > k.shape[2]:
        # a suffix query cannot be longer than the key sequence it is a
        # suffix of; Tq < Tk is the decode/chunked-decode shape (queries
        # are the LAST Tq valid positions — bottom-aligned causal mask)
        raise ValueError(
            "fused_attention: causal=True requires Tq <= Tk (got %d vs "
            "%d)" % (q.shape[2], k.shape[2]))
    set_output(op, block, "Out", q.shape, q.dtype)


def _fused_attention_compute(ins, attrs, ctx, op_index):
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    k_len = ins.get("KLen", [None])[0]
    causal = attrs.get("causal", False)
    rate = float(attrs.get("dropout_rate", 0.0))
    is_test = attrs.get("is_test", False) or ctx.is_test
    scale = attrs.get("scale", None)
    seed = None
    if rate and not is_test:
        import jax
        kd = jax.random.key_data(ctx.rng_key(op_index)).astype(jnp.uint32)
        seed = kd.reshape(-1)[0] ^ kd.reshape(-1)[-1]

    from .pallas import flash_attention as fa

    if rate and is_test:
        # downgrade_in_infer: weights *= (1-p) == output *= (1-p)
        post = 1.0 - rate
        rate = 0.0
    else:
        post = None

    mesh = getattr(ctx, "mesh", None)
    if mesh is not None and getattr(ctx, "sequence_parallel", True) \
            and _ring_applicable(mesh, q.shape, k.shape, causal):
        out = _ring_attention(mesh, q, k, v, k_len, seed, causal, rate,
                              scale)
    else:
        from .. import autotune
        from ..flags import flag

        # kernel selection: a tuned per-shape ruling (the autotune
        # decision table's measured A/B) overrides the global flag —
        # unless the operator PINNED FLAGS_pallas_kernels, in which
        # case attention_choice returns None and the flag rules
        choice = autotune.attention_choice(q.shape, k.shape, q.dtype)
        use_pallas = flag("pallas_kernels") if choice is None else choice
        # a tuned Pallas ruling was measured AT this sequence length, so
        # it lifts the flag's seq gate for this shape (the VMEM budget
        # inside supported() still applies)
        max_seq = max(q.shape[2], k.shape[2]) if choice else None
        if use_pallas and fa.supported(q.shape, k.shape, q.dtype,
                                       max_seq=max_seq):
            from .pallas import interpret_mode
            out = fa.flash_attention(q, k, v, k_len, seed, causal, rate,
                                     scale, interpret_mode(ctx))
        else:
            out = fa.reference_attention(q, k, v, k_len, seed, causal, rate,
                                         scale)
    if post is not None:
        out = out * jnp.asarray(post, out.dtype)
    return {"Out": out}


def _ring_applicable(mesh, q_shape, k_shape, causal):
    """Ring attention lowers this op when the mesh has a populated ``sp``
    axis and the sequence dims divide it (the ParallelExecutor threads the
    mesh into the trace exactly when its BuildStrategy allows sp)."""
    from ..parallel.mesh import AXIS_SP

    if AXIS_SP not in mesh.axis_names:
        return False
    sp = mesh.shape[AXIS_SP]
    if sp <= 1:
        return False
    b, _, tq, _ = q_shape
    tk = k_shape[2]
    if tq % sp or tk % sp:
        return False
    if causal and tq != tk:
        return False
    return True


def _ring_attention(mesh, q, k, v, k_len, seed, causal, rate, scale):
    """Lower to sequence-parallel ring attention over the mesh's ``sp``
    axis (parallel/ring_attention.py), composing with ``dp`` batch
    sharding when the batch divides it.  Masks and dropout use GLOBAL
    positions, so the result is loss-parity-exact with the single-chip
    kernel."""
    import functools

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP, shard_map_norep
    from ..parallel.ring_attention import ring_attention_shard

    b, h = q.shape[0], q.shape[1]
    tk = k.shape[2]
    bspec = None
    if AXIS_DP in mesh.axis_names and mesh.shape[AXIS_DP] > 1 \
            and b % mesh.shape[AXIS_DP] == 0:
        bspec = AXIS_DP
    # heads shard over tp when present (tensor-parallel QKV projections
    # leave Q/K/V head-sharded; the ring treats heads as batch, so the
    # composition is a pure spec change plus the dropout head offset)
    hspec = None
    if AXIS_TP in mesh.axis_names and mesh.shape[AXIS_TP] > 1 \
            and h % mesh.shape[AXIS_TP] == 0:
        hspec = AXIS_TP
    if k_len is None:
        k_len = jnp.full((b,), tk, jnp.int32)
    if seed is None:
        seed = jnp.zeros((), jnp.uint32)
    body = functools.partial(
        ring_attention_shard, axis_name=AXIS_SP, causal=causal, scale=scale,
        dropout_rate=rate, batch_axis_name=bspec, head_axis_name=hspec)

    def shard_body(q, k, v, klen, seed):
        return body(q, k, v, k_len=klen, seed=seed)

    spec = P(bspec, hspec, AXIS_SP, None)
    fn = shard_map_norep(
        shard_body, mesh,
        in_specs=(spec, spec, spec, P(bspec), P()), out_specs=spec)
    return fn(q, k, v, k_len.astype(jnp.int32), seed.astype(jnp.uint32))


register_op(
    "fused_attention", ["Q", "K", "V", "KLen"], ["Out"],
    infer=_fused_attention_infer, compute=_fused_attention_compute,
    no_grad_inputs=("KLen",), stateful_random=True,
)


# ---------------------------------------------------------------------------
# paged attention (ISSUE 16): attention over a block-indexed KV pool
# ---------------------------------------------------------------------------

def _paged_attention_infer(op, block):
    q = in_var(op, block, "Q")
    kc = in_var(op, block, "KCache")
    table = in_var(op, block, "PageTable")
    if q is None or kc is None or table is None:
        raise ValueError("paged_attention needs Q, KCache/VCache and "
                         "PageTable inputs")
    if len(q.shape) != 4 or len(kc.shape) != 4 or len(table.shape) != 2:
        raise ValueError(
            "paged_attention expects Q [S, H, Tq, D], KCache "
            "[P, H, ps, D], PageTable [S, max_pages]; got %s / %s / %s"
            % (q.shape, kc.shape, table.shape))
    tmax = table.shape[1] * kc.shape[2]
    if q.shape[2] > tmax:
        raise ValueError(
            "paged_attention: Tq %d exceeds the paged capacity %d"
            % (q.shape[2], tmax))
    import numpy as np
    if np.dtype(kc.dtype) == np.dtype("int8") \
            and in_var(op, block, "KScale") is None:
        raise ValueError(
            "paged_attention: int8 KV pools need KScale/VScale inputs")
    set_output(op, block, "Out", q.shape, q.dtype)


def _paged_attention_compute(ins, attrs, ctx, op_index):
    q = ins["Q"][0]
    k_pool = ins["KCache"][0]
    v_pool = ins["VCache"][0]
    table = ins["PageTable"][0].astype(jnp.int32)
    k_len = ins.get("KLen", [None])[0]
    k_scale = ins.get("KScale", [None])[0]
    v_scale = ins.get("VScale", [None])[0]
    scale = attrs.get("scale", None)

    from .pallas import flash_attention as fa
    from .pallas import interpret_mode
    from .. import autotune
    from ..flags import flag

    # kernel selection on the GATHERED shape (the shape the kernel
    # actually runs): tuned per-shape ruling wins unless the operator
    # pinned FLAGS_pallas_kernels — the fused_attention discipline
    tmax = table.shape[1] * k_pool.shape[2]
    k_shape = (q.shape[0], q.shape[1], tmax, q.shape[3])
    choice = autotune.attention_choice(q.shape, k_shape, q.dtype)
    use_pallas = flag("pallas_kernels") if choice is None else choice
    out = fa.paged_attention(
        q, k_pool, v_pool, table, k_len, k_scale, v_scale,
        causal=attrs.get("causal", True), scale=scale,
        use_pallas=use_pallas, interpret=interpret_mode(ctx))
    return {"Out": out}


register_op(
    "paged_attention",
    ["Q", "KCache", "VCache", "PageTable", "KLen", "KScale", "VScale"],
    ["Out"],
    infer=_paged_attention_infer, compute=_paged_attention_compute,
    grad=None,
    no_grad_inputs=("PageTable", "KLen", "KScale", "VScale"),
)
