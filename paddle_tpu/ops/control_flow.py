"""Control-flow ops: sub-block execution lowered to XLA structured control
flow.

Parity: reference ``recurrent_op.cc:53`` (StepScopes), ``while_op.cc:36``,
``conditional_block_op.cc``, ``beam_search_op.cc``,
``beam_search_decode_op.cc``, ``tensor_array_read_write_op.cc``,
``split_lod_tensor_op.cc`` / ``merge_lod_tensor_op.cc`` — re-designed
TPU-first:

* A sub-block op carries ALL of its external dependencies as inputs
  (the reference's recurrent_op collects "parameters" the same way); its
  compute traces the sub-block's ops inside ``lax.scan`` (recurrent),
  ``lax.cond`` (conditional_block) or ``lax.while_loop`` (while).  Because
  scan and cond are reverse-differentiable, the registry's generic
  auto-vjp gradient works through them unchanged — no hand-written
  while_grad/recurrent_grad graph surgery as in the reference
  (``backward.py:315`` recursive sub-block backward).
* ``while`` uses ``lax.while_loop`` (trip count unknown at compile time),
  which XLA cannot reverse-differentiate; it is the inference/decoding
  construct (beam search, generation).  Training-time recurrence uses
  ``recurrent`` (lax.scan).
* Tensor arrays are fixed-capacity device arrays (``[capacity, ...]``
  with dynamic_update_slice writes): XLA needs static shapes, so the
  reference's growing LoDTensorArray becomes a preallocated ring the
  while loop carries.
* IfElse's row-splitting (``split_lod_tensor``/``merge_lod_tensor``)
  becomes predication: both branches compute on the full batch and the
  merge selects rows by mask — control flow turned into data flow, which
  is exactly what the TPU vector units want.
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import registry
from ..registry import ComputeContext, register_op, set_output, in_var
from ..core import long_dtype


def _sub_ctx(ctx, salt):
    """A ComputeContext for a sub-block with decorrelated RNG.  Platform
    and mesh thread through: platform-keyed choices (bf16 matmul
    accumulation, Pallas mosaic-vs-interpret) must not change inside a
    While/cond body."""
    key = getattr(ctx, "_key", None)
    if key is not None:
        key = jax.random.fold_in(key, salt)
    sub = ComputeContext(key=key, is_test=getattr(ctx, "is_test", False),
                         platform=getattr(ctx, "platform", None),
                         mesh=getattr(ctx, "mesh", None))
    sub.amp = getattr(ctx, "amp", None)
    sub.program = ctx.program
    return sub


def _run_block(block, env, ctx):
    for i, op in enumerate(block.ops):
        registry.compute_op(op, env, ctx, op_index=i)
    return env


def _mask_to(valid, like):
    """Broadcast a [B] bool mask against a [B, ...] array."""
    return valid.reshape((-1,) + (1,) * (like.ndim - 1))


# ---------------------------------------------------------------------------
# recurrent (StaticRNN / DynamicRNN): lax.scan over the time axis
# ---------------------------------------------------------------------------

def _recurrent_infer(op, block):
    program = block.program
    sub = program.block(op.attrs["sub_block"])
    time_major = op.attrs.get("time_major", True)
    x0 = in_var(op, block, "Inputs") or in_var(op, block, "IntInputs")
    t = x0.shape[0] if time_major else x0.shape[1]
    out_names = op.attrs["output_names"]
    for parent_name, blk_name in zip(op.outputs.get("Outputs", []),
                                     out_names):
        v = sub._find_var_recursive(blk_name)
        shape = tuple(v.shape or ())
        if time_major:
            out_shape = (t,) + shape
        else:
            out_shape = shape[:1] + (t,) + shape[1:]
        ov = block._find_var_recursive(parent_name) or \
            block.create_var(name=parent_name)
        ov.shape = out_shape
        ov.dtype = v.dtype
    for parent_name, blk_name in zip(op.outputs.get("FinalStates", []),
                                     op.attrs["state_names"]):
        v = sub._find_var_recursive(blk_name)
        ov = block._find_var_recursive(parent_name) or \
            block.create_var(name=parent_name)
        ov.shape = tuple(v.shape or ())
        ov.dtype = v.dtype


def _recurrent_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    time_major = attrs.get("time_major", True)
    is_reverse = attrs.get("is_reverse", False)
    # float and integer step inputs ride separate slots so a token-id
    # input cannot disqualify the float slot from differentiation
    step_in_names = list(attrs["step_input_names"]) + \
        list(attrs.get("int_step_input_names", []))
    pre_names = attrs["pre_state_names"]
    post_names = attrs["state_names"]
    out_names = attrs["output_names"]

    xs = list(ins.get("Inputs") or []) + list(ins.get("IntInputs") or [])
    init = ins.get("InitStates", [])
    length = (ins.get("Length") or [None])[0]

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    xs_tm = [x if time_major else jnp.swapaxes(x, 0, 1) for x in xs]
    t_len = xs_tm[0].shape[0]
    steps = jnp.arange(t_len)
    if is_reverse:
        xs_tm = [x[::-1] for x in xs_tm]
        steps = steps[::-1]

    sub_salt = 7919 + attrs["sub_block"]

    def body(carry, scanned):
        t, x_t = scanned
        env = dict(base_env)
        env.update(zip(step_in_names, x_t))
        env.update(zip(pre_names, carry))
        step_ctx = _sub_ctx(ctx, sub_salt)
        if getattr(step_ctx, "_key", None) is not None:
            step_ctx._key = jax.random.fold_in(step_ctx._key, t)
        _run_block(sub, env, step_ctx)
        new_carry = tuple(env[n] for n in post_names)
        outs = tuple(env[n] for n in out_names)
        if length is not None:
            valid = t < length          # [B]
            new_carry = tuple(
                jnp.where(_mask_to(valid, n), n, o)
                for n, o in zip(new_carry, carry))
            outs = tuple(
                jnp.where(_mask_to(valid, o), o, jnp.zeros_like(o))
                for o in outs)
        return new_carry, outs

    final, stacked = lax.scan(body, tuple(init), (steps, tuple(xs_tm)))
    if is_reverse:
        stacked = tuple(s[::-1] for s in stacked)
    if not time_major:
        stacked = tuple(jnp.swapaxes(s, 0, 1) for s in stacked)
    return {"Outputs": list(stacked), "FinalStates": list(final)}


register_op(
    "recurrent",
    ["Inputs", "IntInputs", "InitStates", "Params", "Consts", "Length"],
    ["Outputs", "FinalStates"],
    infer=_recurrent_infer, compute=_recurrent_compute,
    no_grad_inputs=("IntInputs", "Consts", "Length"),
)


# ---------------------------------------------------------------------------
# conditional_block: lax.cond over a sub-block (reference
# conditional_block_op.cc) — differentiable
# ---------------------------------------------------------------------------

def _cond_block_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    carried = attrs["carried_names"]

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    pred = jnp.all(ins["Cond"][0])
    carry = tuple(ins.get("LoopVars", []))
    sub_ctx = _sub_ctx(ctx, 104729 + attrs["sub_block"])

    def true_fn(c):
        env = dict(base_env)
        env.update(zip(carried, c))
        _run_block(sub, env, sub_ctx)
        return tuple(env[n] for n in carried)

    out = lax.cond(pred, true_fn, lambda c: c, carry)
    return {"Out": list(out)}


register_op(
    "conditional_block",
    ["Cond", "LoopVars", "Params", "Consts"],
    ["Out"],
    infer=None, compute=_cond_block_compute,
    no_grad_inputs=("Cond", "Consts"),
)


# ---------------------------------------------------------------------------
# while: lax.while_loop over a sub-block (reference while_op.cc:36).
# Forward-only: XLA cannot reverse-differentiate an unbounded loop; the
# training-time recurrence is `recurrent` above.
# ---------------------------------------------------------------------------

def _while_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    carried = attrs["carried_names"]
    cond_name = attrs["cond_name"]

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    carry0 = tuple(ins.get("LoopVars", []))
    idx = {n: i for i, n in enumerate(carried)}
    sub_ctx = _sub_ctx(ctx, 1299709 + attrs["sub_block"])

    def cond_fn(carry):
        return jnp.all(carry[idx[cond_name]])

    def body_fn(carry):
        env = dict(base_env)
        env.update(zip(carried, carry))
        _run_block(sub, env, sub_ctx)
        return tuple(env[n] for n in carried)

    out = lax.while_loop(cond_fn, body_fn, carry0)
    return {"Out": list(out)}


def _while_grad_maker(op, no_grad_set):
    # reached only when a live gradient actually flows into the loop's
    # outputs — fail loudly instead of silently freezing the weights
    raise RuntimeError(
        "cannot differentiate through a While loop: XLA cannot "
        "reverse-differentiate an unbounded lax.while_loop. Use "
        "StaticRNN/DynamicRNN (lax.scan) for trainable recurrence; While "
        "is the inference/decoding construct.")


register_op(
    "while",
    ["Condition", "LoopVars", "Params", "Consts"],
    ["Out"],
    infer=None, compute=_while_compute, grad=_while_grad_maker,
)


# ---------------------------------------------------------------------------
# tensor arrays: fixed-capacity device arrays
# (reference tensor_array_read_write_op.cc + lod_array_length_op.cc)
# ---------------------------------------------------------------------------

def _array_write_infer(op, block):
    x = in_var(op, block, "X")
    arr = in_var(op, block, "Array")
    if arr is not None and arr.shape is not None:
        shape = arr.shape
    else:
        shape = (op.attrs["capacity"],) + tuple(x.shape or ())
    set_output(op, block, "Out", shape, x.dtype)


def _array_write_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    arr = (ins.get("Array") or [None])[0]
    if arr is None:
        arr = jnp.zeros((attrs["capacity"],) + x.shape, x.dtype)
    return {"Out": lax.dynamic_update_index_in_dim(arr, x, i, 0)}


register_op(
    "array_write", ["X", "I", "Array"], ["Out"],
    infer=_array_write_infer, compute=_array_write_compute,
    no_grad_inputs=("I",),
)


def _array_read_infer(op, block):
    arr = in_var(op, block, "Array")
    set_output(op, block, "Out", tuple(arr.shape or ())[1:], arr.dtype)


def _array_read_compute(ins, attrs, ctx, op_index):
    arr = ins["Array"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    return {"Out": lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)}


register_op(
    "array_read", ["Array", "I"], ["Out"],
    infer=_array_read_infer, compute=_array_read_compute,
    no_grad_inputs=("I",),
)


def _array_length_compute(ins, attrs, ctx, op_index):
    return {"Out": jnp.full((1,), ins["X"][0].shape[0], long_dtype())}


register_op(
    "lod_array_length", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), "int64"),
    compute=_array_length_compute, grad=None,
)


# ---------------------------------------------------------------------------
# split/merge by mask (IfElse plumbing, predication-style)
# ---------------------------------------------------------------------------

def _split_lod_tensor_compute(ins, attrs, ctx, op_index):
    # predication redesign: both branches see the full batch; the merge
    # selects.  (The reference physically partitions rows by mask.)
    x = ins["X"][0]
    return {"OutTrue": x, "OutFalse": x}


register_op(
    "split_lod_tensor", ["X", "Mask"], ["OutTrue", "OutFalse"],
    infer=lambda op, block: (
        set_output(op, block, "OutTrue", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
        set_output(op, block, "OutFalse", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
    ),
    compute=_split_lod_tensor_compute, no_grad_inputs=("Mask",),
)


def _merge_lod_tensor_compute(ins, attrs, ctx, op_index):
    mask = ins["Mask"][0]
    in_true, in_false = ins["InTrue"][0], ins["InFalse"][0]
    m = mask.reshape((-1,) + (1,) * (in_true.ndim - 1)).astype(bool)
    return {"Out": jnp.where(m, in_true, in_false)}


register_op(
    "merge_lod_tensor", ["Mask", "InTrue", "InFalse"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "InTrue").shape,
        in_var(op, block, "InTrue").dtype),
    compute=_merge_lod_tensor_compute, no_grad_inputs=("Mask",),
)


# ---------------------------------------------------------------------------
# beam search (reference beam_search_op.cc / beam_search_decode_op.cc),
# re-designed for fixed [batch, beam] layout (no LoD growth)
# ---------------------------------------------------------------------------

def _beam_search_infer(op, block):
    pre = in_var(op, block, "PreIds")
    b, k = pre.shape
    set_output(op, block, "SelectedIds", (b, k), "int64")
    set_output(op, block, "SelectedScores", (b, k),
               in_var(op, block, "PreScores").dtype)
    set_output(op, block, "ParentIdx", (b, k), "int64")


def _beam_search_compute(ins, attrs, ctx, op_index):
    pre_ids = ins["PreIds"][0]            # [B, K] int64
    pre_scores = ins["PreScores"][0]      # [B, K] float
    scores = ins["Scores"][0]             # [B, K, V] step log-probs
    end_id = attrs["end_id"]
    k = scores.shape[1]
    v = scores.shape[2]

    finished = pre_ids == end_id          # [B, K]
    neg_inf = jnp.asarray(-1e9, scores.dtype)
    # finished beams may only re-emit end_id, contributing 0 to the score
    step = jnp.where(finished[:, :, None], neg_inf, scores)
    step = step.at[:, :, end_id].set(
        jnp.where(finished, jnp.zeros_like(pre_scores),
                  scores[:, :, end_id]))
    total = pre_scores[:, :, None] + step  # [B, K, V]
    flat = total.reshape(total.shape[0], k * v)
    top_scores, top_idx = lax.top_k(flat, k)
    parent = (top_idx // v).astype(long_dtype())
    token = (top_idx % v).astype(long_dtype())
    return {"SelectedIds": token, "SelectedScores": top_scores,
            "ParentIdx": parent}


register_op(
    "beam_search", ["PreIds", "PreScores", "Scores"],
    ["SelectedIds", "SelectedScores", "ParentIdx"],
    infer=_beam_search_infer, compute=_beam_search_compute, grad=None,
)


def _beam_search_decode_infer(op, block):
    ids = in_var(op, block, "Ids")        # [T, B, K]
    t, b, k = ids.shape
    set_output(op, block, "SentenceIds", (b, k, t), "int64")
    set_output(op, block, "SentenceScores",
               (b, k), in_var(op, block, "Scores").dtype)


def _beam_search_decode_compute(ins, attrs, ctx, op_index):
    ids = ins["Ids"][0]                   # [T, B, K] tokens per step
    parents = ins["Parents"][0]           # [T, B, K] beam backpointers
    scores = ins["Scores"][0]             # [B, K] final beam scores
    t, b, k = ids.shape
    beam0 = jnp.broadcast_to(jnp.arange(k, dtype=long_dtype()), (b, k))

    def back(carry, xs):
        beam = carry                      # [B, K] position at step t
        ids_t, par_t = xs
        tok = jnp.take_along_axis(ids_t, beam, axis=1)
        prev = jnp.take_along_axis(par_t, beam, axis=1)
        return prev, tok

    _, toks = lax.scan(back, beam0, (ids[::-1], parents[::-1]))
    sent = jnp.transpose(toks[::-1], (1, 2, 0))   # [B, K, T]
    return {"SentenceIds": sent, "SentenceScores": scores}


register_op(
    "beam_search_decode", ["Ids", "Parents", "Scores"],
    ["SentenceIds", "SentenceScores"],
    infer=_beam_search_decode_infer, compute=_beam_search_decode_compute,
    grad=None,
)
