"""Control-flow ops: sub-block execution lowered to XLA structured control
flow.

Parity: reference ``recurrent_op.cc:53`` (StepScopes), ``while_op.cc:36``,
``conditional_block_op.cc``, ``beam_search_op.cc``,
``beam_search_decode_op.cc``, ``tensor_array_read_write_op.cc``,
``split_lod_tensor_op.cc`` / ``merge_lod_tensor_op.cc`` — re-designed
TPU-first:

* A sub-block op carries ALL of its external dependencies as inputs
  (the reference's recurrent_op collects "parameters" the same way); its
  compute traces the sub-block's ops inside ``lax.scan`` (recurrent),
  ``lax.cond`` (conditional_block) or ``lax.while_loop`` (while).  Because
  scan and cond are reverse-differentiable, the registry's generic
  auto-vjp gradient works through them unchanged — no hand-written
  while_grad/recurrent_grad graph surgery as in the reference
  (``backward.py:315`` recursive sub-block backward).
* ``while`` uses ``lax.while_loop`` (trip count unknown at compile time),
  which XLA cannot reverse-differentiate; it is the inference/decoding
  construct (beam search, generation).  Training-time recurrence uses
  ``recurrent`` (lax.scan).
* Tensor arrays are fixed-capacity device arrays (``[capacity, ...]``
  with dynamic_update_slice writes): XLA needs static shapes, so the
  reference's growing LoDTensorArray becomes a preallocated ring the
  while loop carries.
* IfElse's row-splitting (``split_lod_tensor``/``merge_lod_tensor``)
  becomes predication: both branches compute on the full batch and the
  merge selects rows by mask — control flow turned into data flow, which
  is exactly what the TPU vector units want.
* The reference's per-step scope plumbing has no analog here and is
  deliberately absent: ``shrink_rnn_memory_op.cc`` (shrink the step
  batch as short sequences finish) and ``rnn_memory_helper_op.cc``
  (step-scope memory hand-off) exist to serve dynamically-shrinking
  step batches, which XLA's static shapes forbid — scan steps stay
  full-width and masked (ops/sequence.py rank-table family docs), and
  scan itself carries the memories.  ``parallel_do_op.cc:114`` /
  ``get_places_op.cc`` (deprecated per-op data parallelism) are
  subsumed by the mesh runtime (parallel/parallel_executor.py).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import registry
from ..registry import ComputeContext, register_op, set_output, in_var
from ..core import long_dtype


def _sub_ctx(ctx, salt):
    """A ComputeContext for a sub-block with decorrelated RNG.  Platform
    and mesh thread through: platform-keyed choices (bf16 matmul
    accumulation, Pallas mosaic-vs-interpret) must not change inside a
    While/cond body."""
    key = getattr(ctx, "_key", None)
    if key is not None:
        key = jax.random.fold_in(key, salt)
    sub = ComputeContext(key=key, is_test=getattr(ctx, "is_test", False),
                         platform=getattr(ctx, "platform", None),
                         mesh=getattr(ctx, "mesh", None))
    sub.amp = getattr(ctx, "amp", None)
    sub.program = ctx.program
    return sub


def _run_block(block, env, ctx):
    for i, op in enumerate(block.ops):
        registry.compute_op(op, env, ctx, op_index=i)
    return env


def _mask_to(valid, like):
    """Broadcast a [B] bool mask against a [B, ...] array."""
    return valid.reshape((-1,) + (1,) * (like.ndim - 1))


# ---------------------------------------------------------------------------
# recurrent (StaticRNN / DynamicRNN): lax.scan over the time axis
# ---------------------------------------------------------------------------

def _recurrent_infer(op, block):
    program = block.program
    sub = program.block(op.attrs["sub_block"])
    time_major = op.attrs.get("time_major", True)
    x0 = in_var(op, block, "Inputs") or in_var(op, block, "IntInputs")
    t = x0.shape[0] if time_major else x0.shape[1]
    out_names = op.attrs["output_names"]
    for parent_name, blk_name in zip(op.outputs.get("Outputs", []),
                                     out_names):
        v = sub._find_var_recursive(blk_name)
        shape = tuple(v.shape or ())
        if time_major:
            out_shape = (t,) + shape
        else:
            out_shape = shape[:1] + (t,) + shape[1:]
        ov = block._find_var_recursive(parent_name) or \
            block.create_var(name=parent_name)
        ov.shape = out_shape
        ov.dtype = v.dtype
    for parent_name, blk_name in zip(op.outputs.get("FinalStates", []),
                                     op.attrs["state_names"]):
        v = sub._find_var_recursive(blk_name)
        ov = block._find_var_recursive(parent_name) or \
            block.create_var(name=parent_name)
        ov.shape = tuple(v.shape or ())
        ov.dtype = v.dtype


def _recurrent_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    time_major = attrs.get("time_major", True)
    is_reverse = attrs.get("is_reverse", False)
    # float and integer step inputs ride separate slots so a token-id
    # input cannot disqualify the float slot from differentiation
    step_in_names = list(attrs["step_input_names"]) + \
        list(attrs.get("int_step_input_names", []))
    pre_names = attrs["pre_state_names"]
    post_names = attrs["state_names"]
    out_names = attrs["output_names"]

    xs = list(ins.get("Inputs") or []) + list(ins.get("IntInputs") or [])
    init = ins.get("InitStates", [])
    length = (ins.get("Length") or [None])[0]

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    xs_tm = [x if time_major else jnp.swapaxes(x, 0, 1) for x in xs]
    t_len = xs_tm[0].shape[0]
    steps = jnp.arange(t_len)
    if is_reverse:
        xs_tm = [x[::-1] for x in xs_tm]
        steps = steps[::-1]

    sub_salt = 7919 + attrs["sub_block"]

    def body(carry, scanned):
        t, x_t = scanned
        env = dict(base_env)
        env.update(zip(step_in_names, x_t))
        env.update(zip(pre_names, carry))
        step_ctx = _sub_ctx(ctx, sub_salt)
        if getattr(step_ctx, "_key", None) is not None:
            step_ctx._key = jax.random.fold_in(step_ctx._key, t)
        _run_block(sub, env, step_ctx)
        # carry must be scan-dtype-stable: under AMP a black-list op in
        # the body (e.g. a softmax in an attention cell) can promote a
        # bf16 memory to fp32 — cast updates back to the memory's dtype
        # (x64-degraded, so an int64 init from numpy doesn't warn)
        from ..core import materialize_dtype as _mat

        new_carry = tuple(
            v if v.dtype == _mat(c.dtype) else v.astype(_mat(c.dtype))
            for v, c in ((env[n], c)
                         for n, c in zip(post_names, carry)))
        outs = tuple(env[n] for n in out_names)
        if length is not None:
            valid = t < length          # [B]
            new_carry = tuple(
                jnp.where(_mask_to(valid, n), n, o)
                for n, o in zip(new_carry, carry))
            outs = tuple(
                jnp.where(_mask_to(valid, o), o, jnp.zeros_like(o))
                for o in outs)
        return new_carry, outs

    final, stacked = lax.scan(body, tuple(init), (steps, tuple(xs_tm)))
    if is_reverse:
        stacked = tuple(s[::-1] for s in stacked)
    if not time_major:
        stacked = tuple(jnp.swapaxes(s, 0, 1) for s in stacked)
    return {"Outputs": list(stacked), "FinalStates": list(final)}


register_op(
    "recurrent",
    ["Inputs", "IntInputs", "InitStates", "Params", "Consts", "Length"],
    ["Outputs", "FinalStates"],
    infer=_recurrent_infer, compute=_recurrent_compute,
    no_grad_inputs=("IntInputs", "Consts", "Length"),
)


# ---------------------------------------------------------------------------
# conditional_block: lax.cond over a sub-block (reference
# conditional_block_op.cc) — differentiable
# ---------------------------------------------------------------------------

def _cond_block_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    carried = attrs["carried_names"]

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    pred = jnp.all(ins["Cond"][0])
    carry = tuple(ins.get("LoopVars", []))
    sub_ctx = _sub_ctx(ctx, 104729 + attrs["sub_block"])

    def true_fn(c):
        env = dict(base_env)
        env.update(zip(carried, c))
        _run_block(sub, env, sub_ctx)
        return tuple(env[n] for n in carried)

    out = lax.cond(pred, true_fn, lambda c: c, carry)
    return {"Out": list(out)}


register_op(
    "conditional_block",
    ["Cond", "LoopVars", "Params", "Consts"],
    ["Out"],
    infer=None, compute=_cond_block_compute,
    no_grad_inputs=("Cond", "Consts"),
)


# ---------------------------------------------------------------------------
# while: lax.while_loop over a sub-block (reference while_op.cc:36).
# Forward-only: XLA cannot reverse-differentiate an unbounded loop; the
# training-time recurrence is `recurrent` above.
# ---------------------------------------------------------------------------

def _while_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    carried = attrs["carried_names"]
    cond_name = attrs["cond_name"]
    max_trips = attrs.get("max_trip_count", 0)

    base_env = {}
    base_env.update(zip(attrs.get("param_names", []), ins.get("Params", [])))
    base_env.update(zip(attrs.get("const_names", []), ins.get("Consts", [])))

    carry0 = tuple(ins.get("LoopVars", []))
    idx = {n: i for i, n in enumerate(carried)}
    sub_ctx = _sub_ctx(ctx, 1299709 + attrs["sub_block"])

    def body_env(carry):
        env = dict(base_env)
        env.update(zip(carried, carry))
        _run_block(sub, env, sub_ctx)
        return tuple(env[n] for n in carried)

    if max_trips:
        # bounded, predicated scan: differentiable (the WhileGrad
        # capability, reference while_op.cc:101).  Every step computes
        # the body and selects it only while the condition holds, so any
        # execution taking <= max_trip_count trips matches the unbounded
        # loop exactly; trade-off is max_trip_count body evaluations
        # regardless of the actual trip count.
        def step(carry, _):
            pred = jnp.all(carry[idx[cond_name]])
            new = body_env(carry)
            out = tuple(jnp.where(pred, n, c) for n, c in zip(new, carry))
            return out, None

        out, _ = lax.scan(step, carry0, None, length=int(max_trips))
        return {"Out": list(out)}

    def cond_fn(carry):
        return jnp.all(carry[idx[cond_name]])

    out = lax.while_loop(cond_fn, body_env, carry0)
    return {"Out": list(out)}


def _while_grad_maker(op, no_grad_set):
    from ..framework import grad_var_name

    if not op.attrs.get("max_trip_count", 0):
        # reached only when a live gradient actually flows into the
        # loop's outputs — fail loudly instead of silently freezing the
        # weights
        raise RuntimeError(
            "cannot differentiate through a While loop without a "
            "declared bound: XLA cannot reverse-differentiate an "
            "unbounded lax.while_loop. Pass While(cond, "
            "max_trip_count=N) to lower the loop to a bounded, "
            "predicated (and differentiable) scan, or use "
            "StaticRNN/DynamicRNN for recurrence over a sequence.")
    g_inputs = {slot: list(op.inputs.get(slot, []))
                for slot in ("Condition", "LoopVars", "Params", "Consts")}
    # Out names alias LoopVars (the reference's in-place while contract):
    # their grad names therefore alias too — the grad op reads the
    # output-side grads and overwrites them with the input-side grads
    g_inputs["GRAD::Out"] = [grad_var_name(n) for n in op.outputs["Out"]]
    g_outputs = {}
    any_grad = False
    for slot in ("LoopVars", "Params"):
        outs = []
        for n in op.inputs.get(slot, []):
            if n in no_grad_set:
                outs.append("")
            else:
                outs.append(grad_var_name(n))
                any_grad = True
        g_outputs["GRAD::" + slot] = outs
    if not any_grad:
        return []
    return [dict(type="while_grad", inputs=g_inputs, outputs=g_outputs,
                 attrs=dict(op.attrs))]


def _while_grad_infer(gop, block):
    for slot in ("LoopVars", "Params"):
        for n, g in zip(gop.inputs.get(slot, []),
                        gop.outputs.get("GRAD::" + slot, [])):
            if not g:
                continue
            v = block._find_var_recursive(n)
            if v is not None:
                block.create_var(name=g, shape=v.shape, dtype=v.dtype,
                                 persistable=False)


def _while_grad_compute(ins, attrs, ctx, op_index):
    """Re-run the bounded scan under jax.vjp, differentiating w.r.t. the
    floating loop vars and params individually (the slots mix bool/int
    counters with float carries, so the generic per-slot maker cannot
    serve)."""
    from ..core import dtype_is_floating

    loopvars = list(ins.get("LoopVars", []))
    params = list(ins.get("Params", []))
    d_lv = [i for i, v in enumerate(loopvars)
            if v is not None and dtype_is_floating(v.dtype)]
    d_pr = [i for i, v in enumerate(params)
            if v is not None and dtype_is_floating(v.dtype)]

    fwd_attrs = {k: v for k, v in attrs.items()}

    def fwd(lv_diff, pr_diff):
        lv = list(loopvars)
        for i, v in zip(d_lv, lv_diff):
            lv[i] = v
        pr = list(params)
        for i, v in zip(d_pr, pr_diff):
            pr[i] = v
        full = {"Condition": ins.get("Condition", []),
                "LoopVars": lv, "Params": pr,
                "Consts": ins.get("Consts", [])}
        outs = _while_compute(full, fwd_attrs, ctx, op_index)
        # only the floating outputs (same positions as the floating
        # carries — carry dtypes are loop-invariant): bool/int outputs
        # would demand float0 cotangents
        return [outs["Out"][i] for i in d_lv]

    outs, vjp = jax.vjp(fwd, [loopvars[i] for i in d_lv],
                        [params[i] for i in d_pr])
    gouts = ins.get("GRAD::Out", [])
    cts = []
    for i, o in zip(d_lv, outs):
        g = gouts[i] if i < len(gouts) else None
        cts.append(g.astype(o.dtype) if g is not None
                   else jnp.zeros_like(o))
    d_lv_vals, d_pr_vals = vjp(cts)

    g_lv = [None] * len(loopvars)
    for i, v in zip(d_lv, d_lv_vals):
        g_lv[i] = v
    g_pr = [None] * len(params)
    for i, v in zip(d_pr, d_pr_vals):
        g_pr[i] = v
    return {"GRAD::LoopVars": g_lv, "GRAD::Params": g_pr}


register_op(
    "while",
    ["Condition", "LoopVars", "Params", "Consts"],
    ["Out"],
    infer=None, compute=_while_compute, grad=_while_grad_maker,
)

register_op(
    "while_grad",
    ["Condition", "LoopVars", "Params", "Consts", "GRAD::Out"],
    ["GRAD::LoopVars", "GRAD::Params"],
    infer=_while_grad_infer, compute=_while_grad_compute, grad=None,
)


# ---------------------------------------------------------------------------
# tensor arrays: fixed-capacity device arrays
# (reference tensor_array_read_write_op.cc + lod_array_length_op.cc)
# ---------------------------------------------------------------------------

def _array_write_infer(op, block):
    x = in_var(op, block, "X")
    arr = in_var(op, block, "Array")
    if arr is not None and arr.shape is not None:
        shape = arr.shape
    else:
        shape = (op.attrs["capacity"],) + tuple(x.shape or ())
    set_output(op, block, "Out", shape, x.dtype)


def _array_write_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    arr = (ins.get("Array") or [None])[0]
    if arr is None:
        arr = jnp.zeros((attrs["capacity"],) + x.shape, x.dtype)
    return {"Out": lax.dynamic_update_index_in_dim(arr, x, i, 0)}


register_op(
    "array_write", ["X", "I", "Array"], ["Out"],
    infer=_array_write_infer, compute=_array_write_compute,
    no_grad_inputs=("I",),
)


def _array_read_infer(op, block):
    arr = in_var(op, block, "Array")
    set_output(op, block, "Out", tuple(arr.shape or ())[1:], arr.dtype)


def _array_read_compute(ins, attrs, ctx, op_index):
    arr = ins["Array"][0]
    i = ins["I"][0].reshape(()).astype(jnp.int32)
    return {"Out": lax.dynamic_index_in_dim(arr, i, 0, keepdims=False)}


register_op(
    "array_read", ["Array", "I"], ["Out"],
    infer=_array_read_infer, compute=_array_read_compute,
    no_grad_inputs=("I",),
)


def _array_length_compute(ins, attrs, ctx, op_index):
    return {"Out": jnp.full((1,), ins["X"][0].shape[0], long_dtype())}


register_op(
    "lod_array_length", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), "int64"),
    compute=_array_length_compute, grad=None,
)


# ---------------------------------------------------------------------------
# split/merge by mask (IfElse plumbing, predication-style)
# ---------------------------------------------------------------------------

def _split_lod_tensor_compute(ins, attrs, ctx, op_index):
    # predication redesign: both branches see the full batch; the merge
    # selects.  (The reference physically partitions rows by mask.)
    x = ins["X"][0]
    return {"OutTrue": x, "OutFalse": x}


register_op(
    "split_lod_tensor", ["X", "Mask"], ["OutTrue", "OutFalse"],
    infer=lambda op, block: (
        set_output(op, block, "OutTrue", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
        set_output(op, block, "OutFalse", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
    ),
    compute=_split_lod_tensor_compute, no_grad_inputs=("Mask",),
)


def _merge_lod_tensor_compute(ins, attrs, ctx, op_index):
    mask = ins["Mask"][0]
    in_true, in_false = ins["InTrue"][0], ins["InFalse"][0]
    m = mask.reshape((-1,) + (1,) * (in_true.ndim - 1)).astype(bool)
    return {"Out": jnp.where(m, in_true, in_false)}


register_op(
    "merge_lod_tensor", ["Mask", "InTrue", "InFalse"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "InTrue").shape,
        in_var(op, block, "InTrue").dtype),
    compute=_merge_lod_tensor_compute, no_grad_inputs=("Mask",),
)


# ---------------------------------------------------------------------------
# beam search (reference beam_search_op.cc / beam_search_decode_op.cc),
# re-designed for fixed [batch, beam] layout (no LoD growth)
# ---------------------------------------------------------------------------

def _beam_search_infer(op, block):
    pre = in_var(op, block, "PreIds")
    b, k = pre.shape
    set_output(op, block, "SelectedIds", (b, k), "int64")
    set_output(op, block, "SelectedScores", (b, k),
               in_var(op, block, "PreScores").dtype)
    set_output(op, block, "ParentIdx", (b, k), "int64")


def _beam_search_compute(ins, attrs, ctx, op_index):
    pre_ids = ins["PreIds"][0]            # [B, K] int64
    pre_scores = ins["PreScores"][0]      # [B, K] float
    scores = ins["Scores"][0]             # [B, K, V] step log-probs
    end_id = attrs["end_id"]
    k = scores.shape[1]
    v = scores.shape[2]

    finished = pre_ids == end_id          # [B, K]
    neg_inf = jnp.asarray(-1e9, scores.dtype)
    # finished beams may only re-emit end_id, contributing 0 to the score
    step = jnp.where(finished[:, :, None], neg_inf, scores)
    step = step.at[:, :, end_id].set(
        jnp.where(finished, jnp.zeros_like(pre_scores),
                  scores[:, :, end_id]))
    total = pre_scores[:, :, None] + step  # [B, K, V]
    flat = total.reshape(total.shape[0], k * v)
    top_scores, top_idx = lax.top_k(flat, k)
    parent = (top_idx // v).astype(long_dtype())
    token = (top_idx % v).astype(long_dtype())
    return {"SelectedIds": token, "SelectedScores": top_scores,
            "ParentIdx": parent}


register_op(
    "beam_search", ["PreIds", "PreScores", "Scores"],
    ["SelectedIds", "SelectedScores", "ParentIdx"],
    infer=_beam_search_infer, compute=_beam_search_compute, grad=None,
)


def _beam_search_decode_infer(op, block):
    ids = in_var(op, block, "Ids")        # [T, B, K]
    t, b, k = ids.shape
    set_output(op, block, "SentenceIds", (b, k, t), "int64")
    set_output(op, block, "SentenceScores",
               (b, k), in_var(op, block, "Scores").dtype)


def _beam_search_decode_compute(ins, attrs, ctx, op_index):
    ids = ins["Ids"][0]                   # [T, B, K] tokens per step
    parents = ins["Parents"][0]           # [T, B, K] beam backpointers
    scores = ins["Scores"][0]             # [B, K] final beam scores
    t, b, k = ids.shape
    beam0 = jnp.broadcast_to(jnp.arange(k, dtype=long_dtype()), (b, k))

    def back(carry, xs):
        beam = carry                      # [B, K] position at step t
        ids_t, par_t = xs
        tok = jnp.take_along_axis(ids_t, beam, axis=1)
        prev = jnp.take_along_axis(par_t, beam, axis=1)
        return prev, tok

    _, toks = lax.scan(back, beam0, (ids[::-1], parents[::-1]))
    sent = jnp.transpose(toks[::-1], (1, 2, 0))   # [B, K, T]
    return {"SentenceIds": sent, "SentenceScores": scores}


register_op(
    "beam_search_decode", ["Ids", "Parents", "Scores"],
    ["SentenceIds", "SentenceScores"],
    infer=_beam_search_decode_infer, compute=_beam_search_decode_compute,
    grad=None,
)
