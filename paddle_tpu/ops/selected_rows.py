"""SelectedRows: sparse row-slice gradients.

Parity: reference ``framework/selected_rows.h:32`` (row-index list + value
tensor), ``operators/lookup_table_op.cc`` (sparse grad kernel),
``math/selected_rows_functor.cc`` (merge-add), and the SelectedRows
kernels registered by every optimizer (``sgd_op.cc``, ``adam_op.cc``...)
— re-designed TPU-first:

* A SelectedRows value is a jax pytree ``(rows int32[N], values [N, D])``
  with the table height as static aux data, so it flows through the
  traced program, jit, and pjit like any other value.  ``N`` equals the
  number of looked-up ids (static), never the table height: the backward
  of a lookup touches O(batch·seq) rows, not O(vocab) — the
  correctness-of-scale property the reference gets from SelectedRows.
* Duplicate row merging (reference MergeAdd) uses ``jnp.unique`` with a
  static ``size=`` so it stays jit-compatible: the deduped row list is
  padded with a ``height`` sentinel and updates are applied as masked
  scatter-adds of deltas (duplicate-safe).
* Optimizer sparse kernels implement the reference's *lazy* semantics:
  only touched rows' moments/params move (adam_op.cc SelectedRows kernel);
  untouched rows are bit-identical across the step.
"""

import jax
import jax.numpy as jnp

from ..registry import register_op, set_output, in_var
from ..framework import grad_var_name

__all__ = ["SelectedRows", "merge_rows", "to_dense"]


class SelectedRows:
    """rows: int32[N] indices into dim 0 of a [height, ...] table;
    values: [N, ...] gradient slices; height: static table height."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def __repr__(self):
        return "SelectedRows(rows=%s, values=%s, height=%d)" % (
            self.rows.shape, self.values.shape, self.height)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda sr: ((sr.rows, sr.values), sr.height),
    lambda height, leaves: SelectedRows(leaves[0], leaves[1], height),
)


def merge_rows(sr):
    """Reference MergeAdd: combine duplicate rows (static-shape dedupe).

    Returns (uniq_rows int32[N] padded with ``height`` sentinel,
    merged_values [N, ...], valid bool[N]).
    """
    n = sr.rows.shape[0]
    uniq, inv = jnp.unique(
        sr.rows, size=n, fill_value=sr.height, return_inverse=True)
    merged = jnp.zeros_like(sr.values).at[inv.reshape(-1)].add(sr.values)
    valid = uniq < sr.height
    return uniq.astype(jnp.int32), merged, valid


def to_dense(sr):
    """Densify (reference SelectedRows::Get / scatter semantics)."""
    dense = jnp.zeros((sr.height,) + tuple(sr.values.shape[1:]),
                      sr.values.dtype)
    return dense.at[sr.rows].add(sr.values)


def scatter_update_rows(table, uniq, valid, new_rows, old_rows):
    """table[uniq] <- new_rows where valid, duplicate-sentinel-safe:
    applied as += (new - old) masked to zero on sentinel entries."""
    from .control_flow import _mask_to

    safe = jnp.where(valid, uniq, 0)
    delta = jnp.where(_mask_to(valid, new_rows), new_rows - old_rows, 0)
    return table.at[safe].add(delta)


# ---------------------------------------------------------------------------
# lookup_table sparse grad (reference lookup_table_op.cc grad SelectedRows
# kernel; selected by the layer's is_sparse attr)
# ---------------------------------------------------------------------------

def lookup_table_grad_maker(op, no_grad_set):
    """Custom grad maker: sparse path emits lookup_table_sparse_grad."""
    from ..registry import _auto_grad_maker

    if not op.attrs.get("is_sparse", False):
        return _auto_grad_maker(op, no_grad_set)
    w_name = op.inputs["W"][0]
    if w_name in no_grad_set:
        return []
    return [dict(
        type="lookup_table_sparse_grad",
        inputs={
            "W": list(op.inputs["W"]),
            "Ids": list(op.inputs["Ids"]),
            "GRAD::Out": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        outputs={"GRAD::W": [grad_var_name(w_name)]},
        attrs=dict(op.attrs),
    )]


def _lookup_sparse_grad_infer(op, block):
    w = in_var(op, block, "W")
    for g_name in op.outputs.get("GRAD::W", []):
        if not g_name:
            continue
        block.create_var(name=g_name, shape=w.shape, dtype=w.dtype,
                         persistable=False)


def _lookup_sparse_grad_compute(ins, attrs, ctx, op_index):
    w, ids, gout = ins["W"][0], ins["Ids"][0], ins["GRAD::Out"][0]
    height = w.shape[0]
    flat = ids.reshape(-1).astype(jnp.int32)
    values = gout.reshape(flat.shape[0], w.shape[1])
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        values = values * (flat != pad)[:, None].astype(values.dtype)
    return {"GRAD::W": SelectedRows(flat, values, height)}


register_op(
    "lookup_table_sparse_grad", ["W", "Ids", "GRAD::Out"], ["GRAD::W"],
    infer=_lookup_sparse_grad_infer, compute=_lookup_sparse_grad_compute,
    grad=None, no_grad_inputs=("Ids",),
)


# ---------------------------------------------------------------------------
# get_tensor_from_selected_rows (reference
# get_tensor_from_selected_rows_op.cc): densify for fetching/inspection
# ---------------------------------------------------------------------------

def _get_tensor_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        return {"Out": to_dense(x)}
    return {"Out": x}


register_op(
    "get_tensor_from_selected_rows", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_get_tensor_compute, grad=None,
)
