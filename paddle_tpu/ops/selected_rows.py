"""SelectedRows: sparse row-slice gradients.

Parity: reference ``framework/selected_rows.h:32`` (row-index list + value
tensor), ``operators/lookup_table_op.cc`` (sparse grad kernel),
``math/selected_rows_functor.cc`` (merge-add), and the SelectedRows
kernels registered by every optimizer (``sgd_op.cc``, ``adam_op.cc``...)
— re-designed TPU-first:

* A SelectedRows value is a jax pytree ``(rows int32[N], values [N, D])``
  with the table height as static aux data, so it flows through the
  traced program, jit, and pjit like any other value.  ``N`` equals the
  number of looked-up ids (static), never the table height: the backward
  of a lookup touches O(batch·seq) rows, not O(vocab) — the
  correctness-of-scale property the reference gets from SelectedRows.
* Duplicate row merging (reference MergeAdd) uses ``jnp.unique`` with a
  static ``size=`` so it stays jit-compatible: the deduped row list is
  padded with a ``height`` sentinel and updates are applied as masked
  scatter-adds of deltas (duplicate-safe).
* Optimizer sparse kernels implement the reference's *lazy* semantics:
  only touched rows' moments/params move (adam_op.cc SelectedRows kernel);
  untouched rows are bit-identical across the step.
"""

import re

import jax
import jax.numpy as jnp

from ..core import VarType
from ..registry import register_op, set_output, in_var
from ..framework import grad_var_name

__all__ = ["SelectedRows", "merge_rows", "to_dense", "merged_sumsq",
           "map_values", "sparse_lookup_tables", "is_row_slot_of"]

# the Optimizer._add_accumulator slot strings whose vars are per-row
# state (shape [height, ...] mirroring the param) — scalar accumulators
# (beta1_pow_acc...) are excluded by the height gate at the call sites
_ROW_SLOT_STRS = ("velocity", "momentum", "moment1", "moment2", "moment",
                  "mean_square", "mean_grad", "squared", "linear",
                  "inf_norm", "_avg_squared_grad", "_avg_squared_update")


def is_row_slot_of(name, table):
    """True when ``name`` is an optimizer accumulator var of ``table``
    (``<table>_<slot>_<uid>``, the ``Optimizer._add_accumulator`` +
    ``unique_name.generate`` naming).  The explicit slot list keeps a
    user param that merely shares the table's name prefix (``emb`` vs
    ``emb_out_w_0``) from being row-sharded or delta-encoded as if it
    were optimizer state; callers still apply the shape gate (leading
    dim == table height)."""
    if not name.startswith(table + "_"):
        return False
    return re.fullmatch(
        re.escape(table) + "_(%s)_\\d+" % "|".join(_ROW_SLOT_STRS),
        name) is not None


def sparse_lookup_tables(program, attr="is_sparse"):
    """{table var name: Variable} of every ``lookup_table`` W whose op
    sets ``attr`` (``is_sparse`` / ``is_distributed``), across ALL
    blocks — the one table scan shared by telemetry, the sharding
    policy, and the incremental-checkpoint autodetect."""
    out = {}
    for blk in program.blocks:
        for op in blk.ops:
            if op.type != "lookup_table" or \
                    not op.attrs.get(attr, False):
                continue
            for w in op.inputs.get("W", []):
                v = blk._find_var_recursive(w)
                if v is not None and v.shape and w not in out:
                    out[w] = v
    return out


class SelectedRows:
    """rows: int32[N] indices into dim 0 of a [height, ...] table;
    values: [N, ...] gradient slices; height: static table height."""

    def __init__(self, rows, values, height):
        self.rows = rows
        self.values = values
        self.height = int(height)

    def __repr__(self):
        return "SelectedRows(rows=%s, values=%s, height=%d)" % (
            self.rows.shape, self.values.shape, self.height)


jax.tree_util.register_pytree_node(
    SelectedRows,
    lambda sr: ((sr.rows, sr.values), sr.height),
    lambda height, leaves: SelectedRows(leaves[0], leaves[1], height),
)


def merge_rows(sr):
    """Reference MergeAdd: combine duplicate rows (static-shape dedupe).

    Returns (uniq_rows int32[N] padded with ``height`` sentinel,
    merged_values [N, ...], valid bool[N]).
    """
    n = sr.rows.shape[0]
    uniq, inv = jnp.unique(
        sr.rows, size=n, fill_value=sr.height, return_inverse=True)
    merged = jnp.zeros_like(sr.values).at[inv.reshape(-1)].add(sr.values)
    valid = uniq < sr.height
    return uniq.astype(jnp.int32), merged, valid


def to_dense(sr):
    """Densify (reference SelectedRows::Get / scatter semantics).
    Sentinel rows (``rows == height``, produced by merged/padded
    SelectedRows) are dropped by jax's out-of-bounds scatter mode."""
    dense = jnp.zeros((sr.height,) + tuple(sr.values.shape[1:]),
                      sr.values.dtype)
    return dense.at[sr.rows].add(sr.values, mode="drop")


def map_values(sr, fn):
    """A new SelectedRows with ``fn`` applied to the values (same rows).
    Only valid for fns that commute with duplicate-row merging (scalar
    scale); merge first for anything nonlinear (clip, norms)."""
    return SelectedRows(sr.rows, fn(sr.values), sr.height)


def merged_sumsq(sr):
    """sum(dense(sr) ** 2) without materializing the dense gradient:
    duplicates must merge BEFORE squaring (||sum of dups||^2, not
    sum of ||dup||^2) — padded slots merge to zero and drop out."""
    _, merged, _ = merge_rows(sr)
    return jnp.sum(merged * merged)


def scatter_update_rows(table, uniq, valid, new_rows, old_rows):
    """table[uniq] <- new_rows where valid, duplicate-sentinel-safe:
    applied as += (new - old) masked to zero on sentinel entries."""
    from .control_flow import _mask_to

    safe = jnp.where(valid, uniq, 0)
    delta = jnp.where(_mask_to(valid, new_rows), new_rows - old_rows, 0)
    return table.at[safe].add(delta)


# ---------------------------------------------------------------------------
# lookup_table sparse grad (reference lookup_table_op.cc grad SelectedRows
# kernel; selected by the layer's is_sparse attr)
# ---------------------------------------------------------------------------

def lookup_table_grad_maker(op, no_grad_set):
    """Custom grad maker: sparse path emits lookup_table_sparse_grad."""
    from ..registry import _auto_grad_maker

    if not op.attrs.get("is_sparse", False):
        return _auto_grad_maker(op, no_grad_set)
    w_name = op.inputs["W"][0]
    if w_name in no_grad_set:
        return []
    return [dict(
        type="lookup_table_sparse_grad",
        inputs={
            "W": list(op.inputs["W"]),
            "Ids": list(op.inputs["Ids"]),
            "GRAD::Out": [grad_var_name(n) for n in op.outputs["Out"]],
        },
        outputs={"GRAD::W": [grad_var_name(w_name)]},
        attrs=dict(op.attrs),
    )]


def _lookup_sparse_grad_infer(op, block):
    w = in_var(op, block, "W")
    for g_name in op.outputs.get("GRAD::W", []):
        if not g_name:
            continue
        # typed SELECTED_ROWS so build-time consumers (clip/regularizer
        # appenders) can keep the gradient sparse through aggregation
        block.create_var(name=g_name, shape=w.shape, dtype=w.dtype,
                         persistable=False, type=VarType.SELECTED_ROWS)


def _lookup_sparse_grad_compute(ins, attrs, ctx, op_index):
    w, ids, gout = ins["W"][0], ins["Ids"][0], ins["GRAD::Out"][0]
    height = w.shape[0]
    flat = ids.reshape(-1).astype(jnp.int32)
    values = gout.reshape(flat.shape[0], w.shape[1])
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        values = values * (flat != pad)[:, None].astype(values.dtype)
    return {"GRAD::W": SelectedRows(flat, values, height)}


register_op(
    "lookup_table_sparse_grad", ["W", "Ids", "GRAD::Out"], ["GRAD::W"],
    infer=_lookup_sparse_grad_infer, compute=_lookup_sparse_grad_compute,
    grad=None, no_grad_inputs=("Ids",),
)


# ---------------------------------------------------------------------------
# get_tensor_from_selected_rows (reference
# get_tensor_from_selected_rows_op.cc): densify for fetching/inspection
# ---------------------------------------------------------------------------

def _get_tensor_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        return {"Out": to_dense(x)}
    return {"Out": x}


register_op(
    "get_tensor_from_selected_rows", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_get_tensor_compute, grad=None,
)


# ---------------------------------------------------------------------------
# sparse_weight_decay: lazy L1/L2 regularization on a SelectedRows grad
# (the reference regularizer's SelectedRows path: gather only the touched
# param rows and fold the decay into the merged sparse gradient — the
# dense path's full-table `scale(param) + sum` would materialize an
# O(vocab) gradient and un-lazy the optimizer update)
# ---------------------------------------------------------------------------

def _sparse_decay_infer(op, block):
    g = in_var(op, block, "Grad")
    for name in op.outputs.get("Out", []):
        if name:
            block.create_var(name=name, shape=g.shape, dtype=g.dtype,
                             persistable=False,
                             type=VarType.SELECTED_ROWS)


def _sparse_decay_compute(ins, attrs, ctx, op_index):
    from .control_flow import _mask_to

    g, p = ins["Grad"][0], ins["Param"][0]
    coeff = attrs["coeff"]
    mode = attrs.get("mode", "l2")
    if not isinstance(g, SelectedRows):
        term = p if mode == "l2" else jnp.sign(p)
        return {"Out": g + coeff * term.astype(g.dtype)}
    # merge duplicates FIRST: decay applies once per unique touched row,
    # exactly like the dense grad's per-row decay term
    uniq, merged, valid = merge_rows(g)
    safe = jnp.where(valid, uniq, 0)
    term = p[safe] if mode == "l2" else jnp.sign(p[safe])
    mask = _mask_to(valid, merged).astype(merged.dtype)
    vals = merged + coeff * term.astype(merged.dtype) * mask
    return {"Out": SelectedRows(uniq, vals, g.height)}


register_op(
    "sparse_weight_decay", ["Grad", "Param"], ["Out"],
    infer=_sparse_decay_infer, compute=_sparse_decay_compute, grad=None,
    no_grad_inputs=("Grad", "Param"),
)
