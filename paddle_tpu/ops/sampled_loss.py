"""Sampled / tree-structured classification losses: nce,
hierarchical_sigmoid, bilinear_tensor_product.

Parity: reference ``operators/nce_op.{cc,h}`` (NCE with uniform negative
sampling; cost -log(o/(o+b)) for true and -log(b/(o+b)) for sampled
classes, b = num_neg/num_classes, nce_op.h:94-135),
``operators/hierarchical_sigmoid_op.{cc,h}`` + ``math/matrix_bit_code.cc``
(complete-binary-tree sigmoid path loss via SimpleCode bit arithmetic),
``operators/bilinear_tensor_product_op.cc``.

TPU-first: the per-element Eigen loops become batched gathers + einsums;
negative samples are drawn from the trace-time PRNG key (deterministic
per step, so the auto-vjp recompute sees identical samples).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..registry import register_op, set_output, in_var
from ..core import long_dtype

__all__ = []


# -- nce --------------------------------------------------------------------

def _nce_infer(op, block):
    x = in_var(op, block, "Input")
    label = in_var(op, block, "Label")
    num_true = label.shape[1] if len(label.shape) > 1 and \
        label.shape[1] not in (-1, None) else 1
    num_neg = int(op.attrs.get("num_neg_samples", 10))
    set_output(op, block, "Cost", (x.shape[0], 1), x.dtype)
    set_output(op, block, "SampleLogits",
               (x.shape[0], num_true + num_neg), x.dtype)
    set_output(op, block, "SampleLabels",
               (x.shape[0], num_true + num_neg), "int64")


def _nce_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]                       # [B, D]
    label = ins["Label"][0]                   # [B, num_true]
    if label.ndim == 1:
        label = label[:, None]
    weight = ins["Weight"][0]                 # [C, D]
    biases = ins.get("Bias")
    bias = biases[0] if biases and biases[0] is not None else None
    sw = ins.get("SampleWeight")
    sample_weight = sw[0] if sw and sw[0] is not None else None
    num_classes = int(attrs["num_total_classes"])
    num_neg = int(attrs.get("num_neg_samples", 10))
    b_const = float(num_neg) / num_classes    # nce_op.h:94

    bsz, num_true = x.shape[0], label.shape[1]
    key = ctx.rng_key(op_index)
    negs = jax.random.randint(key, (bsz, num_neg), 0, num_classes)
    samples = jnp.concatenate([label.astype(jnp.int32),
                               negs.astype(jnp.int32)], axis=1)

    w_rows = weight[samples]                  # [B, S, D]
    logits = jnp.einsum("bd,bsd->bs", x, w_rows)
    if bias is not None:
        logits = logits + bias.reshape(-1)[samples]
    o = jax.nn.sigmoid(logits)
    eps = 1e-12
    cost_true = -jnp.log(o[:, :num_true] /
                         (o[:, :num_true] + b_const) + eps)
    cost_neg = -jnp.log(b_const / (o[:, num_true:] + b_const) + eps)
    cost = jnp.sum(cost_true, 1) + jnp.sum(cost_neg, 1)
    if sample_weight is not None:
        cost = cost * sample_weight.reshape(-1)
    return {"Cost": cost[:, None], "SampleLogits": o,
            "SampleLabels": samples.astype(long_dtype())}


register_op(
    "nce", ["Input", "Label", "Weight", "Bias", "SampleWeight"],
    ["Cost", "SampleLogits", "SampleLabels"],
    infer=_nce_infer, compute=_nce_compute,
    no_grad_inputs=("Label", "SampleWeight"), stateful_random=True,
)


# -- hierarchical_sigmoid ---------------------------------------------------

def _hsigmoid_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", (x.shape[0], 1), x.dtype)


def _hsigmoid_compute(ins, attrs, ctx, op_index):
    """SimpleCode tree (math/matrix_bit_code.h): for label l the code is
    c = l + num_classes; path node j has row index (c >> (len-j)) - 1
    and target bit (c >> (len-1-j)) & 1, where len = floor(log2(c)).
    Loss = sum_j BCE-with-logits(x.w_j + b_j, bit_j)."""
    x = ins["X"][0]                           # [B, D]
    w = ins["W"][0]                           # [C-1, D]
    label = ins["Label"][0].reshape(-1)       # [B]
    biases = ins.get("Bias")
    bias = biases[0] if biases and biases[0] is not None else None
    num_classes = int(attrs["num_classes"])
    max_len = int(np.ceil(np.log2(max(num_classes, 2))))

    code = label.astype(jnp.int32) + num_classes  # [B]
    # bit length - 1 == floor(log2(code)), in integer arithmetic:
    # float log2 misrounds near powers of two for codes >= 2^23
    bits = jnp.arange(1, 32)
    clen = jnp.sum((code[:, None] >> bits) > 0, axis=1).astype(jnp.int32)

    j = jnp.arange(max_len + 1)[None, :]      # [1, J]
    active = j < clen[:, None]                # [B, J]
    shift_idx = jnp.maximum(clen[:, None] - j, 0)
    node = jnp.right_shift(code[:, None], shift_idx) - 1
    node = jnp.clip(node, 0, w.shape[0] - 1)
    bit_shift = jnp.maximum(clen[:, None] - 1 - j, 0)
    bit = jnp.bitwise_and(jnp.right_shift(code[:, None], bit_shift), 1)

    w_rows = w[node]                          # [B, J, D]
    pre = jnp.einsum("bd,bjd->bj", x, w_rows)
    if bias is not None:
        pre = pre + bias.reshape(-1)[node]
    # BCE with logits, target = bit
    losses = jax.nn.softplus(pre) - bit.astype(pre.dtype) * pre
    out = jnp.sum(jnp.where(active, losses, 0.0), axis=1)
    return {"Out": out[:, None]}


register_op(
    "hierarchical_sigmoid", ["X", "W", "Label", "Bias"], ["Out"],
    infer=_hsigmoid_infer, compute=_hsigmoid_compute,
    no_grad_inputs=("Label",),
)


# -- bilinear_tensor_product ------------------------------------------------

def _btp_infer(op, block):
    x = in_var(op, block, "X")
    w = in_var(op, block, "Weight")
    set_output(op, block, "Out", (x.shape[0], w.shape[0]), x.dtype)


def _btp_compute(ins, attrs, ctx, op_index):
    """out[b, k] = x[b] . W[k] . y[b] (+ bias[k])
    (bilinear_tensor_product_op.cc) — one einsum on the MXU."""
    x = ins["X"][0]
    y = ins["Y"][0]
    w = ins["Weight"][0]                      # [K, Dx, Dy]
    biases = ins.get("Bias")
    bias = biases[0] if biases and biases[0] is not None else None
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias is not None:
        out = out + bias.reshape(1, -1)
    return {"Out": out}


register_op(
    "bilinear_tensor_product", ["X", "Y", "Weight", "Bias"], ["Out"],
    infer=_btp_infer, compute=_btp_compute,
)
