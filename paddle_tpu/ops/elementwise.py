"""Elementwise binary ops with Fluid's axis-broadcast semantics.

Parity: reference ``elementwise_{add,sub,mul,div,max,min,pow}_op.cc`` and
the comparison/logical families (``compare_op.cc``, ``logical_op.cc``) —
TPU-native: plain jnp broadcasting; XLA fuses these into neighboring
matmuls/convolutions so they cost no extra HBM round-trip.

Fluid's ``axis`` attribute aligns a lower-rank Y against X starting at
``axis`` (elementwise_op_function.h); we reproduce it by right-padding Y
with singleton dims.
"""

import numpy as np

import jax.numpy as jnp

from ..registry import register_op, set_output, in_var, broadcast_shapes


def _align_y(x, y, axis):
    if y.ndim == x.ndim:
        return y
    if axis == -1 or axis is None:
        axis = x.ndim - y.ndim
    pad = x.ndim - axis - y.ndim
    if pad > 0:
        y = y.reshape(y.shape + (1,) * pad)
    return y


def _ew_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    axis = op.attrs.get("axis", -1)
    ys = list(y.shape)
    if len(ys) < len(x.shape):
        a = axis if axis != -1 else len(x.shape) - len(ys)
        ys = [1] * a + ys + [1] * (len(x.shape) - a - len(ys))
    out = broadcast_shapes(tuple(x.shape), tuple(ys))
    set_output(op, block, "Out", out, x.dtype)


def _make_ew(name, fn):
    def compute(ins, attrs, ctx, op_index):
        from .selected_rows import SelectedRows, map_values, to_dense

        x, y = ins["X"][0], ins["Y"][0]
        if isinstance(x, SelectedRows):
            # sparse grad * scalar (the global-norm clip scale) stays
            # sparse: a uniform scale commutes with duplicate-row
            # merging.  Anything else densifies for correctness.
            if name == "elementwise_mul" and \
                    int(np.prod(np.shape(y))) == 1:
                return {"Out": map_values(
                    x, lambda v: v * jnp.reshape(y, ()).astype(v.dtype))}
            x = to_dense(x)
        y = _align_y(x, y, attrs.get("axis", -1))
        return {"Out": fn(x, y)}

    register_op(name, ["X", "Y"], ["Out"], infer=_ew_infer, compute=compute)


_make_ew("elementwise_add", lambda x, y: x + y)
_make_ew("elementwise_sub", lambda x, y: x - y)
_make_ew("elementwise_mul", lambda x, y: x * y)
_make_ew("elementwise_div", lambda x, y: x / y)
_make_ew("elementwise_max", jnp.maximum)
_make_ew("elementwise_min", jnp.minimum)
_make_ew("elementwise_pow", jnp.power)
_make_ew("elementwise_mod", jnp.mod)
_make_ew("elementwise_floordiv", jnp.floor_divide)


# -- comparisons (compare_op.cc) -- outputs bool, not differentiable --------

def _cmp_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    out = broadcast_shapes(tuple(x.shape), tuple(y.shape))
    set_output(op, block, "Out", out, np.bool_)


def _make_cmp(name, fn):
    register_op(
        name, ["X", "Y"], ["Out"], infer=_cmp_infer,
        compute=lambda ins, attrs, ctx, op_index: {
            "Out": fn(ins["X"][0], ins["Y"][0])
        },
        grad=None,
    )


_make_cmp("less_than", lambda x, y: x < y)
_make_cmp("less_equal", lambda x, y: x <= y)
_make_cmp("greater_than", lambda x, y: x > y)
_make_cmp("greater_equal", lambda x, y: x >= y)
_make_cmp("equal", lambda x, y: x == y)
_make_cmp("not_equal", lambda x, y: x != y)


# -- logical ops (logical_op.cc) --------------------------------------------

def _make_logical(name, fn, unary=False):
    slots = ["X"] if unary else ["X", "Y"]
    register_op(
        name, slots, ["Out"],
        infer=(lambda op, block: set_output(
            op, block, "Out", in_var(op, block, "X").shape, np.bool_))
        if unary else _cmp_infer,
        compute=(lambda ins, attrs, ctx, op_index: {"Out": fn(ins["X"][0])})
        if unary else (lambda ins, attrs, ctx, op_index: {
            "Out": fn(ins["X"][0], ins["Y"][0])}),
        grad=None,
    )


_make_logical("logical_and", jnp.logical_and)
_make_logical("logical_or", jnp.logical_or)
_make_logical("logical_xor", jnp.logical_xor)
_make_logical("logical_not", jnp.logical_not, unary=True)
