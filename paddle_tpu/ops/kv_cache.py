"""KV-cache write op — the serving engine's donated in-place cache update.

The reference framework has no KV-cache story (its inference stack
re-runs the full decoder per step); this op is the TPU-native primitive
the serving engine's decode loop is built on.  A cache is an ordinary
persistable scope variable ``[S, H, Tmax, D]`` (S = decode slots): the
executor classifies it as state, and because training-style state
donation applies, the XLA-level update is **in place** — the decode step
never copies the cache through HBM, it overwrites one ``[t, D]`` stripe
per (slot, head).

``kv_cache_write(Cache, X, Pos, Slot?) -> Out``:

* ``Cache`` [S, H, Tmax, D] — the persistent cache (Out reuses the SAME
  variable name, making the op a read-modify-write on executor state);
* ``X``     [B, H, t, D]    — new keys/values for B requests;
* ``Pos``   [B] int32       — per-request time offset (0 for prefill,
  the current length for decode);
* ``Slot``  [B] int32, optional — which cache slot each request owns.
  Omitted = identity (B == S, row b writes slot b): the decode-loop
  fast path, lowered as one vmapped dynamic_update_slice.  Present =
  scattered prefill (an admitted batch lands in recycled slots).

Writes clamp like ``lax.dynamic_update_slice`` (pos+t is bounded by the
engine's bucket admission, so clamping never fires in practice).  No
gradient: serving is forward-only, and a cache write has no meaningful
cotangent (``grad=None`` keeps backward.py from ever differentiating
through it).
"""

import jax
import jax.numpy as jnp

from ..registry import register_op, set_output, in_var


def _kv_cache_write_infer(op, block):
    cache = in_var(op, block, "Cache")
    x = in_var(op, block, "X")
    if cache is None or x is None:
        raise ValueError("kv_cache_write needs Cache and X inputs")
    if len(cache.shape) != 4 or len(x.shape) != 4:
        raise ValueError(
            "kv_cache_write expects Cache [S, H, Tmax, D] and X "
            "[B, H, t, D], got %s / %s" % (cache.shape, x.shape))
    set_output(op, block, "Out", cache.shape, cache.dtype)


def _kv_cache_write_compute(ins, attrs, ctx, op_index):
    cache = ins["Cache"][0]
    x = ins["X"][0].astype(cache.dtype)
    pos = ins["Pos"][0].astype(jnp.int32).reshape(-1)
    slot = ins.get("Slot", [None])[0]
    if slot is None:
        # decode fast path: row b writes slot b, one vmapped in-place
        # stripe update across the whole slot batch
        out = jax.vmap(
            lambda c, xb, p: jax.lax.dynamic_update_slice(
                c, xb, (0, p, 0)))(cache, x, pos)
        return {"Out": out}
    slot = slot.astype(jnp.int32).reshape(-1)
    # scattered prefill: B is a trace-time constant (the admitted batch),
    # one dynamic_update_slice per request row
    out = cache
    for b in range(x.shape[0]):
        out = jax.lax.dynamic_update_slice(
            out, x[b][None], (slot[b], 0, pos[b], 0))
    return {"Out": out}


register_op(
    "kv_cache_write", ["Cache", "X", "Pos", "Slot"], ["Out"],
    infer=_kv_cache_write_infer, compute=_kv_cache_write_compute,
    grad=None, no_grad_inputs=("Pos", "Slot"),
)
