"""KV-cache write op — the serving engine's donated in-place cache update.

The reference framework has no KV-cache story (its inference stack
re-runs the full decoder per step); this op is the TPU-native primitive
the serving engine's decode loop is built on.  A cache is an ordinary
persistable scope variable ``[S, H, Tmax, D]`` (S = decode slots): the
executor classifies it as state, and because training-style state
donation applies, the XLA-level update is **in place** — the decode step
never copies the cache through HBM, it overwrites one ``[t, D]`` stripe
per (slot, head).

``kv_cache_write(Cache, X, Pos, Slot?) -> Out``:

* ``Cache`` [S, H, Tmax, D] — the persistent cache (Out reuses the SAME
  variable name, making the op a read-modify-write on executor state);
* ``X``     [B, H, t, D]    — new keys/values for B requests;
* ``Pos``   [B] int32       — per-request time offset (0 for prefill,
  the current length for decode);
* ``Slot``  [B] int32, optional — which cache slot each request owns.
  Omitted = identity (B == S, row b writes slot b): the decode-loop
  fast path, lowered as one vmapped dynamic_update_slice.  Present =
  scattered prefill (an admitted batch lands in recycled slots).

Writes clamp like ``lax.dynamic_update_slice`` (pos+t is bounded by the
engine's bucket admission, so clamping never fires in practice).  No
gradient: serving is forward-only, and a cache write has no meaningful
cotangent (``grad=None`` keeps backward.py from ever differentiating
through it).
"""

import jax
import jax.numpy as jnp

from ..registry import register_op, set_output, in_var


def _kv_cache_write_infer(op, block):
    cache = in_var(op, block, "Cache")
    x = in_var(op, block, "X")
    if cache is None or x is None:
        raise ValueError("kv_cache_write needs Cache and X inputs")
    if len(cache.shape) != 4 or len(x.shape) != 4:
        raise ValueError(
            "kv_cache_write expects Cache [S, H, Tmax, D] and X "
            "[B, H, t, D], got %s / %s" % (cache.shape, x.shape))
    set_output(op, block, "Out", cache.shape, cache.dtype)


def _kv_cache_write_compute(ins, attrs, ctx, op_index):
    cache = ins["Cache"][0]
    x = ins["X"][0].astype(cache.dtype)
    pos = ins["Pos"][0].astype(jnp.int32).reshape(-1)
    slot = ins.get("Slot", [None])[0]
    if slot is None:
        # decode fast path: row b writes slot b, one vmapped in-place
        # stripe update across the whole slot batch
        out = jax.vmap(
            lambda c, xb, p: jax.lax.dynamic_update_slice(
                c, xb, (0, p, 0)))(cache, x, pos)
        return {"Out": out}
    slot = slot.astype(jnp.int32).reshape(-1)
    # scattered prefill: B is a trace-time constant (the admitted batch),
    # one dynamic_update_slice per request row
    out = cache
    for b in range(x.shape[0]):
        out = jax.lax.dynamic_update_slice(
            out, x[b][None], (slot[b], 0, pos[b], 0))
    return {"Out": out}


register_op(
    "kv_cache_write", ["Cache", "X", "Pos", "Slot"], ["Out"],
    infer=_kv_cache_write_infer, compute=_kv_cache_write_compute,
    grad=None, no_grad_inputs=("Pos", "Slot"),
)


# ---------------------------------------------------------------------------
# paged KV cache (ISSUE 16): block-indexed writes over a page table
# ---------------------------------------------------------------------------
#
# The fixed-region cache above pays HBM at the bucket bound per slot; the
# paged cache pays per PAGE ACTUALLY WRITTEN.  The pool is one persistable
# var ``[P, H, page_size, D]`` shared by every slot; a host-owned page
# table ``[S, max_pages]`` int32 maps each slot's logical page j to a
# physical pool page (entries past the slot's valid length are arbitrary
# — attention masks them via k_len exactly like stale fixed-region
# content).  Sharing a prompt prefix across slots is a page-table aliasing
# decision, not a copy: aliased pages hold identical K/V by construction
# (causal prefix K/V depend only on prefix tokens), so a re-prefill
# through a shared page re-writes identical content — a semantic no-op.
#
# ``kv_cache_paged_write(Cache, X, Pos, PageTable, Slot?, Scale?)``:
#
# * ``Cache``     [P, H, ps, D] — the page pool (float, or int8 under
#   quantized KV — then ``Scale`` [P, H, ps] carries the per-token-row
#   dequant scales, the per-channel grid along the time axis);
# * ``X``         [B, H, t, D]  — new keys/values;
# * ``Pos``       [B] int32     — global time offset of X's first token;
# * ``PageTable`` [S, max_pages] int32 — per-slot physical page lists;
# * ``Slot``      [B] int32, optional — identity when omitted (decode).
#
# Decode (t == 1): one scatter row per slot at page
# ``table[b, pos // ps]``, offset ``pos % ps``.  Prefill (t > 1)
# requires ``t % ps == 0`` (bucket bounds are page-aligned by the
# serving admission) and scatters whole pages.


def _paged_write_infer(op, block):
    cache = in_var(op, block, "Cache")
    x = in_var(op, block, "X")
    table = in_var(op, block, "PageTable")
    if cache is None or x is None or table is None:
        raise ValueError(
            "kv_cache_paged_write needs Cache, X and PageTable inputs")
    if len(cache.shape) != 4 or len(x.shape) != 4 or len(table.shape) != 2:
        raise ValueError(
            "kv_cache_paged_write expects Cache [P, H, ps, D], X "
            "[B, H, t, D], PageTable [S, max_pages]; got %s / %s / %s"
            % (cache.shape, x.shape, table.shape))
    set_output(op, block, "Out", cache.shape, cache.dtype)
    scale = in_var(op, block, "Scale")
    if scale is not None:
        set_output(op, block, "OutScale", scale.shape, scale.dtype)


def _quantize_rows(x):
    """Per-token-row int8 grid: one abs-max scale per (token, head) row
    over the D channels — the per-channel machinery of ``ops/quantize``
    applied along the KV time axis.  Returns (int8 values, f32 scales
    with trailing D reduced away)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-12) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _paged_write_compute(ins, attrs, ctx, op_index):
    cache = ins["Cache"][0]
    x = ins["X"][0]
    pos = ins["Pos"][0].astype(jnp.int32).reshape(-1)
    table = ins["PageTable"][0].astype(jnp.int32)
    slot = ins.get("Slot", [None])[0]
    scales = ins.get("Scale", [None])[0]
    quantized = cache.dtype == jnp.int8
    ps = cache.shape[2]
    b, h, t, d = x.shape
    out = {}
    if quantized:
        qx, qs = _quantize_rows(x)           # [B,H,t,D] int8, [B,H,t] f32
    else:
        qx, qs = x.astype(cache.dtype), None
    if t == 1:
        # decode fast path: row b writes one token of slot b — page and
        # offset from the slot's own table row, one batched scatter
        rows = jnp.arange(b, dtype=jnp.int32) if slot is None \
            else slot.astype(jnp.int32).reshape(-1)
        page = table[rows, pos // ps]                       # [B]
        off = pos % ps                                      # [B]
        out["Out"] = cache.at[page, :, off, :].set(
            qx[:, :, 0, :], mode="drop")
        if quantized and scales is not None:
            out["OutScale"] = scales.at[page, :, off].set(
                qs[:, :, 0], mode="drop")
        return out
    rows = jnp.arange(b, dtype=jnp.int32) if slot is None \
        else slot.astype(jnp.int32).reshape(-1)
    if t % ps:
        # k-token verify shape (speculative decoding): t is a small
        # trace-time constant, not page-aligned — scatter per token.
        # Tokens straddle a page boundary correctly because each token
        # looks up its own page.
        cur_s = scales
        for j in range(t):
            page = table[rows, (pos + j) // ps]
            off = (pos + j) % ps
            cache = cache.at[page, :, off, :].set(qx[:, :, j, :],
                                                  mode="drop")
            if quantized and cur_s is not None:
                cur_s = cur_s.at[page, :, off].set(qs[:, :, j],
                                                   mode="drop")
        out["Out"] = cache
        if quantized and cur_s is not None:
            out["OutScale"] = cur_s
        return out
    # prefill: t is page-aligned; scatter whole pages.  B and t are
    # trace-time constants (the admitted bucket), so the page count per
    # request is static: [B, H, npg, ps, D] -> [B*npg] pool rows.
    npg = t // ps
    pages = table[rows][:, :npg].reshape(-1)                # [B*npg]
    chunks = qx.reshape(b, h, npg, ps, d).transpose(0, 2, 1, 3, 4)
    out["Out"] = cache.at[pages].set(
        chunks.reshape(b * npg, h, ps, d), mode="drop")
    if quantized and scales is not None:
        schunks = qs.reshape(b, h, npg, ps).transpose(0, 2, 1, 3)
        out["OutScale"] = scales.at[pages].set(
            schunks.reshape(b * npg, h, ps), mode="drop")
    return out


register_op(
    "kv_cache_paged_write",
    ["Cache", "X", "Pos", "PageTable", "Slot", "Scale"],
    ["Out", "OutScale"],
    infer=_paged_write_infer, compute=_paged_write_compute,
    grad=None, no_grad_inputs=("Pos", "PageTable", "Slot", "Scale"),
)
