"""pipeline_region op — GPipe over the ``pp`` mesh axis, from the Program.

Lowering of ``layers.Pipeline`` (no reference analog; SURVEY.md §2.4 lists
pipeline parallelism as absent upstream).  The op owns a sub-block whose
ops are partitioned into S structurally-identical stages.  Two kernels:

* single-device (or no populated ``pp`` axis): run the stages
  sequentially per microbatch — the semantic ground truth.
* mesh with ``pp`` axis of size S (threaded by ParallelExecutor as
  ``ctx.mesh``): classic GPipe — per-stage parameters stack on a leading
  stage dim sharded over ``pp``, activations flow stage-to-stage with
  ``ppermute``, one ``lax.fori_loop`` of M + S - 1 ticks.

Both kernels execute the SAME stage template (stage 0's op list bound to
stage s's parameters) with the SAME per-stage PRNG fold, so dropout masks
— and therefore losses — are bit-identical between the sequential and
pipelined schedules when the batch is not dp-sharded inside the region
(dp == 1) or the region draws no randomness.  With dp > 1 the microbatch
slices shard over dp (each replica pipelines its own slice — no redundant
compute) and in-stage random draws decorrelate per dp shard.  Dropout
masks are drawn per (stage, microbatch) — both schedules fold the stage
key by the microbatch index identically, so regularization statistics
match the unpipelined model and schedule parity stays exact.

Gradients ride the registry's generic auto-vjp: the backward op re-runs
this kernel under ``jax.vjp``, which differentiates the fori_loop +
ppermute schedule — microbatch gradient accumulation IS the autodiff of
the loop.  Inside stages the mesh is NOT re-exposed (no nested sp ring
inside pp; sequence parallelism composes with dp instead).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import registry
from ..registry import ComputeContext, register_op, set_output, in_var


def _pipeline_infer(op, block):
    c = in_var(op, block, "Carry")
    set_output(op, block, "Out", c.shape, c.dtype)


def _stage_ctx(ctx, base_key, stage_idx):
    sub = ComputeContext(
        key=None if base_key is None else jax.random.fold_in(base_key,
                                                             stage_idx),
        is_test=getattr(ctx, "is_test", False),
        platform=getattr(ctx, "platform", None))
    sub.program = ctx.program
    sub.amp = getattr(ctx, "amp", None)
    return sub


def _stage_param_names(ops, param_set):
    seen, out = set(), []
    for o in ops:
        for n in o.input_arg_names:
            if n in param_set and n not in seen:
                seen.add(n)
                out.append(n)
    return out


def _stage_signature(ops, carry_in, carry_out, stage_params, side_names,
                     const_set):
    """Canonical structure of one stage: op types, attrs, and each
    input/output name's ROLE (not its spelling)."""
    pidx = {n: j for j, n in enumerate(stage_params)}
    sides = set(side_names)
    local = {}                      # name -> (producer op idx, slot, pos)

    def role(n):
        if n == carry_in:
            return ("carry",)
        if n in pidx:
            return ("param", pidx[n])
        if n in sides:
            return ("side", n)      # sides are shared: names must match
        if n in const_set:
            return ("const", n)
        if n in local:
            return ("local",) + local[n]
        return ("extern", n)

    sig = []
    for i, o in enumerate(ops):
        ins_sig = tuple(
            (slot, tuple(role(n) for n in names))
            for slot, names in sorted(o.inputs.items()))
        attrs_sig = tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in o.attrs.items()))
        sig.append((o.type, ins_sig, attrs_sig))
        for slot, names in sorted(o.outputs.items()):
            for pos, n in enumerate(names):
                if n:
                    local[n] = (i, slot, pos)
    sig.append(("__carry_out__", role(carry_out)))
    return sig


def _pipeline_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    s_count = attrs["stages"]
    bounds = attrs["stage_bounds"]
    carry0 = ins["Carry"][0]
    b = carry0.shape[0]
    m = attrs.get("microbatches") or s_count
    if b % m:
        raise ValueError(
            "pipeline_region: microbatches (%d) must divide the batch (%d)"
            % (m, b))
    mb = b // m

    side_names = list(attrs["side_names"]) + \
        list(attrs.get("int_side_names", []))
    side_vals = list(ins.get("Sides", [])) + list(ins.get("IntSides", []))
    param_names = attrs["param_names"]
    param_vals = dict(zip(param_names, ins.get("Params", [])))
    const_env = dict(zip(attrs["const_names"], ins.get("Consts", [])))

    ranges = [(0 if i == 0 else bounds[i - 1], bounds[i])
              for i in range(s_count)]
    stage_ops = [sub.ops[a:e] for a, e in ranges]
    param_set = set(param_names)
    per_stage = [_stage_param_names(ops, param_set) for ops in stage_ops]
    t_ops = stage_ops[0]
    t_params = per_stage[0]
    # structural identity is checked on a FULL signature — op types,
    # attrs, and the role of every input/output name (carry / param slot /
    # side / const / stage-local producer).  Type-only comparison would
    # let e.g. per-stage dropout rates or a side-var swap silently run
    # stage 0's template with wrong math on every stage.
    sigs = [_stage_signature(stage_ops[s], attrs["carry_in_names"][s],
                             attrs["carry_out_names"][s], per_stage[s],
                             side_names, set(attrs["const_names"]))
            for s in range(s_count)]
    for s in range(1, s_count):
        if sigs[s] != sigs[0]:
            for j, (a, b2) in enumerate(zip(sigs[s], sigs[0])):
                if a != b2:
                    raise ValueError(
                        "pipeline_region stages must be structurally "
                        "identical: stage %d differs from stage 0 at "
                        "element %d:\n  stage %d: %s\n  stage 0: %s"
                        % (s, j, s, a, b2))
            raise ValueError(
                "pipeline_region: stage %d signature length differs "
                "from stage 0" % s)
    stacked = []
    for j in range(len(t_params)):
        vals = [param_vals[per_stage[s][j]] for s in range(s_count)]
        shapes = {tuple(v.shape) for v in vals}
        if len(shapes) != 1:
            raise ValueError(
                "param %r (slot %d) has mismatched shapes across stages: "
                "%s" % (t_params[j], j, sorted(shapes)))
        stacked.append(jnp.stack(vals))

    carry_in0 = attrs["carry_in_names"][0]
    carry_out0 = attrs["carry_out_names"][0]
    base_key = None
    try:
        base_key = ctx.rng_key(op_index)
    except RuntimeError:
        pass

    def stage_fn(stage_idx, pvals, carry, sides_mb, key_extra=None,
                 mb_idx=None):
        env = dict(const_env)
        env.update(zip(t_params, pvals))
        env.update(zip(side_names, sides_mb))
        env[carry_in0] = carry
        key = base_key
        if key is not None and mb_idx is not None:
            # decorrelate in-stage random draws per MICROBATCH: without
            # this every microbatch in the region shares one dropout
            # mask, a correlated-regularization divergence from the
            # unpipelined model.  Both schedules fold by the same
            # microbatch index, so sequential/GPipe parity is exact.
            key = jax.random.fold_in(key, mb_idx)
        if key is not None and key_extra is not None:
            # dp-sharded schedule: decorrelate in-stage random draws per
            # dp shard (each shard sees a different batch slice)
            key = jax.random.fold_in(key, key_extra)
        sctx = _stage_ctx(ctx, key, stage_idx)
        for j, o in enumerate(t_ops):
            registry.compute_op(o, env, sctx, op_index=j)
        return env[carry_out0].astype(carry0.dtype)

    side_mb = [v.reshape((m, mb) + tuple(v.shape[1:])) for v in side_vals]
    x_mb = carry0.reshape((m, mb) + tuple(carry0.shape[1:]))

    mesh = getattr(ctx, "mesh", None)
    pp_ok = False
    if mesh is not None:
        from ..parallel.mesh import AXIS_PP
        pp_ok = AXIS_PP in mesh.axis_names and \
            mesh.shape[AXIS_PP] == s_count and s_count > 1
    if not pp_ok:
        # sequential ground truth: same template, same PRNG folds
        outs = []
        for t in range(m):
            c = x_mb[t]
            for s in range(s_count):
                c = stage_fn(s, [p[s] for p in stacked], c,
                             [sv[t] for sv in side_mb], mb_idx=t)
            outs.append(c)
        out = jnp.stack(outs).reshape(carry0.shape)
        return {"Out": out}

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import AXIS_DP, AXIS_PP, shard_map_norep

    # shard the microbatch batch dim over dp so dp replicas process their
    # own batch slices through the pipeline (instead of redundantly
    # recomputing the full batch); in-stage random draws then differ per
    # dp shard (sequential parity remains exact when dp == 1 or the
    # region draws no randomness)
    dp = mesh.shape.get(AXIS_DP, 1) if hasattr(mesh.shape, "get") else (
        mesh.shape[AXIS_DP] if AXIS_DP in mesh.axis_names else 1)
    dp_sharded = AXIS_DP in mesh.axis_names and dp > 1 and mb % dp == 0
    mb_spec = P(None, AXIS_DP) if dp_sharded else P()

    def body(stacked_local, x_mb, side_mb):
        s_idx = lax.axis_index(AXIS_PP)
        my_params = [p[0] for p in stacked_local]
        extra = lax.axis_index(AXIS_DP) if dp_sharded else None

        def tick(t, st):
            cur, outs = st
            fresh = x_mb[jnp.clip(t, 0, m - 1)]
            cur = jnp.where(s_idx == 0, fresh, cur)
            my_mb = jnp.clip(t - s_idx, 0, m - 1)
            sides_t = [lax.dynamic_index_in_dim(v, my_mb, 0,
                                                keepdims=False)
                       for v in side_mb]
            out = stage_fn(s_idx, my_params, cur, sides_t, extra,
                           mb_idx=my_mb)
            done = t - (s_count - 1)
            take = (s_idx == s_count - 1) & (done >= 0)
            updated = lax.dynamic_update_index_in_dim(
                outs, out, jnp.clip(done, 0, m - 1), 0)
            outs = jnp.where(take, updated, outs)
            nxt = lax.ppermute(out, AXIS_PP,
                               [(j, (j + 1) % s_count)
                                for j in range(s_count)])
            return nxt, outs

        outs0 = jnp.zeros_like(x_mb)
        cur0 = jnp.zeros_like(x_mb[0])
        _, outs = lax.fori_loop(0, m + s_count - 1, tick, (cur0, outs0))
        # broadcast the last stage's collected outputs to every device
        mask = (s_idx == s_count - 1).astype(outs.dtype)
        return lax.psum(outs * mask, AXIS_PP)

    # GSPMD workaround (jax 0.4.37, reproduced in isolation): a
    # concatenate/stack computed INSIDE jit and fed straight into a
    # shard_map whose in_spec shards it over the second axis of a
    # multi-axis mesh comes back scaled by the OTHER axis's size — the
    # partitioner lays the stack out sharded and the shard_map input
    # conversion sums shards instead of gathering them (echoing the
    # stacked value through an identity shard_map multiplies it by dp).
    # Pinning the stacked params to a replicated layout before the
    # shard_map sidesteps the bad partition; they were replicated as
    # separate state vars anyway, so this adds no memory.
    from jax.sharding import NamedSharding
    rep = NamedSharding(mesh, P())
    stacked = [jax.lax.with_sharding_constraint(p, rep) for p in stacked]
    fn = shard_map_norep(
        body, mesh,
        in_specs=([P(AXIS_PP)] * len(stacked), mb_spec,
                  [mb_spec] * len(side_mb)),
        out_specs=mb_spec)
    outs = fn(stacked, x_mb, side_mb)
    return {"Out": outs.reshape(carry0.shape)}


register_op(
    "pipeline_region", ["Carry", "Sides", "IntSides", "Params", "Consts"],
    ["Out"], infer=_pipeline_infer, compute=_pipeline_compute,
    no_grad_inputs=("IntSides",), stateful_random=True,
)
