"""pipeline_region op — scheduled pipelining over the ``pp`` mesh axis.

Lowering of ``layers.Pipeline`` (no reference analog; SURVEY.md §2.4 lists
pipeline parallelism as absent upstream).  The op owns a sub-block whose
ops are partitioned into structurally-identical stages.  Kernels:

* single-device (or no populated ``pp`` axis): run the stages
  sequentially per microbatch — the semantic ground truth.
* mesh with a ``pp`` axis (threaded by ParallelExecutor as
  ``ctx.mesh``), schedule selected by
  ``BuildStrategy.pipeline_schedule`` (``ctx.pipeline_schedule``;
  ``ctx.pipeline_microbatches`` overrides the microbatch attr):

  - ``gpipe`` (default): per-stage parameters stack on a leading stage
    dim sharded over ``pp``, activations flow stage-to-stage with
    ``ppermute``, one ``lax.fori_loop`` of M + S - 1 ticks.
  - ``1f1b``: same forward schedule as a ``jax.custom_vjp`` whose
    backward is a combined M + 2(S-1)-tick loop — each tick recomputes
    one stage forward just-in-time (stashing its INPUT in a
    min(M, 2S-1)-slot circular buffer, so backward memory is
    M-independent) and retires one stage backward via per-stage
    ``jax.vjp``, cotangents flowing down-ring.  Consts and PRNG key
    material ride as explicit custom_vjp arguments (closing over
    outer-trace tracers is illegal there).
  - ``interleaved``: the program's S_total stages split round-robin
    into v = S_total/pp chunks per device (requires S_total % pp == 0
    and M % pp == 0); groups of pp microbatches ride the ring v times,
    vM + S - 1 ticks — bubble shrinks by ~v at equal (S, M).

  The per-tick stage-idle accounting of the executed schedule
  (``parallel.pipeline.schedule_stats``) feeds the goodput ledger's
  ``pipeline_bubble`` bucket via the ParallelExecutor.

Both kernels execute the SAME stage template (stage 0's op list bound to
stage s's parameters) with the SAME per-stage PRNG fold, so dropout masks
— and therefore losses — are bit-identical between the sequential and
pipelined schedules when the batch is not dp-sharded inside the region
(dp == 1) or the region draws no randomness.  With dp > 1 the microbatch
slices shard over dp (each replica pipelines its own slice — no redundant
compute) and in-stage random draws decorrelate per dp shard.  Dropout
masks are drawn per (stage, microbatch) — both schedules fold the stage
key by the microbatch index identically, so regularization statistics
match the unpipelined model and schedule parity stays exact.

Gradients ride the registry's generic auto-vjp: the backward op re-runs
this kernel under ``jax.vjp``, which differentiates the fori_loop +
ppermute schedule — microbatch gradient accumulation IS the autodiff of
the loop.  Inside stages the mesh is NOT re-exposed (no nested sp ring
inside pp; sequence parallelism composes with dp instead).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import registry
from ..registry import ComputeContext, register_op, set_output, in_var


def _pipeline_infer(op, block):
    c = in_var(op, block, "Carry")
    set_output(op, block, "Out", c.shape, c.dtype)


def _stage_ctx(ctx, base_key, stage_idx):
    sub = ComputeContext(
        key=None if base_key is None else jax.random.fold_in(base_key,
                                                             stage_idx),
        is_test=getattr(ctx, "is_test", False),
        platform=getattr(ctx, "platform", None))
    sub.program = ctx.program
    sub.amp = getattr(ctx, "amp", None)
    return sub


def _stage_param_names(ops, param_set):
    seen, out = set(), []
    for o in ops:
        for n in o.input_arg_names:
            if n in param_set and n not in seen:
                seen.add(n)
                out.append(n)
    return out


def _stage_signature(ops, carry_in, carry_out, stage_params, side_names,
                     const_set):
    """Canonical structure of one stage: op types, attrs, and each
    input/output name's ROLE (not its spelling)."""
    pidx = {n: j for j, n in enumerate(stage_params)}
    sides = set(side_names)
    local = {}                      # name -> (producer op idx, slot, pos)

    def role(n):
        if n == carry_in:
            return ("carry",)
        if n in pidx:
            return ("param", pidx[n])
        if n in sides:
            return ("side", n)      # sides are shared: names must match
        if n in const_set:
            return ("const", n)
        if n in local:
            return ("local",) + local[n]
        return ("extern", n)

    sig = []
    for i, o in enumerate(ops):
        ins_sig = tuple(
            (slot, tuple(role(n) for n in names))
            for slot, names in sorted(o.inputs.items()))
        attrs_sig = tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in o.attrs.items()))
        sig.append((o.type, ins_sig, attrs_sig))
        for slot, names in sorted(o.outputs.items()):
            for pos, n in enumerate(names):
                if n:
                    local[n] = (i, slot, pos)
    sig.append(("__carry_out__", role(carry_out)))
    return sig


def _pipeline_compute(ins, attrs, ctx, op_index):
    program = ctx.program
    sub = program.block(attrs["sub_block"])
    s_count = attrs["stages"]
    bounds = attrs["stage_bounds"]
    carry0 = ins["Carry"][0]
    b = carry0.shape[0]
    m = attrs.get("microbatches") or s_count
    # BuildStrategy.pipeline_microbatches (tune_pipeline's knob)
    # overrides the program attr — on the mesh path AND the sequential
    # ground truth, so schedule-parity checks compare equal microbatch
    # structures (PRNG folds are per-microbatch)
    override_m = getattr(ctx, "pipeline_microbatches", None)
    if override_m:
        m = int(override_m)
    if b % m:
        raise ValueError(
            "pipeline_region: microbatches (%d) must divide the batch (%d)"
            % (m, b))
    mb = b // m

    side_names = list(attrs["side_names"]) + \
        list(attrs.get("int_side_names", []))
    side_vals = list(ins.get("Sides", [])) + list(ins.get("IntSides", []))
    param_names = attrs["param_names"]
    param_vals = dict(zip(param_names, ins.get("Params", [])))
    const_env = dict(zip(attrs["const_names"], ins.get("Consts", [])))

    ranges = [(0 if i == 0 else bounds[i - 1], bounds[i])
              for i in range(s_count)]
    stage_ops = [sub.ops[a:e] for a, e in ranges]
    param_set = set(param_names)
    per_stage = [_stage_param_names(ops, param_set) for ops in stage_ops]
    t_ops = stage_ops[0]
    t_params = per_stage[0]
    # structural identity is checked on a FULL signature — op types,
    # attrs, and the role of every input/output name (carry / param slot /
    # side / const / stage-local producer).  Type-only comparison would
    # let e.g. per-stage dropout rates or a side-var swap silently run
    # stage 0's template with wrong math on every stage.
    sigs = [_stage_signature(stage_ops[s], attrs["carry_in_names"][s],
                             attrs["carry_out_names"][s], per_stage[s],
                             side_names, set(attrs["const_names"]))
            for s in range(s_count)]
    for s in range(1, s_count):
        if sigs[s] != sigs[0]:
            for j, (a, b2) in enumerate(zip(sigs[s], sigs[0])):
                if a != b2:
                    raise ValueError(
                        "pipeline_region stages must be structurally "
                        "identical: stage %d differs from stage 0 at "
                        "element %d:\n  stage %d: %s\n  stage 0: %s"
                        % (s, j, s, a, b2))
            raise ValueError(
                "pipeline_region: stage %d signature length differs "
                "from stage 0" % s)
    stacked = []
    for j in range(len(t_params)):
        vals = [param_vals[per_stage[s][j]] for s in range(s_count)]
        shapes = {tuple(v.shape) for v in vals}
        if len(shapes) != 1:
            raise ValueError(
                "param %r (slot %d) has mismatched shapes across stages: "
                "%s" % (t_params[j], j, sorted(shapes)))
        stacked.append(jnp.stack(vals))

    carry_in0 = attrs["carry_in_names"][0]
    carry_out0 = attrs["carry_out_names"][0]
    base_key = None
    try:
        base_key = ctx.rng_key(op_index)
    except RuntimeError:
        pass

    def stage_fn(stage_idx, pvals, carry, sides_mb, key_extra=None,
                 mb_idx=None):
        env = dict(const_env)
        env.update(zip(t_params, pvals))
        env.update(zip(side_names, sides_mb))
        env[carry_in0] = carry
        key = base_key
        if key is not None and mb_idx is not None:
            # decorrelate in-stage random draws per MICROBATCH: without
            # this every microbatch in the region shares one dropout
            # mask, a correlated-regularization divergence from the
            # unpipelined model.  Both schedules fold by the same
            # microbatch index, so sequential/GPipe parity is exact.
            key = jax.random.fold_in(key, mb_idx)
        if key is not None and key_extra is not None:
            # dp-sharded schedule: decorrelate in-stage random draws per
            # dp shard (each shard sees a different batch slice)
            key = jax.random.fold_in(key, key_extra)
        sctx = _stage_ctx(ctx, key, stage_idx)
        for j, o in enumerate(t_ops):
            registry.compute_op(o, env, sctx, op_index=j)
        return env[carry_out0].astype(carry0.dtype)

    side_mb = [v.reshape((m, mb) + tuple(v.shape[1:])) for v in side_vals]
    x_mb = carry0.reshape((m, mb) + tuple(carry0.shape[1:]))

    from ..parallel.pipeline import normalize_schedule

    schedule = normalize_schedule(getattr(ctx, "pipeline_schedule", None))
    mesh = getattr(ctx, "mesh", None)
    pp_ok = False
    virtual = 1
    if mesh is not None and s_count > 1:
        from ..parallel.mesh import AXIS_PP
        if AXIS_PP in mesh.axis_names:
            pp = mesh.shape[AXIS_PP]
            if pp > 1:
                if schedule == "interleaved":
                    # v stage chunks per device: the program's stage
                    # count splits round-robin over the pp axis
                    if s_count % pp == 0:
                        virtual = s_count // pp
                        pp_ok = True
                        if m % pp:
                            raise ValueError(
                                "pipeline_region: the interleaved "
                                "schedule sends groups of S "
                                "microbatches around the ring "
                                "together — microbatches (%d) must be "
                                "a multiple of the pp axis size (%d)"
                                % (m, pp))
                else:
                    pp_ok = pp == s_count
    if not pp_ok:
        # sequential ground truth: same template, same PRNG folds
        outs = []
        for t in range(m):
            c = x_mb[t]
            for s in range(s_count):
                c = stage_fn(s, [p[s] for p in stacked], c,
                             [sv[t] for sv in side_mb], mb_idx=t)
            outs.append(c)
        out = jnp.stack(outs).reshape(carry0.shape)
        return {"Out": out}

    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import AXIS_DP, AXIS_PP, shard_map_norep

    pp = mesh.shape[AXIS_PP]

    # shard the microbatch batch dim over dp so dp replicas process their
    # own batch slices through the pipeline (instead of redundantly
    # recomputing the full batch); in-stage random draws then differ per
    # dp shard (sequential parity remains exact when dp == 1 or the
    # region draws no randomness)
    dp = mesh.shape.get(AXIS_DP, 1) if hasattr(mesh.shape, "get") else (
        mesh.shape[AXIS_DP] if AXIS_DP in mesh.axis_names else 1)
    dp_sharded = AXIS_DP in mesh.axis_names and dp > 1 and mb % dp == 0
    mb_spec = P(None, AXIS_DP) if dp_sharded else P()
    # bodies return the collected outputs with a leading per-stage dim
    # [1, M, mb, ...]; out_specs P(pp, ...) makes the caller's slice of
    # the LAST stage a true single-source broadcast inserted by GSPMD —
    # satellite fix: no lax.psum over a masked all-stage-sized buffer,
    # and the slice transpose routes cotangents to the producing stage
    # exactly
    staged_spec = P(AXIS_PP, None, AXIS_DP) if dp_sharded else P(AXIS_PP)
    # the wrap-around (pp-1 -> 0) edge is dead for the fill-drain
    # schedules (stage 0 always ingests a fresh microbatch): dropped
    # from the permutation (satellite fix).  The interleaved ring keeps
    # it — that's how microbatches start their next round.
    perm_fwd = [(j, j + 1) for j in range(pp - 1)]
    n_fsides = len(attrs["side_names"])

    def _dyn(v, i):
        return lax.dynamic_index_in_dim(v, i, 0, keepdims=False)

    if schedule == "gpipe":
        def body(stacked_local, x_mb, side_mb):
            s_idx = lax.axis_index(AXIS_PP)
            my_params = [p[0] for p in stacked_local]
            extra = lax.axis_index(AXIS_DP) if dp_sharded else None
            total = m + s_count - 1

            def tick(t, st):
                cur, outs = st
                fresh = x_mb[jnp.clip(t, 0, m - 1)]
                cur = jnp.where(s_idx == 0, fresh, cur)
                my_mb = jnp.clip(t - s_idx, 0, m - 1)
                sides_t = [_dyn(v, my_mb) for v in side_mb]
                out = stage_fn(s_idx, my_params, cur, sides_t, extra,
                               mb_idx=my_mb)
                done = t - (s_count - 1)
                take = (s_idx == s_count - 1) & (done >= 0)
                updated = lax.dynamic_update_index_in_dim(
                    outs, out, jnp.clip(done, 0, m - 1), 0)
                outs = jnp.where(take, updated, outs)
                # the final tick's rotation is discarded with the loop
                # carry: skip the ICI transfer (satellite fix)
                nxt = lax.cond(
                    t < total - 1,
                    lambda o: lax.ppermute(o, AXIS_PP, perm_fwd),
                    lambda o: o, out)
                return nxt, outs

            outs0 = jnp.zeros_like(x_mb)
            cur0 = jnp.zeros_like(x_mb[0])
            _, outs = lax.fori_loop(0, total, tick, (cur0, outs0))
            return outs[None]

    elif schedule == "interleaved":
        from ..parallel.pipeline import interleaved_loop, \
            interleaved_order

        # device-major restack: device d hosts the program's stages
        # {r*pp + d : r < v} as chunk array [v, ...]
        order = jnp.asarray(interleaved_order(pp, virtual))
        stacked = [jnp.take(p, order, axis=0).reshape(
            (pp, virtual) + tuple(p.shape[1:])) for p in stacked]

        def body(stacked_local, x_mb, side_mb):
            my_chunks = [p[0] for p in stacked_local]   # [v, ...] each
            extra = lax.axis_index(AXIS_DP) if dp_sharded else None

            def apply_fn(rnd, vs_idx, cur, midx):
                my_params = [_dyn(p, rnd) for p in my_chunks]
                sides_t = [_dyn(v, midx) for v in side_mb]
                return stage_fn(vs_idx, my_params, cur, sides_t, extra,
                                mb_idx=midx)

            return interleaved_loop(AXIS_PP, pp, m, virtual, x_mb,
                                    apply_fn)

    else:  # 1f1b
        if not jnp.issubdtype(jnp.asarray(carry0).dtype, jnp.floating):
            raise ValueError(
                "pipeline_region: the 1f1b schedule differentiates the "
                "carry per stage and needs a floating carry, got %s"
                % carry0.dtype)
        const_names = list(attrs["const_names"])
        const_vals = [const_env[n] for n in const_names]
        key_impl_spec = None
        key_raw = []
        if base_key is not None:
            key_impl_spec = jax.random.key_impl(base_key)
            key_raw = [jax.random.key_data(base_key)]

        def run_factory(consts, key_data):
            """Closure-free stage runner for the custom_vjp: consts and
            PRNG key material arrive as explicit args (custom_vjp
            functions must not capture outer-trace tracers)."""
            key0 = None
            if key_data:
                key0 = jax.random.wrap_key_data(key_data[0],
                                                impl=key_impl_spec)

            def run(stage_idx, pvals, carry, sides_t, extra, mb_i):
                env = dict(zip(const_names, consts))
                env.update(zip(t_params, pvals))
                env.update(zip(side_names, sides_t))
                env[carry_in0] = carry
                key = key0
                if key is not None and mb_i is not None:
                    key = jax.random.fold_in(key, mb_i)
                if key is not None and extra is not None:
                    key = jax.random.fold_in(key, extra)
                sctx = _stage_ctx(ctx, key, stage_idx)
                for j, o in enumerate(t_ops):
                    registry.compute_op(o, env, sctx, op_index=j)
                return env[carry_out0].astype(carry0.dtype)

            return run

        from ..parallel.pipeline import make_1f1b

        f1 = make_1f1b(
            AXIS_PP, pp, m, run_factory,
            dp_extra_fn=(lambda: lax.axis_index(AXIS_DP))
            if dp_sharded else None)

        def body(stacked_local, x_mb, side_mb, consts, key_data):
            return f1(list(stacked_local), x_mb,
                      list(side_mb[:n_fsides]), list(side_mb[n_fsides:]),
                      consts, key_data)

    # GSPMD workaround (jax 0.4.37, reproduced in isolation): a
    # concatenate/stack computed INSIDE jit and fed straight into a
    # shard_map whose in_spec shards it over the second axis of a
    # multi-axis mesh comes back scaled by the OTHER axis's size — the
    # partitioner lays the stack out sharded and the shard_map input
    # conversion sums shards instead of gathering them (echoing the
    # stacked value through an identity shard_map multiplies it by dp).
    # Pinning the stacked params to a replicated layout before the
    # shard_map sidesteps the bad partition; they were replicated as
    # separate state vars anyway, so this adds no memory.
    rep = NamedSharding(mesh, P())
    stacked = [jax.lax.with_sharding_constraint(p, rep) for p in stacked]
    if schedule == "1f1b":
        fn = shard_map_norep(
            body, mesh,
            in_specs=([P(AXIS_PP)] * len(stacked), mb_spec,
                      [mb_spec] * len(side_mb),
                      [P()] * len(const_vals), [P()] * len(key_raw)),
            out_specs=staged_spec)
        staged = fn(stacked, x_mb, side_mb, const_vals, key_raw)
    else:
        fn = shard_map_norep(
            body, mesh,
            in_specs=([P(AXIS_PP)] * len(stacked), mb_spec,
                      [mb_spec] * len(side_mb)),
            out_specs=staged_spec)
        staged = fn(stacked, x_mb, side_mb)
    outs = staged[pp - 1]
    return {"Out": outs.reshape(carry0.shape)}


register_op(
    "pipeline_region", ["Carry", "Sides", "IntSides", "Params", "Consts"],
    ["Out"], infer=_pipeline_infer, compute=_pipeline_compute,
    no_grad_inputs=("IntSides",), stateful_random=True,
)
