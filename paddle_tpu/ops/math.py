"""Linear-algebra and scalar math ops: mul, matmul, sum, scale, mean, clip...

Parity: reference ``mul_op.cc``, ``matmul_op.cc``, ``sum_op.cc``,
``scale_op.cc``, ``mean_op.cc``, ``clip_op.cc``, ``clip_by_norm_op.cc``,
``squared_l2_norm_op.cc``, ``l1_norm_op.cc``, ``sign_op.cc``,
``minus_op.cc``, ``cos_sim_op.cc``, ``isfinite_op.cc`` — TPU-native: every
matmul lowers to a single ``jnp.matmul``/``lax.dot_general`` so XLA tiles it
onto the MXU.  fp16 inputs request explicit fp32 accumulation via
``preferred_element_type``; bf16 inputs keep bf16 outputs (the MXU
accumulates partial products in fp32 internally) so backward cotangents
stay bf16 — see ``_mm_accum_dtype``.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..core import convert_dtype, dtype_is_floating
from ..registry import register_op, set_output, in_var, same_shape_infer


def _flatten_to_2d(x, num_col_dims):
    lead = 1
    for s in x.shape[:num_col_dims]:
        lead *= s
    rest = 1
    for s in x.shape[num_col_dims:]:
        rest *= s
    return x.reshape(lead, rest)


def _mm_accum_dtype(a, b, ctx=None):
    # bf16 operands keep bf16 outputs: the TPU MXU accumulates partial
    # products in fp32 internally regardless, and requesting an explicit
    # fp32 output (then downcasting) makes every backward cotangent fp32
    # — the transposed dots then run as fp32*bf16, off the fast bf16 MXU
    # pipeline.  KNOWN BACKEND DIVERGENCE: off-TPU backends give no such
    # fp32-accumulation guarantee for bf16 dots, so bf16 numerics on the
    # CPU backend may accumulate at lower precision than the same program
    # on TPU.  Requesting fp32 outputs off-TPU was tried and rejected:
    # the fp32 cotangent cascade changes the emitted backward HLO
    # everywhere (the exact pessimization described above), a worse
    # trade than the documented precision gap — bf16-AMP on CPU is a
    # test-suite configuration, not a deployment target.  fp16
    # (GPU-style AMP) always gets explicit fp32 accumulation.
    if a.dtype == jnp.float16:
        return jnp.float32
    return None


# -- mul (fc's matmul: flatten then 2-D gemm; mul_op.cc) --------------------

def _mul_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    xnc = op.attrs.get("x_num_col_dims", 1)
    ync = op.attrs.get("y_num_col_dims", 1)
    out_shape = tuple(x.shape[:xnc]) + tuple(y.shape[ync:])
    set_output(op, block, "Out", out_shape, x.dtype)


def _mul_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    xnc = attrs.get("x_num_col_dims", 1)
    ync = attrs.get("y_num_col_dims", 1)
    x2 = _flatten_to_2d(x, xnc)
    y2 = _flatten_to_2d(y, ync)
    out = jnp.matmul(x2, y2,
                     preferred_element_type=_mm_accum_dtype(x2, y2, ctx))
    out = out.astype(x.dtype)
    return {"Out": out.reshape(tuple(x.shape[:xnc]) + tuple(y.shape[ync:]))}


register_op("mul", ["X", "Y"], ["Out"], infer=_mul_infer, compute=_mul_compute)


# -- matmul (batched, with transpose flags; matmul_op.cc) -------------------

def _matmul_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    tx = op.attrs.get("transpose_X", False)
    ty = op.attrs.get("transpose_Y", False)
    xs, ys = list(x.shape), list(y.shape)
    if len(xs) == 1:
        xs = [1, xs[0]]
    if len(ys) == 1:
        ys = [ys[0], 1]
    if tx:
        xs[-1], xs[-2] = xs[-2], xs[-1]
    if ty:
        ys[-1], ys[-2] = ys[-2], ys[-1]
    batch = xs[:-2] if len(xs) > len(ys) else ys[:-2]
    out = tuple(batch) + (xs[-2], ys[-1])
    if len(x.shape) == 1 and len(y.shape) == 1:
        out = (1,)
    set_output(op, block, "Out", out, x.dtype)


def _matmul_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", False)
    ty = attrs.get("transpose_Y", False)
    alpha = attrs.get("alpha", 1.0)
    squeeze_out = x.ndim == 1 and y.ndim == 1
    if x.ndim == 1:
        x = x[None, :]
    if y.ndim == 1:
        y = y[:, None]
    if tx:
        x = jnp.swapaxes(x, -1, -2)
    if ty:
        y = jnp.swapaxes(y, -1, -2)
    out = jnp.matmul(x, y, preferred_element_type=_mm_accum_dtype(x, y, ctx))
    out = out.astype(ins["X"][0].dtype)
    if alpha != 1.0:
        out = out * alpha
    if squeeze_out:
        out = out.reshape(1)
    return {"Out": out}


register_op("matmul", ["X", "Y"], ["Out"], infer=_matmul_infer,
            compute=_matmul_compute)


# -- sum (variadic add; sum_op.cc) ------------------------------------------

def _sum_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _sum_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows, to_dense
    import jax.numpy as _jnp

    xs = [x for x in ins["X"] if x is not None]
    sparse = [x for x in xs if isinstance(x, SelectedRows)]
    dense = [x for x in xs if not isinstance(x, SelectedRows)]
    if sparse and not dense:
        # all-sparse: concatenation IS addition (reference sum_op
        # SelectedRows kernel appends row lists)
        rows = _jnp.concatenate([s.rows for s in sparse])
        vals = _jnp.concatenate([s.values for s in sparse])
        return {"Out": SelectedRows(rows, vals, sparse[0].height)}
    if sparse:
        dense = dense + [to_dense(s) for s in sparse]
    out = dense[0]
    for x in dense[1:]:
        out = out + x
    return {"Out": out}


register_op("sum", ["X"], ["Out"], infer=_sum_infer, compute=_sum_compute)


# -- scale ------------------------------------------------------------------

def _scale_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows, map_values

    x = ins["X"][0]
    scale = attrs.get("scale", 1.0)
    bias = attrs.get("bias", 0.0)
    if isinstance(x, SelectedRows):
        # bias-free scale commutes with duplicate-row merging; a biased
        # scale of a gradient would add the bias per DUPLICATE, which is
        # not the dense semantics — densify for that (rare) case
        if bias == 0.0:
            return {"Out": map_values(x, lambda v: v * scale)}
        from .selected_rows import to_dense

        x = to_dense(x)
    if attrs.get("bias_after_scale", True):
        return {"Out": x * scale + bias}
    return {"Out": (x + bias) * scale}


register_op("scale", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
            compute=_scale_compute)


# -- mean (scalar [1] output like mean_op.cc) -------------------------------

def _mean_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", (1,), x.dtype)


register_op(
    "mean", ["X"], ["Out"], infer=_mean_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.mean(ins["X"][0]).reshape(1)
    },
)


# -- minus / sign -----------------------------------------------------------

register_op(
    "minus", ["X", "Y"], ["Out"], infer=same_shape_infer("X", "Out"),
    compute=lambda ins, attrs, ctx, op_index: {"Out": ins["X"][0] - ins["Y"][0]},
)

register_op(
    "sign", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
    compute=lambda ins, attrs, ctx, op_index: {"Out": jnp.sign(ins["X"][0])},
)


# -- clip family ------------------------------------------------------------

def _clip_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows, merge_rows
    from .control_flow import _mask_to

    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        # clip applies to the SUMMED gradient per row (dense semantics),
        # so duplicates merge first; padded slots stay exactly zero
        # (clip(0) may be nonzero when min > 0) so the sentinel rows
        # remain scatter-inert
        uniq, merged, valid = merge_rows(x)
        clipped = jnp.clip(merged, attrs["min"], attrs["max"])
        clipped = clipped * _mask_to(valid, clipped).astype(clipped.dtype)
        return {"Out": SelectedRows(uniq, clipped, x.height)}
    return {"Out": jnp.clip(x, attrs["min"], attrs["max"])}


register_op("clip", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
            compute=_clip_compute)


def _clip_by_norm_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows, map_values, merged_sumsq

    x = ins["X"][0]
    max_norm = attrs["max_norm"]
    if isinstance(x, SelectedRows):
        # reference clip_by_norm SelectedRows kernel: the norm is over
        # the MERGED rows (== the dense grad's norm); the scale then
        # applies uniformly, which commutes with merging
        norm = jnp.sqrt(merged_sumsq(x))
        scale = jnp.where(norm > max_norm,
                          max_norm / jnp.maximum(norm, 1e-12), 1.0)
        return {"Out": map_values(
            x, lambda v: v * scale.astype(v.dtype))}
    norm = jnp.sqrt(jnp.sum(x * x))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {"Out": x * scale.astype(x.dtype)}


register_op("clip_by_norm", ["X"], ["Out"], infer=same_shape_infer("X", "Out"),
            compute=_clip_by_norm_compute)


def _scalar_out_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", (1,), x.dtype)


def _squared_l2_norm_compute(ins, attrs, ctx, op_index):
    from .selected_rows import SelectedRows, merged_sumsq

    x = ins["X"][0]
    if isinstance(x, SelectedRows):
        # global-norm clipping's per-grad term: ||dense(grad)||^2
        # without materializing the dense gradient
        return {"Out": merged_sumsq(x).reshape(1)}
    return {"Out": jnp.sum(x * x).reshape(1)}


register_op(
    "squared_l2_norm", ["X"], ["Out"], infer=_scalar_out_infer,
    compute=_squared_l2_norm_compute,
)

register_op(
    "l1_norm", ["X"], ["Out"], infer=_scalar_out_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.sum(jnp.abs(ins["X"][0])).reshape(1)
    },
)

register_op(
    "squared_l2_distance", ["X", "Y"], ["sub_result", "Out"],
    infer=lambda op, block: (
        set_output(op, block, "sub_result", in_var(op, block, "X").shape,
                   in_var(op, block, "X").dtype),
        set_output(op, block, "Out", (in_var(op, block, "X").shape[0], 1),
                   in_var(op, block, "X").dtype),
    ),
    compute=lambda ins, attrs, ctx, op_index: (
        lambda sub: {"sub_result": sub,
                     "Out": jnp.sum(sub * sub, axis=tuple(range(1, sub.ndim)),
                                    keepdims=False).reshape(-1, 1)}
    )(ins["X"][0] - ins["Y"][0]),
)


# -- isfinite (debugging: FLAGS_check_nan_inf parity) -----------------------

register_op(
    "isfinite", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), np.bool_),
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.all(
            jnp.stack([jnp.all(jnp.isfinite(x)) for x in ins["X"]])
        ).reshape(1)
    },
    grad=None,
)

# has_inf / has_nan: the isfinite family's other two members
# (reference isfinite_op.cc registers all three as OverflowOp variants)

register_op(
    "has_inf", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), np.bool_),
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.any(
            jnp.stack([jnp.any(jnp.isinf(x)) for x in ins["X"]])
        ).reshape(1)
    },
    grad=None,
)

register_op(
    "has_nan", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), np.bool_),
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.any(
            jnp.stack([jnp.any(jnp.isnan(x)) for x in ins["X"]])
        ).reshape(1)
    },
    grad=None,
)


# -- cos_sim ----------------------------------------------------------------

def _cos_sim_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", (x.shape[0], 1), x.dtype)
    set_output(op, block, "XNorm", (x.shape[0], 1), x.dtype)
    y = in_var(op, block, "Y")
    set_output(op, block, "YNorm", (y.shape[0], 1), y.dtype)


def _cos_sim_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / (xn * yn)
    return {"Out": out, "XNorm": xn, "YNorm": yn}


register_op("cos_sim", ["X", "Y"], ["Out", "XNorm", "YNorm"],
            infer=_cos_sim_infer, compute=_cos_sim_compute)


# -- piecewise_lr (in-graph step-function LR; layers.piecewise_decay) -------

def _piecewise_lr_compute(ins, attrs, ctx, op_index):
    step = ins["Step"][0]
    boundaries = attrs["boundaries"]
    values = attrs["values"]
    out = jnp.full_like(step, values[-1])
    # walk from the right so earlier boundaries win
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        out = jnp.where(step < b, v, out)
    return {"Out": out}


register_op(
    "piecewise_lr", ["Step"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "Step").shape, "float32"
    ),
    compute=_piecewise_lr_compute, grad=None,
)
