"""Tensor manipulation ops: reshape, transpose, concat, split, slice, ...

Parity: reference ``reshape_op.cc``, ``transpose_op.cc``, ``concat_op.cc``,
``split_op.cc``, ``squeeze/unsqueeze``, ``flatten_op.cc``, ``slice_op.cc``,
``expand_op.cc``, ``stack/unstack``, ``gather_op.cc``, ``scatter_op.cc``,
``pad_op.cc``, ``reverse_op.cc``, ``one_hot_op.cc``, ``top_k_op.cc``,
``lookup_table_op.cc``, ``multiplex_op.cc``, ``label_smooth_op.cc`` —
all shape-static so XLA can lay out and fuse freely.
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..core import convert_dtype, long_dtype, materialize_dtype
from ..registry import register_op, set_output, in_var


# -- reshape ----------------------------------------------------------------

def _resolve_reshape(in_shape, spec):
    out = []
    for i, s in enumerate(spec):
        if s == 0:
            out.append(in_shape[i])
        else:
            out.append(s)
    if -1 in out:
        known = 1
        for s in out:
            if s != -1:
                known *= s
        total = 1
        for s in in_shape:
            total *= s
        out[out.index(-1)] = total // known
    return tuple(out)


def _reshape_infer(op, block):
    x = in_var(op, block, "X")
    spec = list(op.attrs["shape"])
    if -1 not in x.shape:
        out = _resolve_reshape(x.shape, spec)
    else:
        # dynamic dims present: resolve what we can — 0 copies the input
        # dim (possibly -1), -1 stays symbolic
        out = tuple(
            (x.shape[i] if i < len(x.shape) else -1) if s == 0 else s
            for i, s in enumerate(spec)
        )
    set_output(op, block, "Out", out, x.dtype)


def _reshape_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    return {"Out": x.reshape(_resolve_reshape(x.shape, list(attrs["shape"])))}


register_op("reshape", ["X"], ["Out"], infer=_reshape_infer,
            compute=_reshape_compute)


def _flatten_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= s
    rest = 1
    for s in x.shape[axis:]:
        rest *= s
    set_output(op, block, "Out", (lead, rest), x.dtype)


register_op(
    "flatten", ["X"], ["Out"], infer=_flatten_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": ins["X"][0].reshape(
            int(np.prod(ins["X"][0].shape[: attrs.get("axis", 1)] or (1,))),
            -1,
        )
    },
)


def _squeeze_infer(op, block):
    x = in_var(op, block, "X")
    axes = op.attrs.get("axes", [])
    if axes:
        axes = [a % len(x.shape) for a in axes]
        out = tuple(s for i, s in enumerate(x.shape) if i not in axes or s != 1)
    else:
        out = tuple(s for s in x.shape if s != 1)
    set_output(op, block, "Out", out, x.dtype)


def _squeeze_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axes = attrs.get("axes", [])
    if axes:
        axes = tuple(a % x.ndim for a in axes if x.shape[a % x.ndim] == 1)
        return {"Out": jnp.squeeze(x, axis=axes)}
    return {"Out": jnp.squeeze(x)}


register_op("squeeze", ["X"], ["Out"], infer=_squeeze_infer,
            compute=_squeeze_compute)


def _unsqueeze_infer(op, block):
    x = in_var(op, block, "X")
    out = list(x.shape)
    for a in sorted(op.attrs["axes"]):
        out.insert(a if a >= 0 else a + len(out) + 1, 1)
    set_output(op, block, "Out", out, x.dtype)


def _unsqueeze_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a if a >= 0 else a + x.ndim + 1)
    return {"Out": x}


register_op("unsqueeze", ["X"], ["Out"], infer=_unsqueeze_infer,
            compute=_unsqueeze_compute)


# -- transpose --------------------------------------------------------------

def _transpose_infer(op, block):
    x = in_var(op, block, "X")
    perm = op.attrs["axis"]
    set_output(op, block, "Out", tuple(x.shape[p] for p in perm), x.dtype)


register_op(
    "transpose", ["X"], ["Out"], infer=_transpose_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.transpose(ins["X"][0], attrs["axis"])
    },
)


# -- concat / split / stack -------------------------------------------------

def _concat_infer(op, block):
    xs = [block.var_recursive(n) for n in op.inputs["X"]]
    axis = op.attrs.get("axis", 0) % len(xs[0].shape)
    out = list(xs[0].shape)
    sizes = [v.shape[axis] for v in xs]
    # any unknown (-1) contributor makes the result unknown, not a
    # meaningless negative sum
    out[axis] = -1 if any(s < 0 for s in sizes) else sum(sizes)
    set_output(op, block, "Out", out, xs[0].dtype)


register_op(
    "concat", ["X"], ["Out"], infer=_concat_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))
    },
)


def _split_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("axis", 0) % len(x.shape)
    sections = op.attrs.get("sections", [])
    num = op.attrs.get("num", 0)
    outs = op.outputs["Out"]
    if sections:
        sizes = sections
    else:
        n = num or len(outs)
        sizes = [x.shape[axis] // n] * n
    for name, size in zip(outs, sizes):
        shape = list(x.shape)
        shape[axis] = size
        v = block._find_var_recursive(name) or block.create_var(name=name)
        v.shape = tuple(shape)
        v.dtype = x.dtype


def _split_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axis = attrs.get("axis", 0) % x.ndim
    sections = attrs.get("sections", [])
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        return {"Out": jnp.split(x, idx, axis=axis)}
    n = attrs.get("num", 0) or attrs["__num_outputs__"]
    return {"Out": jnp.split(x, n, axis=axis)}


register_op("split", ["X"], ["Out"], infer=_split_infer,
            compute=_split_compute)


def _stack_infer(op, block):
    xs = [block.var_recursive(n) for n in op.inputs["X"]]
    axis = op.attrs.get("axis", 0)
    out = list(xs[0].shape)
    out.insert(axis if axis >= 0 else axis + len(out) + 1, len(xs))
    set_output(op, block, "Y", out, xs[0].dtype)


register_op(
    "stack", ["X"], ["Y"], infer=_stack_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Y": jnp.stack(ins["X"], axis=attrs.get("axis", 0))
    },
)


# -- slice / expand / reverse / pad ----------------------------------------

def _slice_infer(op, block):
    x = in_var(op, block, "Input")
    shape = list(x.shape)
    for ax, st, en in zip(op.attrs["axes"], op.attrs["starts"],
                          op.attrs["ends"]):
        dim = shape[ax]
        st2 = max(st + dim, 0) if st < 0 else min(st, dim)
        en2 = max(en + dim, 0) if en < 0 else min(en, dim)
        shape[ax] = max(en2 - st2, 0)
    set_output(op, block, "Out", shape, x.dtype)


def _slice_compute(ins, attrs, ctx, op_index):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(attrs["axes"], attrs["starts"], attrs["ends"]):
        idx[ax] = slice(st, en)
    return {"Out": x[tuple(idx)]}


register_op("slice", ["Input"], ["Out"], infer=_slice_infer,
            compute=_slice_compute)


def _expand_infer(op, block):
    x = in_var(op, block, "X")
    times = op.attrs["expand_times"]
    set_output(op, block, "Out",
               tuple(s * t for s, t in zip(x.shape, times)), x.dtype)


register_op(
    "expand", ["X"], ["Out"], infer=_expand_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.tile(ins["X"][0], attrs["expand_times"])
    },
)

register_op(
    "reverse", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))
    },
)


def _pad_infer(op, block):
    x = in_var(op, block, "X")
    p = op.attrs["paddings"]
    out = [s + p[2 * i] + p[2 * i + 1] for i, s in enumerate(x.shape)]
    set_output(op, block, "Out", out, x.dtype)


def _pad_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    p = attrs["paddings"]
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))}


register_op("pad", ["X"], ["Out"], infer=_pad_infer, compute=_pad_compute)


# -- gather / scatter -------------------------------------------------------

def _gather_infer(op, block):
    x = in_var(op, block, "X")
    ids = in_var(op, block, "Index")
    set_output(op, block, "Out", (ids.shape[0],) + tuple(x.shape[1:]), x.dtype)


register_op(
    "gather", ["X", "Index"], ["Out"], infer=_gather_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.take(ins["X"][0], ins["Index"][0].reshape(-1), axis=0)
    },
    no_grad_inputs=("Index",),
)


def _scatter_compute(ins, attrs, ctx, op_index):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    ids = ids.reshape(-1)
    if attrs.get("overwrite", True):
        out = x.at[ids].set(upd)
    else:
        out = x.at[ids].add(upd)
    return {"Out": out}


register_op(
    "scatter", ["X", "Ids", "Updates"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_scatter_compute, no_grad_inputs=("Ids",),
)


# -- one_hot / label_smooth / multiplex ------------------------------------

def _one_hot_infer(op, block):
    x = in_var(op, block, "X")
    depth = op.attrs["depth"]
    shape = tuple(x.shape[:-1]) + (depth,) if x.shape[-1] == 1 else \
        tuple(x.shape) + (depth,)
    set_output(op, block, "Out", shape, np.float32)


def _one_hot_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    if x.shape and x.shape[-1] == 1:
        x = x.reshape(x.shape[:-1])
    return {"Out": jax.nn.one_hot(x, attrs["depth"], dtype=jnp.float32)}


register_op("one_hot", ["X"], ["Out"], infer=_one_hot_infer,
            compute=_one_hot_compute, grad=None)


def _label_smooth_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    eps = attrs.get("epsilon", 0.0)
    if ins.get("PriorDist") and ins["PriorDist"][0] is not None:
        prior = ins["PriorDist"][0]
        return {"Out": (1 - eps) * x + eps * prior}
    return {"Out": (1 - eps) * x + eps / x.shape[-1]}


register_op(
    "label_smooth", ["X", "PriorDist"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_label_smooth_compute,
)


def _multiplex_compute(ins, attrs, ctx, op_index):
    ids = ins["Ids"][0].reshape(-1)
    stacked = jnp.stack(ins["X"], axis=0)  # [n, batch, ...]
    return {"Out": jnp.take_along_axis(
        stacked, ids[None, :, None].astype(jnp.int32), axis=0
    )[0] if stacked.ndim == 3 else stacked[ids, jnp.arange(ids.shape[0])]}


register_op(
    "multiplex", ["X", "Ids"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape,
        in_var(op, block, "X").dtype),
    compute=_multiplex_compute, no_grad_inputs=("Ids",),
)


# -- top_k ------------------------------------------------------------------

def _top_k_infer(op, block):
    x = in_var(op, block, "X")
    k = op.attrs["k"]
    out = tuple(x.shape[:-1]) + (k,)
    set_output(op, block, "Out", out, x.dtype)
    set_output(op, block, "Indices", out, np.int64)


def _top_k_compute(ins, attrs, ctx, op_index):
    vals, idx = jax.lax.top_k(ins["X"][0], attrs["k"])
    return {"Out": vals, "Indices": idx.astype(long_dtype())}


register_op("top_k", ["X"], ["Out", "Indices"], infer=_top_k_infer,
            compute=_top_k_compute, grad=None)


# -- argsort ----------------------------------------------------------------

def _argsort_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "Indices", x.shape, np.int64)


def _argsort_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    idx = jnp.argsort(x, axis=axis)
    return {"Out": jnp.sort(x, axis=axis), "Indices": idx.astype(long_dtype())}


register_op("argsort", ["X"], ["Out", "Indices"], infer=_argsort_infer,
            compute=_argsort_compute, grad=None)


# -- lookup_table (embedding; lookup_table_op.cc) ---------------------------

def _lookup_table_infer(op, block):
    w = in_var(op, block, "W")
    ids = in_var(op, block, "Ids")
    shape = tuple(ids.shape[:-1]) + (w.shape[1],) if ids.shape[-1] == 1 \
        else tuple(ids.shape) + (w.shape[1],)
    set_output(op, block, "Out", shape, w.dtype)


def _lookup_table_compute(ins, attrs, ctx, op_index):
    w, ids = ins["W"][0], ins["Ids"][0]
    squeeze = ids.shape and ids.shape[-1] == 1
    flat = ids.reshape(-1)
    out = None
    if attrs.get("is_sparse", False) and ctx.mesh is not None \
            and ctx.state_specs and ctx.op is not None:
        # row-sharded table on the mesh: gather only local rows + psum
        # the [N, D] activations over the table axis — never an
        # all-gathered [vocab, D] table (parallel/embedding.py).  Gated
        # to is_sparse tables: their backward is the custom
        # SelectedRows grad op, so no AD flows through this lowering.
        from ..parallel.embedding import sharded_sparse_lookup

        out = sharded_sparse_lookup(ctx, w, flat,
                                    ctx.op.inputs["W"][0])
    if out is None:
        out = jnp.take(w, flat, axis=0)
    pad = attrs.get("padding_idx", -1)
    if pad is not None and pad != -1:
        mask = (flat != pad)[:, None]
        out = out * mask.astype(out.dtype)
    shape = (ids.shape[:-1] if squeeze else ids.shape) + (w.shape[1],)
    return {"Out": out.reshape(shape)}


def _lookup_table_grad(op, no_grad_set):
    # sparse path (is_sparse attr) emits a SelectedRows gradient
    from .selected_rows import lookup_table_grad_maker
    return lookup_table_grad_maker(op, no_grad_set)


register_op(
    "lookup_table", ["W", "Ids"], ["Out"], infer=_lookup_table_infer,
    compute=_lookup_table_compute, grad=_lookup_table_grad,
    no_grad_inputs=("Ids",),
)


# -- crop (reference crop_op.cc) --------------------------------------------

def _crop_infer(op, block):
    x = in_var(op, block, "X")
    shape = op.attrs.get("shape") or None
    if not shape:
        y = in_var(op, block, "Y")
        shape = y.shape
    set_output(op, block, "Out", tuple(shape), x.dtype)


def _crop_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    shape = attrs.get("shape") or None
    if not shape:
        shape = ins["Y"][0].shape
    offsets_in = ins.get("Offsets")
    if offsets_in and offsets_in[0] is not None:
        if attrs.get("offsets"):
            raise ValueError(
                "crop: runtime input Offsets and attr offsets are mutually "
                "exclusive (crop_op.cc contract)")
        offs = [offsets_in[0][i] for i in range(x.ndim)]
        static_offs = None
    else:
        offs = list(attrs.get("offsets") or [0] * x.ndim)
        static_offs = offs
    if any(s == -1 for s in shape):
        # -1 = "rest of the dim from the offset" (batch-dim convention);
        # needs static offsets since XLA slice sizes are compile-time
        if static_offs is None:
            raise ValueError(
                "crop: shape dims of -1 require attr offsets, not the "
                "runtime Offsets input (slice sizes are static under XLA)")
        shape = [x.shape[i] - static_offs[i] if s == -1 else s
                 for i, s in enumerate(shape)]
    out = jax.lax.dynamic_slice(x, offs, tuple(shape))
    return {"Out": out}


register_op("crop", ["X", "Y", "Offsets"], ["Out"],
            infer=_crop_infer, compute=_crop_compute,
            no_grad_inputs=("Y", "Offsets"))


# -- pad2d (reference pad2d_op.cc: constant / reflect / edge modes) ---------

def _pad2d_infer(op, block):
    x = in_var(op, block, "X")
    p = op.attrs["paddings"]  # [top, bottom, left, right]
    fmt = op.attrs.get("data_format", "NCHW")
    n, a, b, c = x.shape
    if fmt == "NCHW":
        out = (n, a, b + p[0] + p[1], c + p[2] + p[3])
    else:  # NHWC
        out = (n, a + p[0] + p[1], b + p[2] + p[3], c)
    set_output(op, block, "Out", out, x.dtype)


def _pad2d_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    p = attrs["paddings"]
    fmt = attrs.get("data_format", "NCHW")
    mode = attrs.get("mode", "constant")
    hw = [(p[0], p[1]), (p[2], p[3])]
    pads = [(0, 0), (0, 0)] + hw if fmt == "NCHW" else \
        [(0, 0)] + hw + [(0, 0)]
    if mode == "constant":
        out = jnp.pad(x, pads, constant_values=attrs.get("pad_value", 0.0))
    elif mode == "reflect":
        out = jnp.pad(x, pads, mode="reflect")
    elif mode == "edge":
        out = jnp.pad(x, pads, mode="edge")
    else:
        raise ValueError("pad2d: unknown mode %r" % mode)
    return {"Out": out}


register_op("pad2d", ["X"], ["Out"], infer=_pad2d_infer,
            compute=_pad2d_compute)


# -- pad_constant_like (reference pad_constant_like_op.cc) ------------------

def _pad_const_like_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    set_output(op, block, "Out", x.shape, y.dtype)


def _pad_const_like_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    pads = [(0, sx - sy) for sx, sy in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads,
                           constant_values=attrs.get("pad_value", 0.0))}


register_op("pad_constant_like", ["X", "Y"], ["Out"],
            infer=_pad_const_like_infer, compute=_pad_const_like_compute,
            no_grad_inputs=("X",))


# -- unstack (reference unstack_op.h) ---------------------------------------

def _unstack_infer(op, block):
    x = in_var(op, block, "X")
    axis = op.attrs.get("axis", 0)
    if axis < 0:
        axis += len(x.shape)
    out_shape = tuple(x.shape[:axis]) + tuple(x.shape[axis + 1:])
    for name in op.outputs.get("Y", []):
        v = block._find_var_recursive(name) or block.create_var(name=name)
        v.shape = out_shape
        v.dtype = x.dtype


def _unstack_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    if axis < 0:
        axis += x.ndim
    n = x.shape[axis]
    parts = jnp.split(x, n, axis=axis)
    return {"Y": [jnp.squeeze(p, axis=axis) for p in parts]}


register_op("unstack", ["X"], ["Y"], infer=_unstack_infer,
            compute=_unstack_compute)


# -- is_empty (reference is_empty_op.cc) ------------------------------------

register_op(
    "is_empty", ["X"], ["Out"],
    infer=lambda op, block: set_output(op, block, "Out", (1,), "bool"),
    compute=lambda ins, attrs, ctx, op_index: {
        # shape is static under XLA: the answer is a trace-time constant
        "Out": jnp.full((1,), ins["X"][0].size == 0, jnp.bool_)
    },
    grad=None,
)


# -- fill (reference fill_op.cc: row-major float values + dtype attr) -------

def _fill_infer(op, block):
    set_output(op, block, "Out", op.attrs["shape"],
               op.attrs.get("dtype", "float32"))


def _fill_compute(ins, attrs, ctx, op_index):
    dtype = materialize_dtype(attrs.get("dtype", "float32"))
    vals = np.asarray(attrs["value"], dtype=np.float64).reshape(
        tuple(attrs["shape"]))
    return {"Out": jnp.asarray(vals.astype(dtype))}


register_op("fill", [], ["Out"], infer=_fill_infer, compute=_fill_compute,
            grad=None)


# -- scale_sub_region (v1 legacy ScaleSubRegionLayer): scale a per-sample
# [c0..c1, h0..h1, w0..w1] block of an NCHW tensor by ``value`` ----------

def _scale_sub_region_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)


def _scale_sub_region_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]                       # [B, C, H, W]
    idx = ins["Indices"][0]               # [B, 6] 1-based inclusive
    value = attrs.get("value", 1.0)
    b, c, h, w = x.shape
    ci = jnp.arange(c).reshape(1, c, 1, 1)
    hi = jnp.arange(h).reshape(1, 1, h, 1)
    wi = jnp.arange(w).reshape(1, 1, 1, w)
    lo = (idx[:, 0::2] - 1).astype(jnp.int32)   # [B, 3] c0,h0,w0 0-based
    hi_ = idx[:, 1::2].astype(jnp.int32)        # [B, 3] exclusive ends
    mask = ((ci >= lo[:, 0].reshape(b, 1, 1, 1)) &
            (ci < hi_[:, 0].reshape(b, 1, 1, 1)) &
            (hi >= lo[:, 1].reshape(b, 1, 1, 1)) &
            (hi < hi_[:, 1].reshape(b, 1, 1, 1)) &
            (wi >= lo[:, 2].reshape(b, 1, 1, 1)) &
            (wi < hi_[:, 2].reshape(b, 1, 1, 1)))
    return {"Out": jnp.where(mask, x * value, x)}


register_op("scale_sub_region", ["X", "Indices"], ["Out"],
            infer=_scale_sub_region_infer,
            compute=_scale_sub_region_compute,
            no_grad_inputs=("Indices",))
