"""Creation / initialization / random ops.

Parity: reference ``fill_constant_op.cc``, ``fill_zeros_like_op.cc``,
``uniform_random_op.cc``, ``gaussian_random_op.cc``,
``truncated_gaussian_random_op.cc``, ``assign_op.cc``, ``cast_op.cc``,
``assign_value_op.cc``, ``shape_op.cc``, ``increment_op.cc``,
``fill_constant_batch_size_like_op.cc`` — TPU-native: randomness is
counter-based PRNG (threefry) threaded by the executor, so the whole program
stays deterministic and jit-compatible (no global RNG state mutation).
"""

import numpy as np

import jax
import jax.numpy as jnp

from ..core import convert_dtype, long_dtype, materialize_dtype
from ..registry import register_op, set_output, in_var, same_shape_infer


def _attr_dtype(attrs, default="float32"):
    return convert_dtype(attrs.get("dtype", default))


# -- fill_constant ----------------------------------------------------------

def _fill_constant_infer(op, block):
    set_output(op, block, "Out", op.attrs["shape"], _attr_dtype(op.attrs))


def _fill_constant_compute(ins, attrs, ctx, op_index):
    dtype = materialize_dtype(_attr_dtype(attrs))
    return {"Out": jnp.full(tuple(attrs["shape"]), attrs.get("value", 0.0),
                            dtype=dtype)}


register_op(
    "fill_constant", [], ["Out"],
    infer=_fill_constant_infer, compute=_fill_constant_compute, grad=None,
)


# -- fill_zeros_like --------------------------------------------------------

def _fill_zeros_like_compute(ins, attrs, ctx, op_index):
    return {"Out": jnp.zeros_like(ins["X"][0])}


register_op(
    "fill_zeros_like", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape, in_var(op, block, "X").dtype
    ),
    compute=_fill_zeros_like_compute, grad=None,
)


# -- fill_constant_batch_size_like -----------------------------------------

def _fcbsl_infer(op, block):
    shape = list(op.attrs["shape"])
    set_output(op, block, "Out", shape, _attr_dtype(op.attrs))


def _bsl_shape(ins, attrs):
    """*_batch_size_like shape rule: copy the input's batch dim."""
    shape = list(attrs["shape"])
    shape[attrs.get("output_dim_idx", 0)] = \
        ins["Input"][0].shape[attrs.get("input_dim_idx", 0)]
    return tuple(shape)


def _fcbsl_compute(ins, attrs, ctx, op_index):
    return {"Out": jnp.full(_bsl_shape(ins, attrs), attrs.get("value", 0.0),
                            dtype=materialize_dtype(_attr_dtype(attrs)))}


register_op(
    "fill_constant_batch_size_like", ["Input"], ["Out"],
    infer=_fcbsl_infer, compute=_fcbsl_compute, grad=None,
)


# -- random ops -------------------------------------------------------------

def _uniform_random_compute(ins, attrs, ctx, op_index):
    key = ctx.rng_key(op_index)
    dtype = materialize_dtype(_attr_dtype(attrs))
    lo = attrs.get("min", -1.0)
    hi = attrs.get("max", 1.0)
    return {"Out": jax.random.uniform(
        key, tuple(attrs["shape"]), dtype=dtype, minval=lo, maxval=hi)}


register_op(
    "uniform_random", [], ["Out"],
    infer=_fill_constant_infer, compute=_uniform_random_compute,
    grad=None, stateful_random=True,
)


def _gaussian_random_compute(ins, attrs, ctx, op_index):
    key = ctx.rng_key(op_index)
    dtype = materialize_dtype(_attr_dtype(attrs))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    return {"Out": mean + std * jax.random.normal(
        key, tuple(attrs["shape"]), dtype=dtype)}


register_op(
    "gaussian_random", [], ["Out"],
    infer=_fill_constant_infer, compute=_gaussian_random_compute,
    grad=None, stateful_random=True,
)


def _truncated_gaussian_compute(ins, attrs, ctx, op_index):
    key = ctx.rng_key(op_index)
    dtype = materialize_dtype(_attr_dtype(attrs))
    mean = attrs.get("mean", 0.0)
    std = attrs.get("std", 1.0)
    # truncated to +-2 std like the reference (truncated_gaussian_random_op.cc)
    z = jax.random.truncated_normal(key, -2.0, 2.0, tuple(attrs["shape"]), dtype)
    return {"Out": mean + std * z}


register_op(
    "truncated_gaussian_random", [], ["Out"],
    infer=_fill_constant_infer, compute=_truncated_gaussian_compute,
    grad=None, stateful_random=True,
)


# -- assign / cast / shape / increment -------------------------------------

register_op(
    "assign", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape, in_var(op, block, "X").dtype
    ),
    compute=lambda ins, attrs, ctx, op_index: {"Out": ins["X"][0]},
    grad="auto",
)


def _assign_value_compute(ins, attrs, ctx, op_index):
    dtype = _attr_dtype(attrs)
    vals = np.asarray(attrs["values"], dtype=dtype).reshape(tuple(attrs["shape"]))
    return {"Out": jnp.asarray(vals)}


register_op(
    "assign_value", [], ["Out"],
    infer=_fill_constant_infer, compute=_assign_value_compute, grad=None,
)


def _cast_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, convert_dtype(op.attrs["out_dtype"]))


def _cast_compute(ins, attrs, ctx, op_index):
    return {"Out": ins["X"][0].astype(
        materialize_dtype(attrs["out_dtype"]))}


register_op("cast", ["X"], ["Out"], infer=_cast_infer, compute=_cast_compute,
            grad="auto")


def _shape_infer(op, block):
    x = in_var(op, block, "Input")
    set_output(op, block, "Out", (len(x.shape),), np.int64)


register_op(
    "shape", ["Input"], ["Out"],
    infer=_shape_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "Out": jnp.asarray(ins["Input"][0].shape, dtype=long_dtype())
    },
    grad=None,
)


def _increment_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    # preserve dtype: int loop counters must stay int under while_loop
    return {"Out": x + jnp.asarray(attrs.get("step", 1.0), x.dtype)}


register_op(
    "increment", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", in_var(op, block, "X").shape, in_var(op, block, "X").dtype
    ),
    compute=_increment_compute, grad=None,
)


# -- *_batch_size_like randoms (reference *_batch_size_like_op.cc) ----------

def _uniform_bsl_compute(ins, attrs, ctx, op_index):
    key = ctx.rng_key(op_index)
    dtype = materialize_dtype(_attr_dtype(attrs))
    return {"Out": jax.random.uniform(
        key, _bsl_shape(ins, attrs), dtype=dtype,
        minval=attrs.get("min", -1.0), maxval=attrs.get("max", 1.0))}


register_op(
    "uniform_random_batch_size_like", ["Input"], ["Out"],
    infer=_fcbsl_infer, compute=_uniform_bsl_compute,
    grad=None, stateful_random=True,
)


def _gaussian_bsl_compute(ins, attrs, ctx, op_index):
    key = ctx.rng_key(op_index)
    dtype = materialize_dtype(_attr_dtype(attrs))
    return {"Out": attrs.get("mean", 0.0) + attrs.get("std", 1.0)
            * jax.random.normal(key, _bsl_shape(ins, attrs), dtype=dtype)}


register_op(
    "gaussian_random_batch_size_like", ["Input"], ["Out"],
    infer=_fcbsl_infer, compute=_gaussian_bsl_compute,
    grad=None, stateful_random=True,
)


# -- print (reference print_op.cc -> jax.debug.print lowering) --------------

def _print_compute(ins, attrs, ctx, op_index):
    x = ins["In"][0]
    msg = attrs.get("message", "")
    phase = attrs.get("print_phase", "FORWARD")
    if phase in ("FORWARD", "BOTH"):
        def esc(s):  # user text must not hit the format engine
            return str(s).replace("{", "{{").replace("}", "}}")

        parts = []
        if msg:
            parts.append(esc(msg))
        if attrs.get("print_tensor_name", True):
            parts.append(esc(attrs.get("__var_name__", "")))
        if attrs.get("print_tensor_shape", True):
            parts.append("shape=%s" % (tuple(x.shape),))
        if attrs.get("print_tensor_type", True):
            parts.append("dtype=%s" % x.dtype)
        parts.append("value={v}")
        jax.debug.print(" ".join(parts), v=x, ordered=False)
    return {"Out": x}


def _print_grad_compute(ins, attrs, ctx, op_index):
    g = ins["GRAD::Out"][0]
    if attrs.get("print_phase") in ("BACKWARD", "BOTH"):
        fwd_attrs = dict(attrs, print_phase="FORWARD",
                         __var_name__=attrs.get("__grad_name__", ""))
        _print_compute({"In": [g]}, fwd_attrs, ctx, op_index)
    return {"GRAD::In": g}


def _print_grad_infer(op, block):
    from ..registry import in_var, set_output
    g = in_var(op, block, "GRAD::Out")
    set_output(op, block, "GRAD::In", g.shape, g.dtype)


register_op(
    "print_grad", ["GRAD::Out"], ["GRAD::In"],
    infer=_print_grad_infer, compute=_print_grad_compute, grad=None,
)


def _print_grad(op, no_grad_set):
    # pass the cotangent straight through (auto-vjp would re-run the
    # forward and print twice); print it when the phase asks for it,
    # mirroring print_op.cc's backward registration.  Wired through
    # GRAD:: slots so backward.py materializes (sums) the cotangent
    # before this op reads it.
    from ..framework import grad_var_name
    x = op.inputs["In"][0]
    if x in no_grad_set:
        return []
    g_out = grad_var_name(op.outputs["Out"][0])
    attrs = dict(op.attrs)
    attrs["__grad_name__"] = g_out
    return [dict(type="print_grad",
                 inputs={"GRAD::Out": [g_out]},
                 outputs={"GRAD::In": [grad_var_name(x)]},
                 attrs=attrs)]


register_op(
    "print", ["In"], ["Out"],
    infer=same_shape_infer("In", "Out"),
    compute=_print_compute, grad=_print_grad,
)
