"""Detection op suite: prior_box, anchor_generator, box_coder,
iou_similarity, bipartite_match, target_assign, multiclass_nms,
roi_pool, polygon_box_transform.

Parity: reference ``operators/detection/`` (prior_box_op.h:96-160 prior
layout incl. the min/max/aspect-ratio ordering flag,
anchor_generator_op.h:40-90 stride-area anchors, box_coder_op.h
encode/decode center-size with prior variances, iou_similarity_op,
bipartite_match_op.cc:61-115 greedy bipartite + per-prediction argmax
fill, target_assign_op.h scatter with mismatch_value, multiclass_nms_op
per-class NMS with score/nms/keep thresholds) and ``roi_pool_op.cc``.

TPU-first: every per-pixel/per-box loop is a broadcasted tensor
expression; the greedy NMS/bipartite selections are ``lax.fori_loop``
over fixed trip counts with masking (XLA-friendly static shapes);
LoD-style outputs become padded arrays + explicit counts.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..registry import register_op, set_output, in_var

__all__ = []

_BIG_NEG = -1e9


# -- iou_similarity ---------------------------------------------------------

def _iou_matrix(a, b, normalized=True):
    """a [N,4], b [M,4] -> [N,M] IoU (xmin,ymin,xmax,ymax)."""
    off = 0.0 if normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    ix1 = jnp.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = jnp.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = jnp.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = jnp.minimum(a[:, None, 3], b[None, :, 3])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def _iou_infer(op, block):
    x = in_var(op, block, "X")
    y = in_var(op, block, "Y")
    if len(x.shape) == 3:  # batched: [B, N, 4] -> [B, N, M]
        set_output(op, block, "Out",
                   (x.shape[0], x.shape[1], y.shape[-2]), x.dtype)
    else:
        set_output(op, block, "Out", (x.shape[0], y.shape[0]), x.dtype)


def _iou_compute(ins, attrs, ctx, op_index):
    x, y = ins["X"][0], ins["Y"][0]
    if x.ndim == 3:  # batched [B,N,4] x [M,4] or [B,M,4]
        if y.ndim == 3:
            return {"Out": jax.vmap(_iou_matrix)(x, y)}
        return {"Out": jax.vmap(lambda a: _iou_matrix(a, y))(x)}
    return {"Out": _iou_matrix(x, y)}


register_op("iou_similarity", ["X", "Y"], ["Out"],
            infer=_iou_infer, compute=_iou_compute, grad=None)


# -- prior_box --------------------------------------------------------------

def _prior_box_shapes(attrs):
    min_sizes = [float(s) for s in attrs["min_sizes"]]
    max_sizes = [float(s) for s in attrs.get("max_sizes", []) or []]
    ars = [1.0]
    for ar in attrs.get("aspect_ratios", []) or []:
        ar = float(ar)
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(ar)
            if attrs.get("flip", False) and \
                    not any(abs(1.0 / ar - e) < 1e-6 for e in ars):
                ars.append(1.0 / ar)
    n = len(ars) * len(min_sizes) + len(max_sizes)
    return min_sizes, max_sizes, ars, n


def _prior_box_wh(attrs):
    """Per-prior (half_w, half_h) in pixels, in the reference's
    emission order (prior_box_op.h:110-160; default order: aspect
    ratios of each min_size first, then the sqrt(min*max) square)."""
    min_sizes, max_sizes, ars, _ = _prior_box_shapes(attrs)
    order_flag = attrs.get("min_max_aspect_ratios_order", False)
    ws, hs = [], []
    for s_i, ms in enumerate(min_sizes):
        if order_flag:
            ws.append(ms / 2.0)
            hs.append(ms / 2.0)
            if max_sizes:
                m = np.sqrt(ms * max_sizes[s_i]) / 2.0
                ws.append(m)
                hs.append(m)
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                ws.append(ms * np.sqrt(ar) / 2.0)
                hs.append(ms / np.sqrt(ar) / 2.0)
        else:
            for ar in ars:
                ws.append(ms * np.sqrt(ar) / 2.0)
                hs.append(ms / np.sqrt(ar) / 2.0)
            if max_sizes:
                m = np.sqrt(ms * max_sizes[s_i]) / 2.0
                ws.append(m)
                hs.append(m)
    return np.asarray(ws, np.float32), np.asarray(hs, np.float32)


def _prior_box_infer(op, block):
    x = in_var(op, block, "Input")
    _, _, _, n = _prior_box_shapes(op.attrs)
    h, w = x.shape[2], x.shape[3]
    set_output(op, block, "Boxes", (h, w, n, 4), "float32")
    set_output(op, block, "Variances", (h, w, n, 4), "float32")


def _prior_box_compute(ins, attrs, ctx, op_index):
    fmap = ins["Input"][0]       # [N, C, H, W]
    image = ins["Image"][0]      # [N, C, Hi, Wi]
    h, w = fmap.shape[2], fmap.shape[3]
    img_h, img_w = image.shape[2], image.shape[3]
    step_w = float(attrs.get("step_w", 0) or 0) or img_w / w
    step_h = float(attrs.get("step_h", 0) or 0) or img_h / h
    offset = float(attrs.get("offset", 0.5))
    half_w, half_h = _prior_box_wh(attrs)
    cx = (jnp.arange(w) + offset) * step_w      # [W]
    cy = (jnp.arange(h) + offset) * step_h      # [H]
    cx = cx[None, :, None]
    cy = cy[:, None, None]
    hw = jnp.asarray(half_w)[None, None, :]
    hh = jnp.asarray(half_h)[None, None, :]
    boxes = jnp.stack([
        jnp.broadcast_to((cx - hw) / img_w, (h, w, hw.shape[-1])),
        jnp.broadcast_to((cy - hh) / img_h, (h, w, hw.shape[-1])),
        jnp.broadcast_to((cx + hw) / img_w, (h, w, hw.shape[-1])),
        jnp.broadcast_to((cy + hh) / img_h, (h, w, hw.shape[-1])),
    ], axis=-1)
    if attrs.get("clip", False):
        boxes = jnp.clip(boxes, 0.0, 1.0)
    variances = jnp.asarray(attrs.get("variances",
                                      [0.1, 0.1, 0.2, 0.2]), jnp.float32)
    variances = jnp.broadcast_to(variances, boxes.shape)
    return {"Boxes": boxes.astype(jnp.float32), "Variances": variances}


register_op("prior_box", ["Input", "Image"], ["Boxes", "Variances"],
            infer=_prior_box_infer, compute=_prior_box_compute, grad=None)


# -- anchor_generator -------------------------------------------------------

def _anchor_gen_infer(op, block):
    x = in_var(op, block, "Input")
    n = len(op.attrs["anchor_sizes"]) * len(op.attrs["aspect_ratios"])
    h, w = x.shape[2], x.shape[3]
    set_output(op, block, "Anchors", (h, w, n, 4), "float32")
    set_output(op, block, "Variances", (h, w, n, 4), "float32")


def _anchor_gen_compute(ins, attrs, ctx, op_index):
    fmap = ins["Input"][0]
    h, w = fmap.shape[2], fmap.shape[3]
    stride = attrs.get("stride", [16.0, 16.0])
    sw, sh = float(stride[0]), float(stride[1])
    offset = float(attrs.get("offset", 0.5))
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ars = [float(a) for a in attrs["aspect_ratios"]]
    # anchor_generator_op.h:57-75: rounded base box from stride area
    aws, ahs = [], []
    for ar in ars:
        for s in sizes:
            base_w = np.round(np.sqrt(sw * sh / ar))
            base_h = np.round(base_w * ar)
            aws.append(s / sw * base_w)
            ahs.append(s / sh * base_h)
    aw = jnp.asarray(aws, jnp.float32)[None, None, :]
    ah = jnp.asarray(ahs, jnp.float32)[None, None, :]
    x_ctr = (jnp.arange(w) * sw + offset * (sw - 1))[None, :, None]
    y_ctr = (jnp.arange(h) * sh + offset * (sh - 1))[:, None, None]
    n = aw.shape[-1]
    anchors = jnp.stack([
        jnp.broadcast_to(x_ctr - 0.5 * (aw - 1), (h, w, n)),
        jnp.broadcast_to(y_ctr - 0.5 * (ah - 1), (h, w, n)),
        jnp.broadcast_to(x_ctr + 0.5 * (aw - 1), (h, w, n)),
        jnp.broadcast_to(y_ctr + 0.5 * (ah - 1), (h, w, n)),
    ], axis=-1)
    variances = jnp.broadcast_to(
        jnp.asarray(attrs.get("variances", [0.1, 0.1, 0.2, 0.2]),
                    jnp.float32), anchors.shape)
    return {"Anchors": anchors.astype(jnp.float32),
            "Variances": variances}


register_op("anchor_generator", ["Input"], ["Anchors", "Variances"],
            infer=_anchor_gen_infer, compute=_anchor_gen_compute,
            grad=None)


# -- box_coder --------------------------------------------------------------

def _center_form(b, off):
    w = b[..., 2] - b[..., 0] + off
    h = b[..., 3] - b[..., 1] + off
    cx = (b[..., 2] + b[..., 0]) / 2
    cy = (b[..., 3] + b[..., 1]) / 2
    return cx, cy, w, h


def _box_coder_infer(op, block):
    t = in_var(op, block, "TargetBox")
    p = in_var(op, block, "PriorBox")
    if op.attrs.get("code_type", "encode_center_size") \
            .endswith("encode_center_size"):
        set_output(op, block, "OutputBox",
                   (t.shape[0], p.shape[0], 4), "float32")
    else:
        set_output(op, block, "OutputBox", t.shape, "float32")


def _box_coder_compute(ins, attrs, ctx, op_index):
    tb = ins["TargetBox"][0]
    pb = ins["PriorBox"][0]
    pvs = ins.get("PriorBoxVar")
    pv = pvs[0] if pvs and pvs[0] is not None else None
    off = 0.0 if attrs.get("box_normalized", True) else 1.0
    code = attrs.get("code_type", "encode_center_size")
    pcx, pcy, pw, ph = _center_form(pb, off)           # [M]
    if code.endswith("encode_center_size"):
        tcx, tcy, tw, th = _center_form(tb, off)       # [N]
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
            jnp.log(jnp.abs(th[:, None] / ph[None, :])),
        ], axis=-1)                                     # [N, M, 4]
        if pv is not None:
            out = out / pv[None, :, :]
    else:  # decode_center_size: tb [N, M, 4] against prior j per column
        t = tb
        if pv is not None:
            t = t * pv[None, :, :]
        cx = t[..., 0] * pw[None, :] + pcx[None, :]
        cy = t[..., 1] * ph[None, :] + pcy[None, :]
        w = jnp.exp(t[..., 2]) * pw[None, :]
        h = jnp.exp(t[..., 3]) * ph[None, :]
        out = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - off, cy + h / 2 - off], axis=-1)
    return {"OutputBox": out.astype(jnp.float32)}


register_op("box_coder", ["TargetBox", "PriorBox", "PriorBoxVar"],
            ["OutputBox"],
            infer=_box_coder_infer, compute=_box_coder_compute, grad=None)


# -- bipartite_match --------------------------------------------------------

def _bipartite_match_single(dist, per_prediction=False,
                            dist_threshold=0.5):
    """dist [G, P] -> (col_to_row [P] int32, col_dist [P]).  Greedy
    global-max bipartite (bipartite_match_op.cc:65-105); with
    match_type='per_prediction' (bipartite_match_op.cc:199-243),
    unmatched columns whose best dist >= dist_threshold take their
    argmax row."""
    g, p = dist.shape
    match = jnp.full((p,), -1, jnp.int32)
    mdist = jnp.zeros((p,), dist.dtype)
    row_used = jnp.zeros((g,), bool)
    col_used = jnp.zeros((p,), bool)

    def body(_, carry):
        match, mdist, row_used, col_used = carry
        masked = jnp.where(row_used[:, None] | col_used[None, :],
                           _BIG_NEG, dist)
        flat = jnp.argmax(masked)
        i, j = flat // p, flat % p
        best = masked[i, j]
        ok = best > 0
        match = jnp.where(ok, match.at[j].set(i.astype(jnp.int32)),
                          match)
        mdist = jnp.where(ok, mdist.at[j].set(best), mdist)
        row_used = jnp.where(ok, row_used.at[i].set(True), row_used)
        col_used = jnp.where(ok, col_used.at[j].set(True), col_used)
        return match, mdist, row_used, col_used

    match, mdist, _, _ = lax.fori_loop(
        0, min(g, p), body, (match, mdist, row_used, col_used))
    if per_prediction:
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        fill = (match == -1) & (best_val >= dist_threshold)
        match = jnp.where(fill, best_row, match)
        mdist = jnp.where(fill, best_val, mdist)
    return match, mdist


def _bipartite_infer(op, block):
    d = in_var(op, block, "DistMat")
    b = d.shape[0] if len(d.shape) == 3 else 1
    p = d.shape[-1]
    set_output(op, block, "ColToRowMatchIndices", (b, p), "int32")
    set_output(op, block, "ColToRowMatchDist", (b, p), "float32")


def _bipartite_compute(ins, attrs, ctx, op_index):
    dist = ins["DistMat"][0]
    if dist.ndim == 2:
        dist = dist[None]
    per_pred = attrs.get("match_type", "bipartite") == "per_prediction"
    thresh = float(attrs.get("dist_threshold", 0.5))
    match, mdist = jax.vmap(
        lambda d: _bipartite_match_single(d, per_pred, thresh))(dist)
    return {"ColToRowMatchIndices": match,
            "ColToRowMatchDist": mdist.astype(jnp.float32)}


register_op("bipartite_match", ["DistMat"],
            ["ColToRowMatchIndices", "ColToRowMatchDist"],
            infer=_bipartite_infer, compute=_bipartite_compute, grad=None)


# -- target_assign ----------------------------------------------------------

def _target_assign_infer(op, block):
    x = in_var(op, block, "X")
    m = in_var(op, block, "MatchIndices")
    k = x.shape[-1]
    set_output(op, block, "Out", (m.shape[0], m.shape[1], k), x.dtype)
    set_output(op, block, "OutWeight", (m.shape[0], m.shape[1], 1),
               "float32")


def _target_assign_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]          # [B, G, K] gt rows, or [B, G, P, K]
    match = ins["MatchIndices"][0]        # [B, P] gt row or -1
    mismatch = float(attrs.get("mismatch_value", 0))
    safe = jnp.maximum(match, 0).astype(jnp.int32)
    if x.ndim == 4:
        # per-(gt, prior) attributes (target_assign_op.h x[i][j][k]):
        # out[b, p] = x[b, match[b,p], p]
        b_idx = jnp.broadcast_to(jnp.arange(x.shape[0])[:, None],
                                 match.shape)
        p_idx = jnp.broadcast_to(jnp.arange(match.shape[1])[None, :],
                                 match.shape)
        out = x[b_idx, safe, p_idx]
    else:
        out = jnp.take_along_axis(x, safe[:, :, None], axis=1)
    matched = (match >= 0)[:, :, None]
    out = jnp.where(matched, out, jnp.asarray(mismatch, x.dtype))
    weight = matched.astype(jnp.float32)
    negs = ins.get("NegIndices")
    if negs and negs[0] is not None:
        neg = negs[0]                     # [B, Q] prior ids (or -1 pad)
        b_idx = jnp.broadcast_to(jnp.arange(neg.shape[0])[:, None],
                                 neg.shape)
        tgt = jnp.where(neg >= 0, neg, weight.shape[1])
        weight = weight.at[b_idx, tgt, 0].set(1.0, mode="drop")
    return {"Out": out, "OutWeight": weight}


register_op("target_assign", ["X", "MatchIndices", "NegIndices"],
            ["Out", "OutWeight"],
            infer=_target_assign_infer, compute=_target_assign_compute,
            grad=None)


# -- multiclass_nms ---------------------------------------------------------

def _nms_class(boxes, scores, score_thresh, nms_thresh, top_k,
               normalized, eta=1.0):
    """One class: boxes [M,4], scores [M] -> keep mask [M] (greedy NMS
    over the top_k highest scores).  ``eta < 1`` decays the overlap
    threshold after each kept box while it stays above 0.5 — the
    reference's adaptive NMS (multiclass_nms_op.cc NMSFast,
    generate_proposals_op.cc eta attr)."""
    m = boxes.shape[0]
    k = min(top_k, m) if top_k > 0 else m
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    sscores = scores[order]
    iou = _iou_matrix(sboxes, sboxes, normalized)
    valid = sscores > score_thresh

    def body(i, carry):
        keep, thresh = carry
        # suppressed iff any already-kept earlier box overlaps > thresh
        # (thresh is the adaptive threshold at this candidate's turn)
        earlier_kept = jnp.where(jnp.arange(m) < i, keep, False)
        sup = jnp.any(earlier_kept & (iou[:, i] > thresh))
        ok = valid[i] & (i < k) & ~sup
        if eta < 1.0:
            thresh = jnp.where(ok & (thresh > 0.5), thresh * eta, thresh)
        return keep.at[i].set(ok), thresh

    keep_sorted, _ = lax.fori_loop(
        0, m, body,
        (jnp.zeros((m,), bool), jnp.asarray(nms_thresh, jnp.float32)))
    keep = jnp.zeros((m,), bool).at[order].set(keep_sorted)
    return keep


def _multiclass_nms_single(bboxes, scores, attrs):
    """bboxes [M,4], scores [C,M] -> out [keep_top_k, 6], count."""
    c, m = scores.shape
    bg = int(attrs.get("background_label", 0))
    score_thresh = float(attrs.get("score_threshold", 0.0))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", -1))
    keep_top_k = int(attrs.get("keep_top_k", -1))
    normalized = bool(attrs.get("normalized", True))

    def per_class(cls_scores):
        return _nms_class(bboxes, cls_scores, score_thresh, nms_thresh,
                          nms_top_k, normalized,
                          eta=float(attrs.get("nms_eta", 1.0)))

    keep = jax.vmap(per_class)(scores)           # [C, M]
    if 0 <= bg < c:
        keep = keep.at[bg].set(False)
    flat_scores = jnp.where(keep, scores, _BIG_NEG).reshape(-1)  # [C*M]
    total = keep_top_k if keep_top_k > 0 else c * m
    total = min(total, c * m)
    top_scores, top_idx = lax.top_k(flat_scores, total)
    cls_ids = (top_idx // m).astype(jnp.float32)
    box_ids = top_idx % m
    sel_boxes = bboxes[box_ids]
    valid = top_scores > _BIG_NEG / 2
    out = jnp.concatenate([
        jnp.where(valid, cls_ids, -1.0)[:, None],
        jnp.where(valid, top_scores, 0.0)[:, None],
        jnp.where(valid[:, None], sel_boxes, 0.0),
    ], axis=1)
    return out, jnp.sum(valid.astype(jnp.int32))


def _multiclass_nms_infer(op, block):
    s = in_var(op, block, "Scores")
    b = s.shape[0]
    keep = int(op.attrs.get("keep_top_k", -1))
    m = s.shape[-1]
    n = keep if keep > 0 else (None if m in (None, -1) else
                               s.shape[1] * m)
    set_output(op, block, "Out", (b, n, 6), "float32", lod_level=1)
    set_output(op, block, "OutLength", (b,), "int32")


def _multiclass_nms_compute(ins, attrs, ctx, op_index):
    bboxes = ins["BBoxes"][0]             # [B, M, 4]
    scores = ins["Scores"][0]             # [B, C, M]
    out, count = jax.vmap(
        lambda b, s: _multiclass_nms_single(b, s, attrs))(bboxes, scores)
    return {"Out": out, "OutLength": count}


register_op("multiclass_nms", ["BBoxes", "Scores"], ["Out", "OutLength"],
            infer=_multiclass_nms_infer, compute=_multiclass_nms_compute,
            grad=None)


# -- roi_pool ---------------------------------------------------------------

def _roi_pool_infer(op, block):
    x = in_var(op, block, "X")
    rois = in_var(op, block, "ROIs")
    set_output(op, block, "Out",
               (rois.shape[0], x.shape[1],
                int(op.attrs["pooled_height"]),
                int(op.attrs["pooled_width"])), x.dtype)


def _roi_pool_compute(ins, attrs, ctx, op_index):
    """Max-pool each ROI into a fixed [ph, pw] grid
    (roi_pool_op.cc semantics; ROIs are [R, 4] pixel coords with a
    companion RoisBatch [R] image index, replacing the LoD)."""
    x = ins["X"][0]                       # [N, C, H, W]
    rois = ins["ROIs"][0]                 # [R, 4]
    rbs = ins.get("RoisBatch")
    roi_batch = rbs[0] if rbs and rbs[0] is not None else \
        jnp.zeros((rois.shape[0],), jnp.int32)
    ph = int(attrs["pooled_height"])
    pw = int(attrs["pooled_width"])
    scale = float(attrs.get("spatial_scale", 1.0))
    n, c, h, w = x.shape

    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one_roi(roi, b):
        x1 = jnp.round(roi[0] * scale)
        y1 = jnp.round(roi[1] * scale)
        x2 = jnp.round(roi[2] * scale)
        y2 = jnp.round(roi[3] * scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        img = x[b]                        # [C, H, W]

        def pool_bin(py, px):
            y_lo = y1 + jnp.floor(py * bin_h)
            y_hi = y1 + jnp.ceil((py + 1) * bin_h)
            x_lo = x1 + jnp.floor(px * bin_w)
            x_hi = x1 + jnp.ceil((px + 1) * bin_w)
            ymask = (ys >= y_lo) & (ys < jnp.maximum(y_hi, y_lo + 1)) \
                & (ys >= 0) & (ys < h)
            xmask = (xs >= x_lo) & (xs < jnp.maximum(x_hi, x_lo + 1)) \
                & (xs >= 0) & (xs < w)
            mask = ymask[:, None] & xmask[None, :]
            return jnp.max(jnp.where(mask[None], img, _BIG_NEG),
                           axis=(1, 2))

        grid = jax.vmap(lambda py: jax.vmap(
            lambda px: pool_bin(py, px))(jnp.arange(pw)))(jnp.arange(ph))
        # grid [ph, pw, C] -> [C, ph, pw]; empty bins -> 0
        grid = jnp.where(grid <= _BIG_NEG / 2, 0.0, grid)
        return grid.transpose(2, 0, 1)

    out = jax.vmap(one_roi)(rois.astype(jnp.float32),
                            roi_batch.astype(jnp.int32))
    return {"Out": out.astype(x.dtype)}


register_op("roi_pool", ["X", "ROIs", "RoisBatch"], ["Out"],
            infer=_roi_pool_infer, compute=_roi_pool_compute,
            no_grad_inputs=("ROIs", "RoisBatch"))


# -- polygon_box_transform --------------------------------------------------

def _pbt_compute(ins, attrs, ctx, op_index):
    """polygon_box_transform_op.cc:43-48: even channels out = col - in,
    odd channels out = row - in, on a [N, C, H, W] geometry map."""
    x = ins["X"][0]
    n, c, h, w = x.shape
    cols = jnp.arange(w, dtype=x.dtype)
    rows = jnp.arange(h, dtype=x.dtype)
    ch = jnp.arange(c)
    base = jnp.where((ch % 2 == 0)[None, :, None, None],
                     jnp.broadcast_to(cols[None, None, None, :],
                                      (1, c, h, w)),
                     jnp.broadcast_to(rows[None, None, :, None],
                                      (1, c, h, w)))
    return {"Out": base - x}


register_op("polygon_box_transform", ["X"], ["Out"],
            infer=lambda op, block: set_output(
                op, block, "Out", in_var(op, block, "X").shape,
                in_var(op, block, "X").dtype),
            compute=_pbt_compute, grad=None)


# -- mine_hard_examples -----------------------------------------------------

def _mine_hard_infer(op, block):
    m = in_var(op, block, "MatchIndices")
    set_output(op, block, "NegIndices", m.shape, "int32")
    set_output(op, block, "NegCount", (m.shape[0],), "int32")
    set_output(op, block, "UpdatedMatchIndices", m.shape, "int32")


def _mine_hard_compute(ins, attrs, ctx, op_index):
    """Hard-negative mining (mine_hard_examples_op.cc:29-80), both modes.

    max_negative: eligible negatives are unmatched priors with match_dist
    below neg_dist_threshold; the num_pos*neg_pos_ratio highest-conf-loss
    ones are selected.  hard_example: every prior competes on
    cls_loss+loc_loss, the top sample_size survive — mined unmatched
    priors become negatives, unmined matched priors lose their match.
    NegIndices is a compacted, -1-padded [N, P] index array + NegCount
    (the LoD replacement)."""
    cls_loss = ins["ClsLoss"][0]                 # [N, P]
    match = ins["MatchIndices"][0]               # [N, P]
    mdist = ins["MatchDist"][0]
    mining_type = attrs.get("mining_type", "max_negative")
    if mining_type not in ("max_negative", "hard_example"):
        raise ValueError(
            "mine_hard_examples: unknown mining_type %r" % mining_type)
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    thresh = float(attrs.get("neg_dist_threshold", 0.5))

    n, p = match.shape
    unmatched = match == -1
    if mining_type == "hard_example":
        # every prior is eligible; rank by cls+loc loss, cap at
        # sample_size (mine_hard_examples_op.cc kHardExample)
        sample_size = int(attrs.get("sample_size") or 0)
        if sample_size <= 0:
            raise ValueError(
                "mine_hard_examples: mining_type='hard_example' needs "
                "sample_size > 0 (mine_hard_examples_op.cc enforces it)")
        eligible = jnp.ones((n, p), bool)
        loss = cls_loss
        loc = ins.get("LocLoss")
        if loc and loc[0] is not None:
            loss = loss + loc[0]
        num_neg = jnp.full((n,), min(sample_size, p), jnp.int32)
    else:
        # eligible negatives: unmatched priors with match_dist below the
        # threshold; rank by cls_loss alone, cap at num_pos * ratio
        eligible = unmatched & (mdist < thresh)
        loss = cls_loss
        num_pos = jnp.sum((~unmatched).astype(jnp.int32), axis=1)
        num_neg = jnp.minimum(
            (num_pos.astype(jnp.float32) * ratio).astype(jnp.int32),
            jnp.sum(eligible.astype(jnp.int32), axis=1))

    masked = jnp.where(eligible, loss, _BIG_NEG)
    order = jnp.argsort(-masked, axis=1)         # loss-desc prior ids
    rank = jnp.argsort(order, axis=1)            # rank of each prior
    hard = eligible & (rank < num_neg[:, None])  # the mined set

    if mining_type == "hard_example":
        # matched priors not mined are dropped from matching; mined
        # unmatched priors become the negatives
        updated = jnp.where(~unmatched & ~hard, -1, match)
        sel = hard & unmatched
        num_out = jnp.sum(sel.astype(jnp.int32), axis=1)
    else:
        updated = match
        sel = hard
        num_out = num_neg

    # compact selected prior ids (ascending) into the left of each row
    pos = jnp.cumsum(sel.astype(jnp.int32), axis=1) - 1
    b_idx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, p))
    prior_ids = jnp.broadcast_to(jnp.arange(p)[None, :], (n, p))
    neg = jnp.full((n, p), -1, jnp.int32).at[
        b_idx, jnp.where(sel, pos, p)].set(
        prior_ids.astype(jnp.int32), mode="drop")
    return {"NegIndices": neg, "NegCount": num_out.astype(jnp.int32),
            "UpdatedMatchIndices": updated.astype(jnp.int32)}


register_op("mine_hard_examples",
            ["ClsLoss", "LocLoss", "MatchIndices", "MatchDist"],
            ["NegIndices", "NegCount", "UpdatedMatchIndices"],
            infer=_mine_hard_infer, compute=_mine_hard_compute,
            grad=None)


# -- generate_proposals -----------------------------------------------------

def _gen_proposals_infer(op, block):
    s = in_var(op, block, "Scores")
    post = int(op.attrs.get("post_nms_topN", 1000))
    b = s.shape[0]
    set_output(op, block, "RpnRois", (b, post, 4), "float32",
               lod_level=1)
    set_output(op, block, "RpnRoiProbs", (b, post, 1), "float32")
    set_output(op, block, "RpnRoisLength", (b,), "int32")


def _gen_proposals_single(scores, deltas, im_info, anchors, variances,
                          attrs):
    """One image (generate_proposals_op.cc ProposalForOneImage):
    top-preN scores -> decode deltas on anchors -> clip to image ->
    drop tiny boxes -> NMS -> top-postN."""
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.5))
    min_size = float(attrs.get("min_size", 0.1))

    a = scores.shape[0]
    k = min(pre_n, a)
    top_scores, top_idx = lax.top_k(scores, k)
    anc = anchors[top_idx]
    var = variances[top_idx]
    d = deltas[top_idx] * var
    # decode (anchor coords are corner-inclusive like anchor_generator)
    aw = anc[:, 2] - anc[:, 0] + 1.0
    ah = anc[:, 3] - anc[:, 1] + 1.0
    acx = anc[:, 0] + aw / 2
    acy = anc[:, 1] + ah / 2
    cx = d[:, 0] * aw + acx
    cy = d[:, 1] * ah + acy
    w = jnp.exp(jnp.minimum(d[:, 2], 10.0)) * aw
    h = jnp.exp(jnp.minimum(d[:, 3], 10.0)) * ah
    boxes = jnp.stack([cx - w / 2, cy - h / 2,
                       cx + w / 2 - 1.0, cy + h / 2 - 1.0], axis=-1)
    img_h, img_w = im_info[0], im_info[1]
    boxes = jnp.stack([
        jnp.clip(boxes[:, 0], 0.0, img_w - 1.0),
        jnp.clip(boxes[:, 1], 0.0, img_h - 1.0),
        jnp.clip(boxes[:, 2], 0.0, img_w - 1.0),
        jnp.clip(boxes[:, 3], 0.0, img_h - 1.0)], axis=-1)
    scale = im_info[2]
    keep_size = ((boxes[:, 2] - boxes[:, 0] + 1.0 >= min_size * scale) &
                 (boxes[:, 3] - boxes[:, 1] + 1.0 >= min_size * scale))
    eff_scores = jnp.where(keep_size, top_scores, _BIG_NEG)
    keep = _nms_class(boxes, eff_scores, _BIG_NEG / 2, nms_thresh,
                      k, normalized=False,
                      eta=float(attrs.get("eta", 1.0)))
    final_scores = jnp.where(keep, eff_scores, _BIG_NEG)
    n_out = min(post_n, k)
    sel_scores, sel = lax.top_k(final_scores, n_out)
    rois = boxes[sel]
    valid = sel_scores > _BIG_NEG / 2
    rois = jnp.where(valid[:, None], rois, 0.0)
    probs = jnp.where(valid, sel_scores, 0.0)[:, None]
    if n_out < post_n:
        pad = post_n - n_out
        rois = jnp.pad(rois, ((0, pad), (0, 0)))
        probs = jnp.pad(probs, ((0, pad), (0, 0)))
        valid = jnp.pad(valid, (0, pad))
    return rois, probs, jnp.sum(valid.astype(jnp.int32))


def _gen_proposals_compute(ins, attrs, ctx, op_index):
    """Accepted layouts: scores [B, A_total] / deltas [B, A_total, 4]
    already in the anchors' flattening order ((H, W, A)-major, matching
    anchor_generator's [H, W, A, 4] output), or the reference conv-head
    NCHW form scores [B, A, H, W] / deltas [B, 4A, H, W] (transposed to
    (H, W, A)-major here, generate_proposals_op.cc Transpose)."""
    scores = ins["Scores"][0]
    deltas = ins["BboxDeltas"][0]
    im_info = ins["ImInfo"][0]        # [B, 3]
    anchors = ins["Anchors"][0].reshape(-1, 4)
    variances = ins["Variances"][0].reshape(-1, 4)
    if scores.ndim == 4:              # [B, A, H, W] -> [B, H*W*A]
        scores = scores.transpose(0, 2, 3, 1).reshape(scores.shape[0], -1)
    elif scores.ndim != 2:
        raise ValueError(
            "generate_proposals: scores must be [B, A_total] "
            "(anchor-flattening order) or NCHW [B, A, H, W]; got ndim=%d"
            % scores.ndim)
    if deltas.ndim == 4:              # [B, 4A, H, W] -> [B, H*W*A, 4]
        b_, c4, hh, ww = deltas.shape
        deltas = deltas.reshape(b_, c4 // 4, 4, hh, ww)             .transpose(0, 3, 4, 1, 2).reshape(b_, -1, 4)
    elif deltas.ndim != 3:
        raise ValueError(
            "generate_proposals: bbox_deltas must be [B, A_total, 4] or "
            "NCHW [B, 4A, H, W]; got ndim=%d" % deltas.ndim)
    rois, probs, count = jax.vmap(
        lambda s, d, i: _gen_proposals_single(s, d, i, anchors,
                                              variances, attrs))(
        scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs,
            "RpnRoisLength": count}


register_op("generate_proposals",
            ["Scores", "BboxDeltas", "ImInfo", "Anchors", "Variances"],
            ["RpnRois", "RpnRoiProbs", "RpnRoisLength"],
            infer=_gen_proposals_infer, compute=_gen_proposals_compute,
            grad=None)


# -- rpn_target_assign ------------------------------------------------------

def _rpn_assign_infer(op, block):
    a = in_var(op, block, "Anchor")
    g = in_var(op, block, "GtBoxes")
    b = g.shape[0] if len(g.shape) == 3 else 1
    # anchors may arrive as anchor_generator's [H, W, A, 4]: the count
    # is the product of every dim but the last
    dims = [d for d in a.shape[:-1]]
    n = None if any(d in (None, -1) for d in dims) else int(np.prod(dims))
    set_output(op, block, "ScoreLabels", (b, n), "int32")
    set_output(op, block, "TargetBBox", (b, n, 4), "float32")
    set_output(op, block, "BBoxWeight", (b, n, 1), "float32")


def _rpn_assign_single(anchors, gt, gt_len, attrs):
    """One image (rpn_target_assign_op.cc ScoreAssign):
    fg = best anchor per gt + anchors with max-overlap >= pos_thresh;
    bg = max-overlap < neg_thresh; fg capped at
    fg_fraction*batch_size_per_im, bg at the remainder (deterministic
    first-k in place of reservoir sampling — static shapes)."""
    batch_per_im = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_th = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_th = float(attrs.get("rpn_negative_overlap", 0.3))

    a = anchors.shape[0]
    g = gt.shape[0]
    gt_valid = jnp.arange(g) < gt_len
    iou = _iou_matrix(anchors, gt, normalized=False)        # [A, G]
    iou = jnp.where(gt_valid[None, :], iou, 0.0)
    max_per_anchor = jnp.max(iou, axis=1)
    argmax_gt = jnp.argmax(iou, axis=1)
    # anchors that are the best for some gt are fg regardless of thresh
    best_per_gt = jnp.max(iou, axis=0)                      # [G]
    is_best = jnp.any((iou == best_per_gt[None, :]) & (iou > 0) &
                      gt_valid[None, :], axis=1)
    fg = is_best | (max_per_anchor >= pos_th)
    bg = (~fg) & (max_per_anchor < neg_th)

    fg_cap = int(fg_frac * batch_per_im)
    fg_rank = jnp.cumsum(fg.astype(jnp.int32)) - 1
    fg = fg & (fg_rank < fg_cap)
    n_fg = jnp.sum(fg.astype(jnp.int32))
    bg_rank = jnp.cumsum(bg.astype(jnp.int32)) - 1
    bg = bg & (bg_rank < batch_per_im - n_fg)

    labels = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)

    # encoded regression targets for fg anchors (no variances in RPN)
    matched = gt[argmax_gt]
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw = matched[:, 2] - matched[:, 0] + 1.0
    gh = matched[:, 3] - matched[:, 1] + 1.0
    gcx = matched[:, 0] + gw / 2
    gcy = matched[:, 1] + gh / 2
    tgt = jnp.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                     jnp.log(gw / aw), jnp.log(gh / ah)], axis=-1)
    tgt = jnp.where(fg[:, None], tgt, 0.0)
    weight = fg.astype(jnp.float32)[:, None]
    return labels, tgt, weight


def _rpn_assign_compute(ins, attrs, ctx, op_index):
    anchors = ins["Anchor"][0].reshape(-1, 4)
    gt = ins["GtBoxes"][0]            # [B, G, 4] padded (or [G, 4])
    if gt.ndim == 2:
        gt = gt[None]                 # the unbatched form infer allows
    lens = ins.get("GtLength")
    if lens and lens[0] is not None:
        gt_len = lens[0]
    else:
        gt_len = jnp.full((gt.shape[0],), gt.shape[1], jnp.int32)
    labels, tgt, w = jax.vmap(
        lambda g, l: _rpn_assign_single(anchors, g, l, attrs))(gt, gt_len)
    return {"ScoreLabels": labels, "TargetBBox": tgt, "BBoxWeight": w}


register_op("rpn_target_assign", ["Anchor", "GtBoxes", "GtLength"],
            ["ScoreLabels", "TargetBBox", "BBoxWeight"],
            infer=_rpn_assign_infer, compute=_rpn_assign_compute,
            grad=None)


# -- generate_proposal_labels -----------------------------------------------
# Reference: detection/generate_proposal_labels_op.cc (SampleRoisForOneImage)
# TPU redesign: padded [B, ...] batch with per-image vmap and STATIC
# batch_size_per_im output rows (the reference emits dynamic fg+bg rows;
# here padding rows carry label 0 and zero weights, and RoisNum reports the
# valid count per image — same masking contract as generate_proposals).

def _gpl_infer(op, block):
    rois = in_var(op, block, "RpnRois")
    b = rois.shape[0]
    s = int(op.attrs["batch_size_per_im"])
    if op.attrs.get("class_nums") is None:
        raise ValueError(
            "generate_proposal_labels: class_nums is required (the number "
            "of detection classes incl. background)")
    c = int(op.attrs["class_nums"])
    set_output(op, block, "Rois", (b, s, 4), "float32", lod_level=1)
    set_output(op, block, "LabelsInt32", (b, s, 1), "int32")
    set_output(op, block, "BboxTargets", (b, s, 4 * c), "float32")
    set_output(op, block, "BboxInsideWeights", (b, s, 4 * c), "float32")
    set_output(op, block, "BboxOutsideWeights", (b, s, 4 * c), "float32")
    set_output(op, block, "RoisNum", (b,), "int32")


def _box_to_delta(ex, gt, weights):
    """bbox_util.h BoxToDelta (normalized=False, per-row weights divide)."""
    ew = ex[:, 2] - ex[:, 0] + 1.0
    eh = ex[:, 3] - ex[:, 1] + 1.0
    ecx = ex[:, 0] + 0.5 * ew
    ecy = ex[:, 1] + 0.5 * eh
    gw = gt[:, 2] - gt[:, 0] + 1.0
    gh = gt[:, 3] - gt[:, 1] + 1.0
    gcx = gt[:, 0] + 0.5 * gw
    gcy = gt[:, 1] + 0.5 * gh
    t = jnp.stack([(gcx - ecx) / ew, (gcy - ecy) / eh,
                   jnp.log(jnp.maximum(gw / ew, 1e-10)),
                   jnp.log(jnp.maximum(gh / eh, 1e-10))], axis=-1)
    return t / jnp.asarray(weights, t.dtype)[None, :]


def _gpl_single(rois, roi_len, gt_cls, is_crowd, gt, gt_len, im_info,
                key, attrs):
    s = int(attrs["batch_size_per_im"])
    c = int(attrs["class_nums"])
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_th = float(attrs.get("fg_thresh", 0.25))  # layer-level default
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    weights = list(attrs.get("bbox_reg_weights", [1.0, 1.0, 1.0, 1.0]))
    use_random = bool(attrs.get("use_random", True))

    g = gt.shape[0]
    r = rois.shape[0]
    p = g + r
    gt_valid = jnp.arange(g) < gt_len
    roi_valid = jnp.arange(r) < roi_len
    # proposals = gt boxes first, then scale-corrected rpn rois
    im_scale = im_info[2]
    boxes = jnp.concatenate([gt, rois / im_scale], axis=0)       # [P, 4]
    box_valid = jnp.concatenate([gt_valid, roi_valid])

    iou = _iou_matrix(boxes, gt, normalized=False)               # [P, G]
    iou = jnp.where(gt_valid[None, :] & box_valid[:, None], iou, 0.0)
    max_ov = jnp.max(iou, axis=1)
    gt_ind = jnp.argmax(iou, axis=1)
    # crowd gt rows are excluded from sampling entirely
    crowd_row = jnp.concatenate(
        [(is_crowd > 0) & gt_valid, jnp.zeros((r,), bool)])
    max_ov = jnp.where(crowd_row, -1.0, max_ov)

    fg = box_valid & (max_ov > fg_th)
    bg = box_valid & ~fg & (max_ov >= bg_lo) & (max_ov < bg_hi)

    if use_random:
        # random subset selection: rank candidates by a random key
        # (reservoir-sampling equivalent distribution, static shapes)
        order = jax.random.uniform(key, (p,))
    else:
        order = jnp.arange(p, dtype=jnp.float32) / p
    fg_order = jnp.where(fg, order, 2.0)
    fg_rank = jnp.argsort(jnp.argsort(fg_order))                 # dense rank
    fg_cap = int(np.floor(s * fg_frac))
    fg_sel = fg & (fg_rank < fg_cap)
    n_fg = jnp.sum(fg_sel.astype(jnp.int32))
    bg_order = jnp.where(bg, order, 2.0)
    bg_rank = jnp.argsort(jnp.argsort(bg_order))
    bg_sel = bg & (bg_rank < s - n_fg)
    n_bg = jnp.sum(bg_sel.astype(jnp.int32))

    # slot layout: fg rows occupy [0, n_fg), bg rows [n_fg, n_fg+n_bg)
    fg_slot = jnp.cumsum(fg_sel.astype(jnp.int32)) - 1
    bg_slot = n_fg + jnp.cumsum(bg_sel.astype(jnp.int32)) - 1
    slot = jnp.where(fg_sel, fg_slot, jnp.where(bg_sel, bg_slot, s))

    smp_boxes = jnp.zeros((s, 4)).at[slot].set(boxes, mode="drop")
    labels = jnp.zeros((s,), jnp.int32).at[slot].set(
        jnp.where(fg_sel, gt_cls[gt_ind].astype(jnp.int32), 0),
        mode="drop")
    smp_gts = jnp.zeros((s, 4)).at[slot].set(gt[gt_ind], mode="drop")

    deltas = _box_to_delta(smp_boxes, smp_gts, weights)          # [S, 4]
    cls_of = labels                                              # [S]
    col = 4 * cls_of[:, None] + jnp.arange(4)[None, :]           # [S, 4]
    is_fg_slot = cls_of > 0
    targets = jnp.zeros((s, 4 * c)).at[
        jnp.arange(s)[:, None], jnp.where(is_fg_slot[:, None], col, 0)
    ].set(jnp.where(is_fg_slot[:, None], deltas, 0.0), mode="drop")
    inside = jnp.zeros((s, 4 * c)).at[
        jnp.arange(s)[:, None], jnp.where(is_fg_slot[:, None], col, 0)
    ].set(jnp.where(is_fg_slot[:, None], 1.0, 0.0), mode="drop")

    out_rois = smp_boxes * im_scale
    return (out_rois.astype(jnp.float32), labels[:, None],
            targets.astype(jnp.float32), inside.astype(jnp.float32),
            inside.astype(jnp.float32), (n_fg + n_bg).astype(jnp.int32))


def _gpl_compute(ins, attrs, ctx, op_index):
    rois = ins["RpnRois"][0]          # [B, R, 4]
    gt_cls = ins["GtClasses"][0]      # [B, G]
    crowd = ins["IsCrowd"][0]         # [B, G]
    gt = ins["GtBoxes"][0]            # [B, G, 4]
    im_info = ins["ImInfo"][0]        # [B, 3]
    b = rois.shape[0]
    rl = ins.get("RpnRoisLength")
    roi_len = rl[0] if rl and rl[0] is not None else \
        jnp.full((b,), rois.shape[1], jnp.int32)
    gl = ins.get("GtLength")
    gt_len = gl[0] if gl and gl[0] is not None else \
        jnp.full((b,), gt.shape[1], jnp.int32)
    keys = jax.random.split(ctx.rng_key(op_index), b)
    rois_o, labels, tgts, inw, outw, num = jax.vmap(
        lambda _rois, _rlen, _cls, _crowd, _gt, _glen, _info, _k:
        _gpl_single(_rois, _rlen, _cls, _crowd, _gt, _glen, _info, _k,
                    attrs))(rois, roi_len, gt_cls, crowd, gt, gt_len,
                            im_info, keys)
    return {"Rois": rois_o, "LabelsInt32": labels, "BboxTargets": tgts,
            "BboxInsideWeights": inw, "BboxOutsideWeights": outw,
            "RoisNum": num}


register_op(
    "generate_proposal_labels",
    ["RpnRois", "RpnRoisLength", "GtClasses", "IsCrowd", "GtBoxes",
     "GtLength", "ImInfo"],
    ["Rois", "LabelsInt32", "BboxTargets", "BboxInsideWeights",
     "BboxOutsideWeights", "RoisNum"],
    infer=_gpl_infer, compute=_gpl_compute, grad=None,
    stateful_random=True,
)


# -- roi_perspective_transform ----------------------------------------------
# Reference: detection/roi_perspective_transform_op.cc — warp each
# quadrilateral ROI to a [th, tw] rectangle via the projective transform
# whose matrix maps output coords to source coords, sampling the feature
# map bilinearly.  TPU redesign: one dense gather per ROI (vmap over ROIs,
# broadcast over channels) instead of the reference's per-pixel loops.

def _roi_persp_infer(op, block):
    x = in_var(op, block, "X")
    rois = in_var(op, block, "ROIs")
    th = int(op.attrs.get("transformed_height", 1))
    tw = int(op.attrs.get("transformed_width", 1))
    set_output(op, block, "Out", (rois.shape[0], x.shape[1], th, tw),
               x.dtype)


def _persp_matrix(rx, ry, th, tw):
    """get_transform_matrix (roi_perspective_transform_op.cc:109)."""
    x0, x1, x2, x3 = rx[0], rx[1], rx[2], rx[3]
    y0, y1, y2, y3 = ry[0], ry[1], ry[2], ry[3]
    len1 = jnp.sqrt((x0 - x1) ** 2 + (y0 - y1) ** 2)
    len2 = jnp.sqrt((x1 - x2) ** 2 + (y1 - y2) ** 2)
    len3 = jnp.sqrt((x2 - x3) ** 2 + (y2 - y3) ** 2)
    len4 = jnp.sqrt((x3 - x0) ** 2 + (y3 - y0) ** 2)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    norm_h = th
    norm_w = jnp.minimum(
        jnp.round(est_w * (norm_h - 1) / jnp.maximum(est_h, 1e-6)) + 1.0,
        float(tw))
    dx1, dx2, dx3 = x1 - x2, x3 - x2, x0 - x1 + x2 - x3
    dy1, dy2, dy3 = y1 - y2, y3 - y2, y0 - y1 + y2 - y3
    den = dx1 * dy2 - dx2 * dy1
    den = jnp.where(jnp.abs(den) < 1e-10, 1e-10, den)
    m6 = (dx3 * dy2 - dx2 * dy3) / den / (norm_w - 1)
    m7 = (dx1 * dy3 - dx3 * dy1) / den / (norm_h - 1)
    m3 = (y1 - y0 + m6 * (norm_w - 1) * y1) / (norm_w - 1)
    m4 = (y3 - y0 + m7 * (norm_h - 1) * y3) / (norm_h - 1)
    m0 = (x1 - x0 + m6 * (norm_w - 1) * x1) / (norm_w - 1)
    m1 = (x3 - x0 + m7 * (norm_h - 1) * x3) / (norm_h - 1)
    return m0, m1, x0, m3, m4, y0, m6, m7


def _in_quad(px, py, rx, ry):
    """Vectorized in_quad (roi_perspective_transform_op.cc:45): on-edge
    OR odd ray-crossing count.  px/py are [th, tw] grids."""
    eps = 1e-4
    on_edge = jnp.zeros_like(px, bool)
    n_cross = jnp.zeros_like(px, jnp.int32)
    for i in range(4):
        xs, ys = rx[i], ry[i]
        xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
        horiz = jnp.abs(ys - ye) < eps
        on_h = (jnp.abs(py - ys) < eps) & (jnp.abs(py - ye) < eps) & \
            (px >= jnp.minimum(xs, xe) - eps) & \
            (px <= jnp.maximum(xs, xe) + eps)
        ix = (py - ys) * (xe - xs) / jnp.where(horiz, 1.0, ye - ys) + xs
        on_v = (jnp.abs(ix - px) < eps) & \
            (py >= jnp.minimum(ys, ye) - eps) & \
            (py <= jnp.maximum(ys, ye) + eps)
        on_edge |= jnp.where(horiz, on_h, on_v)
        in_span = ~(py <= jnp.minimum(ys, ye) + eps) & \
            ~(py - jnp.maximum(ys, ye) > eps)
        crosses = (~horiz) & in_span & (ix - px > eps)
        n_cross += crosses.astype(jnp.int32)
    return on_edge | (n_cross % 2 == 1)


def _bilinear_at(img, in_w, in_h):
    """bilinear_interpolate semantics incl. boundary handling; img [H, W],
    in_w/in_h [th, tw] source coords."""
    h, w = img.shape
    oob = (in_w < -0.5) | (in_w > w - 0.5) | (in_h < -0.5) | \
        (in_h > h - 0.5)
    iw = jnp.clip(in_w, 0.0, None)
    ih = jnp.clip(in_h, 0.0, None)
    wf = jnp.floor(iw)
    hf = jnp.floor(ih)
    at_right = wf >= w - 1
    at_bottom = hf >= h - 1
    wf = jnp.where(at_right, float(w - 1), wf)
    hf = jnp.where(at_bottom, float(h - 1), hf)
    iw = jnp.where(at_right, wf, iw)
    ih = jnp.where(at_bottom, hf, ih)
    wc = jnp.where(at_right, wf, wf + 1)
    hc = jnp.where(at_bottom, hf, hf + 1)
    fw = iw - wf
    fh = ih - hf
    wfi, hfi = wf.astype(jnp.int32), hf.astype(jnp.int32)
    wci, hci = wc.astype(jnp.int32), hc.astype(jnp.int32)
    v1 = img[hfi, wfi]
    v2 = img[hci, wfi]
    v3 = img[hci, wci]
    v4 = img[hfi, wci]
    val = (1 - fw) * (1 - fh) * v1 + (1 - fw) * fh * v2 + \
        fw * fh * v3 + (1 - fh) * fw * v4
    return jnp.where(oob, 0.0, val)


def _roi_persp_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]                    # [N, C, H, W]
    rois = ins["ROIs"][0]              # [R, 8]
    scale = float(attrs.get("spatial_scale", 1.0))
    th = int(attrs.get("transformed_height", 1))
    tw = int(attrs.get("transformed_width", 1))
    roi2im_in = ins.get("RoisImageId")
    if roi2im_in and roi2im_in[0] is not None:
        roi2im = roi2im_in[0].reshape(-1).astype(jnp.int32)
    else:
        roi2im = jnp.zeros((rois.shape[0],), jnp.int32)

    out_w = jnp.arange(tw, dtype=x.dtype)[None, :].repeat(th, 0)
    out_h = jnp.arange(th, dtype=x.dtype)[:, None].repeat(tw, 1)

    def one_roi(roi, im_id):
        rx = roi[0::2] * scale
        ry = roi[1::2] * scale
        m0, m1, m2, m3, m4, m5, m6, m7 = _persp_matrix(rx, ry, th, tw)
        wq = m6 * out_w + m7 * out_h + 1.0
        in_w = (m0 * out_w + m1 * out_h + m2) / wq
        in_h = (m3 * out_w + m4 * out_h + m5) / wq
        inside = _in_quad(in_w, in_h, rx, ry)
        img = x[im_id]                                   # [C, H, W]
        vals = jax.vmap(lambda ch: _bilinear_at(ch, in_w, in_h))(img)
        return jnp.where(inside[None], vals, 0.0)        # [C, th, tw]

    out = jax.vmap(one_roi)(rois, roi2im)
    return {"Out": out}


register_op("roi_perspective_transform", ["X", "ROIs", "RoisImageId"],
            ["Out"], infer=_roi_persp_infer, compute=_roi_persp_compute,
            no_grad_inputs=("ROIs", "RoisImageId"))
