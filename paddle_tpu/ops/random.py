"""Stochastic regularization ops: dropout, random_crop, sampling_id.

Parity: reference ``dropout_op.cc`` (attrs dropout_prob, is_test,
dropout_implementation ∈ {downgrade_in_infer, upscale_in_train}),
``sampling_id_op.cc`` — TPU-native: masks come from the executor-threaded
counter PRNG; dropout registers a *custom* grad (consuming the saved Mask)
since the generic vjp path would re-draw randomness.
"""

import jax
import jax.numpy as jnp

from ..framework import grad_var_name
from ..registry import register_op, set_output, in_var
from ..core import long_dtype


def _dropout_infer(op, block):
    x = in_var(op, block, "X")
    set_output(op, block, "Out", x.shape, x.dtype)
    set_output(op, block, "Mask", x.shape, x.dtype)


def _dropout_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if attrs.get("is_test", False):
        if impl == "upscale_in_train":
            return {"Out": x, "Mask": jnp.ones_like(x)}
        return {"Out": x * (1.0 - p), "Mask": jnp.ones_like(x)}
    key = ctx.rng_key(op_index)
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        mask = keep.astype(x.dtype) / max(1.0 - p, 1e-8)
    else:
        mask = keep.astype(x.dtype)
    return {"Out": x * mask, "Mask": mask}


def _dropout_grad_maker(op, no_grad_set):
    # NOTE: out-grad input slots MUST use the "GRAD::" prefix so backward.py
    # materializes (sums) accumulated contributions before this op reads them
    x = op.inputs["X"][0]
    if x in no_grad_set:
        return []
    return [dict(
        type="dropout_mask_grad",
        inputs={"Mask": [op.outputs["Mask"][0]],
                "GRAD::Out": [grad_var_name(op.outputs["Out"][0])]},
        outputs={"GRAD::X": [grad_var_name(x)]},
        attrs={},
    )]


register_op(
    "dropout", ["X"], ["Out", "Mask"], infer=_dropout_infer,
    compute=_dropout_compute, grad=_dropout_grad_maker, stateful_random=True,
)


def _dropout_mask_grad_infer(op, block):
    m = in_var(op, block, "Mask")
    set_output(op, block, "GRAD::X", m.shape, m.dtype)


register_op(
    "dropout_mask_grad", ["Mask", "GRAD::Out"], ["GRAD::X"],
    infer=_dropout_mask_grad_infer,
    compute=lambda ins, attrs, ctx, op_index: {
        "GRAD::X": ins["GRAD::Out"][0] * ins["Mask"][0]
    },
    grad=None,
)


def _sampling_id_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]  # [batch, n] probabilities
    key = ctx.rng_key(op_index)
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
    return {"Out": ids.astype(long_dtype())}


register_op(
    "sampling_id", ["X"], ["Out"],
    infer=lambda op, block: set_output(
        op, block, "Out", (in_var(op, block, "X").shape[0],), "int64"),
    compute=_sampling_id_compute, grad=None, stateful_random=True,
)


# -- random_crop (reference random_crop_op.cc) ------------------------------
# Per-instance uniform crop offsets.  The reference threads an explicit
# Seed->SeedOut chain; here randomness comes from the executor's counter
# PRNG (deterministic per step), and SeedOut echoes Seed for API parity.

def _random_crop_infer(op, block):
    x = in_var(op, block, "X")
    shape = tuple(op.attrs["shape"])
    out = tuple(x.shape[:len(x.shape) - len(shape)]) + shape
    set_output(op, block, "Out", out, x.dtype)
    seed = in_var(op, block, "Seed")
    if seed is not None:
        set_output(op, block, "SeedOut", seed.shape, seed.dtype)


def _random_crop_compute(ins, attrs, ctx, op_index):
    x = ins["X"][0]
    crop = tuple(attrs["shape"])
    batch_dims = x.ndim - len(crop)
    key = ctx.rng_key(op_index)

    def crop_one(inst, k):
        maxs = jnp.asarray([inst.shape[i] - crop[i]
                            for i in range(len(crop))])
        offs = jax.random.randint(k, (len(crop),), 0, maxs + 1)
        return jax.lax.dynamic_slice(inst, offs, crop)

    flat = x.reshape((-1,) + x.shape[batch_dims:])
    keys = jax.random.split(key, flat.shape[0])
    out = jax.vmap(crop_one)(flat, keys)
    out = out.reshape(x.shape[:batch_dims] + crop)
    res = {"Out": out}
    seed = ins.get("Seed")
    if seed and seed[0] is not None:
        res["SeedOut"] = seed[0]
    return res


register_op(
    "random_crop", ["X", "Seed"], ["Out", "SeedOut"],
    infer=_random_crop_infer, compute=_random_crop_compute,
    grad=None, stateful_random=True, no_grad_inputs=("Seed",),
)
