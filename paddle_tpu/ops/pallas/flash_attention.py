"""Flash attention Pallas kernel — fused blockwise attention (fwd + bwd).

The reference's entire attention story is ``nets.scaled_dot_product_attention``
(``python/paddle/fluid/nets.py:323``): materialize the [B, H, Tq, Tk] score
matrix, softmax it, optionally dropout, then a second batched matmul.  On TPU
that round-trips O(T^2) scores through HBM three times per direction.  This
kernel is the single-chip sibling of ``parallel/ring_attention.py``'s online
softmax: Q blocks stay VMEM-resident, K/V stream through VMEM tiles, and the
softmax normalizer is accumulated online, so HBM traffic is O(T*D) and the
QK^T / PV products run back-to-back on the MXU without score materialization.

Masking is structural rather than a dense additive bias: a per-batch key
length (padding) and an optional causal flag — exactly the two mask shapes
the Transformer model builds (padding_attn_bias + causal_mask).  Causal
with Tq == Tk is top-aligned self-attention; with Tq < Tk the queries are
the suffix of the klen valid keys (query i at global position
klen - Tq + i) — the KV-cache decode shape, where a single-token or
chunked query attends a longer cache without the full-length-call
workaround.

Dropout on the attention weights is computed *inside* the kernel from a
counter-based hash of (head, query, key) positions, so the backward kernels
regenerate the identical mask without ever materializing it.  Semantics are
the reference dropout default ``downgrade_in_infer`` (``dropout_op.cc``):
training masks without upscaling, eval scales weights by (1 - p) — applied
by the op as an output scale, since it commutes with the PV matmul.  The hash is a
murmur3-style integer finalizer — deterministic, pure jnp (works in Pallas
interpret mode on CPU), and keyed on the executor-threaded PRNG so separate
ops/steps decorrelate.

Backward follows the standard flash decomposition: host-side
``delta = rowsum(dO * O)`` (this identity holds under dropout too, because
sum_j g_j y_j = dO . O), then one kernel producing dQ (grid over Q blocks)
and one producing dK/dV (grid over K blocks), each recomputing the
probabilities from the saved log-sum-exp.

Long-sequence scope: K/V live fully in VMEM per (batch, head) — fine up to
Tk ~ 8-16k at D=64; beyond that sequence parallelism (ring attention over
the ``sp`` mesh axis) is the intended scaling path, per SURVEY.md §5.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_POS_BIG = 1e30


def _ceil_to(x, m):
    return ((x + m - 1) // m) * m


def _mix32(h):
    """murmur3 finalizer on uint32 — decorrelates position-derived indices."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x7FEB352D)
    h = h ^ (h >> 15)
    h = h * jnp.uint32(0x846CA68B)
    h = h ^ (h >> 16)
    return h


def _keep_mask(seed, bh, gq, gk, rate):
    """Deterministic dropout keep-mask for global positions gq[.,1] x gk[1,.]
    (or any broadcastable pair).  ``seed`` uint32 scalar, ``bh`` int32 scalar.
    Returns bool, True = keep.  Pure jnp: identical in Pallas kernels, in
    interpret mode, and in the XLA fallback path."""
    h = (gq.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)) ^ \
        (gk.astype(jnp.uint32) * jnp.uint32(0xC2B2AE35))
    h = h ^ (seed + jnp.uint32(bh) * jnp.uint32(0x9E3779B1))
    h = _mix32(h)
    # top 24 bits -> uniform in [0, 1)
    thresh = jnp.uint32(int(rate * float(1 << 24)))
    return (h >> jnp.uint32(8)) >= thresh


def _causal_valid(gq, gk, klen, tq, tk):
    """Causal mask term for query/key position grids: top-aligned when
    Tq == Tk (self-attention over equally padded sequences), suffix-
    aligned otherwise — query i sits at global key position
    ``klen - tq + i``, so decode queries see exactly the cache prefix.
    ``klen`` is a scalar (kernel) or broadcastable array (fallback).
    A batch row with klen < Tq has queries below the valid window;
    their rows are FULLY masked and come back as zeros (the fully-
    masked-row contract the kernels already honor for klen == 0), never
    NaN — callers that care should keep Tq <= min(klen)."""
    if tq == tk:
        return gq >= gk
    return gq + (klen - tq) >= gk


def _dot(a, b, in_dtype):
    """MXU matmul with fp32 accumulation; operands in the input dtype so
    bf16 inputs (the AMP path) hit the bf16 MXU pipeline."""
    return jax.lax.dot_general(
        a.astype(in_dtype), b.astype(in_dtype),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_kernel(klen_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                scale, causal, rate, bq, bk, nk, tq, tk, in_dtype):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
    klen = klen_ref[bh, 0]
    seed = seed_ref[0, 0].astype(jnp.uint32)
    gq = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(ki, carry):
        m, l, o = carry
        kb = k_ref[0, pl.dslice(ki * bk, bk), :]       # [bk, d]
        vb = v_ref[0, pl.dslice(ki * bk, bk), :]
        s = _dot(q, kb, in_dtype)                      # [bq, bk] f32
        gk = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = gk < klen
        if causal:
            valid = valid & _causal_valid(gq, gk, klen, tq, tk)
        s = jnp.where(valid, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if rate:
            # downgrade_in_infer (the reference dropout default): train
            # masks WITHOUT upscaling; eval scales by (1-p) (attention.py)
            keep = _keep_mask(seed, bh, gq, gk, rate)
            p = jnp.where(keep, p, 0.0)
        # PV on the MXU in the input dtype (p is an attention weight; bf16
        # is plenty and keeps the AMP path on the fast pipeline)
        pv = jax.lax.dot_general(
            p.astype(in_dtype), vb.astype(in_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        o = o * corr + pv
        return m_new, l, o

    m0 = jnp.full((bq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    o0 = jnp.zeros((bq, q_ref.shape[-1]), jnp.float32)
    m, l, o = jax.lax.fori_loop(0, nk, body, (m0, l0, o0))
    valid_row = l > 0.0
    o_ref[0] = (o / jnp.where(valid_row, l, 1.0)).astype(o_ref.dtype)
    # +BIG sentinel for fully-masked rows zeroes their backward p=exp(s-lse)
    lse_ref[0] = jnp.where(valid_row,
                           m + jnp.log(jnp.maximum(l, 1e-37)), _POS_BIG)


def _dq_kernel(klen_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, *, scale, causal, rate, bq, bk, nk,
               tq, tk, in_dtype):
    bh = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0]
    klen = klen_ref[bh, 0]
    seed = seed_ref[0, 0].astype(jnp.uint32)
    lse = lse_ref[0]                                   # [bq, 1]
    delta = delta_ref[0]
    gq = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(ki, dq):
        kb = k_ref[0, pl.dslice(ki * bk, bk), :]
        vb = v_ref[0, pl.dslice(ki * bk, bk), :]
        s = _dot(q, kb, in_dtype)
        gk = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = gk < klen
        if causal:
            valid = valid & _causal_valid(gq, gk, klen, tq, tk)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse)                           # masked rows: lse=+BIG
        g = _dot(do, vb, in_dtype)                     # dL/dy_jk pre-dropout
        if rate:
            keep = _keep_mask(seed, bh, gq, gk, rate)
            g = jnp.where(keep, g, 0.0)
        ds = p * (g - delta)                           # [bq, bk]
        dq = dq + jax.lax.dot_general(
            ds.astype(in_dtype), kb.astype(in_dtype),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dq

    dq = jax.lax.fori_loop(
        0, nk, body, jnp.zeros((bq, q_ref.shape[-1]), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(klen_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, *, scale, causal, rate, bq, bk,
                nq, tq, tk, in_dtype):
    bh = pl.program_id(0)
    ki = pl.program_id(1)
    kb = k_ref[0]                                      # [bk, d]
    vb = v_ref[0]
    klen = klen_ref[bh, 0]
    seed = seed_ref[0, 0].astype(jnp.uint32)
    gk = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    d = kb.shape[-1]

    def body(qi, carry):
        dk, dv = carry
        qb = q_ref[0, pl.dslice(qi * bq, bq), :].astype(jnp.float32) * scale
        dob = do_ref[0, pl.dslice(qi * bq, bq), :]
        lse = lse_ref[0, pl.dslice(qi * bq, bq), :]    # [bq, 1]
        delta = delta_ref[0, pl.dslice(qi * bq, bq), :]
        s = _dot(qb, kb, in_dtype)                     # [bq, bk]
        gq = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        valid = gk < klen
        if causal:
            valid = valid & _causal_valid(gq, gk, klen, tq, tk)
        s = jnp.where(valid, s, _NEG_INF)
        p = jnp.exp(s - lse)
        if rate:
            keep = _keep_mask(seed, bh, gq, gk, rate)
            p_drop = jnp.where(keep, p, 0.0)
        else:
            p_drop = p
        # dV += P_drop^T @ dO
        dv = dv + jax.lax.dot_general(
            p_drop.astype(in_dtype), dob.astype(in_dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        g = _dot(dob, vb, in_dtype)
        if rate:
            g = jnp.where(keep, g, 0.0)
        ds = p * (g - delta)
        # dK += dS^T @ Q*scale
        dk = dk + jax.lax.dot_general(
            ds.astype(in_dtype), qb.astype(in_dtype),
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pick_blocks(tq, tk):
    bq = min(256, _ceil_to(tq, 8))
    bk = min(512, _ceil_to(tk, 128 if tk >= 128 else 8))
    return bq, _ceil_to(tq, bq), bk, _ceil_to(tk, bk)


def supported(q_shape, k_shape, dtype, max_seq=None):
    """Whether the kernel can take these shapes (VMEM budget for the
    per-(b,h) resident K/V + Q/dO blocks); callers fall back to XLA.
    ``max_seq`` overrides the flag's sequence gate (a tuned per-shape
    ruling was measured at its own length; the VMEM budget below still
    applies)."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    tq, d = q_shape[2], q_shape[3]
    tk = k_shape[2]
    if tq < 1 or tk < 1 or d < 1 or d > 512:
        return False
    from ...flags import flag

    # beyond this length the whole-model compile through the remote TPU
    # compile service has been observed to fail even though the kernel
    # alone compiles (verified to T=4096); the XLA fallback handles long
    # single-chip sequences and ring attention (sp) scales further
    if max(tq, tk) > (max_seq if max_seq is not None
                      else flag("pallas_attention_max_seq")):
        return False
    bq, tq_pad, bk, tk_pad = _pick_blocks(tq, tk)
    itemsize = 2 if dtype == jnp.bfloat16 else 4
    # the worst resident set is the dK/dV kernel: full K/V blocks plus the
    # full padded Q, dO, lse, delta per (b, h) grid step — budget THAT,
    # not just the forward (a Tq >> Tk cross-attention would otherwise
    # pass the gate and blow VMEM at backward compile time).  Pallas
    # DOUBLE-BUFFERS every grid block (including the whole-row K/V
    # "blocks"), so the resident set counts twice.
    resident = 2 * tk_pad * d * itemsize              # K + V per (b, h)
    resident += 2 * tq_pad * d * itemsize             # Q + dO (dkv kernel)
    resident += 2 * tq_pad * 4                        # lse + delta
    blocks = (3 * bq * d + 2 * bq * bk) * 4           # O block + scores
    return 2 * (resident + blocks) < 10 * 1024 * 1024


def _pad_t(x, t_pad):
    t = x.shape[1]
    if t == t_pad:
        return x
    return jnp.pad(x, ((0, 0), (0, t_pad - t), (0, 0)))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention(q, k, v, k_len, seed, causal=False, dropout_rate=0.0,
                    scale=None, interpret=False):
    """Fused attention.  q [B,H,Tq,D]; k/v [B,H,Tk,D]; k_len [B] int32 valid
    key counts (None = all valid); seed uint32 scalar (dropout counter key).
    Returns [B,H,Tq,D] in q's dtype."""
    return _flash_fwd(q, k, v, k_len, seed, causal, dropout_rate, scale,
                      interpret)[0]


def _prep(q, k, v, k_len, seed):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    qf = q.reshape(b * h, tq, d)
    kf = k.reshape(b * h, tk, d)
    vf = v.reshape(b * h, tk, d)
    if k_len is None:
        klen = jnp.full((b,), tk, jnp.int32)
    else:
        klen = jnp.minimum(k_len.astype(jnp.int32).reshape(b), tk)
    klen = jnp.repeat(klen, h).reshape(b * h, 1)
    if seed is None:
        seed = jnp.zeros((), jnp.uint32)
    seed = jnp.broadcast_to(seed.astype(jnp.uint32).reshape(()), (1, 1))
    return qf, kf, vf, klen, seed


def _flash_fwd(q, k, v, k_len, seed, causal, rate, scale, interpret):
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bq, tq_pad, bk, tk_pad = _pick_blocks(tq, tk)
    qf, kf, vf, klen, seedv = _prep(q, k, v, k_len, seed)
    qf, kf, vf = _pad_t(qf, tq_pad), _pad_t(kf, tk_pad), _pad_t(vf, tk_pad)
    bhn, nq, nk = b * h, tq_pad // bq, tk_pad // bk
    kern = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, rate=rate, bq=bq, bk=bk,
        nk=nk, tq=tq, tk=tk, in_dtype=q.dtype)
    o, lse = pl.pallas_call(
        kern,
        grid=(bhn, nq),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, bq, d), lambda bhi, qi: (bhi, qi, 0)),
                  pl.BlockSpec((1, tk_pad, d), lambda bhi, qi: (bhi, 0, 0)),
                  pl.BlockSpec((1, tk_pad, d), lambda bhi, qi: (bhi, 0, 0))],
        out_specs=[pl.BlockSpec((1, bq, d), lambda bhi, qi: (bhi, qi, 0)),
                   pl.BlockSpec((1, bq, 1), lambda bhi, qi: (bhi, qi, 0))],
        out_shape=[jax.ShapeDtypeStruct((bhn, tq_pad, d), q.dtype),
                   jax.ShapeDtypeStruct((bhn, tq_pad, 1), jnp.float32)],
        interpret=interpret,
    )(klen, seedv, qf, kf, vf)
    out = o[:, :tq].reshape(b, h, tq, d)
    return out, (q, k, v, k_len, seed, out, lse)


def _flash_bwd(causal, rate, scale, interpret, res, dout):
    q, k, v, k_len, seed, out, lse = res
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    bq, tq_pad, bk, tk_pad = _pick_blocks(tq, tk)
    qf, kf, vf, klen, seedv = _prep(q, k, v, k_len, seed)
    qf, kf, vf = _pad_t(qf, tq_pad), _pad_t(kf, tk_pad), _pad_t(vf, tk_pad)
    bhn, nq, nk = b * h, tq_pad // bq, tk_pad // bk
    dof = _pad_t(dout.reshape(bhn, tq, d), tq_pad)
    # delta_i = sum_j g_ij y_ij = dO . O (holds under dropout: see module doc)
    delta = jnp.sum(dof.astype(jnp.float32) *
                    _pad_t(out.reshape(bhn, tq, d), tq_pad)
                    .astype(jnp.float32), axis=-1,
                    keepdims=True)                     # [bhn, tq_pad, 1]

    common = dict(scale=scale, causal=causal, rate=rate, bq=bq, bk=bk,
                  tq=tq, tk=tk, in_dtype=q.dtype)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, nk=nk, **common),
        grid=(bhn, nq),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, bq, d), lambda bhi, qi: (bhi, qi, 0)),
                  pl.BlockSpec((1, tk_pad, d), lambda bhi, qi: (bhi, 0, 0)),
                  pl.BlockSpec((1, tk_pad, d), lambda bhi, qi: (bhi, 0, 0)),
                  pl.BlockSpec((1, bq, d), lambda bhi, qi: (bhi, qi, 0)),
                  pl.BlockSpec((1, bq, 1), lambda bhi, qi: (bhi, qi, 0)),
                  pl.BlockSpec((1, bq, 1), lambda bhi, qi: (bhi, qi, 0))],
        out_specs=pl.BlockSpec((1, bq, d), lambda bhi, qi: (bhi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bhn, tq_pad, d), q.dtype),
        interpret=interpret,
    )(klen, seedv, qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, nq=nq, **common),
        grid=(bhn, nk),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec((1, tq_pad, d), lambda bhi, ki: (bhi, 0, 0)),
                  pl.BlockSpec((1, bk, d), lambda bhi, ki: (bhi, ki, 0)),
                  pl.BlockSpec((1, bk, d), lambda bhi, ki: (bhi, ki, 0)),
                  pl.BlockSpec((1, tq_pad, d), lambda bhi, ki: (bhi, 0, 0)),
                  pl.BlockSpec((1, tq_pad, 1), lambda bhi, ki: (bhi, 0, 0)),
                  pl.BlockSpec((1, tq_pad, 1), lambda bhi, ki: (bhi, 0, 0))],
        out_specs=[pl.BlockSpec((1, bk, d), lambda bhi, ki: (bhi, ki, 0)),
                   pl.BlockSpec((1, bk, d), lambda bhi, ki: (bhi, ki, 0))],
        out_shape=[jax.ShapeDtypeStruct((bhn, tk_pad, d), k.dtype),
                   jax.ShapeDtypeStruct((bhn, tk_pad, d), v.dtype)],
        interpret=interpret,
    )(klen, seedv, qf, kf, vf, dof, lse, delta)

    dq = dq[:, :tq].reshape(b, h, tq, d)
    dk = dk[:, :tk].reshape(b, h, tk, d)
    dv = dv[:, :tk].reshape(b, h, tk, d)
    return dq, dk, dv, None, None


flash_attention.defvjp(_flash_fwd, _flash_bwd)


def gather_pages(pool, table, scale=None):
    """Materialize per-slot K or V views from a paged pool.

    ``pool`` [P, H, ps, D] (float or int8), ``table`` [S, max_pages]
    int32 physical page ids, ``scale`` [P, H, ps] f32 per-token-row
    dequant scales (required when the pool is int8).  Returns
    [S, H, max_pages*ps, D] in f32 for int8 pools, pool dtype otherwise.
    One gather per pool — XLA fuses it into the attention consumer, so
    the transient view never round-trips HBM as a separate buffer."""
    s, mp = table.shape
    p, h, ps, d = pool.shape
    pages = pool[table.reshape(-1)]              # [S*mp, H, ps, D]
    kv = pages.reshape(s, mp, h, ps, d).transpose(0, 2, 1, 3, 4) \
        .reshape(s, h, mp * ps, d)
    if pool.dtype == jnp.int8:
        sc = scale[table.reshape(-1)].reshape(s, mp, h, ps) \
            .transpose(0, 2, 1, 3).reshape(s, h, mp * ps)
        kv = kv.astype(jnp.float32) * sc[..., None]
    return kv


def paged_attention(q, k_pool, v_pool, table, k_len, k_scale=None,
                    v_scale=None, causal=True, scale=None,
                    use_pallas=False, interpret=False):
    """The paged-attention path: gather each slot's pages into the
    contiguous [S, H, Tmax, D] view the bottom-aligned suffix-query
    kernels already handle (Tq <= Tk, query i at global position
    klen - Tq + i), then dispatch to the flash kernel or the XLA
    fallback.  Paging changes where K/V LIVE (page pool + table), not
    the attention math — so the klen-aware mask work from the decode
    kernels is reused verbatim."""
    k = gather_pages(k_pool, table, k_scale)
    v = gather_pages(v_pool, table, v_scale)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    if use_pallas and supported(q.shape, k.shape, q.dtype):
        return flash_attention(q, k, v, k_len, None, causal, 0.0, scale,
                               interpret)
    return reference_attention(q, k, v, k_len, None, causal, 0.0, scale)


def reference_attention(q, k, v, k_len, seed, causal=False, dropout_rate=0.0,
                        scale=None):
    """XLA fallback with bit-identical semantics (same hash dropout mask):
    used when the pallas flag is off or shapes exceed the VMEM budget."""
    b, h, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # operands stay in the input dtype (bf16 under AMP -> bf16 MXU pass);
    # scores/softmax accumulate fp32 via preferred_element_type
    s = jnp.einsum("bhqd,bhkd->bhqk", q * jnp.asarray(scale, q.dtype), k,
                   preferred_element_type=jnp.float32)
    gq = jnp.arange(tq)[:, None]
    gk = jnp.arange(tk)[None, :]
    valid = jnp.ones((b, 1, tq, tk), bool)
    klen = (jnp.full((b,), tk, jnp.int32) if k_len is None
            else jnp.minimum(k_len.astype(jnp.int32).reshape(b), tk))
    if k_len is not None:
        valid = gk[None, None] < klen.reshape(b, 1, 1, 1)
    if causal:
        valid = valid & _causal_valid(gq[None, None], gk[None, None],
                                      klen.reshape(b, 1, 1, 1), tq, tk)
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    y = p / jnp.maximum(l, 1e-37)
    if dropout_rate:
        if seed is None:
            seed = jnp.zeros((), jnp.uint32)
        bh = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
        keep = _keep_mask(seed.astype(jnp.uint32),
                          bh, gq[None, None], gk[None, None], dropout_rate)
        # downgrade_in_infer: train-time mask without upscale
        y = jnp.where(keep, y, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", y.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
