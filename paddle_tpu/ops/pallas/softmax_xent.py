"""Fused softmax + cross-entropy Pallas kernel.

Parity target: reference ``softmax_with_cross_entropy_op.cc`` (the fused
hot op) — forward emits per-row loss AND the softmax; backward is the
hand-fused kernel combining the loss cotangent path
``(softmax - onehot) * dloss`` (``softmax_with_cross_entropy_op.cu``)
with the softmax-output cotangent path
``softmax * (dsm - sum(dsm * softmax))`` so downstream consumers of the
Softmax output (e.g. entropy regularizers) differentiate correctly.

Kernel design (pallas_guide.md): grid over row-blocks; each step stages
a ``[BN, C]`` logits tile in VMEM, computes max/exp/sum on the VPU and
writes loss + softmax without an HBM round-trip between the stages XLA
would otherwise schedule separately.  Rows are zero-padded up to a block
multiple and sliced back (see __init__.block_rows).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import block_rows, pad_rows


def _fwd_kernel(logits_ref, label_ref, loss_ref, softmax_ref, *, eps):
    x = logits_ref[...]                      # [BN, C]
    lbl = label_ref[...][:, 0]               # [BN, 1] -> [BN]
    m = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - m)
    s = jnp.sum(e, axis=-1, keepdims=True)
    softmax = e / s
    log_z = jnp.log(s) + m                   # [BN, 1]
    c = x.shape[-1]
    onehot = lbl[:, None] == jax.lax.broadcasted_iota(jnp.int32,
                                                      (1, c), 1)
    picked = jnp.sum(jnp.where(onehot, x, 0.0), axis=-1, keepdims=True)
    if eps:
        # fused uniform label smoothing: target (1-eps)*onehot + eps/C
        # -> loss = (1-eps)*(logZ - picked) + eps*(logZ - mean(x))
        mean_x = jnp.mean(x, axis=-1, keepdims=True)
        loss_ref[...] = (1.0 - eps) * (log_z - picked) + \
            eps * (log_z - mean_x)
    else:
        loss_ref[...] = log_z - picked
    softmax_ref[...] = softmax


def _bwd_kernel(softmax_ref, label_ref, dloss_ref, dsm_ref, dlogits_ref, *,
                eps):
    sm = softmax_ref[...]
    lbl = label_ref[...][:, 0]               # [BN, 1] -> [BN]
    g = dloss_ref[...]                       # [BN, 1]
    dsm = dsm_ref[...]                       # [BN, C]
    c = sm.shape[-1]
    onehot = (lbl[:, None] == jax.lax.broadcasted_iota(jnp.int32, (1, c),
                                                       1)).astype(sm.dtype)
    if eps:
        target = (1.0 - eps) * onehot + eps / c
    else:
        target = onehot
    # loss path + softmax-output path (softmax Jacobian-vector product)
    inner = jnp.sum(dsm * sm, axis=-1, keepdims=True)
    dlogits_ref[...] = (sm - target) * g + sm * (dsm - inner)


def _specs(bn, c):
    # label rides as [N, 1] (2-D): Mosaic requires the last two block dims
    # be (8, 128)-aligned or equal to the array dims — a 1-D (bn,) block
    # over [N] fails that check on real TPU
    return [pl.BlockSpec((bn, c), lambda i: (i, 0)),
            pl.BlockSpec((bn, 1), lambda i: (i, 0))]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def softmax_xent(logits, label, interpret=False, label_smooth_eps=0.0):
    loss, softmax = _fwd(logits, label, interpret, label_smooth_eps)[0]
    return loss, softmax


def _fwd(logits, label, interpret, eps=0.0):
    n, c = logits.shape
    if n == 0:
        z = jnp.zeros((0, 1), logits.dtype), jnp.zeros((0, c),
                                                       logits.dtype)
        return z, (z[1], label)
    bn, n_pad = block_rows(n, row_bytes=2 * c * 4, max_rows=256)
    loss, softmax = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n_pad // bn,),
        in_specs=_specs(bn, c),
        out_specs=[pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, c), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, 1), logits.dtype),
                   jax.ShapeDtypeStruct((n_pad, c), logits.dtype)],
        interpret=interpret,
    )(pad_rows(logits, n_pad),
      pad_rows(label.astype(jnp.int32).reshape(n, 1), n_pad))
    loss, softmax = loss[:n], softmax[:n]
    return (loss, softmax), (softmax, label)


def _bwd(interpret, eps, res, cts):
    softmax, label = res
    dloss, dsm = cts
    n, c = softmax.shape
    if n == 0:
        return jnp.zeros((0, c), softmax.dtype), None
    bn, n_pad = block_rows(n, row_bytes=3 * c * 4, max_rows=256)
    dlogits = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(n_pad // bn,),
        in_specs=_specs(bn, c) + [
            pl.BlockSpec((bn, 1), lambda i: (i, 0)),
            pl.BlockSpec((bn, c), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((bn, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_pad, c), softmax.dtype),
        interpret=interpret,
    )(pad_rows(softmax, n_pad),
      pad_rows(label.astype(jnp.int32).reshape(n, 1), n_pad),
      pad_rows(dloss, n_pad), pad_rows(dsm, n_pad))
    return dlogits[:n], None


softmax_xent.defvjp(_fwd, _bwd)
