"""Fused dequant-matmul Pallas kernels (int8 weights, ISSUE 14).

The ``dequant_matmul`` op's hand-tiled body: int8 weights stay int8 in
HBM (the whole point — 1/4 the weight bytes of the f32 master copies
the bf16 AMP path re-reads every step) and dequantize **in register**
on the way into the dot:

* ``weight_only`` — the weight tile casts int8 -> f32 inside VMEM and
  feeds an f32-accumulated MXU dot; the per-output-channel dequant
  scale multiplies the accumulator before it leaves the kernel.
  Activations keep their dtype (bf16/f32).
* ``dynamic`` — the activation tile additionally quantizes to int8 in
  register (per-row abs-max grid over the full K it already holds) and
  the dot runs int8 x int8 with ``preferred_element_type=int32``; both
  grids apply to the int32 accumulator in one fused epilogue.

Tiling: grid over (M, N) blocks with the full (padded) K resident per
block — serving matmuls are K<=8k where a K-resident [K, 128] int8
stripe plus its f32 cast is well under the VMEM budget, and keeping K
whole means the dynamic mode's per-row abs-max needs no cross-block
reduction.  K pads to the 128 lane, M to the f32 sublane, N to the
128-lane output tile; padding is zeros, which neither dot nor the
abs-max grid observes.

On CPU the kernels run in interpreter mode (numerical parity tests);
the XLA fallback (``ops/quantize.xla_dequant_matmul``) is the
measured-A/B alternative the autotune decision table selects against.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# VMEM working-set budget (bytes); the chip's scoped limit is 16MB,
# leave headroom for Mosaic's own buffers (same budget as conv_bn.py)
_VMEM_BUDGET = 11 * 2 ** 20
_BN = 128          # output-channel (lane) block
_MAX_BM = 256      # row block cap


def _ceil_to(x, m):
    return ((x + m - 1) // m) * m


def _pick_bm(m, kp, itemsize):
    """Largest row block whose double-buffered IO fits the budget next
    to the K-resident weight stripe."""
    resident = kp * _BN * (1 + 4) + _BN * 4      # int8 qw + f32 cast + s
    bm = min(_MAX_BM, _ceil_to(max(m, 1), 8))
    while bm > 8:
        io = 2 * bm * kp * max(itemsize, 4) + 2 * bm * _BN * 4
        if resident + io <= _VMEM_BUDGET:
            break
        bm //= 2
    return max(8, bm)


def supported(m, k, n, dtype):
    """Shape gate: K must stay VMEM-resident per output stripe and the
    tiles must be worthwhile; anything else falls back to the XLA
    dot_general path."""
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.float32)):
        return False
    if k < 128 or n < 128 or m < 1:
        return False   # tiny problems: dispatch overhead beats the fusion
    kp = _ceil_to(k, 128)
    resident = kp * _BN * (1 + 4) + _BN * 4
    min_io = 2 * 8 * kp * 4 + 2 * 8 * _BN * 4
    return resident + min_io <= _VMEM_BUDGET


def _wo_kernel(x_ref, qw_ref, s_ref, o_ref):
    # int8 values are exact in f32: dequant IS the cast, the channel
    # scale rides the accumulator epilogue
    acc = jnp.dot(x_ref[...].astype(jnp.float32),
                  qw_ref[...].astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    o_ref[...] = acc * s_ref[...][None, :]


def _dyn_kernel(x_ref, qw_ref, s_ref, o_ref, *, rng):
    x = x_ref[...].astype(jnp.float32)
    # per-row grid over the FULL K (resident in this block); zero
    # padding never raises an abs-max
    sx = jnp.maximum(jnp.max(jnp.abs(x), axis=1, keepdims=True),
                     1e-12) / rng
    qx = jnp.clip(jnp.round(x / sx), -rng, rng).astype(jnp.int8)
    acc = jax.lax.dot_general(qx, qw_ref[...], (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    o_ref[...] = acc.astype(jnp.float32) * sx * s_ref[...][None, :]


def dequant_matmul(x2, qw, scale, mode="weight_only", bit_length=8,
                   interpret=False):
    """Fused dequant-matmul: ``x2`` [M, K] bf16/f32, ``qw`` [K, N] int8,
    ``scale`` [N] f32 dequant multipliers.  Returns the f32 accumulator
    [M, N] (callers cast to the activation dtype)."""
    m, k = x2.shape
    n = qw.shape[1]
    kp = _ceil_to(k, 128)
    np_ = _ceil_to(n, _BN)
    bm = _pick_bm(m, kp, jnp.dtype(x2.dtype).itemsize)
    mp = _ceil_to(m, bm)
    if (mp, kp) != (m, k):
        x2 = jnp.pad(x2, ((0, mp - m), (0, kp - k)))
    if (kp, np_) != (k, n):
        qw = jnp.pad(qw, ((0, kp - k), (0, np_ - n)))
    if np_ != n:
        scale = jnp.pad(scale, (0, np_ - n))
    scale = scale.astype(jnp.float32)
    if mode == "weight_only":
        kernel = _wo_kernel
    elif mode == "dynamic":
        rng = float((1 << (int(bit_length) - 1)) - 1)
        kernel = functools.partial(_dyn_kernel, rng=rng)
    else:
        raise ValueError("unknown dequant_matmul mode %r" % mode)
    out = pl.pallas_call(
        kernel,
        grid=(mp // bm, np_ // _BN),
        in_specs=[pl.BlockSpec((bm, kp), lambda i, j: (i, 0)),
                  pl.BlockSpec((kp, _BN), lambda i, j: (0, j)),
                  pl.BlockSpec((_BN,), lambda i, j: (j,))],
        out_specs=pl.BlockSpec((bm, _BN), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(x2, qw, scale)
    return out[:m, :n]
