"""Pallas TPU kernels — alternative compute bodies for hot ops.

The op registry's kernels are pure JAX (``registry.py``); the modules
here provide hand-tiled Pallas implementations for ops where explicit
VMEM staging/fusion can beat XLA's automatic fusion (SURVEY.md §7 hot-op
list: softmax_with_cross_entropy, layer_norm).

Selection: gated at each call site by the ``pallas_kernels`` runtime
flag (``flags.flag("pallas_kernels")`` / FLAGS_pallas_kernels env, part
of the executor compile-cache key); default off — measurements on v5e
(see bench notes in each module) show XLA's fused code is already at
parity for these shapes, so the Pallas path is an opt-in escape hatch
and the reference implementation for writing further kernels (ring
attention etc.).  On CPU the kernels run in interpreter mode, which the
tests use for numerical parity checks.
"""

import jax

from ... import flags  # flag "pallas_kernels" is declared in flags.py


def on_tpu():
    try:
        return any(d.platform == "tpu" for d in jax.local_devices())
    except RuntimeError:  # backend not initialized yet
        return False


def interpret_mode(ctx=None):
    """Interpreter fallback for non-TPU execution.

    The decision must follow the device the *executor* places the step on
    (``ctx.platform``, threaded from the Place at trace time), not global
    device presence: a CPUPlace run on a machine whose TPU plugin is loaded
    would otherwise emit Mosaic kernels into a CPU-lowered module and fail.
    """
    platform = getattr(ctx, "platform", None) if ctx is not None else None
    if platform is not None:
        return platform != "tpu"
    return not on_tpu()


def block_rows(n, row_bytes, max_rows, vmem_budget=4 * 1024 * 1024):
    """Pick a row-block size and the padded row count for a [n, ...]
    kernel: fit ``row_bytes`` per row into the VMEM budget, then pad n
    UP to a multiple of the block (an exact-divisor search would
    degenerate to 1-row blocks for prime n).  Returns (bn, n_padded);
    callers zero-pad inputs to n_padded and slice outputs back to n.
    """
    bn = max(1, vmem_budget // max(row_bytes, 1))
    bn = min(bn, max(n, 1), max_rows)
    # Mosaic requires the sublane (second-to-last) block dim be a multiple
    # of 8 (or equal the array dim): round down to 8-aligned, minimum 8 —
    # tiny n still pads up to one 8-row block
    bn = max(8, (bn // 8) * 8)
    n_padded = ((n + bn - 1) // bn) * bn
    return bn, n_padded


def pad_rows(a, n_padded):
    """Zero-pad dim 0 of ``a`` to n_padded rows."""
    import jax.numpy as jnp

    n = a.shape[0]
    if n == n_padded:
        return a
    return jnp.pad(a, [(0, n_padded - n)] + [(0, 0)] * (a.ndim - 1))


from . import softmax_xent, layer_norm, quant_matmul  # noqa: E402,F401
