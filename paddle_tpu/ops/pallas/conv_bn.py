"""Fused BN-apply -> 1x1-conv(matmul) -> batch-stats Pallas kernels.

The ResNet bottleneck's HBM problem (PERF.md roofline): op-by-op
batch_norm costs separate full-activation passes for the normalize and
the statistics around every 1x1 conv, and the 1x1 convs themselves are
memory-bound (arithmetic intensity ~C at bf16).  This kernel chain makes
each 1x1 layer touch HBM the minimum number of times:

* forward — one kernel reads the raw (pre-BN) input tile, normalizes
  with the producer's batch stats in fp32 on the VPU, applies the
  activation, feeds the MXU matmul, writes the raw output tile, and
  accumulates the output's per-channel sum/sum-of-squares on the fly
  (one read + one write per activation; the stats pass disappears).
* backward — one kernel per layer computes dx, dW, dgamma, dbeta in a
  single streamed pass over (x, z, dz): the sum/sumsq cotangents fold
  into dz, both matmuls run per tile, and the per-channel reductions
  ride along (three reads + one write vs. the ~9 passes of the
  op-by-op backward chain).

Layout: NCHW-NATIVE.  The kernels consume activations as [B, C, HW]
(a free reshape of the framework's NCHW tensors) and compute
``z[b] = W[O,C] @ act(norm(x[b]))`` per block — channels are the
contraction dim, so no NCHW<->NHWC transpose ever materializes.  (A
first [M, C]-row-major design lost 2.4x at the model level to exactly
those boundary transposes.)

Measured on a v5e (tools/exp_pallas_bw.py): the normalize prologue and
stats epilogue are free — the fused kernel streams at the same
~480 GB/s as a bare Pallas copy at these shapes.

HONEST MODEL-LEVEL A/B (r4, fetch-synced ResNet-50 b256 bf16): the
fused path measures ~1.2k img/s vs ~2.5k for the default XLA path with
one-pass BN.  Two structural costs: (1) a first [M=B*HW, C] row-major
kernel design forced NCHW<->NHWC boundary transposes at every fused op
(2.4x regression); (2) this NCHW-native redesign removes the transposes
but fragments the matmul per batch element — late ResNet stages have
HW=196/49, far under the 128-lane tile, so most of each MXU/VPU tile is
padding.  Efficient fused kernels here require whole-trunk NHWC layout
(where [M, C] tiling needs no transposes); with the default path already
beating the 0.95x target, that layout pass is recorded as the known
future lever rather than built.  The pass + kernels stay as the
correct, tested, opt-in fused implementation (bench.py --fuse_conv_bn).

Parity: the capability matches the reference's cuDNN fused
conv+BN epilogues (``paddle/fluid/operators/batch_norm_op.cu.cc:1``,
``conv_cudnn_op.cu.cc:1``); the decomposition (stats producers feeding
normalize consumers) is original, built for the XLA one-jaxpr world by
the ``transpiler.fusion`` pass.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


# VMEM working-set budget (bytes).  The chip's scoped VMEM limit is
# 16MB; leave headroom for Mosaic's own buffers.
_VMEM_BUDGET = 11 * 2 ** 20
_MAX_RESIDENT_C = 2048   # w ([O, C]) stays VMEM-resident: O, C capped


def supported(b, c, o, hw, dtype):
    """Shape gate: w must stay VMEM-resident and tiles must be
    worthwhile; anything else falls back to the XLA path."""
    if jnp.dtype(dtype) not in (jnp.dtype(jnp.bfloat16),
                                jnp.dtype(jnp.float32)):
        return False
    if c > _MAX_RESIDENT_C or o > _MAX_RESIDENT_C:
        return False
    if b * hw < 1024 or c < 64 or o < 64:
        return False   # tiny problems: dispatch overhead beats the fusion
    # the backward's resident set (w + fp32 dW accumulator) plus one
    # minimum-size double-buffered block row must fit the budget
    isz = jnp.dtype(dtype).itemsize
    resident = c * o * (isz + 4)
    min_io = 2 * 128 * (c + o) * isz * 2 + 128 * (4 * c + 4 * o) * 4
    return resident + min_io <= _VMEM_BUDGET


def _pick_bhw(b, c, o, hw, itemsize, stack_factor):
    """Largest HW-block whose double-buffered IO + fp32 stack temporaries
    fit the VMEM budget (per single-batch-element grid step)."""
    resident = c * o * (itemsize + 4)
    bhw = 1 << (hw - 1).bit_length()   # next pow2 >= hw
    bhw = min(bhw, 8192)
    while bhw > 128:
        io = 2 * bhw * (c + o) * itemsize * 2
        stack = bhw * stack_factor * (c + o) * 4
        if resident + io + stack <= _VMEM_BUDGET:
            break
        bhw //= 2
    return min(bhw, hw)


def _bparams(mean, rstd, gamma, beta, c):
    # column vectors broadcasting along the HW (lane) dim
    return [a.reshape(1, c, 1).astype(jnp.float32)
            for a in (mean, rstd, gamma, beta)]


# -- forward ----------------------------------------------------------------

def _fwd_kernel(x_ref, w_ref, mean_ref, rstd_ref, gamma_ref, beta_ref,
                shift_ref, z_ref, sum_ref, sumsq_ref, *, apply_bn, act,
                with_stats, hw, bhw, nj):
    bi = pl.program_id(0)
    j = pl.program_id(1)
    x = x_ref[0]                                   # [C, bhw]
    cols_ok = (j * bhw + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 1)) < hw
    if apply_bn:
        xf = x.astype(jnp.float32)
        xf = (xf - mean_ref[0]) * rstd_ref[0] * gamma_ref[0] + beta_ref[0]
        if act == "relu":
            xf = jnp.maximum(xf, 0.0)
        xf = jnp.where(cols_ok, xf, 0.0)
        x = xf.astype(x_ref.dtype)
    else:
        if act == "relu":
            x = jnp.maximum(x, jnp.zeros_like(x))
        x = jnp.where(cols_ok, x, jnp.zeros_like(x))
    z = jax.lax.dot_general(w_ref[...], x, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [O, bhw]
    z_ref[0] = z.astype(z_ref.dtype)

    @pl.when((bi == 0) & (j == 0))
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sumsq_ref[...] = jnp.zeros_like(sumsq_ref)

    if with_stats:
        # stats accumulate shifted by the consumer BN's running mean
        # (cancellation guard — see transpiler.fusion / ops/norm.py);
        # garbage columns were zeroed above, but the shift re-introduces
        # -shift there, so mask zc explicitly
        zc = z - shift_ref[0]
        cols_ok_o = (pl.program_id(1) * bhw + jax.lax.broadcasted_iota(
            jnp.int32, z.shape, 1)) < hw
        zc = jnp.where(cols_ok_o, zc, 0.0)
        sum_ref[...] += jnp.sum(zc, axis=1)
        sumsq_ref[...] += jnp.sum(zc * zc, axis=1)


def _fwd_call(x3, w, mean, rstd, gamma, beta, shift, act, apply_bn,
              with_stats, interpret):
    b, c, hw = x3.shape
    o = w.shape[0]
    isz = jnp.dtype(x3.dtype).itemsize
    bhw = _pick_bhw(b, c, o, hw, isz, stack_factor=2)
    nj = pl.cdiv(hw, bhw)
    grid = (b, nj)
    p = _bparams(mean, rstd, gamma, beta, c)
    sh = shift.reshape(1, o, 1).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, apply_bn=apply_bn, act=act,
                          with_stats=with_stats, hw=hw, bhw=bhw, nj=nj),
        grid=grid,
        in_specs=[pl.BlockSpec((1, c, bhw), lambda bi, j: (bi, 0, j)),
                  pl.BlockSpec((o, c), lambda bi, j: (0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, o, 1), lambda bi, j: (0, 0, 0))],
        out_specs=[pl.BlockSpec((1, o, bhw), lambda bi, j: (bi, 0, j)),
                   pl.BlockSpec((o,), lambda bi, j: (0,)),
                   pl.BlockSpec((o,), lambda bi, j: (0,))],
        out_shape=[jax.ShapeDtypeStruct((b, o, hw), x3.dtype),
                   jax.ShapeDtypeStruct((o,), jnp.float32),
                   jax.ShapeDtypeStruct((o,), jnp.float32)],
        interpret=interpret,
    )(x3, w, *p, sh)


# -- backward ---------------------------------------------------------------

def _bwd_kernel(x_ref, w_ref, z_ref, dz_ref, dsum_ref, dsumsq_ref,
                mean_ref, rstd_ref, gamma_ref, beta_ref, shift_ref,
                dx_ref, dw_ref, dgamma_ref, dbeta_ref, *,
                apply_bn, act, with_stats, hw, bhw):
    bi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((bi == 0) & (j == 0))
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dgamma_ref[...] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[...] = jnp.zeros_like(dbeta_ref)

    dz = dz_ref[0].astype(jnp.float32)             # [O, bhw]
    cols_ok_o = (j * bhw + jax.lax.broadcasted_iota(
        jnp.int32, dz.shape, 1)) < hw
    if with_stats:
        # fwd accumulated sum(z-shift)/sum((z-shift)^2); shift is a
        # constant w.r.t. z, so d/dz picks up 2*(z-shift)*dsumsq
        z = z_ref[0].astype(jnp.float32) - shift_ref[0]
        dz = dz + dsum_ref[...].reshape(-1, 1) \
            + 2.0 * z * dsumsq_ref[...].reshape(-1, 1)
    dz = jnp.where(cols_ok_o, dz, 0.0)
    dz_lo = dz.astype(x_ref.dtype)

    # recompute the prologue activation; columns beyond hw (partial last
    # block) are undefined in VMEM — zero them BEFORE any arithmetic
    # (0 * NaN would still poison the reductions)
    x_raw = x_ref[0]
    cols_ok_c = (j * bhw + jax.lax.broadcasted_iota(
        jnp.int32, x_raw.shape, 1)) < hw
    x = jnp.where(cols_ok_c, x_raw, jnp.zeros_like(x_raw)
                  ).astype(jnp.float32)
    if apply_bn:
        pre = (x - mean_ref[0]) * rstd_ref[0]      # [C, bhw]
        ylin = pre * gamma_ref[0] + beta_ref[0]
        xn = jnp.maximum(ylin, 0.0) if act == "relu" else ylin
    else:
        xn = jnp.maximum(x, 0.0) if act == "relu" else x
    xn_lo = xn.astype(x_ref.dtype)

    # dW += dz @ xn^T   ([O, bhw] x [C, bhw] contracting hw)
    dw_ref[...] += jax.lax.dot_general(
        dz_lo, xn_lo, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)

    # dxn = w^T @ dz    ([O, C] x [O, bhw] contracting o) -> [C, bhw]
    dxn = jax.lax.dot_general(
        w_ref[...], dz_lo, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if apply_bn:
        dylin = dxn * (ylin > 0.0) if act == "relu" else dxn
        dgamma_ref[...] += jnp.sum(dylin * pre, axis=1)
        dbeta_ref[...] += jnp.sum(dylin, axis=1)
        dx = dylin * (gamma_ref[0] * rstd_ref[0])
    else:
        dx = dxn * (x > 0.0) if act == "relu" else dxn
    dx_ref[0] = dx.astype(dx_ref.dtype)


def _bwd_call(x3, w, z3, dz3, dsum, dsumsq, mean, rstd, gamma, beta,
              shift, act, apply_bn, with_stats, interpret):
    b, c, hw = x3.shape
    o = w.shape[0]
    isz = jnp.dtype(x3.dtype).itemsize
    bhw = _pick_bhw(b, c, o, hw, isz, stack_factor=4)
    grid = (b, pl.cdiv(hw, bhw))
    p = _bparams(mean, rstd, gamma, beta, c)
    sh = shift.reshape(1, o, 1).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_bwd_kernel, apply_bn=apply_bn, act=act,
                          with_stats=with_stats, hw=hw, bhw=bhw),
        grid=grid,
        in_specs=[pl.BlockSpec((1, c, bhw), lambda bi, j: (bi, 0, j)),
                  pl.BlockSpec((o, c), lambda bi, j: (0, 0)),
                  pl.BlockSpec((1, o, bhw), lambda bi, j: (bi, 0, j)),
                  pl.BlockSpec((1, o, bhw), lambda bi, j: (bi, 0, j)),
                  pl.BlockSpec((o,), lambda bi, j: (0,)),
                  pl.BlockSpec((o,), lambda bi, j: (0,)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, c, 1), lambda bi, j: (0, 0, 0)),
                  pl.BlockSpec((1, o, 1), lambda bi, j: (0, 0, 0))],
        out_specs=[pl.BlockSpec((1, c, bhw), lambda bi, j: (bi, 0, j)),
                   pl.BlockSpec((o, c), lambda bi, j: (0, 0)),
                   pl.BlockSpec((c,), lambda bi, j: (0,)),
                   pl.BlockSpec((c,), lambda bi, j: (0,))],
        out_shape=[jax.ShapeDtypeStruct((b, c, hw), x3.dtype),
                   jax.ShapeDtypeStruct((o, c), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32)],
        interpret=interpret,
    )(x3, w, z3, dz3, dsum.astype(jnp.float32), dsumsq.astype(jnp.float32),
      *p, sh)


# -- NHWC-native kernels ----------------------------------------------------
#
# Under transpiler.layout.convert_to_nhwc the trunk activation flattens
# to [M = B*H*W, C] for FREE, and the fused 1x1 layer is ONE dense
# matmul z[M, O] = act(norm(x[M, C])) @ w[C, O] — no per-batch
# fragmentation (the NCHW-native kernels' HW=196/49 under-filled the
# 128-lane tile) and no boundary transposes (the original [M, C]
# design's 2.4x loss).  Channels ride the lane dim, so the per-channel
# BN params are natural [1, C] lane vectors.

def _fwd_kernel_nhwc(x_ref, w_ref, mean_ref, rstd_ref, gamma_ref,
                     beta_ref, shift_ref, z_ref, sum_ref, sumsq_ref, *,
                     apply_bn, act, with_stats, m, bm):
    i = pl.program_id(0)
    x = x_ref[...]                                  # [bm, C]
    rows_ok = (i * bm + jax.lax.broadcasted_iota(
        jnp.int32, x.shape, 0)) < m
    if apply_bn:
        xf = x.astype(jnp.float32)
        xf = (xf - mean_ref[...]) * rstd_ref[...] * gamma_ref[...] \
            + beta_ref[...]
        if act == "relu":
            xf = jnp.maximum(xf, 0.0)
        xf = jnp.where(rows_ok, xf, 0.0)
        x = xf.astype(x_ref.dtype)
    else:
        if act == "relu":
            x = jnp.maximum(x, jnp.zeros_like(x))
        x = jnp.where(rows_ok, x, jnp.zeros_like(x))
    z = jax.lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bm, O]
    z_ref[...] = z.astype(z_ref.dtype)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sumsq_ref[...] = jnp.zeros_like(sumsq_ref)

    if with_stats:
        rows_ok_o = (i * bm + jax.lax.broadcasted_iota(
            jnp.int32, z.shape, 0)) < m
        zc = jnp.where(rows_ok_o, z - shift_ref[...], 0.0)
        sum_ref[...] += jnp.sum(zc, axis=0)
        sumsq_ref[...] += jnp.sum(zc * zc, axis=0)


def _fwd_call_nhwc(x2, w, mean, rstd, gamma, beta, shift, act, apply_bn,
                   with_stats, interpret):
    m, c = x2.shape
    o = w.shape[1]
    isz = jnp.dtype(x2.dtype).itemsize
    bm = _pick_bhw(1, c, o, m, isz, stack_factor=2)
    grid = (pl.cdiv(m, bm),)
    p = [a.reshape(1, c).astype(jnp.float32)
         for a in (mean, rstd, gamma, beta)]
    sh = shift.reshape(1, o).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_fwd_kernel_nhwc, apply_bn=apply_bn, act=act,
                          with_stats=with_stats, m=m, bm=bm),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, c), lambda i: (i, 0)),
                  pl.BlockSpec((c, o), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, o), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, o), lambda i: (i, 0)),
                   pl.BlockSpec((o,), lambda i: (0,)),
                   pl.BlockSpec((o,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((m, o), x2.dtype),
                   jax.ShapeDtypeStruct((o,), jnp.float32),
                   jax.ShapeDtypeStruct((o,), jnp.float32)],
        interpret=interpret,
    )(x2, w, *p, sh)


def _bwd_kernel_nhwc(x_ref, w_ref, z_ref, dz_ref, dsum_ref, dsumsq_ref,
                     mean_ref, rstd_ref, gamma_ref, beta_ref, shift_ref,
                     dx_ref, dw_ref, dgamma_ref, dbeta_ref, *,
                     apply_bn, act, with_stats, m, bm):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)
        dgamma_ref[...] = jnp.zeros_like(dgamma_ref)
        dbeta_ref[...] = jnp.zeros_like(dbeta_ref)

    dz = dz_ref[...].astype(jnp.float32)            # [bm, O]
    rows_ok_o = (i * bm + jax.lax.broadcasted_iota(
        jnp.int32, dz.shape, 0)) < m
    if with_stats:
        z = z_ref[...].astype(jnp.float32) - shift_ref[...]
        dz = dz + dsum_ref[...].reshape(1, -1) \
            + 2.0 * z * dsumsq_ref[...].reshape(1, -1)
    dz = jnp.where(rows_ok_o, dz, 0.0)
    dz_lo = dz.astype(x_ref.dtype)

    x_raw = x_ref[...]                               # [bm, C]
    rows_ok_c = (i * bm + jax.lax.broadcasted_iota(
        jnp.int32, x_raw.shape, 0)) < m
    x = jnp.where(rows_ok_c, x_raw, jnp.zeros_like(x_raw)
                  ).astype(jnp.float32)
    if apply_bn:
        pre = (x - mean_ref[...]) * rstd_ref[...]    # [bm, C]
        ylin = pre * gamma_ref[...] + beta_ref[...]
        xn = jnp.maximum(ylin, 0.0) if act == "relu" else ylin
    else:
        xn = jnp.maximum(x, 0.0) if act == "relu" else x
    xn_lo = xn.astype(x_ref.dtype)

    # dW[C, O] += xn^T @ dz  (contract bm)
    dw_ref[...] += jax.lax.dot_general(
        xn_lo, dz_lo, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    # dxn[bm, C] = dz @ w^T  (contract O)
    dxn = jax.lax.dot_general(
        dz_lo, w_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    if apply_bn:
        dylin = dxn * (ylin > 0.0) if act == "relu" else dxn
        dgamma_ref[...] += jnp.sum(dylin * pre, axis=0)
        dbeta_ref[...] += jnp.sum(dylin, axis=0)
        dx = dylin * (gamma_ref[...] * rstd_ref[...])
    else:
        dx = dxn * (x > 0.0) if act == "relu" else dxn
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _bwd_call_nhwc(x2, w, z2, dz2, dsum, dsumsq, mean, rstd, gamma, beta,
                   shift, act, apply_bn, with_stats, interpret):
    m, c = x2.shape
    o = w.shape[1]
    isz = jnp.dtype(x2.dtype).itemsize
    bm = _pick_bhw(1, c, o, m, isz, stack_factor=4)
    grid = (pl.cdiv(m, bm),)
    p = [a.reshape(1, c).astype(jnp.float32)
         for a in (mean, rstd, gamma, beta)]
    sh = shift.reshape(1, o).astype(jnp.float32)
    return pl.pallas_call(
        functools.partial(_bwd_kernel_nhwc, apply_bn=apply_bn, act=act,
                          with_stats=with_stats, m=m, bm=bm),
        grid=grid,
        in_specs=[pl.BlockSpec((bm, c), lambda i: (i, 0)),
                  pl.BlockSpec((c, o), lambda i: (0, 0)),
                  pl.BlockSpec((bm, o), lambda i: (i, 0)),
                  pl.BlockSpec((bm, o), lambda i: (i, 0)),
                  pl.BlockSpec((o,), lambda i: (0,)),
                  pl.BlockSpec((o,), lambda i: (0,)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, c), lambda i: (0, 0)),
                  pl.BlockSpec((1, o), lambda i: (0, 0))],
        out_specs=[pl.BlockSpec((bm, c), lambda i: (i, 0)),
                   pl.BlockSpec((c, o), lambda i: (0, 0)),
                   pl.BlockSpec((c,), lambda i: (0,)),
                   pl.BlockSpec((c,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((m, c), x2.dtype),
                   jax.ShapeDtypeStruct((c, o), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32)],
        interpret=interpret,
    )(x2, w, z2, dz2, dsum.astype(jnp.float32), dsumsq.astype(jnp.float32),
      *p, sh)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def bn_act_matmul_nhwc(x2, w, mean, var, gamma, beta, stats_shift,
                       eps=1e-5, act="relu", apply_bn=True,
                       with_stats=True, interpret=False):
    """z = act(bn(x2)) @ w with fused output stats, NHWC-native.

    ``x2`` is [M, C] (a free reshape of an NHWC activation), ``w`` is
    [C, O]; returns ``(z2 [M, O], sum [O], sumsq [O])``.  Same
    statistics/gradient contract as :func:`bn_act_matmul`; this form
    tiles the whole fused layer as one dense matmul, so late-stage
    ResNet shapes (HW=49) no longer fragment per batch element."""
    return _vjp_fwd_nhwc(x2, w, mean, var, gamma, beta, stats_shift, eps,
                         act, apply_bn, with_stats, interpret)[0]


def _vjp_fwd_nhwc(x2, w, mean, var, gamma, beta, stats_shift, eps, act,
                  apply_bn, with_stats, interpret):
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    z, s, ss = _fwd_call_nhwc(x2, w, mean, rstd, gamma, beta, stats_shift,
                              act, apply_bn, with_stats, interpret)
    return (z, s, ss), (x2, w, z, mean, rstd, gamma, beta, stats_shift)


def _vjp_bwd_nhwc(eps, act, apply_bn, with_stats, interpret, res, cts):
    x2, w, z, mean, rstd, gamma, beta, stats_shift = res
    dz, dsum, dsumsq = cts
    c = x2.shape[1]
    dx, dw, dgamma, dbeta = _bwd_call_nhwc(
        x2, w, z, dz.astype(x2.dtype), dsum, dsumsq, mean, rstd, gamma,
        beta, stats_shift, act, apply_bn, with_stats, interpret)
    dw = dw.astype(w.dtype)
    dshift = jnp.zeros_like(stats_shift)
    if apply_bn:
        dmean, dvar = stats_grads(apply_bn, gamma, rstd, dgamma, dbeta)
        return (dx, dw, dmean.astype(mean.dtype), dvar.astype(mean.dtype),
                dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
                dshift)
    zk = jnp.zeros((c,), mean.dtype)
    return (dx, dw, zk, zk, zk.astype(gamma.dtype), zk.astype(beta.dtype),
            dshift)


bn_act_matmul_nhwc.defvjp(_vjp_fwd_nhwc, _vjp_bwd_nhwc)


# -- per-channel stats grads ------------------------------------------------

def stats_grads(apply_bn, gamma, rstd, dgamma, dbeta):
    """Per-channel mean/var cotangents from the kernel's dgamma/dbeta
    reductions.  With mean/var as *external inputs* (not functions of x
    inside this op) the chain rule collapses to per-channel arithmetic:
    dmean = -rstd*gamma*dbeta; dvar enters through rstd=(var+eps)^-1/2
    (d rstd/d var = -rstd^3/2), giving -gamma*dgamma*rstd^2/2."""
    if not apply_bn:
        z = jnp.zeros_like(dbeta)
        return z, z
    g32 = gamma.astype(jnp.float32).reshape(dbeta.shape)
    r32 = rstd.astype(jnp.float32).reshape(dbeta.shape)
    dmean = -r32 * g32 * dbeta
    dvar = -0.5 * g32 * dgamma * r32 * r32
    return dmean, dvar


# -- custom-vjp wrapper -----------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def bn_act_matmul(x3, w, mean, var, gamma, beta, stats_shift, eps=1e-5,
                  act="relu", apply_bn=True, with_stats=True,
                  interpret=False):
    """z[b] = W @ act(bn(x[b])) with fused output stats, NCHW-native.

    ``x3`` is [B, C, HW] (a free reshape of NCHW), ``w`` is [O, C].
    Returns ``(z3, sum, sumsq)``: z3 is [B, O, HW]; sum/sumsq are fp32
    per-output-channel statistics of (z - stats_shift) — the shift (the
    consumer BN's running mean, zeros when unknown) guards the one-pass
    variance finalize against cancellation; zeros when
    ``with_stats=False``.  ``mean``/``var`` are the batch statistics of
    x computed by x's producer; gradients flow back to them (and on to
    the producer's sum/sumsq) so the BN three-term backward emerges from
    the graph.  ``stats_shift`` is treated as a constant (zero
    cotangent): it holds running statistics.
    """
    return _vjp_fwd(x3, w, mean, var, gamma, beta, stats_shift, eps, act,
                    apply_bn, with_stats, interpret)[0]


def _vjp_fwd(x3, w, mean, var, gamma, beta, stats_shift, eps, act,
             apply_bn, with_stats, interpret):
    rstd = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    z, s, ss = _fwd_call(x3, w, mean, rstd, gamma, beta, stats_shift, act,
                         apply_bn, with_stats, interpret)
    return (z, s, ss), (x3, w, z, mean, rstd, gamma, beta, stats_shift)


def _vjp_bwd(eps, act, apply_bn, with_stats, interpret, res, cts):
    x3, w, z, mean, rstd, gamma, beta, stats_shift = res
    dz, dsum, dsumsq = cts
    c = x3.shape[1]
    dx, dw, dgamma, dbeta = _bwd_call(
        x3, w, z, dz, dsum, dsumsq, mean, rstd, gamma, beta, stats_shift,
        act, apply_bn, with_stats, interpret)
    dw = dw.astype(w.dtype)
    dshift = jnp.zeros_like(stats_shift)
    if apply_bn:
        dmean, dvar = stats_grads(apply_bn, gamma, rstd, dgamma, dbeta)
        return (dx, dw, dmean.astype(mean.dtype), dvar.astype(mean.dtype),
                dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
                dshift)
    zk = jnp.zeros((c,), mean.dtype)
    return (dx, dw, zk, zk, zk.astype(gamma.dtype), zk.astype(beta.dtype),
            dshift)


bn_act_matmul.defvjp(_vjp_fwd, _vjp_bwd)
