"""Fused LayerNorm Pallas kernel (fwd + hand-fused vjp).

Parity target: reference ``layer_norm_op.{cc,cu}`` — mean/var reduction,
normalize, affine, and the three-term backward, each a separate CUDA
kernel there; here one VMEM-resident tile pass per direction.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import block_rows, pad_rows


def _fwd_kernel(x_ref, gamma_ref, beta_ref, y_ref, mu_ref, rstd_ref, *,
                eps):
    x = x_ref[...]                            # [BN, D]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    y_ref[...] = xhat * gamma_ref[...] + beta_ref[...]
    mu_ref[...] = mu
    rstd_ref[...] = rstd


def _bwd_kernel(x_ref, gamma_ref, mu_ref, rstd_ref, dy_ref,
                dx_ref, dgamma_ref, dbeta_ref):
    x = x_ref[...]
    g = dy_ref[...]
    mu = mu_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mu) * rstd
    gg = g * gamma_ref[...]
    d = x.shape[-1]
    m1 = jnp.mean(gg, axis=-1, keepdims=True)
    m2 = jnp.mean(gg * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (gg - m1 - xhat * m2) * rstd
    # partial reductions accumulated across grid steps
    dgamma_ref[...] += jnp.sum(g * xhat, axis=0)
    dbeta_ref[...] += jnp.sum(g, axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def layer_norm(x, gamma, beta, eps=1e-5, interpret=False):
    return _fwd(x, gamma, beta, eps, interpret)[0]


def _fwd(x, gamma, beta, eps, interpret):
    n, d = x.shape
    if n == 0:
        z = jnp.zeros((0, d), x.dtype)
        z1 = jnp.zeros((0, 1), x.dtype)
        return z, (x, gamma, z1, z1)
    bn, n_pad = block_rows(n, row_bytes=4 * d * 4, max_rows=512)
    y, mu, rstd = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(n_pad // bn,),
        in_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, d), x.dtype),
                   jax.ShapeDtypeStruct((n_pad, 1), x.dtype),
                   jax.ShapeDtypeStruct((n_pad, 1), x.dtype)],
        interpret=interpret,
    )(pad_rows(x, n_pad), gamma, beta)
    return y[:n], (x, gamma, mu[:n], rstd[:n])


def _bwd(eps, interpret, res, dy):
    x, gamma, mu, rstd = res
    n, d = x.shape
    if n == 0:
        return (jnp.zeros((0, d), x.dtype), jnp.zeros((d,), x.dtype),
                jnp.zeros((d,), x.dtype))
    bn, n_pad = block_rows(n, row_bytes=4 * d * 4, max_rows=512)

    def kernel(x_ref, gamma_ref, mu_ref, rstd_ref, dy_ref,
               dx_ref, dgamma_ref, dbeta_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            dgamma_ref[...] = jnp.zeros_like(dgamma_ref)
            dbeta_ref[...] = jnp.zeros_like(dbeta_ref)

        _bwd_kernel(x_ref, gamma_ref, mu_ref, rstd_ref, dy_ref,
                    dx_ref, dgamma_ref, dbeta_ref)

    dx, dgamma, dbeta = pl.pallas_call(
        kernel,
        grid=(n_pad // bn,),
        in_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,)),
                  pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                  pl.BlockSpec((bn, d), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((d,), lambda i: (0,)),
                   pl.BlockSpec((d,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((n_pad, d), x.dtype),
                   jax.ShapeDtypeStruct((d,), x.dtype),
                   jax.ShapeDtypeStruct((d,), x.dtype)],
        interpret=interpret,
    )(pad_rows(x, n_pad), gamma, pad_rows(mu, n_pad),
      pad_rows(rstd, n_pad), pad_rows(dy, n_pad))
    return dx[:n], dgamma, dbeta


layer_norm.defvjp(_fwd, _bwd)
