"""LayerHelper: shared machinery for layer functions.

Parity: reference ``python/paddle/fluid/layer_helper.py`` — creates
parameters (var in main program + init op in startup program), temporary
variables, bias/activation append helpers.
"""

from .core import dtype_is_floating
from .framework import default_main_program, default_startup_program
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr
from . import unique_name

__all__ = ["LayerHelper"]


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        if kwargs.get("name") is None:
            self.kwargs["name"] = unique_name.generate(layer_type)

    @property
    def name(self):
        return self.kwargs["name"]

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    # ---- inputs ----------------------------------------------------------
    def multiple_input(self, input_param_name="input"):
        inputs = self.kwargs.get(input_param_name, [])
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return list(inputs)

    def input(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        if len(inputs) != 1:
            raise ValueError("%s layer needs exactly one input" % self.layer_type)
        return inputs[0]

    @property
    def param_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("param_attr", None))

    @property
    def bias_attr(self):
        return ParamAttr.to_attr(self.kwargs.get("bias_attr", None))

    def multiple_param_attr(self, length):
        attr = self.param_attr
        if isinstance(attr, ParamAttr):
            attr = [attr]
        if len(attr) != 1 and len(attr) != length:
            raise ValueError("parameter number mismatch")
        elif len(attr) == 1 and length != 1:
            import copy

            attr = [attr[0]] + [copy.deepcopy(attr[0]) for _ in range(length - 1)]
        return attr

    def iter_inputs_and_params(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        attrs = self.multiple_param_attr(len(inputs))
        yield from zip(inputs, attrs)

    def input_dtype(self, input_param_name="input"):
        inputs = self.multiple_input(input_param_name)
        dtype = None
        for v in inputs:
            if dtype is None:
                dtype = v.dtype
            elif dtype != v.dtype:
                raise ValueError("mismatched input dtypes")
        return dtype

    # ---- creation --------------------------------------------------------
    def create_parameter(self, attr, shape, dtype, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr.to_attr(attr)
        if attr is None or attr.trainable is False and attr.name is None and \
                self.kwargs.get("allow_non_trainable", False):
            return None
        if default_initializer is None:
            default_initializer = (
                ConstantInitializer(0.0) if is_bias else XavierInitializer()
            )
        attr.set_default_initializer(default_initializer)
        name = attr.name or unique_name.generate(
            ".".join([self.name, "b" if is_bias else "w"]))
        attr.name = name
        # variable in main program (attr kwargs already carry the name)
        param = self.main_program.global_block().create_parameter(
            shape=shape, dtype=dtype, **attr.to_kwargs()
        )
        # mirror + init op in startup program
        startup_blk = self.startup_program.global_block()
        if not startup_blk.has_var(name):
            sp = startup_blk.create_parameter(
                shape=shape, dtype=dtype, **attr.to_kwargs()
            )
            attr.initializer(sp, startup_blk)
        return param

    def create_variable_for_type_inference(self, dtype=None, name=None):
        return self.main_program.current_block().create_var(
            name=name or unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            persistable=False,
        )

    # backwards-compatible alias (reference used create_tmp_variable)
    create_tmp_variable = create_variable_for_type_inference

    def create_global_variable(self, persistable=False, *args, **kwargs):
        return self.main_program.global_block().create_var(
            *args, persistable=persistable, **kwargs
        )

    def set_variable_initializer(self, var, initializer):
        startup_blk = self.startup_program.global_block()
        if not startup_blk.has_var(var.name):
            sv = startup_blk.create_var(
                name=var.name, shape=var.shape, dtype=var.dtype,
                persistable=True,
            )
            initializer(sv, startup_blk)
        return var

    def append_op(self, *args, **kwargs):
        return self.main_program.current_block().append_op(*args, **kwargs)

    # ---- common tails ----------------------------------------------------
    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        size = list(input_var.shape[dim_start:dim_end])
        bias_attr = self.bias_attr
        b = self.create_parameter(
            attr=bias_attr, shape=size, dtype=input_var.dtype, is_bias=True
        )
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type="elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": dim_start},
        )
        return tmp

    def append_activation(self, input_var):
        act = self.kwargs.get("act", None)
        if act is None:
            return input_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(
            type=act_type,
            inputs={"X": [input_var]},
            outputs={"Out": [tmp]},
            attrs=act,
        )
        return tmp

    def is_instance(self, param_name, cls):
        param = self.kwargs.get(param_name, None)
        if not isinstance(param, cls):
            raise TypeError("%s must be %s" % (param_name, cls))
