"""Parameter initializers — emit init ops into the startup program.

Parity: reference ``python/paddle/fluid/initializer.py`` (Constant/Uniform/
Normal/TruncatedNormal/Xavier/MSRA/Bilinear emitting fill ops into the
startup program) — same design: initialization is itself a Program run once
by the executor, so it is jitted, device-resident and reproducible from
``program.random_seed``.
"""

import math

import numpy as np

import contextlib as _contextlib

__all__ = [
    "Initializer", "Constant", "Uniform", "Normal", "TruncatedNormal",
    "Xavier", "MSRA", "Bilinear", "NumpyArrayInitializer",
    "ConstantInitializer", "UniformInitializer", "NormalInitializer",
    "TruncatedNormalInitializer", "XavierInitializer", "MSRAInitializer",
    "force_init_on_cpu", "init_on_cpu",
]


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        receptive = 1
        for s in shape[2:]:
            receptive *= s
        return shape[0] * receptive, shape[1] * receptive


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "value": self.value,
                   "dtype": str(var.dtype)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "min": self.low,
                   "max": self.high, "dtype": str(var.dtype),
                   "seed": self.seed},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "mean": self.loc,
                   "std": self.scale, "dtype": str(var.dtype),
                   "seed": self.seed},
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "mean": self.loc,
                   "std": self.scale, "dtype": str(var.dtype),
                   "seed": self.seed},
        )


class XavierInitializer(Initializer):
    """Glorot init (reference initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = (
            uniform, fan_in, fan_out, seed)

    def __call__(self, var, block):
        fi, fo = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / (fi + fo))
        return NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He init (reference initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = self._fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            return UniformInitializer(-limit, limit, self.seed)(var, block)
        std = math.sqrt(2.0 / fi)
        return NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsampling kernel init for conv_transpose
    (reference initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("Bilinear initializer requires a 4-D weight")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype=np.float32)
        size = int(np.prod(shape))
        for i in range(size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            w = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            idx = np.unravel_index(i, shape)
            weight[idx] = w
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": list(shape), "dtype": str(var.dtype),
                   "values": weight.reshape(-1).tolist()},
        )


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": list(self.value.shape), "dtype": str(var.dtype),
                   "values": self.value.astype(var.dtype).reshape(-1).tolist()},
        )


# aliases matching the reference's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


# force_init_on_cpu / init_on_cpu (reference initializer.py:29-61): a
# GPU-era switch pinning random-init ops to the CPU to keep them
# deterministic across device counts.  Initialization here is a jitted
# startup program whose placement XLA owns, so the switch only records
# intent — kept for API parity and introspection.
_force_init_on_cpu_ = False


def force_init_on_cpu():
    """Whether initializer ops are currently requested on CPU."""
    return _force_init_on_cpu_


@_contextlib.contextmanager
def init_on_cpu():
    """Context manager requesting CPU placement for inits built inside
    (reference init_on_cpu)."""
    global _force_init_on_cpu_
    prev = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = prev
