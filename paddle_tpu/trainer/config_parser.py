"""v1 config parser entry point (reference
python/paddle/trainer/config_parser.py ``parse_config`` — the function
that turned a trainer-config script into the ``TrainerConfig`` proto the
``paddle_trainer`` binary consumed).

Here a config is a callable (the v1 "config file" body) run under the
trainer_config_helpers dialect, and the "proto" is the parsed model's
Program-JSON dict plus the recorded optimizer settings — see
``trainer_config_helpers/config_parser_utils.py`` for the machinery.
"""

from ..trainer_config_helpers.config_parser_utils import (  # noqa: F401
    parse_network_config,
    parse_optimizer_config,
    parse_trainer_config,
    reset_parser,
)

__all__ = ["parse_config", "parse_network_config",
           "parse_optimizer_config", "reset_parser"]


class TrainerConfig(object):
    """What parse_config returns (reference TrainerConfig proto shape):
    ``model_config`` (the parsed model) + ``opt_config`` (settings)."""

    def __init__(self, model_config, opt_config):
        self.model_config = model_config
        self.opt_config = opt_config

    def to_dict(self):
        d = {"model_config": self.model_config.to_dict()}
        if self.opt_config is not None:
            d["opt_config"] = {
                "batch_size": self.opt_config.batch_size,
                "learning_rate": self.opt_config.learning_rate,
                "learning_method": type(
                    self.opt_config.learning_method).__name__
                if self.opt_config.learning_method else "sgd",
            }
        return d


def parse_config(trainer_conf, config_arg_str=""):
    """Run a full v1 config callable; return a TrainerConfig-shaped
    object (reference config_parser.parse_config)."""
    model, settings = parse_trainer_config(trainer_conf, config_arg_str)
    return TrainerConfig(model, settings)
