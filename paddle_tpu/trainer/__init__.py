"""v1 trainer package namespace (reference python/paddle/trainer/).

The reference package holds ``config_parser.py`` (config -> TrainerConfig
proto) and the PyDataProvider2 protocol; the parsing surface is re-hosted
over the Program IR in ``config_parser``, and data providers are plain
readers on this stack (trainer_config_helpers/data_sources.py).
"""

from . import config_parser  # noqa: F401
