"""String/number compatibility helpers (reference
``python/paddle/compat.py``).  The reference papered over py2/py3;
these keep the same names as API parity on py3: ``to_text``/``to_bytes``
normalize str/bytes (recursing into list/set/dict containers),
``round`` is banker's-rounding-free (half away from zero, the py2
behavior callers relied on), ``floor_division`` and
``get_exception_message`` are kept verbatim in spirit."""

import math

__all__ = [
    "int_type", "long_type", "to_text", "to_bytes", "round",
    "floor_division", "get_exception_message",
]

int_type = int
long_type = int


def _convert(obj, conv, inplace):
    if obj is None or isinstance(obj, (int, float)):
        return obj
    if isinstance(obj, (str, bytes)):
        return conv(obj)
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(x, conv, inplace) for x in obj]
            return obj
        return [_convert(x, conv, False) for x in obj]
    if isinstance(obj, set):
        new = {_convert(x, conv, False) for x in obj}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    if isinstance(obj, dict):
        new = {_convert(k, conv, False): _convert(v, conv, False)
               for k, v in obj.items()}
        if inplace:
            obj.clear()
            obj.update(new)
            return obj
        return new
    return obj    # reference behavior: unknown types pass through untouched


def to_text(obj, encoding="utf-8", inplace=False):
    """Anything string-like (recursively through list/set/dict) -> str."""
    return _convert(
        obj, lambda s: s.decode(encoding) if isinstance(s, bytes) else s,
        inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Anything string-like (recursively through list/set/dict) -> bytes."""
    return _convert(
        obj, lambda s: s.encode(encoding) if isinstance(s, str) else s,
        inplace)


def round(x, d=0):  # noqa: A001 — reference shadows the builtin
    """Half-away-from-zero rounding (py2 semantics; py3's builtin
    rounds half to even)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return 0.0


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    assert exc is not None
    return str(exc)
