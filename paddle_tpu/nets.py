"""Composite network helpers.

Parity: reference ``python/paddle/fluid/nets.py``:
``simple_img_conv_pool:28``, ``img_conv_group:125``,
``sequence_conv_pool:238``, ``glu:288``, ``scaled_dot_product_attention:323``.
"""

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "glu",
    "scaled_dot_product_attention",
    "sequence_conv_pool",
    "simple_attention",
    "dot_product_attention",
]


def simple_img_conv_pool(
    input, num_filters, filter_size, pool_size, pool_stride,
    pool_padding=0, pool_type="max", global_pooling=False,
    conv_stride=1, conv_padding=0, conv_dilation=1, conv_groups=1,
    param_attr=None, bias_attr=None, act=None, use_cudnn=True,
):
    conv_out = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act,
    )
    return layers.pool2d(
        input=conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling,
    )


def img_conv_group(
    input, conv_num_filter, pool_size, conv_padding=1, conv_filter_size=3,
    conv_act=None, param_attr=None, conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0, pool_stride=1, pool_type="max",
    use_cudnn=True,
):
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def _expand(obj):
        if isinstance(obj, (list, tuple)):
            assert len(obj) == len(conv_num_filter)
            return list(obj)
        return [obj] * len(conv_num_filter)

    conv_padding = _expand(conv_padding)
    conv_filter_size = _expand(conv_filter_size)
    param_attr = _expand(param_attr)
    conv_with_batchnorm = _expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = _expand(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(
            input=tmp, num_filters=conv_num_filter[i],
            filter_size=conv_filter_size[i], padding=conv_padding[i],
            param_attr=param_attr[i], act=local_conv_act,
        )
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    return layers.pool2d(
        input=tmp, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride,
    )


def glu(input, dim=-1):
    """Gated linear unit: split in half, a * sigmoid(b)
    (reference nets.py:288)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    act_b = layers.sigmoid(b)
    return layers.elementwise_mul(x=a, y=act_b)


def scaled_dot_product_attention(
    queries, keys, values, num_heads=1, dropout_rate=0.0,
):
    """Multi-head scaled-dot-product attention (reference nets.py:323 —
    the only attention impl in fluid).  On TPU all head projections and the
    QK^T / PV matmuls are MXU gemms; XLA fuses scale+softmax in between."""
    if not (len(queries.shape) == len(keys.shape) == len(values.shape) == 3):
        raise ValueError("inputs must be 3-D [batch, seq, dim]")
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys must have the same hidden size")
    if keys.shape[1] != values.shape[1]:
        raise ValueError("keys and values must share sequence length")
    if queries.shape[-1] % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")

    def __split_heads(x, num_heads):
        if num_heads == 1:
            return x
        hidden_size = x.shape[-1]
        reshaped = layers.reshape(
            x, shape=[0, 0, num_heads, hidden_size // num_heads]
        )
        return layers.transpose(reshaped, perm=[0, 2, 1, 3])

    def __combine_heads(x):
        if len(x.shape) == 3:
            return x
        trans = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            trans, shape=[0, 0, trans.shape[2] * trans.shape[3]]
        )

    q = __split_heads(queries, num_heads)
    k = __split_heads(keys, num_heads)
    v = __split_heads(values, num_heads)

    key_dim_per_head = keys.shape[-1] // num_heads
    scaled_q = layers.scale(x=q, scale=key_dim_per_head ** -0.5)
    product = layers.matmul(x=scaled_q, y=k, transpose_y=True)

    # softmax over the key axis directly: the reference's flatten-
    # softmax-unflatten dance needs static shapes; rank-4 softmax
    # doesn't (and XLA emits the same kernel)
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    return __combine_heads(ctx_multiheads)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None,
                       length=None):
    """sequence_conv followed by sequence_pool (reference nets.py:238,
    same positional parameter order).  ``input`` is a padded sequence
    batch [B, T, D] with a @LEN companion; returns the pooled
    [B, num_filters] features."""
    conv = layers.sequence_conv(input, num_filters=num_filters,
                                filter_size=filter_size, act=act,
                                param_attr=param_attr,
                                bias_attr=bias_attr, length=length)
    return layers.sequence_pool(conv, pool_type, length=length)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     decoder_size, length=None):
    """Bahdanau additive attention, padded form (reference
    python/paddle/trainer_config_helpers/networks.py simple_attention —
    the v1 seqToseq attention; the fluid reference has no equivalent).

    ``encoded_sequence`` [B, T, H] values; ``encoded_proj`` [B, T, D]
    pre-projected keys (hoist the key projection out of the decode loop
    — one big gemm instead of one per step); ``decoder_state`` [B, D].
    ``length`` masks padded timesteps (defaults to encoded_sequence's
    @LEN companion).  Returns the context vector [B, H].

    score[b,t] = v . tanh(enc_proj[b,t] + W s[b]); masked softmax over
    t; context = sum_t w[b,t] * enc[b,t].
    """
    dec_proj = layers.fc(decoder_state, size=decoder_size, bias_attr=False)
    mixed = layers.tanh(
        layers.elementwise_add(encoded_proj,
                               layers.unsqueeze(dec_proj, axes=[1])))
    scores = layers.squeeze(
        layers.fc(mixed, size=1, num_flatten_dims=2, bias_attr=False),
        axes=[2])                                           # [B, T]
    weights = layers.sequence_softmax(scores, length=length)
    return layers.reduce_sum(
        layers.elementwise_mul(encoded_sequence,
                               layers.unsqueeze(weights, axes=[2])),
        dim=1)


def dot_product_attention(encoded_sequence, attended_sequence,
                          transformed_state, length=None):
    """Single-query dot-product attention (reference
    trainer_config_helpers/networks.py dot_product_attention).

    ``encoded_sequence`` [B, T, D] keys; ``attended_sequence`` [B, T, H]
    values; ``transformed_state`` [B, D] query (pre-projected, as the
    reference expects).  Returns the context [B, H].
    """
    scores = layers.reduce_sum(
        layers.elementwise_mul(encoded_sequence,
                               layers.unsqueeze(transformed_state,
                                                axes=[1])),
        dim=2)                                              # [B, T]
    weights = layers.sequence_softmax(scores, length=length)
    return layers.reduce_sum(
        layers.elementwise_mul(attended_sequence,
                               layers.unsqueeze(weights, axes=[2])),
        dim=1)
