"""Image augmentation helpers (reference
``python/paddle/utils/image_util.py``: the v1-era CHW float pipeline —
resize / crop / flip / mean-subtract / 10-crop oversample /
ImageTransformer).

Same function names and array conventions as the reference (color
images travel as ``(K, H, W)`` float arrays through crop/preprocess;
``flip`` and ``oversample`` take HWC), implemented with vectorized
numpy + PIL.  The finer-grained HWC helpers used by the dataset readers
live in ``paddle_tpu.dataset.image``.
"""

import io

import numpy as np

__all__ = [
    "resize_image", "flip", "crop_img", "decode_jpeg", "preprocess_img",
    "load_meta", "load_image", "oversample", "ImageTransformer",
]


def resize_image(img, target_size):
    """Resize a PIL image so its shorter edge is ``target_size``."""
    from PIL import Image

    w, h = img.size
    scale = target_size / float(min(w, h))
    return img.resize((int(round(w * scale)), int(round(h * scale))),
                      Image.LANCZOS)


def flip(im):
    """Horizontal flip: reverses the LAST axis — (H, W) for grayscale,
    (K, H, W) for the channel-first color layout this module uses."""
    return im[..., ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Crop ``inner_size`` x ``inner_size`` from a (K,H,W) (color) or
    (H,W) (gray) array, zero-padding images smaller than the crop.
    test=True takes the center; test=False takes a random crop and
    flips with probability 1/2."""
    im = np.asarray(im, dtype="float32")
    spatial = im.shape[-2:]
    height, width = (max(inner_size, spatial[0]), max(inner_size, spatial[1]))
    if (height, width) != spatial:
        padded = np.zeros(im.shape[:-2] + (height, width), dtype="float32")
        y0 = (height - spatial[0]) // 2
        x0 = (width - spatial[1]) // 2
        padded[..., y0:y0 + spatial[0], x0:x0 + spatial[1]] = im
        im = padded
    if test:
        y0 = (height - inner_size) // 2
        x0 = (width - inner_size) // 2
    else:
        y0 = np.random.randint(0, height - inner_size + 1)
        x0 = np.random.randint(0, width - inner_size + 1)
    pic = im[..., y0:y0 + inner_size, x0:x0 + inner_size]
    if not test and np.random.randint(2) == 0:
        pic = flip(pic)
    return pic


def decode_jpeg(jpeg_string):
    """JPEG bytes -> (K, H, W) uint8 array (HW for grayscale)."""
    from PIL import Image

    arr = np.array(Image.open(io.BytesIO(jpeg_string)))
    if arr.ndim == 3:
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Augment one (K,H,W) image: crop (random when training, center at
    test), subtract the mean image, flatten."""
    pic = crop_img(im.astype("float32"), crop_size, color, test=not is_train)
    pic -= img_mean
    return pic.ravel()


def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load the dataset's mean image (written by
    ``preprocess_util.DatasetCreater``) and center-crop it to the
    training crop size."""
    mean = np.load(meta_path)["data_mean"]
    border = (mean_img_size - crop_size) // 2
    shape = (3, mean_img_size, mean_img_size) if color \
        else (mean_img_size, mean_img_size)
    assert mean.size == int(np.prod(shape)), (mean.size, shape)
    mean = mean.reshape(shape)
    return mean[..., border:border + crop_size,
                border:border + crop_size].astype("float32")


def load_image(img_path, is_color=True):
    """Open an image from disk as a PIL image (decoded eagerly),
    converted to RGB or grayscale per ``is_color``."""
    from PIL import Image

    img = Image.open(img_path)
    img.load()
    return img.convert("RGB" if is_color else "L")


def oversample(img, crop_dims):
    """Caffe-style 10-crop: for each (H,W,K) image in ``img``, the four
    corner crops + the center crop and their mirrors; returns
    (10*N, ch, cw, K) float32."""
    im_shape = np.asarray(img[0].shape)
    ch, cw = crop_dims
    corners = [(i, j) for i in (0, im_shape[0] - ch)
               for j in (0, im_shape[1] - cw)]
    cy = int(im_shape[0] / 2.0 - ch / 2.0)
    cx = int(im_shape[1] / 2.0 - cw / 2.0)
    corners.append((cy, cx))
    crops = np.empty((10 * len(img), ch, cw, im_shape[-1]), dtype="float32")
    ix = 0
    for im in img:
        for y0, x0 in corners:
            crops[ix] = im[y0:y0 + ch, x0:x0 + cw, :]
            ix += 1
        crops[ix:ix + 5] = crops[ix - 5:ix, :, ::-1, :]   # mirrors
        ix += 5
    return crops


class ImageTransformer(object):
    """Configurable transpose / channel-swap / mean-subtract pipeline
    (reference image_util.py:183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.is_color = is_color
        self.set_transpose(transpose)
        self.set_channel_swap(channel_swap)
        self.set_mean(mean)

    def set_transpose(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.transpose = order

    def set_channel_swap(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.channel_swap = order

    def set_mean(self, mean):
        if mean is not None:
            mean = np.asarray(mean)
            if mean.ndim == 1:
                mean = mean[:, np.newaxis, np.newaxis]
            elif self.is_color:
                assert mean.ndim == 3
        self.mean = mean

    def transformer(self, data):
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[self.channel_swap, :, :]
        if self.mean is not None:
            data = data - self.mean
        return data
