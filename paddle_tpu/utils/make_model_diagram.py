"""Graphviz diagram of a v1 model config (reference
``python/paddle/utils/make_model_diagram.py``: emit a .dot of the layer
graph).  Parses the config through the trainer_config_helpers dialect
and delegates drawing to ``paddle_tpu.net_drawer`` over the resulting
Program — one drawing path for every API dialect."""

import sys

from ..trainer.config_parser import parse_config

__all__ = ["make_diagram"]


def make_diagram(config_fn, dot_path, config_arg_str=""):
    """Parse a v1 config callable, write the op graph as graphviz dot.
    Returns the dot source text."""
    from .. import net_drawer

    conf = parse_config(config_fn, config_arg_str)
    prog = conf.model_config.program if hasattr(conf.model_config, "program") \
        else conf.model_config
    return net_drawer.draw_graph(main_program=prog, path=dot_path)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) < 2:
        raise SystemExit(
            "usage: make_model_diagram <module:callable> <out.dot>")
    mod_name, _, fn_name = argv[0].partition(":")
    import importlib
    fn = getattr(importlib.import_module(mod_name), fn_name or "config")
    make_diagram(fn, argv[1])


if __name__ == "__main__":
    main()
