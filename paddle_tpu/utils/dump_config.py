"""Dump a parsed v1 trainer config (reference
``python/paddle/utils/dump_config.py``: parse a config file and print
the TrainerConfig proto).  Here the "proto" is the TrainerConfig dict
(Program-JSON model + optimizer settings) from
``paddle_tpu.trainer.config_parser.parse_config``."""

import json
import sys

from ..trainer.config_parser import parse_config

__all__ = ["dump_config"]


def dump_config(config_fn, config_arg_str="", out=None):
    """Parse a v1 config callable and write its serialized form."""
    conf = parse_config(config_fn, config_arg_str)
    text = json.dumps(conf.to_dict(), indent=2, sort_keys=True)
    (out or sys.stdout).write(text + "\n")
    return text


def main(argv=None):
    """CLI: ``python -m paddle_tpu.utils.dump_config conf_module:fn
    [config_args]`` — mirrors ``python dump_config.py conf [args]``."""
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        raise SystemExit(
            "usage: dump_config <module:callable> [config_arg_str]")
    mod_name, _, fn_name = argv[0].partition(":")
    import importlib
    fn = getattr(importlib.import_module(mod_name), fn_name or "config")
    dump_config(fn, argv[1] if len(argv) > 1 else "")


if __name__ == "__main__":
    main()
