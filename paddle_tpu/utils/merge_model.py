"""Bundle a v2 topology + trained parameters into ONE deployable file
(reference ``python/paddle/utils/merge_model.py`` ``merge_v2_model``:
proto-size + ModelConfig proto + parameter streams in a single binary
for the C-API/mobile path).

Here the bundle is a tar with two members — ``__model__.json`` (the
pruned Program-JSON written by ``dump_v2_config``) and ``params.npz``
(name -> ndarray) — loadable by ``load_merged_model`` or unpackable by
standard tools on the deployment host."""

import io
import json
import os
import tarfile
import tempfile

import numpy as np

from .dump_v2_config import dump_v2_config

__all__ = ["merge_v2_model", "load_merged_model"]


def merge_v2_model(net, param_file, output_file):
    """``net``: the v2 output layer(s); ``param_file``: a Parameters tar
    written by ``Parameters.to_tar`` (or an open file object of one);
    ``output_file``: bundle destination."""
    from ..v2.parameters import Parameters

    if hasattr(param_file, "read"):
        params = Parameters.from_tar(param_file)
    else:
        with open(param_file, "rb") as f:
            params = Parameters.from_tar(f)

    with tempfile.TemporaryDirectory() as tmp:
        model_path = os.path.join(tmp, "__model__.json")
        dump_v2_config(net, model_path, binary=True)
        npz = io.BytesIO()
        np.savez(npz, **{name: params.get(name) for name in params.names()})
        npz.seek(0)
        with tarfile.open(output_file, "w") as tar:
            tar.add(model_path, arcname="__model__.json")
            info = tarfile.TarInfo("params.npz")
            info.size = len(npz.getbuffer())
            tar.addfile(info, npz)
    return output_file


def load_merged_model(path):
    """Returns (model_doc, {param_name: ndarray}) from a merged bundle."""
    with tarfile.open(path, "r") as tar:
        doc = json.loads(tar.extractfile("__model__.json").read()
                         .decode("utf-8"))
        with np.load(io.BytesIO(tar.extractfile("params.npz").read())) as z:
            params = {k: z[k] for k in z.files}
    return doc, params
