"""Image-classification dataset creator (reference
``python/paddle/utils/preprocess_img.py``): resize every image in a
labeled folder tree to a fixed short edge, store them as compact JPEG
bytes in pickled batches, and write the mean-image meta consumed by
``image_util.load_meta``."""

import io
import os

import numpy as np

from . import preprocess_util
from .image_util import resize_image

__all__ = ["DiskImage", "ImageClassificationDatasetCreater"]


class DiskImage(object):
    """One on-disk image, resized lazily to ``target_size`` short edge
    (reference preprocess_img.py:37)."""

    def __init__(self, path, target_size):
        self.path = path
        self.target_size = target_size
        self.img = None

    def read_image(self):
        if self.img is None:
            from PIL import Image

            img = Image.open(self.path)
            img.load()
            self.img = resize_image(img.convert("RGB"), self.target_size)
        return self.img

    def convert_to_array(self):
        """(K, H, W) float array."""
        arr = np.array(self.read_image())
        if arr.ndim == 3:
            arr = np.transpose(arr, (2, 0, 1))
        return arr

    def convert_to_paddle_format(self):
        """Re-encoded JPEG bytes — what the batch files store."""
        out = io.BytesIO()
        self.read_image().save(out, "jpeg")
        return out.getvalue()


class ImageClassificationDatasetCreater(preprocess_util.DatasetCreater):
    """``data_path/{train,test}/<label>/*.jpg`` -> pickled JPEG batches
    + mean-image meta (npz with ``data_mean`` flattened to match
    ``image_util.load_meta``)."""

    def __init__(self, data_path, batch_size=128, processed_image_size=56,
                 output_path=None):
        super().__init__(data_path, batch_size, output_path)
        self.processed_image_size = processed_image_size

    def process_file(self, path):
        return DiskImage(path, self.processed_image_size) \
            .convert_to_paddle_format()

    def create_meta_file(self, samples):
        """Mean over center-cropped square images, flattened."""
        from PIL import Image

        s = self.processed_image_size
        acc = np.zeros((3, s, s), dtype="float64")
        for jpeg in samples:
            arr = np.array(Image.open(io.BytesIO(jpeg)))
            arr = np.transpose(arr, (2, 0, 1)).astype("float64")
            y0 = (arr.shape[1] - s) // 2
            x0 = (arr.shape[2] - s) // 2
            acc += arr[:, y0:y0 + s, x0:x0 + s]
        mean = (acc / max(len(samples), 1)).astype("float32").ravel()
        os.makedirs(self.output_path, exist_ok=True)
        np.savez(os.path.join(self.output_path, self.meta_filename),
                 data_mean=mean)
