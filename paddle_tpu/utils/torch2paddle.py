"""Import torch-trained weights (reference
``python/paddle/utils/torch2paddle.py``, which decoded lua-torch
binaries and wrote v1 parameter files).  The modern equivalent: map a
PyTorch ``state_dict`` (or a saved ``.pt``/``.pth`` file) onto this
framework's parameters by name.

Works against either surface:
- ``torch2paddle(state_dict, parameters)`` — a ``v2.Parameters`` object
  (sets each matching name via ``Parameters.set``), or
- ``torch2paddle(state_dict, scope=scope, program=prog)`` — a fluid
  scope (sets parameter variables directly).

``name_map`` translates torch names to parameter names; unmapped names
match verbatim.  torch ``nn.Linear`` stores weights [out, in] where
fluid ``fc`` weights are [in, out]; ``transpose_fc=True`` transposes
exactly the Linear weights when ``src`` is a module (detected from the
module tree), or — for a bare state_dict, where layer types are
unknown — the torch names listed in ``transpose_fc`` when it is an
iterable.  ``transpose_fc=True`` with a bare state_dict transposes
every 2-D tensor and is only safe when all of them are Linear weights
(pass the iterable form otherwise)."""

import numpy as np

__all__ = ["load_state_dict", "torch2paddle"]


def _linear_weight_names(module):
    """Torch state_dict keys that are nn.Linear weights."""
    import torch

    return {name + ".weight" if name else "weight"
            for name, m in module.named_modules()
            if isinstance(m, torch.nn.Linear)}


def load_state_dict(path_or_dict):
    """Accept a state_dict, an nn.Module, or a path to a torch save."""
    if isinstance(path_or_dict, dict):
        sd = path_or_dict
    elif hasattr(path_or_dict, "state_dict"):
        sd = path_or_dict.state_dict()
    else:
        import torch
        sd = torch.load(path_or_dict, map_location="cpu")
        if hasattr(sd, "state_dict"):
            sd = sd.state_dict()
        if "state_dict" in sd and isinstance(sd["state_dict"], dict):
            sd = sd["state_dict"]
    out = {}
    for k, v in sd.items():
        out[k] = v.detach().cpu().numpy() if hasattr(v, "detach") \
            else np.asarray(v)
    return out


def torch2paddle(src, parameters=None, scope=None, program=None,
                 name_map=None, transpose_fc=False, strict=True):
    """Copy weights from ``src`` into ``parameters`` or ``scope``.
    Returns the list of parameter names written."""
    sd = load_state_dict(src)
    name_map = name_map or {}
    if transpose_fc is True and hasattr(src, "named_modules"):
        transpose_names = _linear_weight_names(src)
    elif transpose_fc is True:
        transpose_names = {k for k, v in sd.items() if v.ndim == 2}
    elif transpose_fc:
        transpose_names = set(transpose_fc)
    else:
        transpose_names = set()
    written = []

    def targets():
        if parameters is not None:
            names = set(parameters.names())

            def setter(name, arr):
                parameters.set(name, arr)
        else:
            assert scope is not None and program is not None, \
                "pass either parameters= or scope= and program="
            by_name = {p.name: p for p in program.global_block()
                       .all_parameters()}
            names = set(by_name)

            def setter(name, arr):
                expect = tuple(by_name[name].shape)
                if tuple(arr.shape) != expect:
                    raise ValueError(
                        "shape mismatch for %r: torch %s vs parameter %s"
                        % (name, arr.shape, expect))
                scope.set_var(name, np.ascontiguousarray(arr))
        return names, setter

    names, setter = targets()
    for tname, arr in sd.items():
        pname = name_map.get(tname, tname)
        if pname not in names:
            if strict and tname in name_map:
                raise KeyError("mapped target %r not a parameter" % pname)
            continue
        if tname in transpose_names and arr.ndim == 2:
            arr = arr.T
        setter(pname, arr.astype("float32"))
        written.append(pname)
    if strict and not written:
        raise ValueError("no torch tensors matched any parameter; "
                         "pass name_map= to translate names")
    return written
