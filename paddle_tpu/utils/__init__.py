"""Deployment / preprocessing utility suite (reference
``python/paddle/utils/``).

Each module re-imagines its reference counterpart over this framework's
serialization surfaces (Program-JSON instead of protos, npz/tar instead
of raw parameter streams):

- ``image_util`` / ``preprocess_img`` / ``preprocess_util`` — image
  augmentation + folder-of-images -> pickled-batch dataset creation.
- ``dump_config`` / ``dump_v2_config`` — serialize a v1 trainer config /
  v2 topology for embedded deployment.
- ``merge_model`` — bundle topology + trained parameters in one file.
- ``show_pb`` — print a dumped model config.
- ``plotcurve`` — plot cost curves from trainer logs.
- ``make_model_diagram`` — graphviz diagram of a v1 config.
- ``torch2paddle`` — import torch-trained weights into Parameters
  (reference converted lua-torch binaries; here: torch state_dicts).

The reference's ``predefined_net.py`` (named-network zoo over meta
files) is absorbed by ``trainer_config_helpers.networks`` +
``paddle_tpu.models``, which serve the same catalog role as real code.
"""

from . import (  # noqa: F401
    dump_config,
    dump_v2_config,
    image_util,
    make_model_diagram,
    merge_model,
    plotcurve,
    preprocess_img,
    preprocess_util,
    show_pb,
    torch2paddle,
)

__all__ = [
    "image_util", "preprocess_img", "preprocess_util", "dump_config",
    "dump_v2_config", "merge_model", "show_pb", "plotcurve",
    "make_model_diagram", "torch2paddle",
]
