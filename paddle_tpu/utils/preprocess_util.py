"""Folder-of-files -> pickled-batch dataset machinery (reference
``python/paddle/utils/preprocess_util.py``: the v1 offline
preprocessing story — walk a labeled directory tree, shuffle, emit
fixed-size pickled batches plus list/meta files).

Same public surface (``save_file`` … ``DatasetCreater``); internals are
a py3/numpy rewrite.  Concrete per-modality creators subclass
``DatasetCreater`` (see ``preprocess_img``)."""

import os
import pickle

import numpy as np

__all__ = [
    "save_file", "save_list", "exclude_pattern", "list_dirs",
    "list_images", "list_files", "get_label_set_from_dir", "Label",
    "Dataset", "DatasetCreater",
]


def save_file(data, filename):
    """Pickle ``data`` to ``filename``."""
    with open(filename, "wb") as f:
        pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)


def load_file(filename):
    """Inverse of save_file."""
    with open(filename, "rb") as f:
        return pickle.load(f)


def save_list(l, outfile):
    """Write one item per line."""
    with open(outfile, "w") as f:
        for item in l:
            f.write("%s\n" % item)


def exclude_pattern(f):
    """Names starting with '.' or '_' are metadata, not data."""
    return f.startswith(".") or f.startswith("_")


def list_dirs(path):
    return sorted(
        d for d in os.listdir(path)
        if os.path.isdir(os.path.join(path, d)) and not exclude_pattern(d))


def list_images(path, exts=("jpg", "png", "bmp", "jpeg")):
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f)) and not exclude_pattern(f)
        and f.rsplit(".", 1)[-1].lower() in set(exts))


def list_files(path):
    return sorted(
        f for f in os.listdir(path)
        if os.path.isfile(os.path.join(path, f)) and not exclude_pattern(f))


def get_label_set_from_dir(path):
    """{label_name: integer id} from the subdirectory names (one
    directory per class)."""
    return {name: i for i, name in enumerate(list_dirs(path))}


class Label(object):
    """A (id, name) class label."""

    def __init__(self, label, name):
        self.label = int(label)
        self.name = name

    def convert_to_paddle_format(self):
        return self.label

    def __hash__(self):
        return hash((self.label, self.name))

    def __eq__(self, other):
        return (self.label, self.name) == (other.label, other.name)

    def __repr__(self):
        return "Label(%d, %r)" % (self.label, self.name)


class Dataset(object):
    """An in-memory table of samples: ``data`` is a list of tuples,
    ``keys`` names the tuple fields (e.g. ["image", "label"])."""

    def __init__(self, data, keys):
        self.data = list(data)
        self.keys = list(keys)

    def check_valid(self):
        for item in self.data:
            assert len(item) == len(self.keys), (item, self.keys)

    def uniform_permute(self, seed=0):
        """Uniform shuffle (the reference's class-balancing permutes are
        subsumed: one global shuffle gives each batch the dataset's
        label mix in expectation)."""
        rng = np.random.RandomState(seed)
        rng.shuffle(self.data)

    def batches(self, batch_size):
        for i in range(0, len(self.data), batch_size):
            yield self.data[i:i + batch_size]


class DatasetCreater(object):
    """Walk ``data_path/{train,test}/<label>/...``, emit shuffled
    pickled batches + ``train.list`` / ``test.list`` + a ``meta`` file.

    Subclasses implement ``process_file(path) -> sample`` (the stored
    per-file record) and may override ``create_meta_file(samples)`` to
    write modality statistics (e.g. the mean image)."""

    def __init__(self, data_path, batch_size=128, output_path=None):
        self.data_path = data_path
        self.batch_size = batch_size
        self.output_path = output_path or os.path.join(data_path, "batches")
        self.meta_filename = "meta.npz"   # np.savez appends .npz itself
        self.train_list_name = "train.list"
        self.test_list_name = "test.list"

    # -- subclass hooks --
    def process_file(self, path):
        raise NotImplementedError

    def create_meta_file(self, samples):
        pass

    # -- driver --
    def create_dataset_from_dir(self, which):
        src = os.path.join(self.data_path, which)
        label_set = get_label_set_from_dir(src)
        rows = []
        for name, label in sorted(label_set.items(), key=lambda kv: kv[1]):
            for f in list_files(os.path.join(src, name)):
                rows.append((self.process_file(os.path.join(src, name, f)),
                             label))
        ds = Dataset(rows, ["data", "label"])
        ds.check_valid()
        ds.uniform_permute()
        return ds, label_set

    def create_batches(self, which):
        """Returns the list of batch files written for the split."""
        ds, label_set = self.create_dataset_from_dir(which)
        os.makedirs(self.output_path, exist_ok=True)
        files = []
        for i, batch in enumerate(ds.batches(self.batch_size)):
            fn = os.path.join(self.output_path,
                              "%s_batch_%03d" % (which, i))
            save_file({"data": [b[0] for b in batch],
                       "labels": [b[1] for b in batch],
                       "label_set": label_set}, fn)
            files.append(fn)
        save_list(files, os.path.join(
            self.output_path,
            self.train_list_name if which == "train" else self.test_list_name))
        if which == "train":
            self.create_meta_file([r[0] for r in ds.data])
        return files

    def create_dataset(self):
        """Process both splits; the standard entry point."""
        out = {}
        for which in ("train", "test"):
            if os.path.isdir(os.path.join(self.data_path, which)):
                out[which] = self.create_batches(which)
        return out
