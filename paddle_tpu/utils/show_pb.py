"""Print a dumped model config in readable form (reference
``python/paddle/utils/show_pb.py``: parse a ModelConfig/TrainerConfig
proto file and print its text format).  Accepts anything this framework
serializes a model to: a ``dump_v2_config``/``merge_model`` output, a
``save_inference_model`` directory, or a bare Program-JSON file."""

import json
import os
import sys
import tarfile

__all__ = ["read_model", "show"]


def read_model(path):
    """Load the model document from any supported container."""
    if os.path.isdir(path):                      # save_inference_model dir
        path = os.path.join(path, "__model__")
    if tarfile.is_tarfile(path):                 # merge_model bundle
        with tarfile.open(path, "r") as tar:
            return json.loads(
                tar.extractfile("__model__.json").read().decode("utf-8"))
    with open(path, "rb") as f:
        return json.loads(f.read().decode("utf-8"))


def show(path, out=None):
    """Print the model: feeds/fetches, then one line per op."""
    out = out or sys.stdout
    doc = read_model(path)
    prog = doc.get("program", doc)
    if "feed_names" in doc:
        out.write("feeds:   %s\n" % ", ".join(doc["feed_names"]))
    if "fetch_names" in doc:
        out.write("fetches: %s\n" % ", ".join(doc["fetch_names"]))
    for bi, block in enumerate(prog.get("blocks", [])):
        out.write("block %d (%d vars, %d ops)\n"
                  % (bi, len(block.get("vars", [])),
                     len(block.get("ops", []))))
        for op in block.get("ops", []):
            ins = "; ".join("%s=%s" % (k, v)
                            for k, v in sorted(op.get("inputs", {}).items()))
            outs = "; ".join(
                "%s=%s" % (k, v)
                for k, v in sorted(op.get("outputs", {}).items()))
            out.write("  %-28s (%s) -> (%s)\n" % (op["type"], ins, outs))


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        raise SystemExit("usage: show_pb <model file|dir>")
    show(argv[0])


if __name__ == "__main__":
    main()
