"""Dump a v2 network topology for deployment (reference
``python/paddle/utils/dump_v2_config.py``: serialize the pruned
ModelConfig proto for the C-API).  Here the deployable form is the
topology's Program-JSON; ``binary=True`` writes the compact encoding the
embedded C predictor (``paddle_tpu.capi``) loads."""

import json

from ..v2 import config as _cfg
from ..v2.topology import Topology

__all__ = ["dump_v2_config"]


def dump_v2_config(topology, save_path, binary=False):
    """``topology``: one v2 output layer or a list/tuple of them; all
    layers reachable from the outputs are dumped, others pruned."""
    layers = _cfg.as_layers(topology)
    if not layers:
        raise RuntimeError("topology must be a v2 layer or a non-empty "
                           "list/tuple of v2 layers")
    topo = Topology(layers)
    out_names = [l.name for l in layers]
    feeds = [l.name for l in topo.data_layers]
    for l in topo.data_layers:
        if getattr(l.var, "_seq_len_name", None):
            feeds.append(l.var._seq_len_name)
    pruned = topo.program.clone(for_test=True).prune_feed_fetch(
        feeds, out_names)
    doc = {"program": pruned.to_dict(), "feed_names": feeds,
           "fetch_names": out_names}
    if binary:
        with open(save_path, "wb") as f:
            f.write(json.dumps(doc, sort_keys=True).encode("utf-8"))
    else:
        with open(save_path, "w") as f:
            json.dump(doc, f, indent=2, sort_keys=True)
    return doc
