"""Plot cost curves from trainer logs (reference
``python/paddle/utils/plotcurve.py``: scrape ``key=value`` metrics out
of paddle_trainer output and plot them per pass).

Works on this framework's logs the same way: any line containing
``<key>=<float>`` tokens (the v1 trainer, ``v2.trainer.SGD`` event
prints, and the Trainer's EndStepEvent logging all emit this shape)."""

import re
import sys

__all__ = ["parse_log", "plot_paddle_curve"]

_TOKEN = re.compile(r"([A-Za-z_][A-Za-z0-9_.]*)=([-+0-9.eE]+)")


def parse_log(lines, keys):
    """{key: [values in log order]} for every requested key."""
    out = {k: [] for k in keys}
    for line in lines:
        for k, v in _TOKEN.findall(line):
            if k in out:
                try:
                    out[k].append(float(v))
                except ValueError:
                    pass
    return out


def plot_paddle_curve(keys, inputfile, outputfile, format="png",
                      show_fig=False):
    """Read a log stream, plot one curve per key.  ``inputfile`` and
    ``outputfile`` are open file objects (reference signature)."""
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    series = parse_log(inputfile, keys)
    if not any(series.values()):
        sys.stderr.write("plotcurve: no occurrence of keys %s\n" % keys)
        return series
    plt.figure(figsize=(8, 5))
    for k in keys:
        if series[k]:
            plt.plot(range(len(series[k])), series[k], label=k)
    plt.xlabel("step")
    plt.legend()
    plt.savefig(outputfile, format=format, bbox_inches="tight")
    plt.close()
    return series


def main(argv=None):
    """CLI: ``plotcurve.py -i log -o out.png key1 key2 ...`` (stdin if
    no -i, like the reference)."""
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("-i", "--input", default=None)
    p.add_argument("-o", "--output", required=True)
    p.add_argument("--format", default="png")
    p.add_argument("keys", nargs="+")
    a = p.parse_args(argv)
    infile = open(a.input) if a.input else sys.stdin
    try:
        with open(a.output, "wb") as out:
            plot_paddle_curve(a.keys, infile, out, format=a.format)
    finally:
        if a.input:
            infile.close()


if __name__ == "__main__":
    main()
