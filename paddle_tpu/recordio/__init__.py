"""Chunked record-file format (RecordIO-equivalent) — native C++ core.

Parity: reference ``paddle/fluid/recordio/`` (Header/Chunk/Writer/
Scanner, ``chunk.h:27``) + ``python/paddle/fluid/recordio_writer.py``
and the ``paddle.reader.creator.recordio`` reader creator.

The hot path is C++ (``librecordio.cpp``: chunked layout, zlib
compression, crc32 integrity, chunk-skip for sharded scans), compiled
on first import with g++ and bound via ctypes — no pybind11 needed;
records cross the boundary as (ptr, len) views.  A pure-python codec of
the SAME on-disk format (``_pyimpl``) is the fallback when no compiler
is available, and doubles as the cross-check oracle in tests.

Chunk granularity is the sharding unit: ``num_chunks`` + per-chunk
skipping let the elastic master (paddle_tpu.cloud) lease chunk spans to
trainers, which is exactly how the reference's Go master partitions
recordio files (go/master/service.go partition over chunks).
"""

import ctypes
import os
import subprocess
import tempfile

__all__ = ["Writer", "Scanner", "num_chunks", "reader_creator",
           "convert_reader_to_recordio_file", "native_available"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "librecordio.cpp")
_LIB_PATH = os.path.join(_HERE, "_librecordio.so")
_lib = None
_native_failed = False


def _build_native():
    # build to a unique temp name: concurrent first imports (pytest
    # workers, multi-host trainers on a shared FS) must not collide
    fd, tmp = tempfile.mkstemp(dir=_HERE, prefix="_librecordio_",
                               suffix=".so")
    os.close(fd)
    try:
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", _SRC,
               "-o", tmp, "-lz"]
        subprocess.run(cmd, check=True, capture_output=True)
        os.replace(tmp, _LIB_PATH)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _native_failed
    if _lib is not None or _native_failed:
        return _lib
    try:
        if (not os.path.exists(_LIB_PATH) or
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)):
            _build_native()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.rio_writer_open.restype = ctypes.c_void_p
        lib.rio_writer_open.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                        ctypes.c_uint64]
        lib.rio_writer_write.restype = ctypes.c_int
        lib.rio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64]
        lib.rio_writer_flush.restype = ctypes.c_int
        lib.rio_writer_flush.argtypes = [ctypes.c_void_p]
        lib.rio_writer_close.restype = ctypes.c_int
        lib.rio_writer_close.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_open.restype = ctypes.c_void_p
        lib.rio_scanner_open.argtypes = [ctypes.c_char_p]
        lib.rio_scanner_next.restype = ctypes.c_int
        lib.rio_scanner_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64)]
        lib.rio_scanner_skip_chunk.restype = ctypes.c_int
        lib.rio_scanner_skip_chunk.argtypes = [ctypes.c_void_p]
        lib.rio_scanner_set_max_chunks.argtypes = [ctypes.c_void_p,
                                                   ctypes.c_uint64]
        lib.rio_scanner_close.argtypes = [ctypes.c_void_p]
        lib.rio_num_chunks.restype = ctypes.c_int64
        lib.rio_num_chunks.argtypes = [ctypes.c_char_p]
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _native_failed = True
        _lib = None
    return _lib


def native_available():
    return _load() is not None


class Writer:
    """Record writer (reference recordio/writer.h + recordio_writer.py
    context manager).  ``compressor``: 'none' or 'zlib'."""

    def __init__(self, path, compressor="zlib", max_chunk_bytes=1 << 20):
        comp = {"none": 0, "zlib": 1}[compressor]
        lib = _load()
        if lib is not None:
            self._h = lib.rio_writer_open(
                os.fsencode(path), comp, int(max_chunk_bytes))
            if not self._h:
                raise IOError("cannot open %r for writing" % path)
            self._py = None
        else:
            from . import _pyimpl

            self._py = _pyimpl.PyWriter(path, comp, int(max_chunk_bytes))
            self._h = None

    def write(self, record):
        if isinstance(record, str):
            record = record.encode("utf-8")
        if self._py is not None:
            return self._py.write(record)
        if _lib.rio_writer_write(self._h, record, len(record)) != 0:
            raise IOError("recordio write failed")

    def flush_chunk(self):
        """Close the current chunk (controls sharding boundaries)."""
        if self._py is not None:
            return self._py.flush_chunk()
        if _lib.rio_writer_flush(self._h) != 0:
            raise IOError("recordio flush failed")

    def close(self):
        if self._py is not None:
            return self._py.close()
        if self._h is not None:
            rc = _lib.rio_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise IOError("recordio close failed")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class Scanner:
    """Record iterator (reference recordio/scanner.h).  ``skip_chunks``
    fast-forwards whole chunks without decoding — the sharded-read path
    used with the elastic master's chunk leases."""

    def __init__(self, path, skip_chunks=0, max_chunks=0):
        """``skip_chunks`` fast-forwards, ``max_chunks`` caps decoded
        chunks (0 = unlimited): together they scan the chunk range
        [skip, skip+max) — the shard unit of the parallel multi-file
        readers and the elastic master's task leases."""
        lib = _load()
        if lib is not None:
            self._h = lib.rio_scanner_open(os.fsencode(path))
            if not self._h:
                raise IOError("cannot open %r" % path)
            self._py = None
            try:
                for _ in range(skip_chunks):
                    rc = lib.rio_scanner_skip_chunk(self._h)
                    if rc < 0:
                        raise IOError("corrupt recordio file %r" % path)
                    if rc == 0:
                        break
                if max_chunks:
                    lib.rio_scanner_set_max_chunks(self._h, max_chunks)
            except Exception:
                lib.rio_scanner_close(self._h)
                self._h = None
                raise
        else:
            from . import _pyimpl

            self._py = _pyimpl.PyScanner(path, skip_chunks, max_chunks)
            self._h = None

    def __iter__(self):
        if self._py is not None:
            yield from self._py
            return
        data = ctypes.c_char_p()
        length = ctypes.c_uint64()
        while True:
            rc = _lib.rio_scanner_next(self._h, ctypes.byref(data),
                                       ctypes.byref(length))
            if rc == 0:
                return
            if rc < 0:
                raise IOError("corrupt recordio file")
            yield ctypes.string_at(data, length.value)

    def close(self):
        if self._py is not None:
            return self._py.close()
        if self._h is not None:
            _lib.rio_scanner_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def num_chunks(path):
    """Chunk count (the shard index the task-lease queue partitions)."""
    lib = _load()
    if lib is not None:
        n = lib.rio_num_chunks(os.fsencode(path))
        if n < 0:
            raise IOError("cannot index %r" % path)
        return n
    from . import _pyimpl

    return _pyimpl.py_num_chunks(path)


# ---------------------------------------------------------------------------
# reader-layer integration (python/paddle/reader/creator.py:recordio and
# fluid/recordio_writer.py parity)

def reader_creator(paths):
    """Reader over one or more record files; records are bytes."""
    if isinstance(paths, str):
        paths = [p for p in paths.split(",") if p]

    def reader():
        for p in paths:
            with Scanner(p) as s:
                yield from s

    return reader


def convert_reader_to_recordio_file(filename, reader_creator_fn,
                                    serializer=None, compressor="zlib",
                                    max_chunk_bytes=1 << 20,
                                    feeder=None):
    """Materialize a sample reader into a record file
    (fluid/recordio_writer.py parity).  ``serializer(sample) -> bytes``
    defaults to pickle."""
    import pickle

    if feeder is not None:
        raise NotImplementedError(
            "feeder-driven serialization is not supported; pass a "
            "serializer(sample)->bytes instead (default: pickle)")
    serializer = serializer or pickle.dumps
    n = 0
    with Writer(filename, compressor, max_chunk_bytes) as w:
        for sample in reader_creator_fn():
            w.write(serializer(sample))
            n += 1
    return n
