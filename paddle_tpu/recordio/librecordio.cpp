// Native chunked record-file library for paddle_tpu.
//
// Capability parity with the reference's RecordIO
// (paddle/fluid/recordio/{header,chunk,writer,scanner}.cc: chunked,
// optionally-compressed record files with per-chunk checksums), designed
// fresh for this framework:
//
//   file  := chunk*
//   chunk := magic:u32 | compressor:u32 | num_records:u32
//            | uncompressed_len:u64 | payload_len:u64 | crc32:u32
//            | payload[payload_len]
//   payload (before compression) := (len:u32 | bytes)*
//
// compressor: 0 = raw, 1 = zlib (deflate).  crc32 covers the on-disk
// payload bytes.  Chunk granularity enables sharded scanning: a reader
// can seek to the k-th chunk without parsing records (the task-lease
// queue hands out chunk spans).
//
// C ABI consumed by ctypes (paddle_tpu/recordio/__init__.py); no
// CPython API needed — records cross the boundary as (ptr, len) views
// into the scanner's decode buffer.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x50545230;  // "PTR0"

#pragma pack(push, 1)
struct ChunkHeader {
  uint32_t magic;
  uint32_t compressor;
  uint32_t num_records;
  uint64_t uncompressed_len;
  uint64_t payload_len;
  uint32_t crc;
};
#pragma pack(pop)

struct Writer {
  FILE* f = nullptr;
  int compressor = 1;
  uint64_t max_chunk_bytes = 1u << 20;
  std::string buf;
  uint32_t n_records = 0;
  bool error = false;
};

struct Scanner {
  FILE* f = nullptr;
  std::string decoded;       // current chunk's raw payload
  size_t pos = 0;            // cursor into decoded
  uint32_t remaining = 0;    // records left in current chunk
  uint64_t chunks_read = 0;  // decoded chunks so far
  uint64_t max_chunks = 0;   // 0 = unlimited; else stop after this many
  bool error = false;
};

bool write_chunk(Writer* w) {
  if (w->n_records == 0) return true;
  std::string out;
  const std::string* payload = &w->buf;
  if (w->compressor == 1) {
    uLongf bound = compressBound(w->buf.size());
    out.resize(bound);
    uLongf out_len = bound;
    if (compress2(reinterpret_cast<Bytef*>(&out[0]), &out_len,
                  reinterpret_cast<const Bytef*>(w->buf.data()),
                  w->buf.size(), Z_DEFAULT_COMPRESSION) != Z_OK) {
      return false;
    }
    out.resize(out_len);
    payload = &out;
  }
  ChunkHeader h;
  h.magic = kMagic;
  h.compressor = static_cast<uint32_t>(w->compressor);
  h.num_records = w->n_records;
  h.uncompressed_len = w->buf.size();
  h.payload_len = payload->size();
  h.crc = crc32(0L, reinterpret_cast<const Bytef*>(payload->data()),
                payload->size());
  if (fwrite(&h, sizeof(h), 1, w->f) != 1) return false;
  if (!payload->empty() &&
      fwrite(payload->data(), payload->size(), 1, w->f) != 1) {
    return false;
  }
  w->buf.clear();
  w->n_records = 0;
  return true;
}

bool read_chunk(Scanner* s) {
  if (s->max_chunks && s->chunks_read >= s->max_chunks) {
    return false;  // chunk budget exhausted: clean end-of-shard
  }
  ChunkHeader h;
  size_t got = fread(&h, 1, sizeof(h), s->f);
  if (got == 0) return false;  // clean EOF
  if (got != sizeof(h) || h.magic != kMagic) {
    s->error = true;
    return false;
  }
  std::string payload(h.payload_len, '\0');
  if (h.payload_len &&
      fread(&payload[0], 1, h.payload_len, s->f) != h.payload_len) {
    s->error = true;
    return false;
  }
  if (crc32(0L, reinterpret_cast<const Bytef*>(payload.data()),
            payload.size()) != h.crc) {
    s->error = true;
    return false;
  }
  if (h.compressor == 1) {
    s->decoded.resize(h.uncompressed_len);
    uLongf dst_len = h.uncompressed_len;
    if (uncompress(reinterpret_cast<Bytef*>(&s->decoded[0]), &dst_len,
                   reinterpret_cast<const Bytef*>(payload.data()),
                   payload.size()) != Z_OK ||
        dst_len != h.uncompressed_len) {
      s->error = true;
      return false;
    }
  } else {
    s->decoded.swap(payload);
  }
  s->pos = 0;
  s->remaining = h.num_records;
  s->chunks_read++;
  return true;
}

}  // namespace

extern "C" {

void* rio_writer_open(const char* path, int compressor,
                      uint64_t max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  Writer* w = new Writer;
  w->f = f;
  w->compressor = compressor;
  if (max_chunk_bytes) w->max_chunk_bytes = max_chunk_bytes;
  return w;
}

int rio_writer_write(void* wp, const char* data, uint64_t len) {
  Writer* w = static_cast<Writer*>(wp);
  if (w->error) return -1;
  if (len > UINT32_MAX) return -1;  // record length field is u32
  uint32_t len32 = static_cast<uint32_t>(len);
  w->buf.append(reinterpret_cast<const char*>(&len32), sizeof(len32));
  w->buf.append(data, len);
  w->n_records++;
  if (w->buf.size() >= w->max_chunk_bytes) {
    if (!write_chunk(w)) {
      w->error = true;
      return -1;
    }
  }
  return 0;
}

// Force the buffered records out as a chunk (sharding boundary control).
int rio_writer_flush(void* wp) {
  Writer* w = static_cast<Writer*>(wp);
  if (w->error || !write_chunk(w)) return -1;
  return 0;
}

int rio_writer_close(void* wp) {
  Writer* w = static_cast<Writer*>(wp);
  int rc = 0;
  if (w->error || !write_chunk(w)) rc = -1;
  if (fclose(w->f) != 0) rc = -1;
  delete w;
  return rc;
}

void* rio_scanner_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  Scanner* s = new Scanner;
  s->f = f;
  return s;
}

// 1 = record produced, 0 = EOF, -1 = corrupt file.
int rio_scanner_next(void* sp, const char** data, uint64_t* len) {
  Scanner* s = static_cast<Scanner*>(sp);
  while (s->remaining == 0) {
    if (!read_chunk(s)) return s->error ? -1 : 0;
  }
  if (s->pos + sizeof(uint32_t) > s->decoded.size()) {
    s->error = true;
    return -1;
  }
  uint32_t rec_len;
  memcpy(&rec_len, s->decoded.data() + s->pos, sizeof(rec_len));
  s->pos += sizeof(rec_len);
  if (s->pos + rec_len > s->decoded.size()) {
    s->error = true;
    return -1;
  }
  *data = s->decoded.data() + s->pos;
  *len = rec_len;
  s->pos += rec_len;
  s->remaining--;
  return 1;
}

// Skip forward one whole chunk without decoding (sharded scanning).
// 1 = skipped, 0 = EOF, -1 = corrupt.
int rio_scanner_skip_chunk(void* sp) {
  Scanner* s = static_cast<Scanner*>(sp);
  // drop any partially-read chunk state, then skip the next on-disk one
  s->remaining = 0;
  s->pos = 0;
  ChunkHeader h;
  size_t got = fread(&h, 1, sizeof(h), s->f);
  if (got == 0) return 0;
  if (got != sizeof(h) || h.magic != kMagic) return -1;
  if (fseek(s->f, static_cast<long>(h.payload_len), SEEK_CUR) != 0) {
    return -1;
  }
  return 1;
}

// Cap the scan at n decoded chunks (0 = unlimited): with skip_chunk this
// gives [skip, skip+n) chunk-range shards — the unit the open_files-style
// parallel readers and the elastic master's task leases partition.
void rio_scanner_set_max_chunks(void* sp, uint64_t n) {
  static_cast<Scanner*>(sp)->max_chunks = n;
}

void rio_scanner_close(void* sp) {
  Scanner* s = static_cast<Scanner*>(sp);
  fclose(s->f);
  delete s;
}

// Count chunks by walking headers (cheap index for the lease queue).
int64_t rio_num_chunks(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int64_t n = 0;
  ChunkHeader h;
  for (;;) {
    size_t got = fread(&h, 1, sizeof(h), f);
    if (got == 0) break;
    if (got != sizeof(h) || h.magic != kMagic ||
        fseek(f, static_cast<long>(h.payload_len), SEEK_CUR) != 0) {
      fclose(f);
      return -1;
    }
    n++;
  }
  fclose(f);
  return n;
}

}  // extern "C"
