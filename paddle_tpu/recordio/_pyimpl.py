"""Pure-python codec of the SAME on-disk chunk format as
``librecordio.cpp`` — compiler-free fallback and the cross-check oracle
for the native path (tests write with one and read with the other).

Layout (see librecordio.cpp):
  chunk := magic:u32 | compressor:u32 | num_records:u32
           | uncompressed_len:u64 | payload_len:u64 | crc32:u32
           | payload
  payload (raw) := (len:u32 | bytes)*
"""

import struct
import zlib

MAGIC = 0x50545230
_HDR = struct.Struct("<IIIQQI")


class PyWriter:
    def __init__(self, path, compressor=1, max_chunk_bytes=1 << 20):
        self._f = open(path, "wb")
        self._comp = compressor
        self._max = max_chunk_bytes
        self._buf = bytearray()
        self._n = 0

    def write(self, record):
        self._buf += struct.pack("<I", len(record))
        self._buf += record
        self._n += 1
        if len(self._buf) >= self._max:
            self.flush_chunk()

    def flush_chunk(self):
        if not self._n:
            return
        raw = bytes(self._buf)
        payload = zlib.compress(raw) if self._comp == 1 else raw
        self._f.write(_HDR.pack(MAGIC, self._comp, self._n, len(raw),
                                len(payload),
                                zlib.crc32(payload) & 0xFFFFFFFF))
        self._f.write(payload)
        self._buf = bytearray()
        self._n = 0

    def close(self):
        self.flush_chunk()
        self._f.close()


def _read_header(f):
    blob = f.read(_HDR.size)
    if not blob:
        return None
    if len(blob) != _HDR.size:
        raise IOError("truncated chunk header")
    magic, comp, n, raw_len, payload_len, crc = _HDR.unpack(blob)
    if magic != MAGIC:
        raise IOError("bad magic: not a recordio file")
    return comp, n, raw_len, payload_len, crc


class PyScanner:
    def __init__(self, path, skip_chunks=0, max_chunks=0):
        self._f = open(path, "rb")
        self._max_chunks = max_chunks
        self._chunks_read = 0
        for _ in range(skip_chunks):
            h = _read_header(self._f)
            if h is None:
                break
            self._f.seek(h[3], 1)

    def __iter__(self):
        while True:
            if self._max_chunks and self._chunks_read >= self._max_chunks:
                return
            h = _read_header(self._f)
            if h is None:
                return
            self._chunks_read += 1
            comp, n, raw_len, payload_len, crc = h
            payload = self._f.read(payload_len)
            if len(payload) != payload_len:
                raise IOError("truncated chunk payload")
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                raise IOError("chunk crc mismatch")
            raw = zlib.decompress(payload) if comp == 1 else payload
            if len(raw) != raw_len:
                raise IOError("chunk length mismatch")
            pos = 0
            for _ in range(n):
                (rec_len,) = struct.unpack_from("<I", raw, pos)
                pos += 4
                yield raw[pos:pos + rec_len]
                pos += rec_len

    def close(self):
        self._f.close()


def py_num_chunks(path):
    n = 0
    with open(path, "rb") as f:
        while True:
            h = _read_header(f)
            if h is None:
                return n
            f.seek(h[3], 1)
            n += 1
