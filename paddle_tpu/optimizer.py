"""Optimizer library: emits backward + update ops into the program.

Parity: reference ``python/paddle/fluid/optimizer.py`` (1363 LoC): base
``Optimizer:39`` (accumulator creation, ``minimize`` = append_backward +
clip/regularize + per-param update ops), SGD:270, Momentum:316, Adagrad:400,
Adam:475, Adamax:622, DecayedAdagrad:749, Adadelta:830, RMSProp:923,
Ftrl:1072, ModelAverage:1209 — TPU-native: optimizer state are persistable
scope vars updated by optimizer ops inside the same jitted step; sharding
the update (the reference's kReduce strategy) is a pjit sharding choice in
``parallel/``, not a different code path.
"""

from collections import defaultdict

from . import unique_name
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import (Parameter, Program, Variable, default_main_program,
                        default_startup_program, program_guard)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "SGD", "Momentum", "Adagrad", "Adam", "Adamax", "DecayedAdagrad",
    "Adadelta", "RMSProp", "Ftrl", "ModelAverage",
    "SGDOptimizer", "MomentumOptimizer", "AdagradOptimizer", "AdamOptimizer",
    "AdamaxOptimizer", "DecayedAdagradOptimizer", "AdadeltaOptimizer",
    "RMSPropOptimizer", "FtrlOptimizer",
]


class Optimizer:
    """Base optimizer (reference optimizer.py:39)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate must be float or Variable")
        self._name = name
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = {}
        # {accum_name: {param_name: accum_var}}
        self._accumulators = defaultdict(dict)
        self.helper = None

    # -- learning rate -----------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(id(program))
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_map[id(program)] = self._learning_rate
            return
        name = unique_name.generate("learning_rate")
        var = program.global_block().create_var(
            name=name, shape=(1,), dtype="float32", persistable=True
        )
        startup = default_startup_program().global_block()
        sv = startup.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True
        )
        ConstantInitializer(float(self._learning_rate))(sv, startup)
        self._learning_rate_map[id(program)] = var

    def _global_learning_rate(self, program=None):
        if program is None:
            program = default_main_program()
        return self._learning_rate_map.get(id(program))

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        base = self._global_learning_rate()
        mult = (param.optimize_attr or {}).get("learning_rate", 1.0)
        if isinstance(mult, Variable):
            # a per-param LR already computed in-graph (append_LARS
            # writes the fully-scaled rate; reference optimizer.py uses
            # it directly)
            return mult
        if mult == 1.0:
            return base
        helper = LayerHelper("param_lr")
        out = helper.create_variable_for_type_inference(dtype=base.dtype)
        helper.append_op(
            type="scale", inputs={"X": [base]}, outputs={"Out": [out]},
            attrs={"scale": float(mult)},
        )
        return out

    # -- accumulators ------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        if shape is None:
            shape = param.shape
        dtype = dtype or param.dtype
        program = default_main_program()
        var_name = unique_name.generate("%s_%s" % (param.name, name))
        var = program.global_block().create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        startup = default_startup_program().global_block()
        sv = startup.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True
        )
        ConstantInitializer(float(fill_value))(sv, startup)
        self._accumulators[name][param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, parameters_and_grads):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    # -- main entry points -------------------------------------------------
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        program = loss.block.program
        block = program.global_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(
            block, [p for p, g in parameters_and_grads if g is not None]
        )
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None:
                continue
            if param_and_grad[0].trainable:
                optimize_ops.append(
                    self._append_optimize_op(block, param_and_grad)
                )
        self._finish_update(block, parameters_and_grads)
        return optimize_ops

    def apply_gradients(self, params_grads, loss, startup_program=None):
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(
            params_grads, self.regularization
        )
        return self._create_optimization_pass(params_grads, loss,
                                              startup_program)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """append_backward + clip + regularize + update ops
        (reference optimizer.py minimize).  Bound to the loss's program via
        program_guard so minimize works outside the guard that built it."""
        from .framework import default_startup_program

        params_grads = append_backward(loss, parameter_list, no_grad_set)
        with program_guard(loss.block.program,
                           startup_program or default_startup_program()):
            optimize_ops = self.apply_gradients(params_grads, loss,
                                                startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type="sgd",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]]},
        )


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity = self._get_accumulator(self._velocity_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type="momentum",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Velocity": [velocity],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]],
                     "VelocityOut": [velocity]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="adagrad",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"
    _beta1_pow_acc_str = "beta1_pow_acc"
    _beta2_pow_acc_str = "beta2_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p,
                                  fill_value=self._beta1, shape=[1])
            self._add_accumulator(self._beta2_pow_acc_str, p,
                                  fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        m1 = self._get_accumulator(self._moment1_acc_str, param_and_grad[0])
        m2 = self._get_accumulator(self._moment2_acc_str, param_and_grad[0])
        b1p = self._get_accumulator(self._beta1_pow_acc_str, param_and_grad[0])
        b2p = self._get_accumulator(self._beta2_pow_acc_str, param_and_grad[0])
        return block.append_op(
            type="adam",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment1": [m1],
                "Moment2": [m2],
                "Beta1Pow": [b1p],
                "Beta2Pow": [b2p],
            },
            outputs={"ParamOut": [param_and_grad[0]], "Moment1Out": [m1],
                     "Moment2Out": [m2]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _finish_update(self, block, parameters_and_grads):
        """Advance beta1^t / beta2^t (reference optimizer.py Adam
        _finish_update appends scale ops)."""
        for p, g in parameters_and_grads:
            if g is None:
                continue
            b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
            b2p = self._get_accumulator(self._beta2_pow_acc_str, p)
            block.append_op(
                type="scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
                attrs={"scale": self._beta1},
            )
            block.append_op(
                type="scale", inputs={"X": [b2p]}, outputs={"Out": [b2p]},
                attrs={"scale": self._beta2},
            )


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"
    _beta1_pow_acc_str = "beta1_pow_acc"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
            self._add_accumulator(self._beta1_pow_acc_str, p,
                                  fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        b1p = self._get_accumulator(self._beta1_pow_acc_str, param_and_grad[0])
        return block.append_op(
            type="adamax",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "LearningRate": [self._create_param_lr(param_and_grad)],
                "Moment": [moment],
                "InfNorm": [inf_norm],
                "Beta1Pow": [b1p],
            },
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment],
                     "InfNormOut": [inf_norm]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon},
        )

    def _finish_update(self, block, parameters_and_grads):
        for p, g in parameters_and_grads:
            if g is None:
                continue
            b1p = self._get_accumulator(self._beta1_pow_acc_str, p)
            block.append_op(
                type="scale", inputs={"X": [b1p]}, outputs={"Out": [b1p]},
                attrs={"scale": self._beta1},
            )


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        return block.append_op(
            type="decayed_adagrad",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [moment],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]], "MomentOut": [moment]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        g_acc = self._get_accumulator(self._avg_squared_grad_acc_str,
                                      param_and_grad[0])
        u_acc = self._get_accumulator(self._avg_squared_update_acc_str,
                                      param_and_grad[0])
        return block.append_op(
            type="adadelta",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "AvgSquaredGrad": [g_acc],
                "AvgSquaredUpdate": [u_acc],
            },
            outputs={"ParamOut": [param_and_grad[0]],
                     "AvgSquaredGradOut": [g_acc],
                     "AvgSquaredUpdateOut": [u_acc]},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"
    _mean_grad_acc_str = "mean_grad"

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)
            self._add_accumulator(self._mean_grad_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        mean_grad_acc = self._get_accumulator(self._mean_grad_acc_str,
                                              param_and_grad[0])
        return block.append_op(
            type="rmsprop",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "Moment": [momentum_acc],
                "MeanSquare": [mean_square_acc],
                "MeanGrad": [mean_grad_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={
                "ParamOut": [param_and_grad[0]],
                "MomentOut": [momentum_acc],
                "MeanSquareOut": [mean_square_acc],
                "MeanGradOut": [mean_grad_acc],
            },
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super().__init__(learning_rate, **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str,
                                            param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type="ftrl",
            inputs={
                "Param": [param_and_grad[0]],
                "Grad": [param_and_grad[1]],
                "SquaredAccumulator": [squared_acc],
                "LinearAccumulator": [linear_acc],
                "LearningRate": [self._create_param_lr(param_and_grad)],
            },
            outputs={"ParamOut": [param_and_grad[0]],
                     "SquaredAccumOut": [squared_acc],
                     "LinearAccumOut": [linear_acc]},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power},
        )


class ModelAverage(Optimizer):
    """Running parameter average for eval (reference optimizer.py:1209),
    driven by the ``average_accumulates`` op (average_accumulates_op.h):
    three staggered sum buffers (precision-guarded roll every 16384
    updates) plus a restartable trailing window, exactly the reference's
    accumulator protocol.  ``apply()`` swaps
    (sum_1+sum_2+sum_3)/(num_accumulates+old_num_accumulates) into the
    scope."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super().__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._avg_sums = {}

    def _ensure_accumulators(self, program):
        block = program.global_block()
        for p in block.all_parameters():
            if p.name in self._avg_sums:
                continue
            sums = (self._add_accumulator("sum_1", p),
                    self._add_accumulator("sum_2", p),
                    self._add_accumulator("sum_3", p))
            counts = (
                self._add_accumulator("num_accumulates", p, shape=[1],
                                      dtype="int64"),
                self._add_accumulator("old_num_accumulates", p, shape=[1],
                                      dtype="int64"),
                self._add_accumulator("num_updates", p, shape=[1],
                                      dtype="int64"),
            )
            self._avg_sums[p.name] = sums + counts
            s1, s2, s3, na, ona, nu = self._avg_sums[p.name]
            block.append_op(
                type="average_accumulates",
                inputs={"param": [p], "in_sum_1": [s1], "in_sum_2": [s2],
                        "in_sum_3": [s3], "in_num_accumulates": [na],
                        "in_old_num_accumulates": [ona],
                        "in_num_updates": [nu]},
                outputs={"out_sum_1": [s1], "out_sum_2": [s2],
                         "out_sum_3": [s3], "out_num_accumulates": [na],
                         "out_old_num_accumulates": [ona],
                         "out_num_updates": [nu]},
                attrs={"average_window": self.average_window,
                       "min_average_window": self.min_average_window,
                       "max_average_window": self.max_average_window},
            )

    def apply(self, executor, scope=None):
        """Swap averaged params into the scope (context manager)."""
        import contextlib

        import numpy as np

        from .scope import global_scope

        scope = scope or global_scope()

        @contextlib.contextmanager
        def _ctx():
            saved = {}
            for name, accs in self._avg_sums.items():
                s1, s2, s3, na, ona, _ = accs
                saved[name] = scope.var(name)
                total = (np.asarray(scope.var(s1.name))
                         + np.asarray(scope.var(s2.name))
                         + np.asarray(scope.var(s3.name)))
                cnt = float(np.asarray(scope.var(na.name))[0]
                            + np.asarray(scope.var(ona.name))[0]) or 1.0
                scope.set_var(name, (total / cnt).astype(total.dtype))
            try:
                yield
            finally:
                for name, v in saved.items():
                    scope.set_var(name, v)

        return _ctx()


# aliases matching the reference's short names (fluid.optimizer.SGD etc.)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
