"""Program graph drawing CLI/API (reference
python/paddle/fluid/net_drawer.py:103 draw_graph — the user-facing
graphviz tool next to debugger.py's lower-level dump)."""

import argparse
import json
import logging

from .debugger import draw_block_graphviz
from .framework import default_main_program, default_startup_program

__all__ = ["draw_graph"]

logger = logging.getLogger(__name__)


def draw_graph(startup_program=None, main_program=None, path="graph.dot",
               startup_path=None, render=False, **kwargs):
    """Write graphviz dot for the main (and optionally startup) program
    (reference net_drawer.py:draw_graph, which emitted Graph objects via
    the graphviz package; here the dot text is written directly and
    optionally rendered when the ``dot`` binary exists)."""
    if main_program is None:
        main_program = default_main_program()
    out = draw_block_graphviz(main_program.global_block(), path=path,
                              render=render)
    if startup_program is not None or startup_path:
        if startup_program is None:
            startup_program = default_startup_program()
        if not startup_path:
            startup_path = path + ".startup.dot"
        draw_block_graphviz(startup_program.global_block(),
                            path=startup_path, render=render)
    return out


def main():
    p = argparse.ArgumentParser(description="draw a saved Program as dot")
    p.add_argument("program", help="JSON ProgramDesc file "
                   "(Program.to_json / save_train_program output)")
    p.add_argument("--output", default="graph.dot")
    p.add_argument("--render", action="store_true")
    args = p.parse_args()
    from .framework import Program

    with open(args.program) as f:
        payload = json.load(f)
    d = payload.get("program") or payload.get("main") or payload
    prog = Program.from_dict(d)
    out = draw_graph(main_program=prog, path=args.output,
                     render=args.render)
    print(out)


if __name__ == "__main__":
    main()
