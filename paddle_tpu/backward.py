"""Program-level autodiff: append gradient ops to a Program.

Capability parity with the reference's ``python/paddle/fluid/backward.py``
(``append_backward:469``, duplicate-grad summation ``_addup_repetitive_
outputs_:135``, no-grad pruning ``_remove_no_grad_branch_:204``) —
TPU-native: per-op grad ops come from the registry's grad makers (most are
the generic vjp-backed ``<type>_grad``; see ``registry.py``), so the grad
section of the program is still ordinary ops that lower into the same jitted
HLO module as the forward.  Gradients remain first-class program variables
(``w@GRAD``) so clipping, regularizers, and the distributed rewrites can
operate on them exactly like the reference does.
"""

from .framework import Parameter, Variable, grad_var_name
from .registry import make_grad_ops

__all__ = ["append_backward", "calc_gradient"]


def _collect_no_grad_set(block, extra=None):
    s = set(extra or ())
    for v in block.vars.values():
        if v.stop_gradient:
            s.add(v.name)
    return s


def _ops_on_path_to(block, target_names):
    """Indices of ops whose outputs (transitively) feed ``target_names``."""
    needed = set(target_names)
    keep = []
    for i in reversed(range(len(block.ops))):
        op = block.ops[i]
        if set(op.output_arg_names) & needed:
            keep.append(i)
            needed.update(n for n in op.input_arg_names if n)
    return set(keep)


class _GradAccumulator:
    """Tracks pending gradient contributions per forward var and
    materializes ``sum`` ops on demand (the reference's
    _addup_repetitive_outputs_ redesigned as lazy accumulation)."""

    def __init__(self, block):
        self.block = block
        self.pending = {}  # fwd var name -> [grad var names]
        self._clipped = set()  # fwd vars whose grad got an error clip

    def new_contribution_name(self, fwd_name):
        cs = self.pending.setdefault(fwd_name, [])
        if not cs:
            name = grad_var_name(fwd_name)
        else:
            name = grad_var_name(fwd_name) + "@RENAME@%d" % len(cs)
        cs.append(name)
        return name

    def has_grad(self, fwd_name):
        return bool(self.pending.get(fwd_name))

    def materialize(self, fwd_name):
        """Ensure grad_var_name(fwd_name) holds the summed gradient;
        returns the name or None if no grad flows."""
        cs = self.pending.get(fwd_name)
        if not cs:
            return None
        target = grad_var_name(fwd_name)
        if len(cs) == 1:
            if cs[0] != target:
                # single renamed contribution: alias via assign
                self.block.append_op(
                    type="assign", inputs={"X": [cs[0]]}, outputs={"Out": [target]}
                )
                self._propagate_sparse_type(cs, target)
            self.pending[fwd_name] = [target]
            self._maybe_error_clip(fwd_name, target)
            return target
        self.block.append_op(
            type="sum", inputs={"X": list(cs)}, outputs={"Out": [target]}
        )
        self._propagate_sparse_type(cs, target)
        self.pending[fwd_name] = [target]
        self._maybe_error_clip(fwd_name, target)
        return target

    def _propagate_sparse_type(self, contributions, target):
        """A sum/alias of only SELECTED_ROWS contributions is itself a
        SELECTED_ROWS value (the sum kernel concatenates row lists), so
        the summed grad var keeps the type for build-time consumers
        (clip/regularizer sparse paths)."""
        from .core import VarType

        if all(getattr(self.block._find_var_recursive(c), "type", None)
               == VarType.SELECTED_ROWS for c in contributions):
            v = self.block._find_var_recursive(target)
            if v is not None:
                v.type = VarType.SELECTED_ROWS

    def _maybe_error_clip(self, fwd_name, grad_name):
        """Apply the forward var's ``error_clip`` to its summed gradient,
        once, before any consumer reads it (the reference applies
        error_clip_callback to every appended grad op,
        backward.py:469 callbacks=[error_clip_callback])."""
        if fwd_name in self._clipped:
            return
        self._clipped.add(fwd_name)
        fwd_var = self.block._find_var_recursive(fwd_name)
        error_clip = getattr(fwd_var, "error_clip", None) if fwd_var \
            else None
        if error_clip is not None:
            error_clip._append_clip_op(self.block, grad_name)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    loss_grad_input=None):
    """Append gradient ops for ``loss`` to its program; returns
    [(Parameter, grad Variable)] for the optimizer (reference
    backward.py:469).  ``loss_grad_input`` optionally seeds the cotangent
    with an existing Variable instead of ones (calc_gradient's
    target_gradients)."""
    assert isinstance(loss, Variable), "loss must be a Variable"
    block = loss.block
    program = block.program
    no_grad = _collect_no_grad_set(block, no_grad_set)

    # seed d(loss)/d(loss)
    loss_grad = grad_var_name(loss.name)
    if loss_grad_input is not None:
        block.append_op(
            type="assign",
            inputs={"X": [loss_grad_input]},
            outputs={"Out": [loss_grad]},
        )
    else:
        block.append_op(
            type="fill_constant",
            inputs={},
            outputs={"Out": [loss_grad]},
            attrs={
                "shape": list(loss.shape or ()),
                "value": 1.0,
                "dtype": str(loss.dtype),
                "force_cpu": False,
            },
        )

    acc = _GradAccumulator(block)
    acc.pending[loss.name] = [loss_grad]

    path = _ops_on_path_to(block, [loss.name])
    # exclude the fill op we just appended
    n_forward = len(block.ops) - 1

    for i in reversed(range(n_forward)):
        if i not in path:
            continue
        op = block.ops[i]
        # does any output have a live gradient?
        live = [n for n in op.output_arg_names if acc.has_grad(n)]
        if not live:
            continue
        specs = make_grad_ops(op, no_grad)
        appended_any = False
        consumed = {}  # fwd name -> the materialized grad name this op read
        for spec in specs:
            # record the forward op's position so generic grad recompute
            # folds the SAME PRNG key the forward used (registry.py
            # _generic_grad_compute)
            if spec["type"].endswith("_grad"):
                spec["attrs"].setdefault("__fwd_op_index__", i)
            # wire out-grad inputs: materialize sums / leave holes
            for slot, names in list(spec["inputs"].items()):
                if not slot.startswith("GRAD::"):
                    continue
                wired = []
                for n in names:
                    fwd = n[: -len("@GRAD")] if n.endswith("@GRAD") else n
                    g = acc.materialize(fwd)
                    if g is not None:
                        consumed[fwd] = g
                    wired.append(g or "")
                spec["inputs"][slot] = wired
            # rename duplicate grad outputs into fresh contribution names
            for slot, names in list(spec["outputs"].items()):
                renamed = []
                for n in names:
                    if not n:
                        renamed.append("")
                        continue
                    fwd = n[: -len("@GRAD")]
                    if fwd in no_grad:
                        renamed.append("")
                        continue
                    renamed.append(acc.new_contribution_name(fwd))
                spec["outputs"][slot] = renamed
            if not any(n for ns in spec["outputs"].values() for n in ns):
                continue
            block.append_op(
                type=spec["type"],
                inputs=spec["inputs"],
                outputs=spec["outputs"],
                attrs=spec["attrs"],
            )
            appended_any = True
        # drop exactly the cotangent contributions this op's grad ops
        # CONSUMED (recorded at wiring time), so an EARLIER producer of
        # the same name (in-place aliasing: the while op's Out carries,
        # array_write chains) cannot re-consume an already-routed
        # gradient and double-count.  Contributions the grad ops just
        # ADDED under the same name — the grad of an in-place *input*
        # (the reference handles these via grad renaming on its SSA
        # versions) — survive for the earlier producer, INCLUDING the
        # case where they landed under the bare @GRAD name because the
        # aliased output itself had no downstream cotangent.  Tracking
        # consumption explicitly (not by name) is what makes those two
        # cases distinguishable.
        if appended_any:
            for n in op.output_arg_names:
                if not (n and acc.pending.get(n)):
                    continue
                g = consumed.get(n)
                if g is not None:
                    acc.pending[n] = [c for c in acc.pending[n]
                                      if c != g]

    # materialize every accumulated gradient so var@GRAD is always the
    # summed value (fetchable, optimizer-consumable)
    for fwd_name in list(acc.pending.keys()):
        acc.materialize(fwd_name)

    # finalize parameter gradients
    if parameter_list is not None:
        params = []
        for p in parameter_list:
            params.append(block.var_recursive(p) if isinstance(p, str) else p)
    else:
        params = [
            p for p in program.global_block().all_parameters() if p.trainable
        ]

    params_and_grads = []
    for p in params:
        g = acc.materialize(p.name)
        if g is None:
            continue
        params_and_grads.append((p, block.var_recursive(g)))
    return params_and_grads


def calc_gradient(targets, inputs, target_gradients=None, no_grad_set=None):
    """Gradients of ``targets`` w.r.t. ``inputs`` (reference
    backward.py:calc_gradient).  Returns list of grad Variables (or None).

    Multiple targets compose into the scalar sum_i <target_i, tg_i>
    (tg_i defaulting to ones), whose gradient w.r.t. each input is
    exactly the requested vjp — one backward walk serves every target,
    like the reference's multi-target support."""
    if isinstance(targets, Variable):
        targets = [targets]
    if isinstance(inputs, Variable):
        inputs = [inputs]
    if target_gradients is not None and isinstance(target_gradients,
                                                   Variable):
        target_gradients = [target_gradients]
    if target_gradients is not None and \
            len(target_gradients) != len(targets):
        raise ValueError(
            "target_gradients must match targets (%d vs %d)"
            % (len(target_gradients), len(targets)))
    block = targets[0].block

    if len(targets) == 1:
        loss = targets[0]
        loss_grad_input = target_gradients[0] if target_gradients else None
    else:
        from . import unique_name

        parts = []
        for i, t in enumerate(targets):
            tg = target_gradients[i] if target_gradients else None
            val = t
            if tg is not None:
                prod = block.create_var(
                    name=unique_name.generate("calc_grad_prod"))
                block.append_op(type="elementwise_mul",
                                inputs={"X": [t.name], "Y": [tg.name]},
                                outputs={"Out": [prod.name]}, attrs={})
                val = prod
            part = block.create_var(
                name=unique_name.generate("calc_grad_part"))
            block.append_op(type="reduce_sum",
                            inputs={"X": [val.name]},
                            outputs={"Out": [part.name]},
                            attrs={"reduce_all": True, "keep_dim": False})
            parts.append(part.name)
        loss = block.create_var(
            name=unique_name.generate("calc_grad_total"))
        block.append_op(type="sum", inputs={"X": parts},
                        outputs={"Out": [loss.name]}, attrs={})
        loss_grad_input = None
    # reuse append_backward machinery but finalize for `inputs`
    pg = append_backward(loss, parameter_list=None, no_grad_set=no_grad_set,
                         loss_grad_input=loss_grad_input)
    del pg
    result = []
    for v in inputs:
        g = grad_var_name(v.name)
        result.append(block.vars.get(g))
    return result
