"""Profiler: host spans + device (XLA) tracing with chrome-trace export.

Parity: reference ``platform/profiler.{h,cc}`` (RecordEvent spans wrapping
every op run), ``platform/device_tracer`` (CUPTI kernel timestamps),
``tools/timeline.py`` (chrome://tracing export), and the Python context
managers ``fluid/profiler.py:221`` — TPU-native: device-side tracing
delegates to ``jax.profiler`` (XPlane/TensorBoard), host-side named spans
are collected here and exported as chrome-trace JSON directly.
"""

import contextlib
import json
import os
import threading
import time

__all__ = [
    "RecordEvent", "record_event", "mark_event", "profiler",
    "start_profiler", "stop_profiler", "reset_profiler",
    "export_chrome_tracing", "cuda_profiler", "npu_profiler",
]

_state = threading.local()
_events = []
_events_lock = threading.Lock()
_enabled = [False]
_jax_trace_dir = [None]


def _now_us():
    return time.perf_counter_ns() / 1000.0


class RecordEvent:
    """RAII span (reference profiler.h:89 RecordEvent)."""

    def __init__(self, name):
        self.name = name
        self.t0 = None

    def __enter__(self):
        self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if not _enabled[0]:
            return False
        t1 = _now_us()
        with _events_lock:
            _events.append({
                "name": self.name,
                "ts": self.t0,
                "dur": t1 - self.t0,
                "ph": "X",
                "pid": os.getpid(),
                "tid": threading.get_ident(),
            })
        return False


record_event = RecordEvent


def mark_event(name):
    """Instantaneous event (zero-duration span): cache hits/misses and
    other point occurrences, countable in the summary and visible in the
    chrome trace next to the ``RecordEvent`` spans."""
    if not _enabled[0]:
        return
    with _events_lock:
        _events.append({
            "name": name,
            "ts": _now_us(),
            "dur": 0.0,
            "ph": "X",
            "pid": os.getpid(),
            "tid": threading.get_ident(),
        })


def start_profiler(state="All", trace_dir=None):
    """state ∈ {CPU, GPU, All} for parity; device tracing uses
    jax.profiler when a trace_dir is given."""
    _enabled[0] = True
    if trace_dir and state in ("GPU", "All"):
        import jax

        _jax_trace_dir[0] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    _enabled[0] = False
    if _jax_trace_dir[0]:
        import jax

        jax.profiler.stop_trace()
        _jax_trace_dir[0] = None
    if profile_path:
        export_chrome_tracing(profile_path)
    _print_summary(sorted_key)


def reset_profiler():
    with _events_lock:
        _events.clear()


def export_chrome_tracing(path):
    """Write collected host spans as chrome://tracing JSON
    (tools/timeline.py parity)."""
    with _events_lock:
        events = list(_events)
    payload = {"traceEvents": events, "displayTimeUnit": "ms"}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def _print_summary(sorted_key=None):
    with _events_lock:
        events = list(_events)
    if not events:
        return
    totals = {}
    for e in events:
        t = totals.setdefault(e["name"], [0.0, 0, 0.0])
        t[0] += e["dur"]
        t[1] += 1
        t[2] = max(t[2], e["dur"])
    rows = [
        (name, tot / 1000.0, cnt, tot / cnt / 1000.0, mx / 1000.0)
        for name, (tot, cnt, mx) in totals.items()
    ]
    key = {"total": 1, "calls": 2, "ave": 3, "max": 4}.get(sorted_key, 1)
    rows.sort(key=lambda r: r[key], reverse=True)
    print("%-40s %12s %8s %12s %12s" % ("Event", "total(ms)", "calls",
                                        "avg(ms)", "max(ms)"))
    for name, tot, cnt, avg, mx in rows[:50]:
        print("%-40s %12.3f %8d %12.3f %12.3f" % (name, tot, cnt, avg, mx))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             trace_dir=None):
    """Context manager parity with fluid.profiler.profiler (profiler.py:221)."""
    reset_profiler()
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Reference nvprof hook (profiler.py:39); on TPU this aliases to the
    jax trace-based profiler."""
    with profiler():
        yield


npu_profiler = cuda_profiler
