"""Profiler: host spans + device (XLA) tracing with chrome-trace export.

Parity: reference ``platform/profiler.{h,cc}`` (RecordEvent spans wrapping
every op run), ``platform/device_tracer`` (CUPTI kernel timestamps),
``tools/timeline.py`` (chrome://tracing export), and the Python context
managers ``fluid/profiler.py:221`` — TPU-native: device-side tracing
delegates to ``jax.profiler`` (XPlane/TensorBoard), host-side named spans
are collected here and exported as chrome-trace JSON directly.
"""

import contextlib
import json
import os
import threading
import time

from . import monitor

__all__ = [
    "RecordEvent", "record_event", "mark_event", "profiler",
    "start_profiler", "stop_profiler", "reset_profiler", "is_profiling",
    "export_chrome_tracing", "summarize_events", "cuda_profiler",
    "npu_profiler",
]

_state = threading.local()
_events = []
_events_lock = threading.Lock()
_enabled = [False]
_jax_trace_dir = [None]
# tid -> thread name at the time the thread last emitted an event, for
# the chrome-trace M-phase thread_name metadata (dispatch/prefetch
# worker threads are labeled in the timeline instead of raw tids)
_thread_names = {}


def _now_us():
    return time.perf_counter_ns() / 1000.0


def _append_event(name, ts, dur, args=None):
    tid = threading.get_ident()
    ev = {
        "name": name,
        "ts": ts,
        "dur": dur,
        "ph": "X",
        "pid": os.getpid(),
        "tid": tid,
    }
    if args:
        ev["args"] = args
    with _events_lock:
        _thread_names[tid] = threading.current_thread().name
        _events.append(ev)


def is_profiling():
    """True while a profiler session is active (the executors use this
    to decide whether span correlation args are worth computing)."""
    return _enabled[0]


class RecordEvent:
    """RAII span (reference profiler.h:89 RecordEvent).

    ``__enter__`` LATCHES the profiler/monitor enabled states: a span
    that straddles ``stop_profiler`` is kept (it was started under the
    session and measures real work of it), a span started while both are
    disabled skips timing entirely — ``__exit__`` never re-decides
    post-hoc.  Completed spans double-publish into the monitor's
    ``span/<name>`` histograms whenever the monitor is on, so the two
    observability layers agree with or without a profiler session.
    """

    def __init__(self, name, args=None):
        """``args`` (optional dict) lands in the chrome-trace event's
        ``args`` field — the executors tag their dispatch/compile spans
        with ``{run_id, fingerprint, step}`` so the trace, the JSONL
        log, and /metrics can be correlated per program."""
        self.name = name
        self.args = args
        self.t0 = None
        self._prof = False
        self._mon = False

    def __enter__(self):
        self._prof = _enabled[0]
        self._mon = monitor.enabled()
        if self._prof or self._mon:
            self.t0 = _now_us()
        return self

    def __exit__(self, *exc):
        if self.t0 is None:
            return False
        dur = _now_us() - self.t0
        if self._prof:
            _append_event(self.name, self.t0, dur, self.args)
        if self._mon:
            # args ride along so the goodput ledger sees the producer's
            # bucket hint (executors tag their cold/warm step spans)
            monitor.observe_span(self.name, dur, self.args)
        self.t0 = None
        return False


record_event = RecordEvent


def mark_event(name):
    """Instantaneous event (zero-duration span): cache hits/misses and
    other point occurrences, countable in the summary and visible in the
    chrome trace next to the ``RecordEvent`` spans.  Double-publishes as
    a ``mark/<name>`` monitor counter when the monitor is on."""
    if monitor.enabled():
        monitor.mark(name)
    if not _enabled[0]:
        return
    _append_event(name, _now_us(), 0.0)


def start_profiler(state="All", trace_dir=None):
    """state ∈ {CPU, GPU, All} for parity; device tracing uses
    jax.profiler when a trace_dir is given."""
    _enabled[0] = True
    if trace_dir and state in ("GPU", "All"):
        import jax

        _jax_trace_dir[0] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    _enabled[0] = False
    if _jax_trace_dir[0]:
        import jax

        jax.profiler.stop_trace()
        _jax_trace_dir[0] = None
    if profile_path:
        export_chrome_tracing(profile_path)
    _print_summary(sorted_key)


def reset_profiler():
    with _events_lock:
        _events.clear()


def export_chrome_tracing(path):
    """Write collected host spans as chrome://tracing JSON
    (tools/timeline.py parity).  M-phase metadata events label the
    process and every emitting thread (main loop, prefetch producers,
    monitor threads) so the timeline shows names instead of raw tids."""
    with _events_lock:
        events = list(_events)
        tnames = dict(_thread_names)
    pids = sorted({e["pid"] for e in events})
    # the run correlation id rides in the process metadata AND the
    # top-level metadata dict, matching the run_id each JSONL record and
    # the /metrics exposition carry — one id across all three sinks
    meta = [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": "paddle_tpu",
                      "run_id": monitor.run_id()}} for pid in pids]
    for (pid, tid) in sorted({(e["pid"], e["tid"]) for e in events}):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid,
                     "args": {"name": tnames.get(tid, "tid-%d" % tid)}})
    trace_meta = {"run_id": monitor.run_id()}
    gp = monitor.goodput_ledger()
    if gp.steps:
        # the run's wall-clock attribution rides in the trace metadata,
        # so a shipped trace carries its own goodput summary alongside
        # the spans it was derived from
        trace_meta["goodput"] = gp.summary()
    # request lanes (ISSUE 17): buffered trace spans render one lane
    # per request under a 'serving requests' process group — same
    # perf_counter timebase as the host spans, so the exported file
    # opens in Perfetto with requests aligned against the dispatches
    # that served them
    try:
        tr_events, tr_meta = monitor.tracing.chrome_events()
    except Exception:  # noqa: BLE001 — export never fails on telemetry
        tr_events, tr_meta = [], []
    payload = {"traceEvents": meta + tr_meta + events + tr_events,
               "displayTimeUnit": "ms", "metadata": trace_meta}
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)
    return path


def summarize_events(events, sorted_key=None, top=50):
    """Per-name total/calls/avg/max table over chrome-trace events (the
    ``X``-phase ones; ``dur`` in microseconds).  Shared by the live
    ``stop_profiler`` summary and the offline ``tools/trace_summary.py``
    CLI, so both print the identical format.  ``top`` caps the row
    count.  Tolerates foreign traces: events missing ``dur`` (counter/
    instant events re-exported as X) count as zero-duration."""
    totals = {}
    for e in events:
        if not isinstance(e, dict) or e.get("ph", "X") != "X" \
                or "name" not in e:
            continue
        dur = e.get("dur", 0.0) or 0.0
        t = totals.setdefault(e["name"], [0.0, 0, 0.0])
        t[0] += dur
        t[1] += 1
        t[2] = max(t[2], dur)
    rows = [
        (name, tot / 1000.0, cnt, tot / cnt / 1000.0, mx / 1000.0)
        for name, (tot, cnt, mx) in totals.items()
    ]
    key = {"total": 1, "calls": 2, "ave": 3, "max": 4}.get(sorted_key, 1)
    rows.sort(key=lambda r: r[key], reverse=True)
    lines = ["%-40s %12s %8s %12s %12s" % ("Event", "total(ms)", "calls",
                                           "avg(ms)", "max(ms)")]
    for name, tot, cnt, avg, mx in rows[:top]:
        lines.append("%-40s %12.3f %8d %12.3f %12.3f"
                     % (name, tot, cnt, avg, mx))
    return "\n".join(lines)


def _print_summary(sorted_key=None):
    with _events_lock:
        events = list(_events)
    if not events:
        return
    print(summarize_events(events, sorted_key))


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             trace_dir=None):
    """Context manager parity with fluid.profiler.profiler (profiler.py:221)."""
    reset_profiler()
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    """Reference nvprof hook (profiler.py:39); on TPU this aliases to the
    jax trace-based profiler."""
    with profiler():
        yield


npu_profiler = cuda_profiler
