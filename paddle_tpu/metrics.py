"""Host-side metric accumulators.

Parity: reference ``python/paddle/fluid/metrics.py`` (MetricBase,
CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator, EditDistance,
DetectionMAP, Auc) — numpy accumulation across minibatches on the host,
fed from fetched step metrics.
"""

import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "EditDistance", "Auc", "DetectionMAP", "ChunkEvaluator",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted streaming accuracy (feed per-batch acc + batch size)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / self.weight if self.weight else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        avg = self.total_distance / self.seq_num if self.seq_num else 0.0
        err = self.instance_error / self.seq_num if self.seq_num else 0.0
        return avg, err


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        bins = num_thresholds + 1
        self._stat_pos = np.zeros(bins, dtype=np.int64)
        self._stat_neg = np.zeros(bins, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        nb = len(self._stat_pos)
        idx = np.clip((p * (nb - 1)).astype(np.int64), 0, nb - 1)
        for i, lab in zip(idx, labels):
            if lab > 0:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos = max(tp[-1], 1)
        tot_neg = max(fp[-1], 1)
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))


class DetectionMAP(MetricBase):
    """Streaming mean-average-precision for detection (reference
    fluid/metrics.py DetectionMAP / detection_map_op.cc) — host-side
    accumulation (mAP evaluation has no MXU work; keeping it off-graph
    is the TPU-appropriate split).

    update(detections, gt_boxes, gt_labels): detections [N, 6]
    (label, score, x1, y1, x2, y2) from multiclass_nms; gt per image.
    eval() returns mAP over accumulated images (11-point or integral).
    """

    def __init__(self, name=None, overlap_threshold=0.5,
                 ap_version="integral", class_num=None):
        super().__init__(name)
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self._scores = {}   # class -> list of (score, is_tp)
        self._n_gt = {}     # class -> gt count

    @staticmethod
    def _iou(a, b):
        ix1, iy1 = max(a[0], b[0]), max(a[1], b[1])
        ix2, iy2 = min(a[2], b[2]), min(a[3], b[3])
        inter = max(ix2 - ix1, 0.0) * max(iy2 - iy1, 0.0)
        ua = ((a[2] - a[0]) * (a[3] - a[1]) +
              (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    def reset(self):
        """Clear accumulated detections/counts; thresholds are config,
        not state (MetricBase.reset would zero them)."""
        self._scores = {}
        self._n_gt = {}

    def update(self, detections, gt_boxes, gt_labels):
        detections = np.asarray(detections, dtype=np.float64)
        gt_boxes = np.asarray(gt_boxes, dtype=np.float64)
        gt_labels = np.asarray(gt_labels).reshape(-1)
        for c in np.unique(gt_labels):
            self._n_gt[int(c)] = self._n_gt.get(int(c), 0) + \
                int(np.sum(gt_labels == c))
        used = np.zeros(len(gt_boxes), bool)
        order = np.argsort(-detections[:, 1]) if len(detections) else []
        for i in order:
            lbl, score = int(detections[i, 0]), detections[i, 1]
            if lbl < 0:
                continue
            # dets clip to [0, 1] before overlap; the best gt is found
            # over ALL gts of the class (used or not) and a used best is
            # an FP — exactly detection_map_op.h CalcTrueAndFalsePositive
            box = np.clip(detections[i, 2:6], 0.0, 1.0)
            best, best_j = -1.0, -1
            for j, (gb, gl) in enumerate(zip(gt_boxes, gt_labels)):
                if int(gl) != lbl:
                    continue
                ov = self._iou(box, gb)
                if ov > best:
                    best, best_j = ov, j
            tp = best > self.overlap_threshold and not used[best_j]
            if tp:
                used[best_j] = True
            self._scores.setdefault(lbl, []).append((score, tp))

    def eval(self):
        aps = []
        for c, n_gt in self._n_gt.items():
            recs = sorted(self._scores.get(c, []), reverse=True)
            if not recs or n_gt == 0:
                # classes with no detections are skipped, not zeroed
                # (detection_map_op.h CalcMAP true_pos.find == end)
                continue
            tps = np.cumsum([1.0 if t else 0.0 for _, t in recs])
            fps = np.cumsum([0.0 if t else 1.0 for _, t in recs])
            recall = tps / n_gt
            precision = tps / np.maximum(tps + fps, 1e-12)
            if self.ap_version == "11point":
                ap = np.mean([
                    np.max(precision[recall >= t], initial=0.0)
                    for t in np.linspace(0, 1, 11)])
            else:  # integral
                ap = 0.0
                prev_r = 0.0
                for r, p in zip(recall, precision):
                    ap += (r - prev_r) * p
                    prev_r = r
            aps.append(float(ap))
        return float(np.mean(aps)) if aps else 0.0


class ChunkEvaluator(MetricBase):
    """Accumulate chunk_eval counters across mini-batches; eval returns
    (precision, recall, f1) (reference metrics.py:355)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        for label, v in (("num_infer_chunks", num_infer_chunks),
                         ("num_label_chunks", num_label_chunks),
                         ("num_correct_chunks", num_correct_chunks)):
            if not isinstance(v, (int, float, np.ndarray, np.generic)):
                raise ValueError(
                    "%s must be a number or numpy ndarray" % label)
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = float(self.num_correct_chunks) / \
            self.num_infer_chunks if self.num_infer_chunks else 0.0
        recall = float(self.num_correct_chunks) / \
            self.num_label_chunks if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.0
        return precision, recall, f1
