"""Host-side metric accumulators.

Parity: reference ``python/paddle/fluid/metrics.py`` (MetricBase,
CompositeMetric, Precision, Recall, Accuracy, ChunkEvaluator, EditDistance,
DetectionMAP, Auc) — numpy accumulation across minibatches on the host,
fed from fetched step metrics.
"""

import numpy as np

__all__ = [
    "MetricBase", "CompositeMetric", "Precision", "Recall", "Accuracy",
    "EditDistance", "Auc",
]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        for k, v in self.__dict__.items():
            if k.startswith("_"):
                continue
            if isinstance(v, (int, float)):
                setattr(self, k, 0)
            elif isinstance(v, np.ndarray):
                setattr(self, k, np.zeros_like(v))

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return float(self.tp) / denom if denom else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return float(self.tp) / denom if denom else 0.0


class Accuracy(MetricBase):
    """Weighted streaming accuracy (feed per-batch acc + batch size)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        return self.value / self.weight if self.weight else 0.0


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances).reshape(-1)
        self.total_distance += float(np.sum(distances))
        self.seq_num += int(seq_num)
        self.instance_error += int(np.sum(distances > 0))

    def eval(self):
        avg = self.total_distance / self.seq_num if self.seq_num else 0.0
        err = self.instance_error / self.seq_num if self.seq_num else 0.0
        return avg, err


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        bins = num_thresholds + 1
        self._stat_pos = np.zeros(bins, dtype=np.int64)
        self._stat_neg = np.zeros(bins, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        p = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        nb = len(self._stat_pos)
        idx = np.clip((p * (nb - 1)).astype(np.int64), 0, nb - 1)
        for i, lab in zip(idx, labels):
            if lab > 0:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def eval(self):
        tp = np.cumsum(self._stat_pos[::-1])
        fp = np.cumsum(self._stat_neg[::-1])
        tot_pos = max(tp[-1], 1)
        tot_neg = max(fp[-1], 1)
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr))
