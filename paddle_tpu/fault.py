"""Deterministic fault-injection harness (ISSUE 8 tentpole, part 2).

Generalizes the private ``_FAULT_HOOKS`` dict that the elastic-training
drill used to reach into ``parallel/checkpoint.py`` into a first-class,
reusable registry: named **injection points** fire at well-defined
moments of the runtime (executor feed staging, dispatch, step
completion, checkpoint write protocol), and **schedules** decide
deterministically — as a pure function of the step index (plus an
optional seed) — whether a registered fault fires there.  Two runs with
the same schedule inject at identical points, which is what makes a
fault drill a *regression test* instead of a flaky chaos experiment
(the same reasoning that turned the load-based elastic drill into the
step-indexed kill -9 drill in PR 4).

Injection points wired into the runtime (``fire`` is a no-op costing
one module-global bool read when nothing is registered):

==========================  ================================================
point                       context / when
==========================  ================================================
``executor/feed``           after feed coercion, before h2d staging; ctx
                            ``feed_names`` + mutable ``feed_vals`` list
                            (poison a batch here)
``executor/dispatch``       immediately before the step function is
                            dispatched (delay / fail a dispatch here)
``executor/step_done``      after the step's state writeback; ctx
                            ``scope``, ``state_names``, ``fetch_names`` +
                            mutable ``fetches`` list (inject NaN into a
                            named var here)
``checkpoint/before_write`` start of the TrainState write protocol
``checkpoint/after_write``  payload written, manifest not yet
``checkpoint/before_commit`` manifest written, commit rename not yet
                            (kill here => torn ``.tmp`` artifact)
==========================  ================================================

Both executors fire the ``executor/*`` points with their 0-based run
counter as ``step``; the checkpoint points fire with the artifact's
step index.  Drill families (``inject_nan``, ``poison_batch``,
``delay_dispatch``, ``fail_dispatch``, ``kill_mid_save``) are helpers
over ``register``; drills are also installable with no code via
``FLAGS_fault_spec`` (see ``install_from_spec``), so a fault drill can
ride any existing entry point through the environment.

Every firing is recorded in the in-process injection log
(``injections()``), counted in the ``fault/injections`` monitor counter
and logged as a ``fault_injected`` JSONL event (run_id-stamped) when
the monitor is on — the guardian's recovery records correlate with the
injection that caused them.
"""

import hashlib
import os
import signal as _signal
import threading
import time

import numpy as np

from . import flags

__all__ = [
    "FaultSchedule", "FaultInjectedError",
    "register", "unregister", "clear", "active", "fire", "hooks",
    "injections", "clear_injections",
    "inject_nan", "poison_batch", "delay_dispatch", "fail_dispatch",
    "kill_mid_save", "install_from_spec",
]


class FaultInjectedError(RuntimeError):
    """Raised by the ``fail_dispatch`` drill family: a deliberately
    injected dispatch failure (distinct from any real error so tests
    and recovery policies can tell the drill from the disease)."""


def _unit_hash(seed, step):
    """Deterministic uniform [0, 1) from (seed, step) — the schedule's
    probabilistic form must be a pure function of its indices, never of
    process RNG state."""
    h = hashlib.sha256(b"%d:%d" % (int(seed), int(step))).digest()
    return int.from_bytes(h[:8], "big") / float(1 << 64)


class FaultSchedule:
    """When a fault fires, as a pure function of the step index.

    Three composable forms (a step fires if ANY matches):

    * ``steps`` — an explicit collection of step indices;
    * ``every``/``start`` — periodic: every ``every``-th step from
      ``start`` on;
    * ``prob``/``seed`` — pseudo-random: step ``s`` fires iff
      ``hash(seed, s) < prob``; the hash is a pure function of
      ``(seed, step)``, so two runs with the same seed fire at
      identical steps (seed/step-indexed determinism, test-enforced).

    The schedule object holds no runtime state — ``fires(step)`` is
    referentially transparent.  One-shot semantics (a transient fault
    that must not re-fire when rolled-back steps replay) live on the
    registered hook (``register(once=True)``), not here.
    """

    def __init__(self, steps=(), every=0, start=0, prob=0.0, seed=None):
        self.steps = frozenset(int(s) for s in steps)
        self.every = int(every)
        self.start = int(start)
        self.prob = float(prob)
        self.seed = int(flags.flag("fault_seed") if seed is None else seed)
        if self.prob < 0 or self.prob > 1:
            raise ValueError("prob must be in [0, 1], got %r" % prob)
        if not self.steps and not self.every and not self.prob:
            raise ValueError(
                "empty FaultSchedule would never fire: give steps=, "
                "every=, or prob=")

    def fires(self, step):
        step = int(step)
        if step in self.steps:
            return True
        if self.every > 0 and step >= self.start \
                and (step - self.start) % self.every == 0:
            return True
        if self.prob > 0 and _unit_hash(self.seed, step) < self.prob:
            return True
        return False

    def __repr__(self):
        parts = []
        if self.steps:
            parts.append("steps=%s" % sorted(self.steps))
        if self.every:
            parts.append("every=%d from %d" % (self.every, self.start))
        if self.prob:
            parts.append("prob=%g seed=%d" % (self.prob, self.seed))
        return "FaultSchedule(%s)" % ", ".join(parts)


class _Hook:
    def __init__(self, point, fn, schedule, name, once):
        self.point = point
        self.fn = fn
        self.schedule = schedule
        self.name = name
        self.once = bool(once)
        self.spent = False      # once-hooks disarm after their first firing

    def __repr__(self):
        return "<fault hook %r at %r %s%s>" % (
            self.name, self.point, self.schedule,
            " (spent)" if self.spent else "")


_mu = threading.Lock()
_REGISTRY = {}                  # point -> [_Hook]
_SPEC_HOOKS = []                # hooks installed by the latest fault_spec
# the fast-path gate: executors read this one module-global bool per
# step when no faults are registered (the disabled-is-free contract,
# same shape as monitor._enabled)
_ACTIVE = False
# in-process injection log [(point, step, name)] — the determinism
# test's ground truth: two runs with the same schedules produce
# identical logs
_LOG = []


def active():
    """True iff any fault hook is registered (module-global bool)."""
    return _ACTIVE


def hooks(point=None):
    """Registered hooks, optionally filtered by point (diagnostics)."""
    with _mu:
        if point is not None:
            return list(_REGISTRY.get(point, ()))
        return [h for hs in _REGISTRY.values() for h in hs]


def register(point, fn, schedule, name=None, once=False):
    """Register ``fn(step, **ctx)`` to run at ``point`` whenever
    ``schedule.fires(step)``.  ``once=True`` disarms the hook after its
    first firing — the transient-fault form: a rolled-back-and-replayed
    step does not re-trip it (replay would otherwise detect->recover->
    re-inject forever; the budget-exhausted abort is tested separately
    with a persistent hook).  Returns the hook handle for
    ``unregister``."""
    global _ACTIVE
    if not isinstance(schedule, FaultSchedule):
        raise TypeError("schedule must be a FaultSchedule, got %r"
                        % type(schedule).__name__)
    h = _Hook(point, fn, schedule, name or getattr(fn, "__name__", point),
              once)
    with _mu:
        _REGISTRY.setdefault(point, []).append(h)
        _ACTIVE = True
    return h


def unregister(hook):
    global _ACTIVE
    with _mu:
        hs = _REGISTRY.get(hook.point, [])
        if hook in hs:
            hs.remove(hook)
        if not hs:
            _REGISTRY.pop(hook.point, None)
        _ACTIVE = any(_REGISTRY.values())


def clear():
    """Remove every registered fault hook (tests; drill teardown)."""
    global _ACTIVE
    with _mu:
        _REGISTRY.clear()
        del _SPEC_HOOKS[:]
        _ACTIVE = False


def injections():
    """The injection log: [(point, step, hook name)] in firing order."""
    with _mu:
        return list(_LOG)


def clear_injections():
    with _mu:
        del _LOG[:]


def fire(point, step, **ctx):
    """Run every armed hook registered at ``point`` whose schedule fires
    at ``step``.  Near-free when nothing is registered (one bool read —
    callers may also pre-check ``active()``).  Hook exceptions
    propagate: a drill that raises (fail_dispatch) is *supposed* to
    surface in the training loop."""
    if not _ACTIVE:
        return
    with _mu:
        hs = list(_REGISTRY.get(point, ()))
    for h in hs:
        if h.spent or not h.schedule.fires(step):
            continue
        # record + disarm BEFORE running: kill_mid_save/fail_dispatch
        # never return, and a replayed once-fault must stay disarmed
        # even when its firing raised.  The flip side of this ordering
        # is a contract on hooks: a hook that cannot inject (misaimed
        # drill) must RAISE, never silently no-op — otherwise the log
        # would claim an injection that never happened.
        if h.once:
            h.spent = True
        with _mu:
            _LOG.append((point, int(step), h.name))
        _note_injection(point, step, h.name)
        h.fn(step, **ctx)


def _note_injection(point, step, name):
    from . import monitor

    monitor.count("fault/injections")
    if monitor.enabled():
        monitor.log_event({"event": "fault_injected", "ts": time.time(),
                           "point": point, "step": int(step),
                           "fault": name})


# ---------------------------------------------------------------------------
# drill families
# ---------------------------------------------------------------------------

def _floatish(dtype):
    """True for any float dtype incl. ml_dtypes (bfloat16, float8_*),
    which ``np.issubdtype(_, np.floating)`` misses."""
    return np.issubdtype(dtype, np.floating) or "float" in str(dtype)


def _nan_like(v):
    a = np.asarray(v)
    if np.issubdtype(a.dtype, np.floating):
        return np.full(a.shape, np.nan, a.dtype)
    if _floatish(a.dtype):   # bfloat16 etc.: build in f32, cast
        return np.full(a.shape, np.nan, np.float32).astype(a.dtype)
    raise TypeError("cannot NaN-fill non-float var of dtype %s" % a.dtype)


def inject_nan(var_name, schedule, once=True, name=None):
    """Poison the named variable with NaN at scheduled steps — after the
    step completes, in the scope (a persistable var: params, optimizer
    slots) and/or the step's fetch list (a loss).  ``once=True`` by
    default: the canonical transient fault (an SDC blip, a bad
    collective) that a rollback recovers from because the replay is
    clean."""

    def _inject(step, scope=None, fetch_names=(), fetches=None, **_):
        hit = False
        if fetches is not None and var_name in fetch_names:
            i = list(fetch_names).index(var_name)
            fetches[i] = _nan_like(fetches[i])
            hit = True
        if scope is not None and scope.has_var(var_name):
            scope.set_var(var_name, _nan_like(scope.var(var_name)))
            hit = True
        if not hit:
            raise KeyError(
                "inject_nan: %r is neither a fetch of this step nor a "
                "scope var (typo in the drill spec?)" % var_name)

    return register("executor/step_done", _inject, schedule,
                    name=name or "nan_var:%s" % var_name, once=once)


def poison_batch(feed_name, schedule, once=False, fill=float("nan"),
                 name=None):
    """Corrupt the named feed at scheduled steps, before staging.  The
    default NaN fill makes the loss non-finite *in-graph*, which is
    exactly what the guardian's in-graph sentinel must catch; a finite
    ``fill`` (e.g. 1e30) drills the loss-spike detector instead.
    ``once=False`` by default: poisoned *data* is poisoned every time
    the reader yields it, so a replay that does not skip the batch
    deterministically re-trips."""

    def _poison(step, feed_names=(), feed_vals=None, **_):
        if feed_vals is None:
            return
        # misaimed drills fail LOUDLY (like inject_nan's KeyError): a
        # silent no-op would be recorded as an injection and let a
        # recovery test pass against a run that was never faulted
        if feed_name not in feed_names:
            raise KeyError(
                "poison_batch: %r is not a feed of this step (feeds: "
                "%s; typo in the drill spec?)"
                % (feed_name, sorted(feed_names)))
        i = list(feed_names).index(feed_name)
        a = np.asarray(feed_vals[i])
        if not _floatish(a.dtype):
            raise TypeError(
                "poison_batch: feed %r has non-float dtype %s — aim "
                "the drill at a float feed" % (feed_name, a.dtype))
        feed_vals[i] = np.full(a.shape, fill, a.dtype) \
            if np.issubdtype(a.dtype, np.floating) \
            else np.full(a.shape, fill, np.float32).astype(a.dtype)

    return register("executor/feed", _poison, schedule,
                    name=name or "poison_batch:%s" % feed_name, once=once)


def delay_dispatch(seconds, schedule, once=False, name=None):
    """Stall the dispatch path for ``seconds`` at scheduled steps — the
    slow-host / contended-interconnect drill the watchdog's stall
    detection (and the guardian's escalation) trains against."""
    seconds = float(seconds)

    def _delay(step, **_):
        time.sleep(seconds)

    return register("executor/dispatch", _delay, schedule,
                    name=name or "delay_dispatch:%gs" % seconds, once=once)


def fail_dispatch(schedule, once=True, name=None):
    """Raise ``FaultInjectedError`` from the dispatch path at scheduled
    steps — the hard-failure drill (device wedge, RPC loss)."""

    def _fail(step, **_):
        raise FaultInjectedError(
            "injected dispatch failure at step %d" % step)

    return register("executor/dispatch", _fail, schedule,
                    name=name or "fail_dispatch", once=once)


def kill_mid_save(schedule, point="before_commit", sig=_signal.SIGKILL,
                  name=None, once=True):
    """SIGKILL the process at the named point of the checkpoint write
    protocol — the preemption-mid-save drill that must leave a torn
    ``.tmp`` artifact restores ignore (tests/test_elastic_drill.py).
    ``point``: before_write | after_write | before_commit.  ``once``
    only matters for a non-SIGKILL ``sig`` or a respawning supervisor:
    the default kill never returns to disarm anything."""
    if point not in ("before_write", "after_write", "before_commit"):
        raise ValueError("unknown checkpoint point %r" % point)

    def _kill(step, **_):
        os.kill(os.getpid(), sig)

    return register("checkpoint/" + point, _kill, schedule,
                    name=name or "kill_mid_save:%s" % point, once=once)


# ---------------------------------------------------------------------------
# FLAGS_fault_spec: drills with no code changes
# ---------------------------------------------------------------------------

_SPEC_FAMILIES = ("nan_var", "poison_batch", "delay", "fail_dispatch",
                  "kill_save")


def _parse_schedule(text):
    """``"7"`` / ``"7,9"`` / ``"every=4"`` / ``"every=4+2"`` (start=2) /
    ``"prob=0.1"``."""
    text = text.strip()
    if text.startswith("every="):
        body = text[len("every="):]
        if "+" in body:
            every, start = body.split("+", 1)
            return FaultSchedule(every=int(every), start=int(start))
        return FaultSchedule(every=int(body))
    if text.startswith("prob="):
        return FaultSchedule(prob=float(text[len("prob="):]))
    return FaultSchedule(steps=[int(s) for s in text.split(",") if s])


def install_from_spec(spec):
    """Install drills from a ``FLAGS_fault_spec`` string — the env/flag
    entry point that makes drills first-class on ANY run:

        FLAGS_fault_spec="nan_var:fc_0.w_0@5;poison_batch:x@7,9"
        FLAGS_fault_spec="kill_save:before_commit@11"
        FLAGS_fault_spec="delay:0.2@every=8;fail_dispatch:@prob=0.01"

    Grammar: ``family:arg@schedule[:once|:persist]`` joined by ``;``.
    Schedules: explicit steps (``5`` / ``5,9``), ``every=N[+start]``,
    ``prob=P`` (seeded by ``FLAGS_fault_seed``).  Families default to
    their helper's once-ness (nan_var/fail/kill once, poison/delay
    persistent); ``:once``/``:persist`` override.

    REPLACES whatever a previous spec installed: re-applying a spec is
    idempotent (no duplicate hooks), a new spec swaps the drills, and
    an empty spec disarms them — the installed fault state always
    mirrors the flag value.  Transactional: a malformed entry leaves
    the previous spec's hooks untouched.  Hooks registered directly
    (``register``/drill helpers) are never touched.  Returns the list
    of installed hooks."""
    installed = []
    try:
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            try:
                head, sched_text = part.split("@", 1)
                family, _, arg = head.partition(":")
                once = None
                for suffix, val in ((":once", True), (":persist", False)):
                    if sched_text.endswith(suffix):
                        sched_text = sched_text[: -len(suffix)]
                        once = val
                sched = _parse_schedule(sched_text)
                family = family.strip()
                if family not in _SPEC_FAMILIES:
                    raise ValueError("unknown fault family %r (know: %s)"
                                     % (family, ", ".join(_SPEC_FAMILIES)))
                if family == "nan_var":
                    h = inject_nan(arg, sched,
                                   once=True if once is None else once)
                elif family == "poison_batch":
                    h = poison_batch(arg, sched,
                                     once=False if once is None else once)
                elif family == "delay":
                    h = delay_dispatch(float(arg), sched,
                                       once=False if once is None else once)
                elif family == "fail_dispatch":
                    h = fail_dispatch(sched,
                                      once=True if once is None else once)
                else:  # kill_save
                    h = kill_mid_save(sched, point=arg or "before_commit",
                                      once=True if once is None else once)
            except (ValueError, KeyError, TypeError) as e:
                raise ValueError(
                    "FLAGS_fault_spec entry %r is malformed: %s "
                    "(grammar: family:arg@schedule[:once|:persist])"
                    % (part, e))
            installed.append(h)
    except Exception:
        for h in installed:
            unregister(h)
        raise
    for h in _SPEC_HOOKS:
        unregister(h)
    _SPEC_HOOKS[:] = installed
    return installed


def _install_env_spec():
    """An env-set FLAGS_fault_spec observed during flag registration is
    installed here, at the end of this module's import: the flag's
    on_set hook fires while this module may still be mid-import
    (fault -> flags -> hook) and defers to us."""
    try:
        spec = flags.flag("fault_spec")
    except KeyError:            # flags module itself mid-registration
        return
    if str(spec).strip() and not _REGISTRY:
        install_from_spec(spec)


_install_env_spec()
