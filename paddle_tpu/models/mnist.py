"""MNIST models (reference ``benchmark/fluid/models/mnist.py`` cnn_model
and ``tests/book/test_recognize_digits.py`` mlp/conv variants)."""

from .. import layers
from ..nets import simple_img_conv_pool

__all__ = ["mlp", "cnn_model"]


def mlp(img, hidden_sizes=(128, 64), class_dim=10):
    """Two-hidden-layer MLP (test_recognize_digits.py:mlp)."""
    h = img
    for size in hidden_sizes:
        h = layers.fc(h, size=size, act="relu")
    return layers.fc(h, size=class_dim, act="softmax")


def cnn_model(data, class_dim=10):
    """conv-pool x2 + fc (benchmark/fluid/models/mnist.py:cnn_model)."""
    conv_pool_1 = simple_img_conv_pool(
        input=data, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act="relu")
    conv_pool_2 = simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act="relu")
    return layers.fc(conv_pool_2, size=class_dim, act="softmax")
