"""CTR DNN — the click-through-rate workload of the reference's
distributed-training story (reference
``python/paddle/fluid/tests/unittests/dist_ctr.py`` +
``dist_ctr_reader.py``: the pserver-era sparse-embedding model;
SURVEY §7 stage 8, "DeepFM CTR" capability).

Two sparse id paths over huge vocabularies:

* the DNN path — embeddings summed per sample, then an MLP tower;
* the LR ("wide") path — one-dim embeddings summed per sample;

concatenated into a 2-class click predictor.  On this stack the
embeddings are `is_sparse` (SelectedRows gradients) and optionally
`is_distributed` — the EP redesign of the pserver's remote prefetch:
tables row-shard over the mesh's ep/dp axis
(``parallel/embedding.py``) instead of living on parameter servers.
"""

from .. import layers
from ..param_attr import ParamAttr


def ctr_dnn(dnn_data, lr_data, label, dnn_dict_size, lr_dict_size,
            embedding_size=16, tower=(128, 128, 128),
            is_distributed=False):
    """Build the CTR model; returns (avg_cost, predict, auc_var).

    ``dnn_data``/``lr_data`` are int64 ``lod_level=1`` id sequences;
    ``label`` is the [B, 1] click label.
    """
    dnn_emb = layers.embedding(
        dnn_data, size=[dnn_dict_size, embedding_size], is_sparse=True,
        is_distributed=is_distributed,
        param_attr=ParamAttr(name="deep_embedding"))
    dnn_pool = layers.sequence_pool(dnn_emb, pool_type="sum")
    x = dnn_pool
    for i, width in enumerate(tower):
        x = layers.fc(x, size=width, act="relu", name="dnn_fc_%d" % i)

    lr_emb = layers.embedding(
        lr_data, size=[lr_dict_size, 1], is_sparse=True,
        is_distributed=is_distributed)
    lr_pool = layers.sequence_pool(lr_emb, pool_type="sum")

    merge = layers.concat([x, lr_pool], axis=1)
    predict = layers.fc(merge, size=2, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    auc_var, _states = layers.auc(input=predict, label=label)
    return avg_cost, predict, auc_var
