"""AlexNet — the reference's oldest headline benchmark topology
(``benchmark/paddle/image/alexnet.py``: 227x227 input, 5 convs with
LRN after conv1/conv2, three 4096/4096/class FCs with dropout; the
published number is 334 ms/batch at bs=128 on a K40m,
``benchmark/README.md:33-38``).

TPU notes: the v2 config's ``img_conv_layer`` defaults to ReLU, so every
conv here carries act="relu"; LRN is the cross-map response norm the
original paper used (XLA fuses its square/avg-pool/pow chain).  One
fused HLO module end-to-end like every other model in ``models/``.
"""

from .. import layers

__all__ = ["alexnet"]


def alexnet(input, class_dim=1000, is_test=False, groups=1):
    conv1 = layers.conv2d(input=input, num_filters=96, filter_size=11,
                          stride=4, padding=1, act="relu")
    norm1 = layers.lrn(input=conv1, n=5, alpha=1e-4, beta=0.75)
    pool1 = layers.pool2d(input=norm1, pool_size=3, pool_stride=2,
                          pool_type="max")

    conv2 = layers.conv2d(input=pool1, num_filters=256, filter_size=5,
                          stride=1, padding=2, groups=groups, act="relu")
    norm2 = layers.lrn(input=conv2, n=5, alpha=1e-4, beta=0.75)
    pool2 = layers.pool2d(input=norm2, pool_size=3, pool_stride=2,
                          pool_type="max")

    conv3 = layers.conv2d(input=pool2, num_filters=384, filter_size=3,
                          stride=1, padding=1, act="relu")
    conv4 = layers.conv2d(input=conv3, num_filters=384, filter_size=3,
                          stride=1, padding=1, groups=groups, act="relu")
    conv5 = layers.conv2d(input=conv4, num_filters=256, filter_size=3,
                          stride=1, padding=1, groups=groups, act="relu")
    pool5 = layers.pool2d(input=conv5, pool_size=3, pool_stride=2,
                          pool_type="max")

    fc6 = layers.fc(input=pool5, size=4096, act="relu")
    drop6 = layers.dropout(x=fc6, dropout_prob=0.5, is_test=is_test)
    fc7 = layers.fc(input=drop6, size=4096, act="relu")
    drop7 = layers.dropout(x=fc7, dropout_prob=0.5, is_test=is_test)
    return layers.fc(input=drop7, size=class_dim, act="softmax")
