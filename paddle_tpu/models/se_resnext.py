"""SE-ResNeXt (reference ``benchmark/fluid/models/se_resnext.py`` — the
multi-device ParallelExecutor benchmark model, BASELINE config 5).

Squeeze-and-excitation block: global-avg-pool -> fc reduce -> fc excite
(sigmoid) -> channel-wise scale.  Cardinality via grouped conv.
"""

from .. import layers

__all__ = ["SE_ResNeXt", "se_resnext_50"]


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = layers.conv2d(
        input=input, num_filters=num_filters, filter_size=filter_size,
        stride=stride, padding=(filter_size - 1) // 2, groups=groups,
        act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio):
    pool = layers.pool2d(input=input, pool_size=0, pool_type="avg",
                         global_pooling=True)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act="relu")
    excitation = layers.fc(input=squeeze, size=num_channels, act="sigmoid")
    # scale channels: excitation is [N, C]; broadcast over H, W via axis=0
    return layers.elementwise_mul(x=input, y=excitation, axis=0)


def _shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out or stride != 1:
        filter_size = 1
        return conv_bn_layer(input, ch_out, filter_size, stride,
                             is_test=is_test)
    return input


def bottleneck_block(input, num_filters, stride, cardinality,
                     reduction_ratio, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act="relu", is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act="relu", is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test)
    scale = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)
    short = _shortcut(input, num_filters * 2, stride, is_test=is_test)
    return layers.elementwise_add(x=short, y=scale, act="relu")


_DEPTH_CFG = {
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def SE_ResNeXt(input, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False):
    cfg = _DEPTH_CFG[depth]
    if depth == 152:
        conv = conv_bn_layer(input, 64, 3, stride=2, act="relu",
                             is_test=is_test)
        conv = conv_bn_layer(conv, 64, 3, act="relu", is_test=is_test)
        conv = conv_bn_layer(conv, 128, 3, act="relu", is_test=is_test)
    else:
        conv = conv_bn_layer(input, 64, 7, stride=2, act="relu",
                             is_test=is_test)
    conv = layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type="max")

    num_filters_list = [128, 256, 512, 1024]
    for block in range(len(cfg)):
        for i in range(cfg[block]):
            conv = bottleneck_block(
                conv, num_filters_list[block],
                2 if i == 0 and block != 0 else 1,
                cardinality, reduction_ratio, is_test=is_test)

    pool = layers.pool2d(input=conv, pool_size=7, pool_type="avg",
                         global_pooling=True)
    drop = layers.dropout(x=pool, dropout_prob=0.5, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act="softmax")


def se_resnext_50(input, class_dim=1000, is_test=False):
    return SE_ResNeXt(input, class_dim=class_dim, depth=50, is_test=is_test)
