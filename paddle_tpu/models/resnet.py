"""ResNet for ImageNet/cifar10 (reference ``benchmark/fluid/models/resnet.py``
resnet_imagenet/resnet_cifar10 — bottleneck + basicblock variants).

TPU notes: NCHW API surface (parity); XLA relayouts for the MXU.  The
whole network is one fused HLO module under the program-level jit; batch
norm stats update in-graph.
"""

from .. import layers

__all__ = ["resnet_imagenet", "resnet_cifar10"]


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act="relu",
                  is_test=False):
    conv = layers.conv2d(
        input=input, num_filters=ch_out, filter_size=filter_size,
        stride=stride, padding=padding, act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def _shortcut(input, ch_out, stride, is_test=False):
    ch_in = input.shape[1]
    if ch_in != ch_out:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             is_test=is_test)
    return input


def basicblock(input, ch_out, stride, is_test=False):
    short = _shortcut(input, ch_out, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test)
    return layers.elementwise_add(x=short, y=conv2, act="relu")


def bottleneck(input, ch_out, stride, is_test=False):
    short = _shortcut(input, ch_out * 4, stride, is_test=is_test)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test)
    return layers.elementwise_add(x=short, y=conv3, act="relu")


def _layer_warp(block_func, input, ch_out, count, stride, is_test=False):
    res_out = block_func(input, ch_out, stride, is_test=is_test)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test=is_test)
    return res_out


_DEPTH_CFG = {
    18: ([2, 2, 2, 2], basicblock),
    34: ([3, 4, 6, 3], basicblock),
    50: ([3, 4, 6, 3], bottleneck),
    101: ([3, 4, 23, 3], bottleneck),
    152: ([3, 8, 36, 3], bottleneck),
}


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False):
    cfg, block_func = _DEPTH_CFG[depth]
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test)
    pool1 = layers.pool2d(input=conv1, pool_type="max", pool_size=3,
                          pool_stride=2, pool_padding=1)
    res1 = _layer_warp(block_func, pool1, 64, cfg[0], 1, is_test=is_test)
    res2 = _layer_warp(block_func, res1, 128, cfg[1], 2, is_test=is_test)
    res3 = _layer_warp(block_func, res2, 256, cfg[2], 2, is_test=is_test)
    res4 = _layer_warp(block_func, res3, 512, cfg[3], 2, is_test=is_test)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type="avg",
                          global_pooling=True)
    return layers.fc(input=pool2, size=class_dim, act="softmax")


def resnet_cifar10(input, class_dim=10, depth=32, is_test=False):
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = _layer_warp(basicblock, conv1, 16, n, 1, is_test=is_test)
    res2 = _layer_warp(basicblock, res1, 32, n, 2, is_test=is_test)
    res3 = _layer_warp(basicblock, res2, 64, n, 2, is_test=is_test)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         global_pooling=True)
    return layers.fc(input=pool, size=class_dim, act="softmax")
