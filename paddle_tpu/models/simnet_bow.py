"""SimNet-BOW — pairwise text-similarity ranking (reference
``python/paddle/fluid/tests/unittests/dist_simnet_bow.py``: the
bag-of-words twin-tower ranker from the pserver-era dist suite).

Query and title towers share one embedding table (`is_sparse`, the
SelectedRows gradient path); each tower sum-pools its word embeddings
and projects through a shared fc; the score is the cosine similarity.
Training ranks a positive title above a negative one with
``margin_rank_loss`` — the pairwise hinge the reference uses.
"""

from .. import layers
from ..param_attr import ParamAttr


def _tower(ids, dict_size, emb_dim, hid_dim):
    emb = layers.embedding(ids, size=[dict_size, emb_dim], is_sparse=True,
                           param_attr=ParamAttr(name="simnet_emb"))
    pool = layers.sequence_pool(emb, pool_type="sum")
    return layers.fc(pool, size=hid_dim, act="softsign",
                     param_attr=ParamAttr(name="simnet_fc_w"),
                     bias_attr=ParamAttr(name="simnet_fc_b"))


def simnet_bow(query, pos_title, neg_title, dict_size, emb_dim=128,
               hid_dim=128, margin=0.1):
    """Returns (avg_cost, pos_score, neg_score).  All three inputs are
    int64 ``lod_level=1`` word-id sequences; the towers share every
    parameter (twin-tower weight tying, as the reference builds it)."""
    q = _tower(query, dict_size, emb_dim, hid_dim)
    pt = _tower(pos_title, dict_size, emb_dim, hid_dim)
    nt = _tower(neg_title, dict_size, emb_dim, hid_dim)
    pos_score = layers.cos_sim(q, pt)
    neg_score = layers.cos_sim(q, nt)
    label = layers.fill_constant_batch_size_like(
        input=pos_score, shape=[-1, 1], dtype="float32", value=1.0)
    loss = layers.margin_rank_loss(label, pos_score, neg_score,
                                   margin=margin)
    return layers.mean(loss), pos_score, neg_score
