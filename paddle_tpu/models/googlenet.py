"""GoogLeNet (Inception v1) — reference era benchmark topology
(``benchmark/paddle/image/googlenet.py``: 224x224 input, 9 inception
blocks, avg-pool 7, dropout 0.4, single softmax head — the benchmark
config drops the two auxiliary losses; published 1149 ms/batch at
bs=128 on a K40m, ``benchmark/README.md:47-51``).

TPU notes: each inception block is four parallel conv towers concat'd
on the channel axis — XLA schedules the four towers as independent MXU
gemm chains from one fused module; no hand-scheduling needed.  The v2
``img_conv_layer`` default activation is ReLU, kept on every conv.
"""

from .. import layers

__all__ = ["googlenet_v1"]


def _conv(input, ch, filter_size, stride=1, padding=0):
    return layers.conv2d(input=input, num_filters=ch,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act="relu")


def inception(input, filter1, filter3R, filter3, filter5R, filter5, proj):
    """One Inception v1 block: 1x1 / 1x1->3x3 / 1x1->5x5 / 3x3pool->1x1."""
    tower1 = _conv(input, filter1, 1)
    tower3 = _conv(_conv(input, filter3R, 1), filter3, 3, padding=1)
    tower5 = _conv(_conv(input, filter5R, 1), filter5, 5, padding=2)
    pool = layers.pool2d(input=input, pool_size=3, pool_stride=1,
                         pool_padding=1, pool_type="max")
    towerp = _conv(pool, proj, 1)
    return layers.concat([tower1, tower3, tower5, towerp], axis=1)


def googlenet_v1(input, class_dim=1000, is_test=False):
    # stage 1
    conv1 = _conv(input, 64, 7, stride=2, padding=3)
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_type="max", ceil_mode=True)
    # stage 2
    conv2 = _conv(_conv(pool1, 64, 1), 192, 3, padding=1)
    pool2 = layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                          pool_type="max", ceil_mode=True)
    # stage 3
    ince3a = inception(pool2, 64, 96, 128, 16, 32, 32)
    ince3b = inception(ince3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(input=ince3b, pool_size=3, pool_stride=2,
                          pool_type="max", ceil_mode=True)
    # stage 4
    ince4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    ince4b = inception(ince4a, 160, 112, 224, 24, 64, 64)
    ince4c = inception(ince4b, 128, 128, 256, 24, 64, 64)
    ince4d = inception(ince4c, 112, 144, 288, 32, 64, 64)
    ince4e = inception(ince4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(input=ince4e, pool_size=3, pool_stride=2,
                          pool_type="max", ceil_mode=True)
    # stage 5
    ince5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    ince5b = inception(ince5a, 384, 192, 384, 48, 128, 128)
    pool5 = layers.pool2d(input=ince5b, pool_size=7, pool_stride=7,
                          pool_type="avg")

    drop = layers.dropout(x=pool5, dropout_prob=0.4, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act="softmax")
