"""SmallNet — the reference's cifar-scale era benchmark topology
(``benchmark/paddle/image/smallnet_mnist_cifar.py``: 32x32 input, three
5/5/3 convs with 3x3-stride-2 pools — max then two avg — then 64/10
FCs; published 33.1 ms/batch at bs=256 on a K40m,
``benchmark/README.md:55-59``).
"""

from .. import layers

__all__ = ["smallnet"]


def smallnet(input, class_dim=10, is_test=False):
    conv1 = layers.conv2d(input=input, num_filters=32, filter_size=5,
                          stride=1, padding=2, act="relu")
    pool1 = layers.pool2d(input=conv1, pool_size=3, pool_stride=2,
                          pool_padding=1, pool_type="max")

    conv2 = layers.conv2d(input=pool1, num_filters=32, filter_size=5,
                          stride=1, padding=2, act="relu")
    pool2 = layers.pool2d(input=conv2, pool_size=3, pool_stride=2,
                          pool_padding=1, pool_type="avg")

    conv3 = layers.conv2d(input=pool2, num_filters=64, filter_size=3,
                          stride=1, padding=1, act="relu")
    pool3 = layers.pool2d(input=conv3, pool_size=3, pool_stride=2,
                          pool_padding=1, pool_type="avg")

    fc1 = layers.fc(input=pool3, size=64, act="relu")
    return layers.fc(input=fc1, size=class_dim, act="softmax")
