"""VGG-16 (reference ``benchmark/fluid/models/vgg.py`` vgg16_bn_drop)."""

from .. import layers
from ..nets import img_conv_group

__all__ = ["vgg16_bn_drop"]


def vgg16_bn_drop(input, class_dim=1000, is_test=False):
    def conv_block(ipt, num_filter, groups, dropouts):
        return img_conv_group(
            input=ipt,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * groups,
            conv_filter_size=3,
            conv_act="relu",
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act="relu", is_test=is_test)
    drop2 = layers.dropout(x=bn, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    return layers.fc(input=fc2, size=class_dim, act="softmax")
