"""Model zoo mirroring the reference benchmark suite's model set
(``benchmark/fluid/models/``: mnist, vgg, resnet, se_resnext,
machine_translation, stacked_dynamic_lstm) — built from the paddle_tpu
layers DSL, TPU-first (bfloat16-friendly, MXU-sized matmuls/convs).
"""

from . import (alexnet, ctr_dnn, googlenet,  # noqa: F401
               machine_translation, mnist, resnet, se_resnext,
               simnet_bow, smallnet,
               stacked_dynamic_lstm, transformer, vgg)
