"""Transformer-base NMT (BASELINE config 3; reference
``benchmark/fluid/models/machine_translation.py`` +
``python/paddle/fluid/tests/unittests/dist_transformer.py`` capability).

Built entirely from the layers DSL over padded sequences: every attention
projection and the QK^T/PV products are MXU gemms; masks come from the
``<name>@LEN`` companions (sequence_mask) and the causal_mask op.  The
whole encoder-decoder fwd+bwd+Adam step compiles to one HLO module.

Architecture: post-norm Transformer (Vaswani et al.) — d_model 512,
n_head 8, 6+6 layers, ffn 2048, shared-nothing embeddings, label
smoothing + noam LR (wired by the caller).
"""

import numpy as np

from .. import layers
from ..initializer import NumpyArrayInitializer
from ..param_attr import ParamAttr

__all__ = ["transformer", "wrap_encoder", "wrap_decoder",
           "position_encoding_init"]


def position_encoding_init(n_position, d_model):
    """Sinusoid position encoding table [n_position, d_model]."""
    pos = np.arange(n_position)[:, None].astype("float64")
    dim = np.arange(d_model // 2)[None, :].astype("float64")
    angle = pos / np.power(10000.0, 2 * dim / d_model)
    enc = np.zeros((n_position, d_model))
    enc[:, 0::2] = np.sin(angle)
    enc[:, 1::2] = np.cos(angle)
    return enc.astype("float32")


def _multi_head_attention(queries, keys, values, k_len, causal, d_model,
                          n_head, dropout_rate, is_test, cache_name):
    d_key = d_model // n_head
    q = layers.fc(queries, size=d_model, num_flatten_dims=2, bias_attr=False,
                  name=cache_name + "_q")
    k = layers.fc(keys, size=d_model, num_flatten_dims=2, bias_attr=False,
                  name=cache_name + "_k")
    v = layers.fc(values, size=d_model, num_flatten_dims=2, bias_attr=False,
                  name=cache_name + "_v")

    def split_heads(x):
        r = layers.reshape(x, shape=[0, 0, n_head, d_key])
        return layers.transpose(r, perm=[0, 2, 1, 3])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    # fused flash attention: structural masks (k_len padding + causal)
    # instead of a materialized [B, H, Tq, Tk] additive bias; weight
    # dropout happens inside the kernel (ops/attention.py)
    ctx = layers.fused_attention(q, k, v, k_len=k_len, causal=causal,
                                 dropout_rate=dropout_rate, is_test=is_test,
                                 scale=d_key ** -0.5)
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, shape=[0, 0, d_model])
    return layers.fc(ctx, size=d_model, num_flatten_dims=2, bias_attr=False,
                     name=cache_name + "_o")


def _ffn(x, d_inner, d_model, is_test, dropout_rate, name):
    h = layers.fc(x, size=d_inner, num_flatten_dims=2, act="relu",
                  name=name + "_fc1")
    if dropout_rate:
        h = layers.dropout(h, dropout_prob=dropout_rate, is_test=is_test)
    return layers.fc(h, size=d_model, num_flatten_dims=2, name=name + "_fc2")


def _post_process(prev, sublayer_out, dropout_rate, is_test):
    if dropout_rate:
        sublayer_out = layers.dropout(sublayer_out,
                                      dropout_prob=dropout_rate,
                                      is_test=is_test)
    added = layers.elementwise_add(prev, sublayer_out)
    return layers.layer_norm(added, begin_norm_axis=2)


def _prepare_embedding(word, pos_table_name, vocab_size, d_model, max_len,
                       dropout_rate, is_test, name):
    emb = layers.embedding(
        word, size=[vocab_size, d_model],
        param_attr=ParamAttr(name=name + "_word_emb"))
    emb = layers.scale(emb, scale=d_model ** 0.5)
    pos_enc = position_encoding_init(max_len, d_model)
    pos_param = ParamAttr(
        name=pos_table_name,
        initializer=NumpyArrayInitializer(pos_enc),
        trainable=False)
    from ..layer_helper import LayerHelper
    helper = LayerHelper(name + "_posenc")
    table = helper.create_parameter(
        attr=pos_param, shape=[max_len, d_model], dtype="float32")
    out = helper.create_variable_for_type_inference("float32")
    # table[:T] added at trace time (T is the runtime pad length)
    helper.append_op(
        type="add_position_encoding",
        inputs={"X": [emb], "Table": [table]},
        outputs={"Out": [out]})
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate, is_test=is_test)
    out._seq_len_name = word._seq_len_name
    return out


def wrap_encoder(src_word, src_max_len, vocab_size, n_layer=6, n_head=8,
                 d_model=512, d_inner=2048, dropout_rate=0.1, is_test=False,
                 pipeline_microbatches=None, pipeline_layers_per_stage=1):
    """``pipeline_microbatches``: stage the encoder layers into a
    ``layers.Pipeline`` region (``pipeline_layers_per_stage``
    consecutive layers per stage, default one) so the model runs as a
    pipeline schedule when the ParallelExecutor's mesh has a ``pp``
    axis matching the stage count (or dividing it, for the interleaved
    schedule) — same losses either way."""
    src_len = src_word.block._find_var_recursive(src_word._seq_len_name)
    enc_in = _prepare_embedding(src_word, "src_pos_enc", vocab_size, d_model,
                                src_max_len, dropout_rate, is_test, "src")

    def enc_layer(x, i):
        attn = _multi_head_attention(x, x, x, src_len, False, d_model,
                                     n_head, dropout_rate, is_test,
                                     "enc%d_attn" % i)
        x = _post_process(x, attn, dropout_rate, is_test)
        ffn = _ffn(x, d_inner, d_model, is_test, dropout_rate,
                   "enc%d_ffn" % i)
        return _post_process(x, ffn, dropout_rate, is_test)

    x = enc_in
    if pipeline_microbatches:
        g = max(1, int(pipeline_layers_per_stage or 1))
        if n_layer % g:
            raise ValueError(
                "pipeline_layers_per_stage (%d) must divide n_layer "
                "(%d)" % (g, n_layer))
        pipe = layers.Pipeline(microbatches=pipeline_microbatches)
        for s0 in range(0, n_layer, g):
            with pipe.stage():
                h = pipe.carry(x if s0 == 0 else None)
                pipe.side(src_len)
                for i in range(s0, s0 + g):
                    h = enc_layer(h, i)
                pipe.emit(h)
        x = pipe()
    else:
        for i in range(n_layer):
            x = enc_layer(x, i)
    x._seq_len_name = src_word._seq_len_name
    return x


def wrap_decoder(tgt_word, enc_out, tgt_max_len, vocab_size, n_layer=6,
                 n_head=8, d_model=512, d_inner=2048, dropout_rate=0.1,
                 is_test=False, pipeline_microbatches=None,
                 pipeline_layers_per_stage=1):
    tgt_len = tgt_word.block._find_var_recursive(tgt_word._seq_len_name)
    src_len = enc_out.block._find_var_recursive(enc_out._seq_len_name)
    dec_in = _prepare_embedding(tgt_word, "tgt_pos_enc", vocab_size, d_model,
                                tgt_max_len, dropout_rate, is_test, "tgt")

    def dec_layer(x, enc, i):
        self_attn = _multi_head_attention(x, x, x, tgt_len, True, d_model,
                                          n_head, dropout_rate, is_test,
                                          "dec%d_self" % i)
        x = _post_process(x, self_attn, dropout_rate, is_test)
        cross = _multi_head_attention(x, enc, enc, src_len, False,
                                      d_model, n_head, dropout_rate,
                                      is_test, "dec%d_cross" % i)
        x = _post_process(x, cross, dropout_rate, is_test)
        ffn = _ffn(x, d_inner, d_model, is_test, dropout_rate,
                   "dec%d_ffn" % i)
        return _post_process(x, ffn, dropout_rate, is_test)

    x = dec_in
    if pipeline_microbatches:
        g = max(1, int(pipeline_layers_per_stage or 1))
        if n_layer % g:
            raise ValueError(
                "pipeline_layers_per_stage (%d) must divide n_layer "
                "(%d)" % (g, n_layer))
        pipe = layers.Pipeline(microbatches=pipeline_microbatches)
        for s0 in range(0, n_layer, g):
            with pipe.stage():
                h = pipe.carry(x if s0 == 0 else None)
                pipe.side(tgt_len)
                pipe.side(src_len)
                enc = pipe.side(enc_out)   # per-microbatch cross K/V
                for i in range(s0, s0 + g):
                    h = dec_layer(h, enc, i)
                pipe.emit(h)
        x = pipe()
    else:
        for i in range(n_layer):
            x = dec_layer(x, enc_out, i)
    logits = layers.fc(x, size=vocab_size, num_flatten_dims=2,
                       name="dec_logits")
    return logits


def transformer(src_word, tgt_word, label, src_max_len, tgt_max_len,
                src_vocab_size, tgt_vocab_size, n_layer=6, n_head=8,
                d_model=512, d_inner=2048, dropout_rate=0.1,
                label_smooth_eps=0.1, is_test=False,
                pipeline_microbatches=None, pipeline_layers_per_stage=1):
    """Full train graph: returns (avg_cost, logits).

    ``pipeline_microbatches`` stages the encoder and decoder stacks
    into two pipeline regions (``pipeline_layers_per_stage``
    consecutive layers per stage) for ``pp`` meshes — stage
    granularity is the knob that trades fewer/fatter stages (GPipe on
    small meshes) against more/thinner ones (interleaved virtual
    stages)."""
    enc_out = wrap_encoder(src_word, src_max_len, src_vocab_size, n_layer,
                           n_head, d_model, d_inner, dropout_rate, is_test,
                           pipeline_microbatches,
                           pipeline_layers_per_stage)
    logits = wrap_decoder(tgt_word, enc_out, tgt_max_len, tgt_vocab_size,
                          n_layer, n_head, d_model, d_inner, dropout_rate,
                          is_test, pipeline_microbatches,
                          pipeline_layers_per_stage)
    # label: [B, T, 1] int64 ids (padded); mask from tgt lengths
    tgt_len = tgt_word.block._find_var_recursive(tgt_word._seq_len_name)
    # uniform smoothing fused into the loss kernel: the reference's
    # one_hot + label_smooth + soft-label CE materializes a [B, T, V]
    # soft-label tensor (0.5 GB at the benchmark shapes) three times
    cost = layers.softmax_with_cross_entropy(
        logits, label, label_smooth_eps=label_smooth_eps)
    mask = layers.padding_mask(tgt_len, logits)  # [B,T]
    mask3 = layers.unsqueeze(mask, axes=[2])
    masked = layers.elementwise_mul(cost, mask3)
    total = layers.reduce_sum(masked)
    n_tok = layers.reduce_sum(mask)
    avg_cost = layers.elementwise_div(total, n_tok)
    return avg_cost, logits
