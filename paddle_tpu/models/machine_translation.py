"""RNN seq2seq NMT with Bahdanau attention — the fluid_benchmark
``machine_translation.py`` model (reference
``benchmark/fluid/models/machine_translation.py:53`` seq_to_seq_net):
bi-directional LSTM encoder, attention decoder driven step-by-step with
explicit LSTM gate math, softmax prediction per target position.

TPU-first shape discipline: sequences are padded ``[B, T, ...]`` with
``@LEN`` masks (no LoD reorder); the decoder recurrence is a
``DynamicRNN`` (lax.scan), and the attention softmax masks padded
source positions via ``sequence_softmax(length=...)`` instead of the
reference's sequence_expand/sequence_softmax LoD plumbing.  All
encoder-side projections are hoisted out of the scan (one big [B,T]
gemm each instead of T small ones)."""

from .. import layers
from .. import nets
from ..layer_helper import LayerHelper  # noqa: F401 (doc parity)

__all__ = ["seq_to_seq_net", "lstm_step"]


def lstm_step(x_t, hidden_t_prev, cell_t_prev, size):
    """Explicit LSTM gate math (reference machine_translation.py:32)."""
    def linear(inputs):
        return layers.fc(input=inputs, size=size, bias_attr=True)

    forget_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    input_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    output_gate = layers.sigmoid(linear([hidden_t_prev, x_t]))
    cell_tilde = layers.tanh(linear([hidden_t_prev, x_t]))

    cell_t = layers.sums([
        layers.elementwise_mul(forget_gate, cell_t_prev),
        layers.elementwise_mul(input_gate, cell_tilde),
    ])
    hidden_t = layers.elementwise_mul(output_gate, layers.tanh(cell_t))
    return hidden_t, cell_t


def _bi_lstm_encoder(src_emb, size):
    """fwd + reverse dynamic_lstm over the pre-projected input; concat
    hidden states (reference bi_lstm_encoder)."""
    fwd_in = layers.fc(src_emb, size=size * 4, num_flatten_dims=2,
                       bias_attr=False)
    fwd, _ = layers.dynamic_lstm(fwd_in, size=size * 4)
    rev_in = layers.fc(src_emb, size=size * 4, num_flatten_dims=2,
                       bias_attr=False)
    rev, _ = layers.dynamic_lstm(rev_in, size=size * 4, is_reverse=True)
    return layers.concat([fwd, rev], axis=2), rev   # [B, T, 2H], [B, T, H]


def seq_to_seq_net(src, tgt, label, source_dict_dim, target_dict_dim,
                   embedding_dim=512, encoder_size=512, decoder_size=512):
    """Training graph: returns (avg_cost, per-position predictions).

    ``src``/``tgt``/``label`` are int64 ``lod_level=1`` data vars
    ([B, T, 1] padded + @LEN).  ``label`` is ``tgt`` shifted left.
    """
    src_emb = layers.embedding(src, size=[source_dict_dim, embedding_dim])
    encoded_vector, rev = _bi_lstm_encoder(src_emb, encoder_size)

    # attention key projection, hoisted: one [B, T] gemm
    encoded_proj = layers.fc(encoded_vector, size=decoder_size,
                             num_flatten_dims=2, bias_attr=False)
    # decoder boot = backward encoder's first state (reference takes the
    # backward direction's first step)
    backward_first = layers.sequence_first_step(rev)
    decoder_boot = layers.fc(backward_first, size=decoder_size,
                             act="tanh", bias_attr=False)

    src_len = layers.sequence_length(src)

    tgt_emb = layers.embedding(tgt, size=[target_dict_dim, embedding_dim])

    rnn = layers.DynamicRNN()
    with rnn.block():
        current_word = rnn.step_input(tgt_emb)
        enc_vec = rnn.static_input(encoded_vector)
        enc_proj = rnn.static_input(encoded_proj)
        hidden_mem = rnn.memory(init=decoder_boot)
        cell_mem = rnn.memory(shape=[decoder_size], value=0.0)

        # Bahdanau attention (nets.simple_attention, the v1 seqToseq
        # form): masked softmax over tanh(enc_proj + W h) scores
        context = nets.simple_attention(enc_vec, enc_proj, hidden_mem,
                                        decoder_size, length=src_len)

        decoder_input = layers.concat([context, current_word], axis=1)
        h, c = lstm_step(decoder_input, hidden_mem, cell_mem,
                         decoder_size)
        rnn.update_memory(hidden_mem, h)
        rnn.update_memory(cell_mem, c)
        rnn.output(layers.fc(h, size=target_dict_dim, bias_attr=True))
    logits = rnn()                                          # [B, T, V]

    cost = layers.softmax_with_cross_entropy(logits, label)
    tgt_len = layers.sequence_length(tgt)
    mask = layers.padding_mask(tgt_len, logits)             # [B, T]
    masked = layers.elementwise_mul(cost,
                                    layers.unsqueeze(mask, axes=[2]))
    avg_cost = layers.elementwise_div(layers.reduce_sum(masked),
                                      layers.reduce_sum(mask))
    return avg_cost, logits
