"""Stacked dynamic LSTM text classifier (reference
``benchmark/fluid/models/stacked_dynamic_lstm.py`` — the LSTM throughput
benchmark, and the long-sequence capability slice per SURVEY.md §5)."""

from .. import layers

__all__ = ["stacked_lstm_net"]


def stacked_lstm_net(word, dict_dim, class_dim=2, emb_dim=512, hid_dim=512,
                     stacked_num=3):
    emb = layers.embedding(word, size=[dict_dim, emb_dim])
    fc1 = layers.fc(emb, size=hid_dim * 4, num_flatten_dims=2)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim * 4)

    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = layers.fc(inputs[0], size=hid_dim * 4, num_flatten_dims=2)
        fc = layers.elementwise_add(fc, layers.fc(
            inputs[1], size=hid_dim * 4, num_flatten_dims=2))
        lstm, cell = layers.dynamic_lstm(
            input=fc, size=hid_dim * 4, is_reverse=(i % 2) == 0)
        inputs = [fc, lstm]

    fc_last = layers.sequence_pool(inputs[0], pool_type="max")
    lstm_last = layers.sequence_pool(inputs[1], pool_type="max")
    return layers.fc([fc_last, lstm_last], size=class_dim, act="softmax")
