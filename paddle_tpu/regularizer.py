"""Weight-decay regularizers appended onto gradients.

Parity: reference ``python/paddle/fluid/regularizer.py`` (L1/L2 decay
appended to grads before the optimizer op; per-param override via
ParamAttr.regularizer).
"""

from .core import VarType
from .framework import grad_var_name
from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer",
           "append_regularization_ops"]


class WeightDecayRegularizer:
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l2_decay")
        decay = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="scale", inputs={"X": [param]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._regularization_coeff = regularization_coeff

    def __call__(self, param, grad, block):
        helper = LayerHelper("l1_decay")
        sign = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(type="sign", inputs={"X": [param]},
                        outputs={"Out": [sign]})
        decay = helper.create_variable_for_type_inference(dtype=param.dtype)
        block.append_op(
            type="scale", inputs={"X": [sign]}, outputs={"Out": [decay]},
            attrs={"scale": self._regularization_coeff},
        )
        return decay


_SPARSE_DECAY_MODES = {}  # populated below: regularizer class -> mode


def _append_sparse_decay(param, grad, block, reg):
    """The SelectedRows leg of the reference regularizer: decay only the
    touched rows (``sparse_weight_decay`` merges duplicates and gathers
    the param rows) so the gradient STAYS sparse — the dense path's
    full-table ``scale(param)`` + ``sum`` would materialize an O(vocab)
    gradient and de-lazy the optimizer update."""
    mode = _SPARSE_DECAY_MODES.get(type(reg))
    if mode is None:
        raise TypeError(
            "regularizer %r has no SelectedRows (sparse-gradient) "
            "lowering; use L1Decay/L2Decay on is_sparse embedding "
            "params, or set is_sparse=False" % type(reg).__name__)
    helper = LayerHelper("sparse_regularized_grad")
    new_grad = helper.create_variable_for_type_inference(dtype=grad.dtype)
    new_grad.type = VarType.SELECTED_ROWS
    block.append_op(
        type="sparse_weight_decay",
        inputs={"Grad": [grad], "Param": [param]},
        outputs={"Out": [new_grad]},
        attrs={"coeff": reg._regularization_coeff, "mode": mode},
    )
    return new_grad


def append_regularization_ops(parameters_and_grads, regularization=None):
    """Add decay terms into each gradient (reference regularizer.py:
    append_regularization_ops).  Per-param regularizer wins over global.
    SELECTED_ROWS gradients take the lazy touched-rows decay path."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        reg = param.regularizer if param.regularizer is not None \
            else regularization
        if reg is None:
            params_and_grads.append((param, grad))
            continue
        if getattr(grad, "type", None) == VarType.SELECTED_ROWS:
            params_and_grads.append(
                (param, _append_sparse_decay(param, grad, grad.block, reg)))
            continue
        regularization_term = reg(param, grad, grad.block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        helper = LayerHelper("regularized_grad")
        new_grad = helper.create_variable_for_type_inference(dtype=grad.dtype)
        block.append_op(
            type="sum", inputs={"X": [grad, regularization_term]},
            outputs={"Out": [new_grad]},
        )
        params_and_grads.append((param, new_grad))
    return params_and_grads


_SPARSE_DECAY_MODES.update({L2DecayRegularizer: "l2",
                            L1DecayRegularizer: "l1"})


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
