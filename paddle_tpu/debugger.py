"""Program inspection: pretty printer + graphviz export.

Parity: reference ``python/paddle/fluid/debugger.py`` (pprint program
codes + ``draw_block_graphviz``) and ``fluid/graphviz.py`` (the dot
builder); C++ analogs ``ir/graph_viz_pass.cc`` and
``details/multi_devices_graph_print_pass.cc``.

The dot output needs no graphviz python package — it emits the .dot
text directly (op nodes as boxes, var nodes as ellipses, parameter vars
highlighted), and optionally shells out to ``dot`` when asked for an
image and the binary exists.
"""

import shutil
import subprocess

from .framework import Parameter, default_main_program

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _fmt_attr(v):
    if isinstance(v, float):
        return "%.6g" % v
    if isinstance(v, (list, tuple)) and len(v) > 8:
        return "[%s, ...x%d]" % (", ".join(map(str, v[:4])), len(v))
    return repr(v)


def pprint_block_codes(block, show_backward=False):
    """One block as pseudo-code text (reference pprint_block_codes)."""
    lines = ["// block %d (parent %d)" % (block.idx, block.parent_idx)]
    for var in block.vars.values():
        kind = "param" if isinstance(var, Parameter) else "var"
        extra = " persistable" if getattr(var, "persistable", False) \
            and kind != "param" else ""
        lines.append("%s %s : shape=%s dtype=%s%s" % (
            kind, var.name, tuple(var.shape or ()), var.dtype, extra))
    for op in block.ops:
        if not show_backward and op.type.endswith("_grad"):
            continue
        outs = ", ".join(n for ns in op.outputs.values() for n in ns if n)
        ins = ", ".join(n for ns in op.inputs.values() for n in ns if n)
        attrs = ", ".join("%s=%s" % (k, _fmt_attr(v))
                          for k, v in sorted(op.attrs.items())
                          if not k.startswith("__"))
        lines.append("%s = %s(%s)%s" % (
            outs or "_", op.type, ins,
            "  {%s}" % attrs if attrs else ""))
    return "\n".join(lines) + "\n"


def pprint_program_codes(program=None, show_backward=False):
    """Whole program as text, all blocks."""
    program = program or default_main_program()
    return "\n".join(pprint_block_codes(b, show_backward)
                     for b in program.blocks)


def draw_block_graphviz(block, highlights=None, path="./temp.dot",
                        render=False):
    """Write the block's dataflow as a .dot file (reference
    debugger.py:draw_block_graphviz).  Op nodes are boxes, var nodes
    ellipses, parameters filled; ``highlights`` is a set of var names to
    color.  With ``render=True`` and the ``dot`` binary present, also
    writes ``<path>.png``."""
    highlights = set(highlights or ())
    lines = ["digraph G {", '  rankdir="TB";']

    def vid(name):
        return '"var_%s"' % name

    seen_vars = set()
    for var in block.vars.values():
        seen_vars.add(var.name)
        style = "filled"
        color = "lightblue" if isinstance(var, Parameter) else "white"
        if var.name in highlights:
            color = "orange"
        lines.append(
            '  %s [label="%s\\n%s" shape=ellipse style=%s '
            'fillcolor=%s];' % (vid(var.name), var.name,
                                tuple(var.shape or ()), style, color))
    for i, op in enumerate(block.ops):
        oid = '"op_%d"' % i
        lines.append(
            '  %s [label="%s" shape=box style=filled '
            'fillcolor=lightgrey];' % (oid, op.type))
        for n in op.input_arg_names:
            if not n:
                continue
            if n not in seen_vars:
                seen_vars.add(n)
                lines.append('  %s [label="%s" shape=ellipse];'
                             % (vid(n), n))
            lines.append("  %s -> %s;" % (vid(n), oid))
        for n in op.output_arg_names:
            if not n:
                continue
            if n not in seen_vars:
                seen_vars.add(n)
                lines.append('  %s [label="%s" shape=ellipse];'
                             % (vid(n), n))
            lines.append("  %s -> %s;" % (oid, vid(n)))
    lines.append("}")
    dot = "\n".join(lines) + "\n"
    with open(path, "w") as f:
        f.write(dot)
    if render and shutil.which("dot"):
        subprocess.run(["dot", "-Tpng", path, "-o", path + ".png"],
                       check=False, capture_output=True)
    return path
