"""In-program evaluators: accumulate metric counters ACROSS mini-batches
inside the training program, reset/eval via small side programs.

Parity: reference ``python/paddle/fluid/evaluator.py`` (Evaluator base,
ChunkEvaluator:126, EditDistance:217, DetectionMAP:298).  States are
persistable [1]-shaped vars the main program's ``sums`` ops accumulate
in place (the executor's persistable-writeback contract keeps them
across steps); ``reset`` zero-fills them, ``eval`` computes the final
metric from the accumulated counters.

DetectionMAP is the deliberate redesign: its accumulation state is
variable-length (per-class true/false-positive LISTS), which has no
static-shape in-graph representation under XLA — the evaluator computes
the per-batch mAP var in-graph and delegates multi-batch accumulation
to host-side ``metrics.DetectionMAP`` (the API the reference itself
deprecates its evaluator in favor of).
"""

import numpy as np

from . import layers
from .framework import Program, program_guard
from .layer_helper import LayerHelper

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """Base: name scoping, state creation, reset."""

    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states = []
        self.metrics = []
        # reset/eval side programs are built once and reused: the
        # executor's compile cache keys on program identity, so a fresh
        # Program per call would retrace+rejit every epoch
        self._reset_program = None
        self._eval_program = None

    def reset(self, executor, reset_program=None):
        """Zero every state var (runs a small fill program whose outputs
        write back to the shared persistable state)."""
        if reset_program is None:
            if self._reset_program is None:
                self._reset_program = self._build_reset_program()
            reset_program = self._reset_program
        executor.run(reset_program)

    def _build_reset_program(self):
        prog = Program()
        with program_guard(main_program=prog):
            block = prog.global_block()
            for state in self.states:
                var = block.create_var(name=state.name, shape=state.shape,
                                       dtype=state.dtype, persistable=True)
                block.append_op(
                    type="fill_constant", inputs={},
                    outputs={"Out": [var.name]},
                    attrs={"shape": list(state.shape), "value": 0.0,
                           "dtype": str(state.dtype), "force_cpu": False})
        return prog

    def eval(self, executor, eval_program=None):
        raise NotImplementedError

    def _create_state(self, suffix, dtype, shape):
        from . import unique_name

        block = self.helper.main_program.global_block()
        state = block.create_var(
            name=unique_name.generate("_".join([self.helper.name, suffix])),
            persistable=True, dtype=dtype, shape=tuple(shape))
        self.states.append(state)
        return state

    def _fetch_states(self, executor, eval_program=None):
        if eval_program is None:
            if self._eval_program is None:
                prog = Program()
                with program_guard(main_program=prog):
                    block = prog.global_block()
                    for state in self.states:
                        block.create_var(name=state.name,
                                         shape=state.shape,
                                         dtype=state.dtype,
                                         persistable=True)
                self._eval_program = prog
            eval_program = self._eval_program
        else:
            block = eval_program.global_block()
            for state in self.states:
                block.create_var(name=state.name, shape=state.shape,
                                 dtype=state.dtype, persistable=True)
        return executor.run(eval_program,
                            fetch_list=[s.name for s in self.states])


class ChunkEvaluator(Evaluator):
    """Accumulates chunk_eval counters; eval() -> (precision, recall,
    f1) over every batch since the last reset."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None, seq_length=None):
        super().__init__("chunk_eval")
        self.num_infer_chunks = self._create_state(
            suffix="num_infer_chunks", dtype="int64", shape=[1])
        self.num_label_chunks = self._create_state(
            suffix="num_label_chunks", dtype="int64", shape=[1])
        self.num_correct_chunks = self._create_state(
            suffix="num_correct_chunks", dtype="int64", shape=[1])
        (precision, recall, f1, num_infer, num_label, num_correct) = \
            layers.chunk_eval(
                input=input, label=label, chunk_scheme=chunk_scheme,
                num_chunk_types=num_chunk_types,
                excluded_chunk_types=excluded_chunk_types,
                length=seq_length)
        layers.sums(input=[self.num_infer_chunks, num_infer],
                    out=self.num_infer_chunks)
        layers.sums(input=[self.num_label_chunks, num_label],
                    out=self.num_label_chunks)
        layers.sums(input=[self.num_correct_chunks, num_correct],
                    out=self.num_correct_chunks)
        self.metrics.extend([precision, recall, f1])

    def eval(self, executor, eval_program=None):
        num_infer, num_label, num_correct = [
            int(np.asarray(v).ravel()[0])
            for v in self._fetch_states(executor, eval_program)]
        precision = float(num_correct) / num_infer if num_infer else 0.0
        recall = float(num_correct) / num_label if num_label else 0.0
        f1 = 2.0 * precision * recall / (precision + recall) \
            if num_correct else 0.0
        return (np.array([precision], "float32"),
                np.array([recall], "float32"),
                np.array([f1], "float32"))


class EditDistance(Evaluator):
    """Accumulates edit distances; eval() -> (avg_distance,
    avg_instance_error) over every batch since the last reset."""

    def __init__(self, input, label, ignored_tokens=None, **kwargs):
        super().__init__("edit_distance", **kwargs)
        self.total_distance = self._create_state(
            suffix="total_distance", dtype="float32", shape=[1])
        self.seq_num = self._create_state(
            suffix="seq_num", dtype="int64", shape=[1])
        self.instance_error = self._create_state(
            suffix="instance_error", dtype="int64", shape=[1])
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        zero = layers.fill_constant(shape=[1], value=0.0, dtype="float32")
        right = layers.reduce_sum(
            layers.cast(layers.equal(distances, zero), "int64"))
        errors = layers.elementwise_sub(seq_num, right)
        total = layers.reduce_sum(distances)
        layers.sums(input=[self.total_distance, total],
                    out=self.total_distance)
        layers.sums(input=[self.seq_num, seq_num], out=self.seq_num)
        layers.sums(input=[self.instance_error, errors],
                    out=self.instance_error)
        self.metrics.extend([total, errors])

    def eval(self, executor, eval_program=None):
        total, seq_num, errors = [
            float(np.asarray(v).ravel()[0])
            for v in self._fetch_states(executor, eval_program)]
        if not seq_num:
            return np.array([0.0], "float32"), np.array([0.0], "float32")
        return (np.array([total / seq_num], "float32"),
                np.array([errors / seq_num], "float32"))


class DetectionMAP(Evaluator):
    """Per-batch mAP in-graph; multi-batch accumulation host-side (the
    deliberate XLA redesign — see the module docstring).

    ``get_map_var()`` returns ``(cur_map, accum_map)`` where both name
    the per-batch mAP var; fetch it each step and pass it to ``update``
    for the running accumulation, then ``eval_accumulated()``.
    """

    def __init__(self, input, gt_label, gt_box, gt_difficult=None,
                 class_num=None, background_label=0,
                 overlap_threshold=0.5, evaluate_difficult=True,
                 ap_version="integral", **kwargs):
        super().__init__("map_eval")
        gt_label = layers.cast(gt_label, gt_box.dtype)
        parts = [gt_label]
        if gt_difficult is not None:
            parts.append(layers.cast(gt_difficult, gt_box.dtype))
        parts.append(gt_box)
        label = layers.concat(parts, axis=-1)
        self.cur_map = layers.detection_map(
            input, label, class_num, background_label,
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version,
            **kwargs)
        self._maps = []

    def get_map_var(self):
        return self.cur_map, self.cur_map

    def reset(self, executor=None, reset_program=None):
        self._maps = []

    def update(self, batch_map):
        self._maps.append(float(np.asarray(batch_map).ravel()[0]))

    def eval_accumulated(self):
        if not self._maps:
            return np.array([0.0], "float32")
        return np.array([float(np.mean(self._maps))], "float32")

    def eval(self, executor=None, eval_program=None):
        return self.eval_accumulated()
