"""Goodput ledger: exclusive wall-clock attribution (ISSUE 10 tentpole).

The monitor stack can already say *what happened* — spans, per-program
flops, step stats — but not the question every capacity decision hinges
on: of every second this run spent, how much was productive compute vs.
input wait, compile, checkpoint stall, recovery replay, autotune
probing, or plain idle?  The Dapper lesson (PAPERS.md): raw spans are
useless until an aggregation layer turns them into an attributable
timeline.  CheckFreq makes the same point for checkpoint overhead as a
*budgeted fraction* of run time — this module generalizes that fraction
into a first-class, always-computed metric.

The :class:`GoodputLedger` consumes the event streams the monitor
already carries — ``RecordEvent`` span double-publish, ``record_step``
records, ``checkpoint_saved``/``guardian_rollback``/``watchdog_stall``
JSONL events — and classifies every second of run wall-clock into
**exclusive, exhaustive buckets** (:data:`BUCKETS`):

``compute``
    the step-path remainder after the badput below is carved out — the
    seconds the accelerator was (presumably) doing the model's math.
``input_wait``
    fetch-sync waits (the async window edge blocking on the device
    chain) plus the executor's own host->device feed staging.
``trace_compile``
    jaxpr trace + XLA compile (the ``executor/compile`` spans, outer
    lowering and cold-dispatch alike).
``checkpoint_stall``
    the SYNCHRONOUS leg of checkpointing only: the device->host
    snapshot, plus the write when ``async_save`` is off.  Async
    background writes are overlap, not stall (CheckFreq), and are
    tracked separately in ``overlap_seconds``.
``recovery``
    guardian rollback work (restore scan + apply) AND the replayed
    steps after it — a replayed step re-earns a result the run already
    had, so its wall clock is badput even though the device computed.
``probe``
    autotune ladder work: steps inside a ``probe_accounting`` window
    and the compile gaps leading into them.
``pipeline_bubble``
    pipeline-schedule fill/drain waste: the ParallelExecutor carves
    ``step_seconds x bubble_fraction`` out of every warm step of a
    program whose ``pipeline_region`` ops run pipelined on a ``pp``
    mesh, where the fraction is the executed schedule's exact per-tick
    stage-idle accounting (``parallel.pipeline.schedule_stats`` — the
    same tables the lowering is built from).  This is what makes the
    GPipe-vs-interleaved/1F1B delta attributed, not inferred.
``stall_idle``
    watchdog-detected stall windows falling between steps (a hung
    reader, a wedged device with nothing dispatched).
``other``
    everything else between steps — model build, host-side bookkeeping,
    artifact IO; the honest residual that keeps the sum exhaustive.

Exhaustiveness is by construction: every ``note_step`` advances an
``accounted-until`` watermark and attributes *all* wall clock between
the old and new watermark, so the bucket seconds always sum to the
ledger's observed wall clock (the acceptance test drives a monitored
run with a forced checkpoint, an injected-NaN rollback, and an autotune
probe, and checks the sum against externally measured wall clock within
1%).  Exclusivity holds because each classified span/second is consumed
exactly once: nested spans (``executor/trace`` inside
``executor/compile``), container spans (``executor/run``), and
overlapped background work (``prefetch/h2d_transfer``, async
``checkpoint/save``) are excluded from direct attribution.

Everything here is behind the monitor's enabled gate: a dark process
pays the same single module-global bool read per step it always did.
"""

import threading
import time

__all__ = [
    "BUCKETS", "SPAN_BUCKETS", "EXCLUDED_SPANS", "classify_span",
    "GoodputLedger",
]

# the exclusive, exhaustive attribution buckets, in report order
BUCKETS = ("compute", "input_wait", "trace_compile", "checkpoint_stall",
           "recovery", "probe", "pipeline_bubble", "stall_idle", "other")

# span name -> bucket, for spans that are DIRECT badput on the step
# path.  One classification table, two consumers: the live ledger here
# and tools/trace_summary.py's offline bucket section, so a shipped
# chrome trace and the run's own goodput summary agree on attribution.
SPAN_BUCKETS = {
    "executor/fetch_sync": "input_wait",
    "parallel_executor/fetch_sync": "input_wait",
    "executor/h2d_transfer": "input_wait",
    "parallel_executor/h2d_transfer": "input_wait",
    "executor/compile": "trace_compile",
    "parallel_executor/compile": "trace_compile",
    "checkpoint/snapshot": "checkpoint_stall",
    "guardian/rollback": "recovery",
    "pipeline/bubble": "pipeline_bubble",
}

# spans the classifier must NOT attribute directly, and why — nested
# inside a counted span, a container around the whole step, or work
# overlapped under compute on another thread.  trace_summary renders
# these as excluded so the two views stay reconciled.
EXCLUDED_SPANS = {
    "executor/trace": "nested inside executor/compile",
    "parallel_executor/trace": "nested inside parallel_executor/compile",
    "executor/run": "container (whole step)",
    "parallel_executor/run": "container (whole step)",
    "executor/dispatch": "step remainder (compute)",
    "parallel_executor/dispatch": "step remainder (compute)",
    "prefetch/h2d_transfer": "overlap (prefetch producer thread)",
    "checkpoint/save": "classified by checkpoint_saved event "
                       "(async writes are overlap, not stall)",
    "trainer/step": "container (step + bookkeeping)",
    "trainer/checkpoint": "container (snapshot span inside is counted)",
    # serving-engine containers: each wraps one executor step, whose own
    # compile/dispatch/fetch_sync spans carry the attribution — counting
    # the container too would double-book every serving second
    "serving/batch": "container (admission batch around an executor step)",
    "serving/prefill": "container (prefill batch around an executor step)",
    "serving/decode_step": "container (decode step around an executor "
                           "step)",
}


def classify_span(name, args=None):
    """Bucket for one completed span, or None when the span must not be
    attributed directly (container / nested / overlapped — see
    :data:`EXCLUDED_SPANS`).  An explicit ``bucket`` hint in the span's
    args (the executors tag their cold/warm step spans) wins over the
    name table, so new span names inherit attribution from their
    producer instead of silently landing nowhere.  ``args`` may be any
    user payload (RecordEvent doesn't validate it); only dicts are
    inspected — this must never raise into the step path."""
    if isinstance(args, dict):
        hint = args.get("bucket")
        if hint in BUCKETS:
            # step-span hints ("compute") describe the step remainder,
            # which note_step derives — only badput hints attribute
            return None if hint == "compute" else hint
    if name in EXCLUDED_SPANS:
        return None
    return SPAN_BUCKETS.get(name)


class GoodputLedger:
    """Turns the monitor's span/step/event streams into the exclusive
    wall-clock attribution above.

    Feed order does not matter within a step: spans and events arrive
    as they complete, and the following ``note_step`` (or a read-only
    ``summary``) attributes everything up to its own completion time.
    All entry points take their own lock and never raise into the step
    path."""

    # emit a cumulative ``goodput`` JSONL record every N steps so an
    # offline replay has checkpoints, not just per-step deltas
    EMIT_EVERY = 25
    # rolling per-step deltas kept for the watchdog's stall snapshot
    RECENT_STEPS = 32

    def __init__(self, registry=None):
        self._mu = threading.RLock()
        self._registry = registry
        self.reset()

    # ------------------------------------------------------------------
    def reset(self, now=None):
        """Start a fresh attribution window (monitor enable boundary,
        bench rung starts).  ``now`` defaults to the current wall
        clock; the first activity after reset re-anchors the start so a
        ledger reset long before the run does not book the dead time."""
        with self._mu:
            self._t_start = now          # None until first activity
            self._t_accounted = now
            self._totals = {b: 0.0 for b in BUCKETS}
            self._overlap = {}           # e.g. checkpoint_save (async)
            self._steps = 0
            self._probe_steps = 0
            self._recovery_steps = 0
            self._replay_debt = 0
            self._pending = []           # (bucket, seconds, t_done)
            self._stalls = []            # (t0, t1) watchdog windows
            self._recent = []            # (t_end, delta dict)
            self._emit_countdown = 1     # first step emits a record
            self._handles = None
            self._handle_gen = -1

    # -- feeds ---------------------------------------------------------
    def note_span(self, name, dur_s, args=None, now=None):
        """One completed span from ``monitor.observe_span``."""
        bucket = classify_span(name, args)
        if bucket is None:
            return
        now = time.time() if now is None else now
        with self._mu:
            self._touch(now - dur_s)
            self._pending.append((bucket, float(dur_s), now))

    def note_event(self, rec):
        """One JSONL record from ``monitor.log_event`` (tee).  Only the
        event kinds the ledger understands are inspected; everything
        else returns after one dict read."""
        ev = rec.get("event")
        if ev == "checkpoint_saved":
            secs = float(rec.get("seconds") or 0.0)
            if secs <= 0:
                return
            with self._mu:
                self._touch(rec.get("ts"))
                if rec.get("async"):
                    # background write under compute: overlap, not
                    # stall (CheckFreq) — reported, never bucketed
                    self._overlap["checkpoint_save"] = \
                        self._overlap.get("checkpoint_save", 0.0) + secs
                else:
                    self._pending.append(
                        ("checkpoint_stall", secs,
                         rec.get("ts") or time.time()))
        elif ev == "guardian_rollback":
            with self._mu:
                self._touch(rec.get("ts"))
                # the NEXT replay_steps completed steps re-earn work the
                # run already had: badput, attributed to recovery
                self._replay_debt += max(0, int(
                    rec.get("replay_steps") or 0))
        elif ev == "watchdog_stall":
            ts = rec.get("ts")
            dur = float(rec.get("stalled_for_s") or 0.0)
            if ts and dur > 0:
                with self._mu:
                    self._touch(ts - dur)
                    self._stalls.append((ts - dur, ts))
                    del self._stalls[:-16]

    def note_step(self, rec, now=None):
        """One completed executor step from ``monitor.record_step``.
        Attributes ALL wall clock since the previous watermark — the
        between-step gap, then the step itself — and returns the delta
        dict (nonzero buckets only) for the step's JSONL record."""
        now = time.time() if now is None else now
        step_s = float(rec.get("step_seconds") or 0.0)
        probe = bool(rec.get("probe"))
        with self._mu:
            self._touch(now - step_s)
            delta = {b: 0.0 for b in BUCKETS}
            t_begin = max(self._t_accounted, min(now - step_s, now))
            # --- the gap between the previous watermark and this step
            self._attribute_gap(self._t_accounted, t_begin, delta,
                                probe=probe)
            # --- the step itself: replay > probe > span carve-out
            in_step = self._drain_pending(t_begin)
            base = max(0.0, now - t_begin)
            span_s = min(base, step_s) if step_s > 0 else base
            if self._replay_debt > 0 and not probe:
                self._replay_debt -= 1
                self._recovery_steps += 1
                delta["recovery"] += span_s
            elif probe:
                self._probe_steps += 1
                delta["probe"] += span_s
            else:
                # the pipeline-bubble carve-out applies to the step's
                # COMPUTE REMAINDER, not the whole step: the emitted
                # span encodes the schedule's idle fraction as
                # seconds/step_seconds, and input-wait/compile seconds
                # were never pipelined time.  Recover the fraction and
                # apply it after the other carve-outs.
                bub = in_step.pop("pipeline_bubble", 0.0)
                known = sum(in_step.values())
                if known > span_s > 0:
                    # nesting/measurement noise: scale the carve-out
                    # down rather than let compute go negative
                    scale = span_s / known
                    in_step = {b: s * scale for b, s in in_step.items()}
                    known = span_s
                for b, s in in_step.items():
                    delta[b] += s
                rem = max(0.0, span_s - known)
                if bub > 0 and span_s > 0:
                    frac = min(1.0, bub / span_s)
                    delta["pipeline_bubble"] += frac * rem
                    rem -= frac * rem
                delta["compute"] += rem
            # any residue between span_s and the full watermark advance
            # (a step that began before the previous watermark —
            # concurrent executors) stays attributed: the gap handler
            # above covered [t_accounted, t_begin], and span_s covers
            # [t_begin, now]
            self._t_accounted = now
            self._steps += 1
            self._fold(delta)
            self._recent.append((now, delta))
            del self._recent[:-self.RECENT_STEPS]
            self._emit_countdown -= 1
            emit = self._emit_countdown <= 0
            if emit:
                self._emit_countdown = self.EMIT_EVERY
            self._publish()
        out = {b: round(s, 6) for b, s in delta.items() if s > 0}
        return out, emit

    # -- internals -----------------------------------------------------
    def _touch(self, t):
        """Anchor the window start at the FIRST observed activity."""
        if t is None:
            t = time.time()
        if self._t_start is None or t < self._t_start:
            self._t_start = t
        if self._t_accounted is None or self._t_accounted < self._t_start:
            self._t_accounted = self._t_start

    def _drain_pending(self, t_begin):
        """Split the pending classified spans at ``t_begin``: spans that
        completed inside the step window return as the in-step carve-out
        {bucket: seconds}; earlier ones stay pending for the gap
        handler.  Caller holds the lock."""
        in_step, remain = {}, []
        for bucket, secs, t_done in self._pending:
            # strictly after: a span completing exactly at the step
            # boundary belongs to the gap (the gap drain is inclusive,
            # so the pair of boundaries leaves nothing stuck pending)
            if t_done > t_begin:
                in_step[bucket] = in_step.get(bucket, 0.0) + secs
            else:
                remain.append((bucket, secs, t_done))
        self._pending = remain
        return in_step

    def _stall_overlap(self, t0, t1):
        """Seconds of watchdog stall windows overlapping [t0, t1);
        consumed windows are trimmed so no stall second counts twice."""
        total = 0.0
        keep = []
        for s0, s1 in self._stalls:
            lo, hi = max(s0, t0), min(s1, t1)
            if hi > lo:
                total += hi - lo
                if s1 > t1:       # tail extends past the gap: keep it
                    keep.append((t1, s1))
            else:
                keep.append((s0, s1))
        self._stalls = keep
        return total

    def _attribute_gap(self, t0, t1, delta, probe=False, drain=True):
        """Attribute the between-step wall clock [t0, t1): first the
        classified gap spans (sync checkpoint legs, rollback restores),
        then watchdog stall overlap, then probe lead-in compiles, then
        the honest ``other`` residual.  Caller holds the lock."""
        gap = max(0.0, (t1 or 0.0) - (t0 or 0.0))
        if gap <= 0:
            return
        known = {}
        if drain:
            remain = []
            for bucket, secs, t_done in self._pending:
                if t_done <= t1:
                    known[bucket] = known.get(bucket, 0.0) + secs
                else:
                    remain.append((bucket, secs, t_done))
            self._pending = remain
        known_total = sum(known.values())
        if known_total > gap > 0:
            scale = gap / known_total
            known = {b: s * scale for b, s in known.items()}
            known_total = gap
        for b, s in known.items():
            delta[b] += s
        rest = gap - known_total
        if rest <= 0:
            return
        stall = min(rest, self._stall_overlap(t0, t1))
        delta["stall_idle"] += stall
        rest -= stall
        if rest <= 0:
            return
        # the gap leading into a probe step is probe work too: the
        # tuner's cost_analysis compiles happen between its steps
        delta["probe" if probe else "other"] += rest

    def _fold(self, delta):
        for b, s in delta.items():
            if s:
                self._totals[b] += s

    def _publish(self):
        """Registry twin of the totals: ``badput/<bucket>_seconds``
        counters, a ``goodput/compute_seconds`` counter, and the
        ``goodput/ratio`` gauge.  Handles are cached per registry
        generation like the monitor's span histograms.  Caller holds
        the lock."""
        reg = self._registry
        if reg is None:
            return
        if self._handles is None or self._handle_gen != reg.generation:
            self._handle_gen = reg.generation
            self._handles = {"ratio": reg.gauge("goodput/ratio"),
                             "wall": reg.gauge("goodput/wall_seconds"),
                             "compute":
                             reg.counter("goodput/compute_seconds")}
            for b in BUCKETS[1:]:
                self._handles[b] = reg.counter(
                    "badput/%s_seconds" % b)
            self._published = {b: 0.0 for b in BUCKETS}
        for b in BUCKETS:
            inc = self._totals[b] - self._published[b]
            if inc > 0:
                (self._handles["compute"] if b == "compute"
                 else self._handles[b]).inc(inc)
                self._published[b] += inc
        wall = sum(self._totals.values())
        self._handles["wall"].set(wall)
        if wall > 0:
            self._handles["ratio"].set(self._totals["compute"] / wall)

    # -- read side -----------------------------------------------------
    @property
    def steps(self):
        return self._steps

    def totals(self):
        """Attributed bucket seconds so far (no tail projection)."""
        with self._mu:
            return dict(self._totals)

    def summary(self, now=None):
        """The per-run attribution summary: bucket seconds (with the
        not-yet-attributed tail folded through the same gap classifier,
        so the dict is exhaustive as of ``now``), total wall, goodput
        ratio, step/replay/probe counts, and the overlapped (non-stall)
        seconds for context.  Read-only: the watermark does not move."""
        now = time.time() if now is None else now
        with self._mu:
            buckets = dict(self._totals)
            if self._t_start is not None and self._t_accounted is not None:
                tail = {b: 0.0 for b in BUCKETS}
                # non-mutating pass: classify the pending spans/stalls
                # in the tail without consuming them
                pending, stalls = self._pending, self._stalls
                try:
                    self._pending = list(pending)
                    self._stalls = list(stalls)
                    self._attribute_gap(self._t_accounted, now, tail)
                finally:
                    self._pending, self._stalls = pending, stalls
                for b, s in tail.items():
                    buckets[b] += s
            buckets = {b: round(s, 6) for b, s in buckets.items()}
            wall = sum(buckets.values())
            out = {"buckets": buckets,
                   "wall_seconds": round(wall, 6),
                   "goodput_ratio": round(buckets["compute"] / wall, 4)
                   if wall > 0 else None,
                   "steps": self._steps,
                   "probe_steps": self._probe_steps,
                   "recovery_replayed_steps": self._recovery_steps,
                   "overlap_seconds": {k: round(v, 6) for k, v
                                       in self._overlap.items()}}
            return out

    def snapshot_for_stall(self):
        """Compact recent-window view for the watchdog's stall dump: a
        stall report that says '97% input_wait over the last window' is
        actionable; 'no step completed' is not."""
        with self._mu:
            recent = list(self._recent)
            cum = self.summary()
        window = {}
        for _, delta in recent:
            for b, s in delta.items():
                window[b] = window.get(b, 0.0) + s
        total = sum(window.values())
        out = {"cumulative_ratio": cum["goodput_ratio"],
               "recent_steps": len(recent)}
        if total > 0:
            out["recent_fractions"] = {
                b: round(s / total, 3) for b, s in sorted(
                    window.items(), key=lambda kv: -kv[1]) if s > 0}
        return out
