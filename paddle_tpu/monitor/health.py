"""Model-health telemetry + NaN provenance (ISSUE 20 tentpole).

The observability stack covers the *system* axis (StepStats, program
profiles, goodput, tracing, fleet aggregation); this module covers the
*model* axis — the numeric health of the thing being trained:

* **In-graph health probe** (``FLAGS_health``): the executors trace the
  step with the parameter gradients appended as extra fetches and a
  small fused reduction over them — per layer class: gradient L2 norm,
  parameter L2 norm, update/param ratio ``||new-old||/(||old||+eps)``,
  and a non-finite element count — returned as ONE extra ``(L, 4)``
  fetch.  Parameters are classified into layer classes by reusing
  ``spec_layout.classify_params``'s program-structure scan (embedding /
  norm / mlp_col / mlp_row / mlp_bias), so the probe needs no model
  annotations.  The stats are computed on-device every step (fused into
  the step module; they never feed the state math, so the training
  trajectory is bit-identical with the flag on or off) and *published*
  host-side at a decimated cadence (``FLAGS_health_every``): gauges
  ``health/<layer>/*`` + a run_id-stamped ``model_health`` JSONL record.
  Because the fleet digest (ISSUE 19) ships the whole registry, the
  per-layer gauges ride the existing heartbeat envelope to the fleet
  master for free.  Disabled cost is zero health calls — the probe is
  part of the traced jaxpr, so ``FLAGS_health`` is re-keyed through
  ``compile_cache.trace_flag_values``.
* **NaN provenance** (``nan_provenance``): when the guardian sentinel
  trips or ``check_nan_inf`` raises, a one-shot OFF-hot-path
  instrumented replay of the already-quarantined batch through the
  debug-lowered program variant (``transpiler.nan_debug``) evaluates
  per-op output isfinite flags in topological order and names the FIRST
  offending op (op type, output var, layer class).  The record lands in
  the quarantine sidecar, a ``guardian_nan_provenance`` JSONL event,
  and the abort message.  The replay context (program, scope, PRNG key,
  feed) is stashed per step while the probe is on; the PRNG key data
  rides in the record so the replay is reproducible offline from the
  sidecar alone.

``tools/health_report.py`` renders the JSONL records as a per-layer
table; ``alerts.default_rules()`` gains grad-norm-explosion and
update-ratio-collapse rules over the fleet view's per-host health
summary (``aggregate._view_locked``).
"""

import collections
import time

import numpy as np

__all__ = [
    "enabled", "probe_enabled", "build_probe", "wrap_step_probe",
    "note_step", "last_snapshot", "format_snapshot", "stamp",
    "nan_provenance", "HealthProbe",
]

# fast-path gate, same contract as monitor._enabled: a module-global
# bool read is all a disabled process pays (zero health calls — the
# executors gate every call site on `compiled.probe is not None`, and
# the probe is only built while this is True)
_ENABLED = False
_EVERY = [10]

# last published per-layer snapshot (kept even while the monitor is
# off: watchdog stall dumps and guardian abort diagnostics read it)
_SNAPSHOT = [None]

# per-step replay contexts for NaN provenance, step -> context dict;
# bounded: the guardian's deferred observations trail the executor by
# at most the dispatch window, so a small ring covers every step it can
# still decide on
_REPLAY = collections.OrderedDict()
_REPLAY_MAX = 32

_EPS = 1e-12

# logical-axes tuple (spec_layout.classify_params) -> layer class label
_AXES_LABEL = {
    ("vocab", "embed"): "embedding",
    ("norm",): "norm",
    ("embed", "mlp"): "mlp_col",
    ("mlp", "embed"): "mlp_row",
    ("mlp",): "mlp_bias",
}


def _reconcile():
    """FLAGS_health family on_set hook: mirror the flags into the
    module globals (one bool + the publication cadence)."""
    from .. import flags

    global _ENABLED
    try:
        _ENABLED = bool(flags.flag("health"))
        _EVERY[0] = max(1, int(flags.flag("health_every")))
    except KeyError:
        # registration-time env override: the sibling flag registers a
        # beat later; its own on_set re-runs this
        pass
    if not _ENABLED:
        _REPLAY.clear()


def enabled():
    return _ENABLED


def probe_enabled():
    """Whether steps are lowered with the in-graph health probe — part
    of ``compile_cache.trace_flag_values()`` (the probe's extra fetches
    are baked into the jaxpr, so flipping FLAGS_health re-lowers
    instead of serving a stale probed/unprobed trace)."""
    return _ENABLED


class HealthProbe:
    """One program's probe plan: layer classes in publication order,
    the ``(param, grad-or-None)`` members of each, and the flat list of
    gradient vars the executors append as extra fetches."""

    def __init__(self, labels, layers, grad_names):
        self.labels = labels          # ordered layer-class labels
        self.layers = layers          # label -> [(param, grad or None)]
        self.grad_names = grad_names  # extra fetch vars, flat + ordered
        self.stat_names = ("grad_norm", "param_norm", "update_ratio",
                           "nonfinite")
        # param -> label, precomputed once (note_step stashes it into
        # every step's replay context)
        self.param_labels = {p: lb for lb in labels
                             for p, _ in layers[lb]}


def build_probe(program, state_names):
    """Classify ``program``'s parameters into layer classes
    (``spec_layout.classify_params`` — the same program-structure scan
    that drives mesh placement) and plan the probe: which ``@GRAD``
    vars to fetch and which state vars each layer's norms read.
    Returns None when the program trains nothing (no classified param
    and no gradient output — eval/startup programs)."""
    from ..framework import GRAD_VAR_SUFFIX
    from ..parallel.spec_layout import classify_params

    classes = classify_params(program)
    produced = set()
    for blk in program.blocks:
        for op in blk.ops:
            produced.update(n for n in op.output_arg_names if n)
    by_label = {}
    for p in state_names:
        g = p + GRAD_VAR_SUFFIX
        has_grad = g in produced
        label = _AXES_LABEL.get(classes.get(p))
        if label is None:
            if not has_grad:
                # optimizer slots, counters, LR, tables of odd rank:
                # no gradient and no class — not a layer
                continue
            label = "other"
        by_label.setdefault(label, []).append((p, g if has_grad else None))
    if not by_label or not any(g for members in by_label.values()
                               for _, g in members):
        return None
    labels = sorted(by_label)
    grad_names = [g for lb in labels for _, g in by_label[lb]
                  if g is not None]
    return HealthProbe(labels, by_label, grad_names)


def wrap_step_probe(fn, probe, n_user, guarded, state_in, state_out):
    """Wrap a traced step function (already guard-wrapped when
    ``guarded``) with the in-graph stat reduction: the ``@GRAD`` extra
    fetches are consumed and ONE ``(L, 4)`` float32 stats array is
    appended after the user fetches (before the guard's trailing
    ``ok``, which stays last — the executors strip back-to-front).
    The stats never feed the state math: bit-parity with the probe off
    is structural, not incidental."""
    import jax.numpy as jnp

    in_idx = {n: i for i, n in enumerate(state_in)}
    out_idx = {n: i for i, n in enumerate(state_out)}
    grad_pos = {g: n_user + i for i, g in enumerate(probe.grad_names)}

    def probed(feed_vals, state_vals, key):
        fetches, new_state = fn(feed_vals, state_vals, key)
        tail = [fetches[-1]] if guarded else []
        body = fetches[:-1] if guarded else list(fetches)
        rows = []
        for label in probe.labels:
            gsq = jnp.float32(0.0)
            psq = jnp.float32(0.0)
            usq = jnp.float32(0.0)
            nf = jnp.float32(0.0)
            for p, g in probe.layers[label]:
                if g is not None:
                    gv = body[grad_pos[g]].astype(jnp.float32)
                    gsq = gsq + jnp.sum(gv * gv)
                    nf = nf + jnp.sum(
                        (~jnp.isfinite(gv)).astype(jnp.float32))
                ni = out_idx.get(p)
                oi = in_idx.get(p)
                pv = new_state[ni] if ni is not None else (
                    state_vals[oi] if oi is not None else None)
                if pv is not None:
                    pv = pv.astype(jnp.float32)
                    psq = psq + jnp.sum(pv * pv)
                    if ni is not None and oi is not None:
                        dv = pv - state_vals[oi].astype(jnp.float32)
                        usq = usq + jnp.sum(dv * dv)
            pn = jnp.sqrt(psq)
            rows.append(jnp.stack([jnp.sqrt(gsq), pn,
                                   jnp.sqrt(usq) / (pn + _EPS), nf]))
        stats = jnp.stack(rows)
        return list(body[:n_user]) + [stats] + tail, new_state

    return probed


def note_step(executor_name, step, probe, stats, program=None,
              scope=None, rng=None, feed_names=(), feed_vals=(),
              platform=None):
    """One probed executor step completed.  Always stashes the NaN
    replay context (cheap: reference assignments, no device sync — the
    rng key handle is kept as-is and only materialized at provenance
    time); publishes the per-layer snapshot at the decimated
    ``FLAGS_health_every`` cadence (``np.asarray`` on the stats fetch —
    the probe's only host sync, never on off-cadence steps)."""
    from .. import flags
    from . import enabled as _mon_enabled, log_event, registry

    if not _ENABLED:
        return None
    step = int(step)
    _REPLAY[step] = {
        "executor": executor_name, "program": program, "scope": scope,
        "rng": rng, "impl": "rbg" if flags.flag("fast_prng") else None,
        "feed_names": tuple(feed_names), "feed_vals": list(feed_vals),
        "platform": platform,
        "labels": probe.param_labels,
    }
    while len(_REPLAY) > _REPLAY_MAX:
        _REPLAY.popitem(last=False)
    if step % _EVERY[0]:
        return None
    if hasattr(stats, "is_fully_addressable") \
            and not stats.is_fully_addressable:
        # multi-host: the stats fetch is forced replicated (PE fetch
        # shardings), so any local shard holds the full array
        stats = stats.addressable_shards[0].data
    arr = np.asarray(stats, dtype=np.float64)
    snap = {"event": "model_health", "ts": time.time(),
            "executor": executor_name, "step": step, "layers": {}}
    for i, label in enumerate(probe.labels):
        gn, pn, ur, nf = (float(arr[i, 0]), float(arr[i, 1]),
                          float(arr[i, 2]), int(arr[i, 3]))
        snap["layers"][label] = {
            "grad_norm": gn, "param_norm": pn,
            "update_ratio": ur, "nonfinite": nf}
    _SNAPSHOT[0] = snap
    if _mon_enabled():
        reg = registry()
        for label, d in snap["layers"].items():
            base = "health/%s/" % label
            for k in ("grad_norm", "param_norm", "update_ratio"):
                reg.gauge(base + k).set(float(d[k]))
            reg.gauge(base + "nonfinite").set(float(d["nonfinite"]))
    log_event(dict(snap, layers={k: dict(v)
                                 for k, v in snap["layers"].items()}))
    return snap


def last_snapshot():
    """The last published per-layer snapshot dict (or None): watchdog
    stall dumps and guardian abort diagnostics read it regardless of
    the monitor's enablement."""
    return _SNAPSHOT[0]


def format_snapshot(snap=None):
    """One compact line per layer for abort messages / stall dumps:
    ``mlp_col grad_norm=1.2e+03 update_ratio=3.4e-03 nonfinite=0``."""
    snap = snap if snap is not None else _SNAPSHOT[0]
    if not snap:
        return ""
    parts = []
    for label in sorted(snap.get("layers", {})):
        d = snap["layers"][label]
        parts.append("%s grad_norm=%.3g update_ratio=%.3g nonfinite=%d"
                     % (label, d["grad_norm"], d["update_ratio"],
                        d["nonfinite"]))
    return "step %d: %s" % (snap.get("step", -1), "; ".join(parts))


def stamp():
    """Log the last snapshot as a ``model_health`` JSONL record (run
    boundaries — the Trainer stamps it next to the goodput summary so
    post-mortems start from the final per-layer state) and return it."""
    from . import log_event

    snap = _SNAPSHOT[0]
    if snap is not None:
        log_event(dict(snap, ts=time.time(),
                       layers={k: dict(v)
                               for k, v in snap["layers"].items()}))
    return snap


def _clear_for_tests():
    _REPLAY.clear()
    _SNAPSHOT[0] = None


def nan_provenance(step, feed=None):
    """One-shot NaN provenance for ``step``: replay the stashed context
    (optionally overriding the feed with the guardian's quarantined
    ``(names, vals)``) through the debug-lowered op walk and name the
    FIRST op whose output is non-finite.  Returns a JSON-safe record
    (``found`` False when the replay stays finite — host-side
    corruption the graph never produced), or None when disabled or no
    context was stashed.  Never raises: provenance is diagnostics on
    the abort path, it must not mask the real failure."""
    if not _ENABLED:
        return None
    ctx = _REPLAY.get(int(step))
    if ctx is None:
        return None
    from ..transpiler import nan_debug

    names, vals = (feed if feed is not None
                   else (ctx["feed_names"], ctx["feed_vals"]))
    rec = {"step": int(step), "executor": ctx["executor"],
           "found": False, "key_impl": ctx["impl"]}
    t0 = time.perf_counter()
    try:
        import jax

        if ctx["rng"] is not None:
            rec["key_data"] = np.asarray(
                jax.random.key_data(ctx["rng"])).tolist()
        hit = nan_debug.first_nonfinite_op(
            ctx["program"], dict(zip(names, vals)), ctx["scope"],
            key=ctx["rng"], platform=ctx["platform"],
            classify=ctx["labels"])
    except Exception as e:  # noqa: BLE001 — diagnostics must not mask
        rec["error"] = repr(e)
        hit = None
    rec["replay_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    if hit is not None:
        rec.update(hit)
        rec["found"] = True
    return rec
