"""Program-level cost & memory attribution (ISSUE 5 tentpole).

The monitor's StepStats answer "how fast is the run going"; this module
answers "which compiled program is spending the time and the HBM".  At
the one compile each (program, feed signature) already pays — the cold
dispatch in ``Executor.run`` / ``ParallelExecutor.run`` — the executor
calls :func:`capture` with the jitted step and its concrete arguments.
Capture AOT-compiles via ``jit.lower(args).compile()``, reads the
compiled module's ``cost_analysis()`` (flops, bytes accessed) and
``memory_analysis()`` (argument/output/temp/generated-code/alias bytes),
and hands the executable back to the executor, which dispatches every
step of that signature through it — so the capture IS the one compile,
**zero additional lowerings or backend compiles** (jax's AOT and jit
call paths do NOT share a backend-compile cache, so compiling through
the jit call and separately analyzing would pay the XLA pipeline
twice).  The AOT call path costs a few microseconds over the C++ jit
fast path, paid only while capture is enabled (monitor on, or the
preflight explicitly forced).

Profiles land in a process-global registry keyed by
``compile_cache.program_fingerprint`` + feed signature.  Per-program
*step accounting* (steps, wall clock, examples) accumulates via
:func:`note_step`, fed from ``monitor.record_step``; :func:`report_rows`
joins the two into the per-program table (flops, bytes, peak HBM, steps,
wall-clock share, ground-truth MFU from the compiler's own flop count —
the ``est_mfu`` heuristic's replacement) that ``tools/program_report.py``
renders from a live registry or a JSONL log.

**HBM preflight**: before the first dispatch of a newly compiled
program, the estimated peak device memory (arguments + outputs + temps +
generated code - aliased/donated) is compared against the device's
reported capacity (``device.memory_stats()['bytes_limit']``, overridable
via ``FLAGS_preflight_hbm_bytes``).  Over capacity →
``warnings.warn`` with the per-buffer-class breakdown, or
:class:`PreflightOOMError` under ``FLAGS_preflight_oom=strict`` —
instead of letting XLA OOM mid-run.
"""

import contextlib
import os
import threading
import time
import warnings

__all__ = [
    "PreflightOOMError", "ProgramProfile", "capture_enabled", "capture",
    "store_compiled", "get", "profiles", "note_step", "accounting",
    "probe_accounting", "probe_active", "probe_totals", "summary_for",
    "report_rows", "render_table", "reset", "reset_accounting",
    "DEFAULT_PEAK_TFLOPS",
]

# chip peak (bf16 matmul TFLOP/s) for the MFU column; same env knob as
# bench.py so the two agree on the denominator.  v5e default.
DEFAULT_PEAK_TFLOPS = float(os.environ.get("BENCH_PEAK_TFLOPS", "197"))

_mu = threading.Lock()
# (fingerprint, feed_sig, fetch_names, trace_flags, kind, partition) ->
# ProgramProfile: different fetch sets — and different trace-time flag
# choices (kernel selection etc., mirroring compile_cache.trace_key) —
# lower the same program+feeds to different XLA modules with different
# flops/bytes, so both are part of the identity.  ``partition`` is the
# executor's mesh/sharding identity: the same program compiled
# replicated and fsdp-sharded has per-device argument/peak-HBM bytes
# differing by ~N, and the two must not clobber each other's slot
# (the replicated-vs-fsdp A/B rung is exactly this pattern).
_profiles = {}
_acct = {}          # fingerprint -> {steps, wall_s, examples, kind}
# auto-tuner probe dispatches accumulate HERE, never in _acct: a probe
# of the same fingerprint the run later trains steady-state must not
# blend its wall clock into the steady row's share/MFU
_acct_probe = {}
_warned = set()     # (fingerprint, feed_sig, partition) preflight warns issued


class PreflightOOMError(RuntimeError):
    """Estimated peak device memory exceeds capacity
    (``FLAGS_preflight_oom=strict``)."""


class ProgramProfile:
    """One compiled (program, feed signature, fetch set)'s cost/memory
    profile, as captured from the XLA compiled module's own accounting."""

    __slots__ = ("fingerprint", "feed_sig", "fetch_names", "kind", "ts",
                 "cost", "flops",
                 "bytes_accessed", "argument_bytes", "output_bytes",
                 "temp_bytes", "generated_code_bytes", "alias_bytes",
                 "peak_hbm_bytes", "device", "partition")

    def __init__(self, fingerprint, feed_sig, kind, cost=None, flops=0.0,
                 bytes_accessed=0.0, argument_bytes=0, output_bytes=0,
                 temp_bytes=0, generated_code_bytes=0, alias_bytes=0,
                 peak_hbm_bytes=0, device=None, fetch_names=(),
                 partition=None):
        self.fingerprint = fingerprint
        self.feed_sig = tuple(feed_sig)
        self.fetch_names = tuple(fetch_names)
        self.kind = kind
        self.partition = partition
        self.ts = time.time()
        self.cost = dict(cost or {})
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.argument_bytes = int(argument_bytes)
        self.output_bytes = int(output_bytes)
        self.temp_bytes = int(temp_bytes)
        self.generated_code_bytes = int(generated_code_bytes)
        self.alias_bytes = int(alias_bytes)
        self.peak_hbm_bytes = int(peak_hbm_bytes)
        self.device = device

    def breakdown(self):
        """Per-buffer-class bytes, the preflight diagnostic's currency."""
        return {"argument_bytes": self.argument_bytes,
                "output_bytes": self.output_bytes,
                "temp_bytes": self.temp_bytes,
                "generated_code_bytes": self.generated_code_bytes,
                "alias_bytes": self.alias_bytes,
                "peak_hbm_bytes": self.peak_hbm_bytes}

    def as_dict(self):
        d = {"fingerprint": self.fingerprint,
             "kind": self.kind,
             "feed_sig": [[n, list(s), dt] for n, s, dt in self.feed_sig],
             "fetch_names": list(self.fetch_names),
             "flops": self.flops,
             "bytes_accessed": self.bytes_accessed,
             "device": self.device,
             "partition": str(self.partition) if self.partition else None}
        d.update(self.breakdown())
        return d


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------

def _flag(name, default):
    from .. import flags

    try:
        return flags.flag(name)
    except KeyError:
        return default


def _preflight_mode():
    v = str(_flag("preflight_oom", "auto")).strip().lower()
    if v in ("off", "0", "false", "no", "none", ""):
        return "off"
    if v == "strict":
        return "strict"
    return "auto" if v == "auto" else "warn"


def capture_enabled():
    """Whether the executors should capture profiles at the cold
    dispatch.  True when the monitor is on, or when the operator forced
    the HBM preflight (``FLAGS_preflight_oom=warn|strict``) on an
    unmonitored run.  Checked only on compile steps, never per warm
    step: an unmonitored, un-preflighted process runs the executors'
    unmodified jit path."""
    from . import enabled

    return enabled() or _preflight_mode() in ("warn", "strict")


def capture(fingerprint, feed_sig, jit_fn, args, device=None,
            kind="executor", fetch_names=(), partition=None):
    """AOT-compile the step this (jitted fn, concrete args) maps to,
    profile it, and run the HBM preflight — called by the executors at
    the cold dispatch, *before* the step executes.  The returned
    ``jax.stages.Compiled`` is THE executable for this signature: the
    executor dispatches every step of it through the returned object, so
    the one compile that was always going to happen simply happens here
    — where its ``cost_analysis()``/``memory_analysis()`` are readable —
    instead of inside the jit call.  Zero additional lowerings or
    backend compiles; the per-step cost is the AOT call path's few
    microseconds over the C++ jit fast path, paid only while capture is
    enabled.

    Returns the Compiled executable, or None if the backend refuses AOT
    compilation (the executor then falls back to the plain jit call).
    Raises :class:`PreflightOOMError` under ``FLAGS_preflight_oom=strict``
    when the memory estimate exceeds capacity — analysis failures
    themselves never break the step.
    """
    try:
        compiled = jit_fn.lower(*args).compile()
    except Exception:  # noqa: BLE001 — observability must not break steps
        return None
    prof = store_compiled(fingerprint, feed_sig, compiled, device=device,
                          kind=kind, fetch_names=fetch_names,
                          partition=partition)
    if prof is not None:
        _preflight(prof, device)
    return compiled


def store_compiled(fingerprint, feed_sig, compiled, device=None,
                   kind="executor", fetch_names=(), partition=None):
    """Extract cost/memory analyses from a ``jax.stages.Compiled`` and
    store the profile (shared by :func:`capture` and the explicit
    ``Executor.cost_analysis`` fallback path).  No preflight here."""
    cost = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        cost = dict(ca or {})
    except Exception:  # noqa: BLE001
        pass
    mem = {}
    try:
        ma = compiled.memory_analysis()
        if ma is not None:
            mem = {k: int(getattr(ma, k + "_size_in_bytes", 0) or 0)
                   for k in ("argument", "output", "temp",
                             "generated_code", "alias")}
    except Exception:  # noqa: BLE001
        pass
    if not cost and not mem:
        return None
    # donated (aliased) buffers are counted in both arguments and
    # outputs but occupy one allocation; generated code (constants,
    # scratch tables) lives in HBM too
    peak = (mem.get("argument", 0) + mem.get("output", 0)
            + mem.get("temp", 0) + mem.get("generated_code", 0)
            - mem.get("alias", 0))
    prof = ProgramProfile(
        fingerprint, feed_sig, kind, cost=cost,
        flops=cost.get("flops", 0.0) or 0.0,
        bytes_accessed=cost.get("bytes accessed", 0.0) or 0.0,
        argument_bytes=mem.get("argument", 0),
        output_bytes=mem.get("output", 0),
        temp_bytes=mem.get("temp", 0),
        generated_code_bytes=mem.get("generated_code", 0),
        alias_bytes=mem.get("alias", 0),
        peak_hbm_bytes=max(0, peak),
        device=str(getattr(device, "platform", device) or "") or None,
        fetch_names=fetch_names, partition=partition)
    with _mu:
        _profiles[(fingerprint, prof.feed_sig, prof.fetch_names,
                   _trace_flags(), kind, partition)] = prof
    from . import log_event

    log_event(dict(prof.as_dict(), event="program_profile", ts=prof.ts))
    return prof


# ---------------------------------------------------------------------------
# HBM preflight
# ---------------------------------------------------------------------------

def _device_capacity(device):
    """Device memory capacity in bytes: ``FLAGS_preflight_hbm_bytes``
    when set (tests, or backends that misreport), else the backend's
    ``memory_stats()['bytes_limit']``; None = unknown (preflight skips)."""
    override = int(_flag("preflight_hbm_bytes", 0))
    if override > 0:
        return override
    if device is None:
        return None
    try:
        ms = device.memory_stats()
    except Exception:  # noqa: BLE001 — CPU/older backends
        return None
    if not ms:
        return None
    return ms.get("bytes_limit") or None


def _fmt_mib(n):
    """Adaptive byte formatting (toy CPU-test programs are KiB-scale,
    real steps GiB-scale; '0.0 MiB' helps neither)."""
    n = int(n)
    if n >= 1 << 30:
        return "%.2f GiB" % (n / (1 << 30))
    if n >= 1 << 20:
        return "%.1f MiB" % (n / (1 << 20))
    if n >= 1 << 10:
        return "%.1f KiB" % (n / (1 << 10))
    return "%d B" % n


def _preflight(prof, device):
    mode = _preflight_mode()
    if mode == "off":
        return
    # "auto" = ride along on monitor-gated captures in warn mode
    if mode == "auto":
        mode = "warn"
    cap = _device_capacity(device)
    if not cap or prof.peak_hbm_bytes <= cap:
        return
    msg = ("HBM preflight: program %s (%s) estimated peak device memory "
           "%s exceeds capacity %s — arguments %s + outputs %s + temps "
           "%s + generated code %s - aliased(donated) %s"
           % (prof.fingerprint[:12], prof.kind,
              _fmt_mib(prof.peak_hbm_bytes), _fmt_mib(cap),
              _fmt_mib(prof.argument_bytes), _fmt_mib(prof.output_bytes),
              _fmt_mib(prof.temp_bytes),
              _fmt_mib(prof.generated_code_bytes),
              _fmt_mib(prof.alias_bytes)))
    from . import enabled, log_event, registry

    if enabled():
        registry().counter("monitor/preflight_oom").inc()
        log_event({"event": "preflight_oom", "ts": time.time(),
                   "fingerprint": prof.fingerprint, "mode": mode,
                   "capacity_bytes": int(cap),
                   "breakdown": prof.breakdown()})
    if mode == "strict":
        raise PreflightOOMError(msg)
    key = (prof.fingerprint, prof.feed_sig, prof.partition)
    with _mu:
        if key in _warned:
            return
        _warned.add(key)
    warnings.warn(msg, stacklevel=3)


# ---------------------------------------------------------------------------
# registry access + step accounting
# ---------------------------------------------------------------------------

def _trace_flags():
    """Trace-time flag choices baked into a lowering (the same tuple
    compile_cache.trace_key carries): two kernel-selection variants of
    one program must not share a profile slot."""
    from .. import compile_cache

    return compile_cache.trace_flag_values()


def get(fingerprint, feed_sig=None, kind="executor", fetch_names=(),
        partition=None):
    """Profile for (fingerprint, feed_sig, fetch_names, current trace
    flags, kind, partition); with ``feed_sig=None`` the most recently
    captured profile for the fingerprint regardless of signature/fetch
    set/flags/kind/partition."""
    with _mu:
        if feed_sig is not None:
            return _profiles.get((fingerprint, tuple(feed_sig),
                                  tuple(fetch_names), _trace_flags(),
                                  kind, partition))
        best = None
        for key, p in _profiles.items():
            if key[0] == fingerprint and (best is None or p.ts >= best.ts):
                best = p
        return best


def profiles():
    with _mu:
        return list(_profiles.values())


# auto-tuner probe window depth: steps recorded while a probe window is
# open tag their accounting entries, so a tuner's throwaway candidate
# dispatches never blend into the per-program report's wall-share/MFU
# rows (the same program fingerprint later running steady-state clears
# the tag — "probe" means probe-ONLY)
_probe_depth = [0]


@contextlib.contextmanager
def probe_accounting():
    """Mark the dynamic extent of an auto-tuner probe: every step
    recorded inside is PROBE work.  Re-entrant (nested tuners)."""
    with _mu:
        _probe_depth[0] += 1
    try:
        yield
    finally:
        with _mu:
            _probe_depth[0] -= 1


def probe_active():
    """Whether an auto-tuner probe window is open (see
    :func:`probe_accounting`)."""
    return _probe_depth[0] > 0


def note_step(fingerprint, step_seconds, examples, kind="executor"):
    """Fold one completed step into the per-program accounting (called
    from ``monitor.record_step`` when a fingerprint is attached).
    Steps inside a :func:`probe_accounting` window land in a SEPARATE
    probe bucket — a tuner probing the very fingerprint the run then
    trains steady-state must not blend its candidates' wall clock into
    the steady row."""
    with _mu:
        acct = _acct_probe if probe_active() else _acct
        a = acct.get(fingerprint)
        if a is None:
            a = acct[fingerprint] = {"steps": 0, "wall_s": 0.0,
                                     "examples": 0, "kind": kind}
        a["steps"] += 1
        a["wall_s"] += float(step_seconds or 0.0)
        a["examples"] += int(examples or 0)
        a["kind"] = kind


def accounting():
    """Steady-state step accounting (probe work excluded; see
    :func:`probe_totals`)."""
    with _mu:
        return {fp: dict(a) for fp, a in _acct.items()}


def probe_totals():
    """The tuner-probe accounting bucket, keyed like
    :func:`accounting`."""
    with _mu:
        return {fp: dict(a) for fp, a in _acct_probe.items()}


def summary_for(fingerprint):
    """Compact profile + accounting summary for one program — the
    watchdog attaches this for the last dispatched program so a stall
    report names the suspect."""
    if not fingerprint:
        return None
    prof = get(fingerprint)
    with _mu:
        a = dict(_acct.get(fingerprint) or {})
    out = {"fingerprint": fingerprint[:12]}
    if a:
        out.update({"steps": a["steps"],
                    "wall_s": round(a["wall_s"], 3)})
    if prof is not None:
        out.update({"flops": prof.flops,
                    "bytes_accessed": prof.bytes_accessed,
                    "peak_hbm_bytes": prof.peak_hbm_bytes})
    return out


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def report_rows(peak_tflops=None, profiles_by_fp=None, acct_by_fp=None,
                probe_acct_by_fp=None):
    """Join profiles + step accounting into per-program report rows,
    sorted by wall-clock share.  ``profiles_by_fp``/``acct_by_fp``/
    ``probe_acct_by_fp`` override the live registry (the JSONL-replay
    path of ``tools/program_report.py``).

    Tuner-probe work (the separate :func:`probe_totals` bucket) renders
    as its OWN rows flagged ``probe=True`` — excluded from the
    wall-share denominator and the MFU column, so throwaway candidate
    dispatches never dilute the steady-state attribution the report
    exists for (even when they share a fingerprint with steady rows)."""
    peak = (peak_tflops if peak_tflops else DEFAULT_PEAK_TFLOPS) * 1e12
    if acct_by_fp is None:
        acct_by_fp = accounting()
        if probe_acct_by_fp is None:
            probe_acct_by_fp = probe_totals()
    probe_acct_by_fp = probe_acct_by_fp or {}
    if profiles_by_fp is None:
        profiles_by_fp = {}
        for p in profiles():
            cur = profiles_by_fp.get(p.fingerprint)
            if cur is None or p.ts >= cur.ts:
                profiles_by_fp[p.fingerprint] = p
    fps = set(acct_by_fp) | set(profiles_by_fp)
    total_wall = sum((acct_by_fp.get(fp) or {}).get("wall_s", 0.0)
                     for fp in fps)

    def _row(fp, a, p, probe):
        steps = int(a.get("steps", 0))
        wall = float(a.get("wall_s", 0.0))
        row = {"fingerprint": fp, "fp12": fp[:12],
               "kind": a.get("kind") or (p.kind if p is not None else ""),
               "steps": steps, "wall_s": round(wall, 6),
               "wall_share": 0.0 if probe else round(wall / total_wall, 4)
               if total_wall > 0 else 0.0,
               "examples": int(a.get("examples", 0)),
               "flops_per_step": float(p.flops) if p is not None else None,
               "bytes_per_step": float(p.bytes_accessed)
               if p is not None else None,
               "peak_hbm_bytes": int(p.peak_hbm_bytes)
               if p is not None else None}
        if probe:
            row["probe"] = True
            row["mfu"] = None
        elif p is not None and wall > 0 and p.flops:
            row["mfu"] = round(p.flops * steps / wall / peak, 4)
        else:
            row["mfu"] = None
        return row

    rows = [_row(fp, acct_by_fp.get(fp) or {}, profiles_by_fp.get(fp),
                 False) for fp in fps]
    rows += [_row(fp, a, profiles_by_fp.get(fp), True)
             for fp, a in probe_acct_by_fp.items()]
    rows.sort(key=lambda r: (-r["wall_s"], r["fingerprint"]))
    return rows


def render_table(rows):
    """Fixed-width text table of :func:`report_rows` output (shared by
    the CLI and in-process reporting)."""
    hdr = "%-12s %-10s %8s %10s %7s %12s %12s %10s %7s" % (
        "program", "executor", "steps", "wall(s)", "share",
        "GFLOP/step", "GB/step", "peakHBM", "MFU")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        kind = ("probe:" + (r["kind"] or "?")) if r.get("probe") \
            else (r["kind"] or "?")
        lines.append("%-12s %-10s %8d %10.3f %6.1f%% %12s %12s %10s %7s" % (
            r["fp12"], kind[:10], r["steps"], r["wall_s"],
            100.0 * r["wall_share"],
            "%.3f" % (r["flops_per_step"] / 1e9)
            if r["flops_per_step"] is not None else "-",
            "%.4f" % (r["bytes_per_step"] / 1e9)
            if r["bytes_per_step"] is not None else "-",
            _fmt_mib(r["peak_hbm_bytes"])
            if r["peak_hbm_bytes"] is not None else "-",
            "%.3f" % r["mfu"] if r["mfu"] is not None else "-"))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------

def reset_accounting():
    """Drop step accounting but keep captured profiles (they are compile
    artifacts, still valid across a monitor enable/disable flip)."""
    with _mu:
        _acct.clear()
        _acct_probe.clear()


def reset():
    """Drop everything (tests)."""
    with _mu:
        _profiles.clear()
        _acct.clear()
        _acct_probe.clear()
        _warned.clear()
