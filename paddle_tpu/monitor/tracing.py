"""Per-request distributed tracing (ISSUE 17 tentpole).

The monitor stack answers "where did this *process's* wall clock go"
(goodput ledger, program profiles); this module answers "where did
*this request's* 24ms go".  Dapper-shaped: every serving request gets a
``trace_id``, every lifecycle stage (queue wait, page wait, prefill,
each decode tick it rode, terminal) emits one ``trace_span`` JSONL
record carrying ``trace_id``/``span_id``/``parent_id``, and the context
crosses process boundaries by riding the control-plane RPC envelope
(``cloud.MasterClient`` stamps it, ``cloud/server.py`` extracts it), so
spans written by different hosts' JSONL logs assemble into one tree
after the fact.

Discipline (CheckFreq's lesson, the monitor's existing contract):

* **disabled cost is one module-global bool read** — every producer
  gates on ``tracing.enabled()`` (or a ``req.trace is None`` check that
  the gate decided) before touching any tracing call; the serving step
  path performs ZERO tracing calls while disabled (test-enforced with a
  raising monkeypatch, like the goodput ledger's);
* **enablement rides the flag pattern**: ``FLAGS_trace`` flips the
  module bool through the same on_set-reconcile scheme as the
  ``FLAGS_monitor*`` family (``tracing.enable()``/``disable()`` are
  set_flags conveniences);
* **no new sinks**: spans emit through ``monitor.log_event`` (the
  rotating JSONL writer, run_id-stamped) plus a bounded in-process ring
  buffer so bench/tests can assemble trees without a log dir.

Span taxonomy (names are the breakdown table's contract):

* ``request`` — the root, one per serving request, emitted at the
  terminal (status ok/failed/expired/quarantined); duration is
  submit-to-terminal on the host monotonic clock.
* ``queue_wait`` — submit to admission (attrs: bucket, queue_depth,
  fill_around).
* ``page_wait`` — first paged-KV admission refusal to the grant
  (back-pressure wait; only present when the gate refused at least
  once).
* ``page_alloc`` — zero-duration grant marker (attrs: pages, shared,
  pool in_use/free).
* ``prefill`` / ``batch`` — the compiled dispatch the request rode
  (attrs: slot, batch, bucket, padding tokens).
* ``decode`` — one per decode tick the request rode (attrs: slot,
  tick, active, spec_accepted/spec_proposed under speculation).
* ``rpc/<method>`` / ``rpc_server/<method>`` / ``rpc_retry`` — the
  cluster control-plane legs (client, server, reconnect attempt).
* ``cluster_session`` / ``cluster/heartbeat`` / ``cluster/barrier`` —
  membership-session spans; RPC spans nest under them via the
  thread-local current-span context.
* ``fleet_request`` / ``route`` — the pod-scale serving legs
  (``serving.fleet``): the client-side root over route + dispatch
  (+ any re-routes), and the fleet master's routing decision.  The
  ``route`` span's context rides back on the route RESPONSE, so the
  replica-side ``request`` tree parents under the master's decision —
  one request assembles into one tree across three processes
  (client, master, replica).
"""

import collections
import contextlib
import itertools
import os
import threading
import time
import uuid

__all__ = [
    "enabled", "enable", "disable", "reset", "spans",
    "Span", "RequestTrace", "current", "use_span", "span",
    "inject", "extract", "server_span", "client_span", "now_us",
    "assemble", "breakdown", "breakdown_summary", "render_table",
    "chrome_events",
]

# fast-path gate, same shape as monitor._enabled: one module-global
# bool read is all a disabled process pays per instrumentation site
_enabled = False

# bounded in-process span buffer: bench rungs and tests assemble trees
# from here without configuring a JSONL dir; CI/cluster runs read the
# JSONL twin written through monitor.log_event
_BUFFER_SPANS = 65536
_spans = collections.deque(maxlen=_BUFFER_SPANS)

# span ids only need uniqueness within a trace; trace ids must be
# globally unique across hosts (they join cross-process logs)
_span_seq = itertools.count(1)
_PID_TAG = "%04x" % (os.getpid() & 0xffff)

_tls = threading.local()


def now_us():
    """Monotonic microseconds, same base as the profiler's chrome-trace
    timestamps (``perf_counter_ns``) so request lanes align with host
    spans in one exported timeline."""
    return time.perf_counter_ns() / 1000.0


def _new_trace_id():
    return uuid.uuid4().hex[:16]


def _new_span_id():
    return "%s-%06x" % (_PID_TAG, next(_span_seq))


def enabled():
    return _enabled


def _reconcile():
    """Bring the module bool in line with ``FLAGS_trace`` (called from
    the flag's on_set hook, monitor-family style)."""
    global _enabled
    from .. import flags

    try:
        _enabled = bool(flags.flag("trace"))
    except KeyError:       # import-time registration order
        _enabled = False


def enable():
    """Turn request tracing on — a set_flags convenience; the flag
    stays the source of truth."""
    from .. import flags

    flags.set_flags({"trace": True})


def disable():
    from .. import flags

    flags.set_flags({"trace": False})


def reset():
    """Drop the in-process span buffer (bench rungs call this at rung
    boundaries so each artifact's trees are its own)."""
    _spans.clear()


def spans():
    """Snapshot of the buffered ``trace_span`` records (dicts)."""
    return list(_spans)


def _emit(name, trace_id, span_id, parent_id, t0_us, dur_us,
          status="ok", attrs=None, ts=None):
    """Append one finished-span record to the buffer and the JSONL log.
    ``t0_us`` is the monotonic start (chrome alignment), ``ts`` the
    wall-clock start (cross-process ordering); run_id-stamped here so
    buffered records carry it even without a JSONL writer."""
    from . import run_id, log_event

    rec = {"event": "trace_span", "trace_id": trace_id,
           "span_id": span_id, "parent_id": parent_id, "name": name,
           "ts": time.time() - (now_us() - t0_us) / 1e6
           if ts is None else ts,
           "mono_us": round(t0_us, 1),
           "dur_ms": round(dur_us / 1e3, 4),
           "status": status, "run_id": run_id()}
    if attrs:
        rec["attrs"] = attrs
    _spans.append(rec)
    try:
        log_event(dict(rec))
    except Exception:  # noqa: BLE001 — telemetry never breaks the path
        pass
    return rec


class Span:
    """One explicit span: created open, emitted on ``finish`` (emission
    is idempotent — the second finish is a no-op).  ``parent`` may be a
    Span or an extracted RPC context."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "_t0", "_ts", "_done")

    def __init__(self, name, parent=None, trace_id=None, attrs=None):
        self.name = name
        if parent is not None:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = trace_id or _new_trace_id()
            self.parent_id = None
        self.span_id = _new_span_id()
        self.attrs = dict(attrs) if attrs else {}
        self._t0 = now_us()
        self._ts = time.time()
        self._done = False

    def child(self, name, attrs=None):
        return Span(name, parent=self, attrs=attrs)

    def context(self):
        """The propagated wire context (the Dapper tuple): what an RPC
        envelope carries across the process boundary."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def event(self, name, attrs=None, status="ok"):
        """Zero-duration child marker (reconnect attempts, grants)."""
        _emit(name, self.trace_id, _new_span_id(), self.span_id,
              now_us(), 0.0, status=status, attrs=attrs)

    def emit_open(self):
        """Emit the span NOW with status ``open`` (long-lived session
        roots: the anchor must exist in the log even if the process
        dies before ``finish``).  ``finish`` re-emits the same span_id
        with the terminal status; assembly prefers the terminal one."""
        _emit(self.name, self.trace_id, self.span_id, self.parent_id,
              self._t0, 0.0, status="open", attrs=self.attrs or None,
              ts=self._ts)

    def finish(self, status="ok", **attrs):
        if self._done:
            return
        self._done = True
        if attrs:
            self.attrs.update(attrs)
        _emit(self.name, self.trace_id, self.span_id, self.parent_id,
              self._t0, now_us() - self._t0, status=status,
              attrs=self.attrs or None, ts=self._ts)


class _Ctx:
    """An extracted wire context acting as a Span-shaped parent."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id


def current():
    """The calling thread's current span (set by ``use_span``/``span``),
    or None — what ``MasterClient.call`` parents its rpc spans to."""
    return getattr(_tls, "span", None)


@contextlib.contextmanager
def use_span(s):
    """Install ``s`` as the thread's current span for the block
    (None = no-op), so nested RPC calls parent to it."""
    if s is None:
        yield None
        return
    prev = getattr(_tls, "span", None)
    _tls.span = s
    try:
        yield s
    finally:
        _tls.span = prev


@contextlib.contextmanager
def span(name, parent=None, attrs=None):
    """Create-install-finish in one block: finishes ``ok`` on normal
    exit, ``error`` when the block raises.  Yields None when tracing is
    disabled (the block runs untraced)."""
    if not _enabled:
        yield None
        return
    s = Span(name, parent=parent if parent is not None else current(),
             attrs=attrs)
    prev = getattr(_tls, "span", None)
    _tls.span = s
    try:
        yield s
    except BaseException:
        s.finish("error")
        raise
    finally:
        _tls.span = prev
        s.finish("ok")


# -- RPC propagation ---------------------------------------------------------

def client_span(method, endpoint):
    """The client leg of one RPC: a child of the thread's current span
    (or a fresh root outside any session context)."""
    return Span("rpc/%s" % method, parent=current(),
                attrs={"method": method, "endpoint": endpoint})


def inject(envelope, s=None):
    """Stamp the wire context into an RPC envelope dict (no-op when
    tracing is off or no span is given/current)."""
    s = s if s is not None else current()
    if _enabled and s is not None:
        envelope["trace"] = s.context()
    return envelope


def extract(ctx):
    """Wire context -> a parent for server-side spans (None-safe)."""
    if not ctx or "trace_id" not in ctx:
        return None
    return _Ctx(ctx["trace_id"], ctx.get("span_id"))


def server_span(method, ctx):
    """The server leg: a child of the extracted client context (or a
    fresh root for untraced callers)."""
    return Span("rpc_server/%s" % method, parent=extract(ctx),
                attrs={"method": method})


# -- per-request lifecycle helper -------------------------------------------

class RequestTrace:
    """One serving request's span bookkeeping, hung on
    ``ServingRequest.trace`` by the engine's submit when tracing is on
    (None otherwise — every later site gates on that None, so the
    disabled path never calls in here).

    Keyed by REQUEST, never by slot: a freed slot re-prefilled between
    decode ticks carries the new request's RequestTrace (the PR-16
    OOB-sentinel discipline, regression-tested).

    ``parent`` (a Span or extracted RPC context) adopts the caller's
    trace: a fleet replica serving an RPC-dispatched request joins the
    remote caller's tree instead of rooting its own."""

    __slots__ = ("trace_id", "root_id", "root_parent_id", "request_id",
                 "_t0", "_ts", "_attrs", "_queue_t0", "_queue_open",
                 "_page_t0", "ticks", "_done")

    def __init__(self, request_id, kind, length, parent=None, **attrs):
        if parent is not None:
            self.trace_id = parent.trace_id
            self.root_parent_id = parent.span_id
        else:
            self.trace_id = _new_trace_id()
            self.root_parent_id = None
        self.root_id = _new_span_id()
        self.request_id = request_id
        self._t0 = now_us()
        self._ts = time.time()
        self._attrs = {"request_id": request_id, "kind": kind,
                       "length": int(length)}
        self._attrs.update(attrs)
        self._queue_t0 = self._t0
        self._queue_open = True
        self._page_t0 = None
        self.ticks = 0
        self._done = False
        if self.root_parent_id is not None:
            # cross-process request: anchor the root NOW, open-status.
            # A replica SIGKILLed mid-request must leave a ROOTED open
            # subtree behind — orphan children with an unemitted parent
            # would break the remote caller's tree assembly (the fleet
            # failover drill's --assert-complete depends on this).
            _emit("request", self.trace_id, self.root_id,
                  self.root_parent_id, self._t0, 0.0, status="open",
                  attrs=dict(self._attrs), ts=self._ts)

    def _child(self, name, t0_us, dur_us, attrs=None, status="ok"):
        _emit(name, self.trace_id, _new_span_id(), self.root_id,
              t0_us, dur_us, status=status, attrs=attrs)

    # -- lifecycle hooks (engine side) ---------------------------------
    def admitted(self, bucket, queue_depth, fill_around):
        """Scheduler admission: closes the queue_wait span."""
        if not self._queue_open:
            return
        self._queue_open = False
        now = now_us()
        self._child("queue_wait", self._queue_t0, now - self._queue_t0,
                    attrs={"bucket": bucket, "queue_depth": queue_depth,
                           "fill_around": bool(fill_around)})

    def page_refused(self):
        """Paged-KV admission gate refusal: the back-pressure wait
        starts at the FIRST refusal (later refusals extend it)."""
        if self._page_t0 is None:
            self._page_t0 = now_us()

    def pages_granted(self, pages, shared, in_use, free):
        """Page grant: emits the page_wait span (if the gate ever
        refused) and the zero-duration page_alloc marker."""
        now = now_us()
        if self._page_t0 is not None:
            self._child("page_wait", self._page_t0, now - self._page_t0,
                        attrs={"pages": int(pages)})
            self._page_t0 = None
        self._child("page_alloc", now, 0.0,
                    attrs={"pages": int(pages), "shared": int(shared),
                           "pool_in_use": int(in_use),
                           "pool_free": int(free)})

    def note_prefill(self, t0_us, dur_us, slot, batch, bucket, padding):
        self._child("prefill", t0_us, dur_us,
                    attrs={"slot": slot, "batch": int(batch),
                           "bucket": bucket, "padding": int(padding)})

    def note_batch(self, t0_us, dur_us, slot, batch, bucket, padding):
        """One-shot inference dispatch (the InferenceEngine's analog of
        prefill; the breakdown table folds it into the same column)."""
        self._child("batch", t0_us, dur_us,
                    attrs={"slot": slot, "batch": int(batch),
                           "bucket": bucket, "padding": int(padding)})

    def note_decode(self, t0_us, dur_us, slot, tick, active,
                    spec_accepted=None, spec_proposed=None):
        """One decode tick this request rode (slot id + speculation
        accept/reject counts when speculative)."""
        self.ticks += 1
        attrs = {"slot": slot, "tick": int(tick), "active": int(active)}
        if spec_proposed is not None:
            attrs["spec_accepted"] = int(spec_accepted)
            attrs["spec_proposed"] = int(spec_proposed)
        self._child("decode", t0_us, dur_us, attrs=attrs)

    def finish(self, status="ok", **attrs):
        """Terminal: emits the root span (idempotent — the first
        terminal decision wins, like the scheduler's own complete/fail
        races).  A still-open queue_wait (failed before admission)
        closes with the terminal status."""
        if self._done:
            return
        self._done = True
        now = now_us()
        if self._queue_open:
            self._queue_open = False
            self._child("queue_wait", self._queue_t0,
                        now - self._queue_t0, status=status)
        if attrs:
            self._attrs.update(attrs)
        self._attrs["ticks"] = self.ticks
        _emit("request", self.trace_id, self.root_id,
              self.root_parent_id, self._t0, now - self._t0,
              status=status, attrs=self._attrs, ts=self._ts)


# ---------------------------------------------------------------------------
# assembly + breakdown (one table, two consumers: tools/request_trace.py
# CLI over JSONL, bench rungs over the in-process buffer)
# ---------------------------------------------------------------------------

_TERMINAL = ("ok", "failed", "expired", "quarantined", "cancelled",
             "error")


def assemble(records):
    """Group ``trace_span`` records into per-trace trees.

    Returns ``{trace_id: tree}`` where tree is a dict with ``spans``
    (deduped by span_id, terminal status preferred over ``open``),
    ``root`` (the parentless span, or None), and ``complete`` — root
    present with a terminal status AND every parent link resolves
    inside the tree."""
    by_trace = {}
    for rec in records:
        if rec.get("event") != "trace_span" or not rec.get("trace_id"):
            continue
        t = by_trace.setdefault(rec["trace_id"],
                                {"spans": {}, "root": None})
        sid = rec.get("span_id")
        prev = t["spans"].get(sid)
        # emit_open anchors re-emit on finish: keep the terminal record
        if prev is None or prev.get("status") == "open":
            t["spans"][sid] = rec
    trees = {}
    for tid, t in by_trace.items():
        spans_ = list(t["spans"].values())
        ids = set(t["spans"])
        roots = [s for s in spans_ if not s.get("parent_id")]
        root = roots[0] if roots else None
        links_ok = all(s.get("parent_id") in ids for s in spans_
                       if s.get("parent_id"))
        trees[tid] = {
            "trace_id": tid, "spans": spans_, "root": root,
            "complete": (root is not None
                         and root.get("status") in _TERMINAL
                         and links_ok and len(roots) == 1),
            "run_ids": sorted({s.get("run_id") for s in spans_
                               if s.get("run_id")}),
        }
    return trees


STAGES = ("route", "queue_wait", "padding", "page_wait", "prefill",
          "decode", "spec_reject", "other")


def breakdown(tree):
    """Per-request latency attribution in milliseconds, summing (by
    construction) to the root span's duration:

    * ``queue_wait`` / ``page_wait`` — their spans' durations;
    * ``prefill`` — prefill/batch dispatch time, minus the ``padding``
      share (pad tokens / bucket: the compute the request's padding
      wasted);
    * ``decode`` — the ticks the request rode, minus the
      ``spec_reject`` share (rejected draft positions / verify window:
      the speculation work the target threw away);
    * ``route`` — fleet routing decisions (the ``rpc/route`` client
      legs of a fleet-dispatched request; zero for direct dispatch);
    * ``other`` — the unattributed remainder (host bookkeeping, loop
      scheduling gaps; for fleet trees also the data-plane RPC legs).

    Returns None for non-request trees: the root must be a ``request``
    (engine-direct) or ``fleet_request`` (fleet-routed — the engine's
    ``request`` span is then a CHILD inside the same tree, and its
    children attribute exactly once)."""
    root = tree.get("root")
    if root is None or root.get("name") not in ("request",
                                                "fleet_request"):
        return None
    lat = float(root.get("dur_ms") or 0.0)
    out = {k: 0.0 for k in STAGES}
    for s in tree["spans"]:
        name = s.get("name")
        dur = float(s.get("dur_ms") or 0.0)
        a = s.get("attrs") or {}
        if name == "rpc/route":
            out["route"] += dur
        elif name == "queue_wait":
            out["queue_wait"] += dur
        elif name == "page_wait":
            out["page_wait"] += dur
        elif name in ("prefill", "batch"):
            bucket = a.get("bucket") or 0
            pad = min(a.get("padding") or 0, bucket)
            pad_ms = dur * pad / bucket if bucket else 0.0
            out["padding"] += pad_ms
            out["prefill"] += dur - pad_ms
        elif name == "decode":
            k = a.get("spec_proposed")
            if k:
                rej = k - (a.get("spec_accepted") or 0)
                rej_ms = dur * rej / (k + 1)
                out["spec_reject"] += rej_ms
                out["decode"] += dur - rej_ms
            else:
                out["decode"] += dur
    attributed = sum(out.values())
    out["other"] = max(0.0, lat - attributed)
    return {"trace_id": tree["trace_id"],
            "request_id": (root.get("attrs") or {}).get("request_id"),
            "status": root.get("status"), "latency_ms": lat,
            "attributed_ms": round(attributed, 4),
            "stages": {k: round(v, 4) for k, v in out.items()}}


def _pctl(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1,
            max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[i]


def breakdown_summary(trees):
    """Aggregate stage percentiles over every complete request tree
    (the ``--json`` schema bench embeds)."""
    rows = [breakdown(t) for t in trees.values()]
    rows = [r for r in rows if r is not None]
    done = [r for r in rows if r["status"] in _TERMINAL]
    complete = [r for r in rows
                if trees[r["trace_id"]]["complete"]]
    stages = {}
    for st in STAGES:
        vals = sorted(r["stages"][st] for r in complete)
        stages[st] = {
            "p50_ms": round(_pctl(vals, 0.50), 4) if vals else None,
            "p99_ms": round(_pctl(vals, 0.99), 4) if vals else None,
            "mean_ms": round(sum(vals) / len(vals), 4) if vals else None,
        }
    lats = sorted(r["latency_ms"] for r in complete)
    return {"requests": len(rows), "terminal": len(done),
            "complete": len(complete),
            "complete_fraction": (round(len(complete) / len(done), 4)
                                  if done else None),
            "p50_latency_ms": _pctl(lats, 0.50),
            "p99_latency_ms": _pctl(lats, 0.99),
            "stages": stages}


def render_table(summary):
    """The human-facing latency-breakdown table."""
    lines = ["%-12s %12s %12s %12s" % ("stage", "p50(ms)", "p99(ms)",
                                       "mean(ms)")]
    for st in STAGES:
        s = summary["stages"][st]
        lines.append("%-12s %12s %12s %12s" % (
            st, *("%.3f" % s[k] if s[k] is not None else "-"
                  for k in ("p50_ms", "p99_ms", "mean_ms"))))
    lines.append(
        "%d requests (%d terminal, %d complete trees); latency p50 %s "
        "p99 %s ms" % (
            summary["requests"], summary["terminal"],
            summary["complete"],
            "%.3f" % summary["p50_latency_ms"]
            if summary["p50_latency_ms"] is not None else "-",
            "%.3f" % summary["p99_latency_ms"]
            if summary["p99_latency_ms"] is not None else "-"))
    return "\n".join(lines)


# -- chrome-trace request lanes ---------------------------------------------

# request lanes render in their own synthetic process group so Perfetto
# shows one lane per request next to (not interleaved with) the host
# thread lanes; the offset keeps the synthetic pid clear of real pids
_LANE_PID_OFFSET = 1000000


def chrome_events(max_lanes=64):
    """Buffered spans as chrome-trace events: one lane (synthetic tid)
    per trace, under a dedicated 'serving requests' process.  Returns
    ``(events, meta)`` for export_chrome_tracing to merge; timestamps
    share the profiler's perf_counter base, so request lanes line up
    with the host spans they explain."""
    from . import run_id

    trees = assemble(_spans)
    pid = os.getpid() + _LANE_PID_OFFSET
    events, meta = [], []
    ordered = sorted(trees.values(),
                     key=lambda t: min((s.get("mono_us") or 0)
                                       for s in t["spans"]))
    if not ordered:
        return [], []
    meta.append({"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": "paddle_tpu serving requests",
                          "run_id": run_id()}})
    for lane, tree in enumerate(ordered[:max_lanes]):
        tid = lane + 1
        root = tree.get("root") or {}
        label = (root.get("attrs") or {}).get("request_id") \
            or tree["trace_id"]
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid,
                     "args": {"name": "req %s [%s]"
                              % (label, tree["trace_id"][:8])}})
        for s in tree["spans"]:
            ev = {"name": s["name"], "ph": "X", "pid": pid, "tid": tid,
                  "ts": s.get("mono_us") or 0.0,
                  "dur": (s.get("dur_ms") or 0.0) * 1000.0,
                  "args": {"trace_id": s.get("trace_id"),
                           "span_id": s.get("span_id"),
                           "status": s.get("status")}}
            if s.get("attrs"):
                ev["args"].update(s["attrs"])
            events.append(ev)
    return events, meta
