"""Watchdog: turn a silent pipeline hang into a diagnostic.

A stalled dispatch thread, a prefetch producer stuck on a dead source,
or a device-side wedge all look identical from the training loop: no
step completes.  The watchdog tracks (a) the last completed step and
(b) per-thread heartbeats from the dispatch/prefetch workers; when no
step completes within ``stall_seconds`` it fires a diagnostic — queue
states, heartbeat ages, the last completed span — through its sink
(JSONL log + stderr + a ``monitor/watchdog_stalls`` counter) instead of
letting the job hang mutely.  It never raises or kills anything: the
stall may be a genuinely slow step (giant compile), so the dump is
evidence, not a verdict.
"""

import threading
import time

__all__ = ["Watchdog"]


class Watchdog:
    """``heartbeat(name)`` from worker threads, ``step_completed()``
    from the executors, ``check()`` evaluates the stall condition
    (callable manually in tests; ``start()`` runs it on a daemon thread
    every ``stall_seconds/4``, capped at 1s)."""

    def __init__(self, stall_seconds, sink=None, probe=None):
        self.stall_seconds = float(stall_seconds)
        self._sink = sink          # callable(diagnostic_dict)
        self._probe = probe        # callable() -> extra context dict
        self._hb = {}              # name -> last monotonic heartbeat
        self._last_step = time.monotonic()
        self._steps = 0
        self._last_fired = None
        self._stop = threading.Event()
        self._thread = None

    # -- signals -------------------------------------------------------
    def heartbeat(self, name):
        # single dict-slot store: atomic under the GIL, no lock on the
        # worker hot path
        self._hb[name] = time.monotonic()

    def step_completed(self):
        self._steps += 1
        self._last_step = time.monotonic()
        self._last_fired = None    # re-arm: progress clears the alarm

    # -- evaluation ----------------------------------------------------
    def check(self, now=None):
        """Returns the diagnostic dict if the pipeline is stalled (and
        feeds it to the sink), else None.  Fires at most once per stall
        window so a long hang logs a heartbeat-rate trickle, not a
        flood."""
        now = time.monotonic() if now is None else now
        age = now - self._last_step
        if age < self.stall_seconds:
            return None
        if self._last_fired is not None \
                and now - self._last_fired < self.stall_seconds:
            return None
        self._last_fired = now
        # .copy() is atomic under the GIL; iterating self._hb directly
        # could race a worker's first-ever heartbeat insert
        hb = self._hb.copy()
        diag = {"event": "watchdog_stall",
                "ts": time.time(),
                "stalled_for_s": round(age, 3),
                "stall_seconds": self.stall_seconds,
                "steps_completed": self._steps,
                "heartbeat_age_s": {
                    n: round(now - t, 3) for n, t in sorted(hb.items())
                }}
        if self._probe is not None:
            try:
                diag.update(self._probe() or {})
            except Exception as e:  # noqa: BLE001 — diagnostics must land
                diag["probe_error"] = repr(e)
        if self._sink is not None:
            self._sink(diag)
        return diag

    # -- background thread ---------------------------------------------
    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="monitor-watchdog", daemon=True)
            self._thread.start()
        return self

    def _loop(self):
        interval = min(max(self.stall_seconds / 4.0, 0.05), 1.0)
        while not self._stop.wait(interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the stall detector must
                pass           # outlive any one bad check


    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
