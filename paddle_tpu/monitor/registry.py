"""Process-global metrics registry: Counter / Gauge / Histogram.

The always-on half of the observability story (ISSUE 2): the profiler
(``profiler.py``) collects *spans* you opt into per session; the
registry holds *metrics* that accumulate for the life of the process and
can be exported at any moment (Prometheus text exposition, JSONL, the
console reporter).  Metrics are thread-safe — the dispatch queue, the
prefetch producer thread, and the watchdog all write concurrently — and
cheap enough to update on the step hot path (one lock + a few adds; the
executors additionally gate every update on ``monitor.enabled()`` so an
unmonitored process pays a single attribute read per step).
"""

import bisect
import re
import threading

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "DEFAULT_BUCKETS"]

# latency-shaped buckets in SECONDS (steps span ~100us toy programs to
# multi-second giant-batch steps); fixed per ISSUE 2 — a fixed layout
# keeps histogram merges/exports trivial and the observe() cost constant
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def sanitize(name):
    """Map a span-style metric name (``executor/fetch_sync``) to a
    Prometheus-legal one (``executor_fetch_sync``)."""
    return re.sub(r"[^a-zA-Z0-9_:]", "_", name)


class Counter:
    """Monotonically increasing count (steps, cache hits, stalls)."""

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self._value = 0.0

    def inc(self, amount=1):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._mu:
            self._value += amount

    @property
    def value(self):
        with self._mu:
            return self._value

    def snapshot(self):
        return {"type": "counter", "name": self.name, "value": self.value}

    def expose(self):
        n = sanitize(self.name)
        return ["# TYPE %s counter" % n, "%s %s" % (n, _fmt(self.value))]


class Gauge:
    """Point-in-time value (queue depth, occupancy, bytes in use)."""

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._mu = threading.Lock()
        self._value = 0.0

    def set(self, value):
        with self._mu:
            self._value = float(value)

    def inc(self, amount=1):
        with self._mu:
            self._value += amount

    def dec(self, amount=1):
        with self._mu:
            self._value -= amount

    @property
    def value(self):
        with self._mu:
            return self._value

    def snapshot(self):
        return {"type": "gauge", "name": self.name, "value": self.value}

    def expose(self):
        n = sanitize(self.name)
        return ["# TYPE %s gauge" % n, "%s %s" % (n, _fmt(self.value))]


class Histogram:
    """Fixed-bucket histogram (cumulative, Prometheus semantics):
    ``observe(v)`` increments every bucket whose upper bound >= v at
    export time — internally we store per-bucket counts and cumulate on
    export, so observe() is one bisect + one add under the lock."""

    def __init__(self, name, buckets=DEFAULT_BUCKETS, help=""):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self._mu = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        i = bisect.bisect_left(self.buckets, value)
        with self._mu:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        with self._mu:
            return self._count

    @property
    def sum(self):
        with self._mu:
            return self._sum

    def snapshot(self):
        with self._mu:
            counts = list(self._counts)
            s, c = self._sum, self._count
        return {"type": "histogram", "name": self.name,
                "buckets": list(self.buckets), "counts": counts,
                "sum": s, "count": c}

    def expose(self):
        snap = self.snapshot()
        n = sanitize(self.name)
        lines = ["# TYPE %s histogram" % n]
        cum = 0
        for bound, cnt in zip(snap["buckets"], snap["counts"]):
            cum += cnt
            lines.append('%s_bucket{le="%s"} %d' % (n, _fmt(bound), cum))
        cum += snap["counts"][-1]
        lines.append('%s_bucket{le="+Inf"} %d' % (n, cum))
        lines.append("%s_sum %s" % (n, _fmt(snap["sum"])))
        lines.append("%s_count %d" % (n, snap["count"]))
        return lines


def _fmt(v):
    """Prometheus number formatting: integral floats print bare."""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Get-or-create metric store.  One process-global instance lives in
    ``monitor`` (``monitor.registry()``); tests may build private ones."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics = {}
        # bumped by reset(): holders of cached metric handles (monitor's
        # span-histogram cache, the StepStats aggregator) compare this to
        # drop handles orphaned by a reset
        self.generation = 0

    def _get_or_create(self, name, cls, **kwargs):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    "metric %r already registered as %s, not %s"
                    % (name, type(m).__name__, cls.__name__))
            return m

    def counter(self, name, help=""):
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name, help=""):
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name, buckets=DEFAULT_BUCKETS, help=""):
        m = self._get_or_create(name, Histogram, buckets=buckets, help=help)
        if tuple(sorted(buckets)) != m.buckets:
            raise ValueError(
                "histogram %r already registered with buckets %s"
                % (name, m.buckets))
        return m

    def get(self, name):
        with self._mu:
            return self._metrics.get(name)

    def names(self):
        with self._mu:
            return sorted(self._metrics)

    def metrics(self):
        """The live metric objects (one lock, no copies) — the digest
        builder's cheap iteration path: reading each metric's value is
        a per-metric lock, not a full snapshot() dict build."""
        with self._mu:
            return list(self._metrics.values())

    def snapshot(self):
        """{name: metric snapshot dict} for the JSONL/console exporters."""
        with self._mu:
            metrics = list(self._metrics.values())
        return {m.name: m.snapshot() for m in metrics}

    def expose_text(self):
        """Prometheus text exposition (format version 0.0.4)."""
        with self._mu:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Drop every metric (tests)."""
        with self._mu:
            self._metrics.clear()
            self.generation += 1
