"""Monitor exporters: rotating JSONL event log, Prometheus-style text
exposition over a tiny stdlib HTTP endpoint, and a periodic console
reporter.

All three read the same registry/aggregator state; none of them sits on
the step hot path (the JSONL writer is called once per step from
``monitor.record_step``, the other two run on their own daemon threads).
"""

import json
import os
import sys
import threading
import time

__all__ = ["JsonlWriter", "ConsoleReporter", "start_http_server"]


class JsonlWriter:
    """Rotating JSONL event log: one JSON object per line (StepStats
    records, watchdog diagnostics, lifecycle events).  Rotation keeps
    ``backups`` closed generations (``monitor-<pid>.jsonl.1``...) so an
    always-on training job cannot fill the disk."""

    def __init__(self, log_dir, prefix="monitor", max_bytes=64 << 20,
                 backups=2):
        self.log_dir = log_dir
        self.max_bytes = int(max_bytes)
        self.backups = int(backups)
        os.makedirs(log_dir, exist_ok=True)
        # pid-suffixed so bench-ladder rung subprocesses sharing one
        # FLAGS_monitor_log_dir never interleave within a file
        self.path = os.path.join(log_dir, "%s-%d.jsonl"
                                 % (prefix, os.getpid()))
        self._mu = threading.Lock()
        self._f = open(self.path, "a")

    def write(self, record):
        try:
            line = json.dumps(record, default=_json_default)
        except Exception:  # noqa: BLE001 — telemetry never breaks the step
            return
        with self._mu:
            if self._f is None:
                return
            try:
                self._f.write(line + "\n")
                # flush per line: the log's job is post-mortem diagnosis
                # of hangs/crashes, exactly when buffered tails get lost
                self._f.flush()
                if self._f.tell() >= self.max_bytes:
                    self._rotate()
            except OSError as e:
                # disk full / fs error: drop the writer rather than let
                # a telemetry write kill the training step
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
                print("[monitor] event log disabled: %r" % e,
                      file=sys.stderr, flush=True)

    def _rotate(self):
        self._f.close()
        self._f = None           # stays None if the re-open below fails
        for i in range(self.backups, 0, -1):
            src = self.path + (".%d" % (i - 1) if i > 1 else "")
            dst = self.path + ".%d" % i
            if os.path.exists(src):
                os.replace(src, dst)
        self._f = open(self.path, "a")

    def close(self):
        with self._mu:
            if self._f is not None:
                self._f.close()
                self._f = None


def _json_default(o):
    try:
        return float(o)       # numpy scalars, jax weak types
    except (TypeError, ValueError):
        return repr(o)


class ConsoleReporter:
    """Daemon thread printing a one-line monitor summary every
    ``interval_s`` seconds (stderr, so stdout JSON artifacts like
    bench.py's stay machine-parseable)."""

    def __init__(self, aggregator, registry, interval_s=30.0,
                 stream=None):
        self._agg = aggregator
        self._registry = registry
        self.interval_s = float(interval_s)
        self._stream = stream
        self._stop = threading.Event()
        self._thread = None

    def format_line(self):
        s = self._agg.summary()
        parts = ["[monitor] steps=%d" % s.get("steps", 0)]
        if "mean_step_seconds" in s:
            parts.append("step_ms=%.3f" % (s["mean_step_seconds"] * 1e3))
        if "examples_per_sec" in s:
            parts.append("ex/s=%.1f" % s["examples_per_sec"])
        cc = s.get("last_compile_cache") or {}
        if "hit_ratio" in cc:
            parts.append("cache_hit=%.0f%%" % (100.0 * cc["hit_ratio"]))
        if "last_dispatch_queue_depth" in s:
            parts.append("queue=%d" % s["last_dispatch_queue_depth"])
        pf = s.get("last_prefetch") or {}
        if pf.get("capacity"):
            parts.append("prefetch=%d/%d" % (pf.get("occupancy", 0),
                                             pf["capacity"]))
        stalls = self._registry.get("monitor/watchdog_stalls")
        if stalls is not None and stalls.value:
            parts.append("STALLS=%d" % stalls.value)
        return " ".join(parts)

    def report_once(self):
        print(self.format_line(), file=self._stream or sys.stderr,
              flush=True)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.report_once()
            except Exception:  # noqa: BLE001 — a race with a concurrent
                pass           # aggregator reset must not kill the thread

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="monitor-console", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


def start_http_server(port, expose_fn, host="127.0.0.1"):
    """Serve ``expose_fn()`` (Prometheus text) at ``/metrics`` on a
    daemon thread.  ``port=0`` binds an ephemeral port; the bound server
    is returned (``server.server_address[1]`` is the port,
    ``server.shutdown()`` stops it)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = expose_fn().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):   # scrapes are not console news
            pass

    server = ThreadingHTTPServer((host, port), _Handler)
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever,
                         name="monitor-http", daemon=True)
    t.start()
    return server
