"""Declarative SLO alerting over the fleet view (ISSUE 19).

An :class:`AlertRule` is (metric selector, comparison, threshold,
``for_seconds`` hysteresis, severity).  The :class:`AlertEngine`
evaluates every rule against the :meth:`FleetAggregator.fleet_view`
dict each time a digest lands and drives a **firing -> resolved**
lifecycle per ``(rule, host)``:

* a breached condition becomes *pending*; it FIRES only after holding
  continuously for ``for_seconds`` (hysteresis — one slow heartbeat
  window must not page anyone);
* a firing alert emits exactly ONE ``alert`` JSONL event (deduped —
  re-evaluations while it stays breached are silent) and counts into
  the ``alerts/`` counter family (``alerts/fired``, per-severity
  ``alerts/severity/<sev>``);
* when the condition clears (or its host vanishes from the view), the
  alert RESOLVES — one ``alert`` event with ``state=resolved``,
  ``alerts/resolved`` counted — and re-arms: a fresh breach starts a
  fresh pending window.

Metric selectors (strings, resolved against the view):

========================  ==================================================
``goodput_ratio``          fleet compute/wall ratio
``p50:<hist>``/``p99:<hist>``  exact merged-histogram percentile
``counter:<name>``         fleet counter total
``host:step_time``         per-host latest step wall-time window mean
``host:queue_depth``       per-host serving queue depth
``host:digest_age``        seconds since the host's last digest landed
``host:straggler``         1.0 while the straggler detector flags the host
``host:checkpoint_age``    seconds since checkpoint activity (hosts that
                           have checkpointed at least once)
``host:lease_expired``     1.0 while an expired member's tombstone stands
``host:quarantined``       1.0 while a quarantined replica's stands
``host:grad_norm``         per-host worst-layer gradient L2 norm (the
                           FLAGS_health probe's ``health`` summary)
``host:update_ratio``      per-host minimum layer update/param ratio
``host:nonfinite``         per-host total non-finite gradient elements
========================  ==================================================

``default_rules()`` covers the six conditions the ISSUE names:
goodput-ratio collapse, p99 over the SLO target, replica quarantine,
lease expiry, straggler persistence, and checkpoint staleness — plus a
digest-staleness rule (a peer going dark is the first thing the
watchdog satellite wants named).
"""

import time

__all__ = ["AlertRule", "AlertEngine", "default_rules"]

_OPS = {
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    "==": lambda v, t: v == t,
}

SEVERITIES = ("info", "warning", "critical")


class AlertRule:
    """One declarative rule.  ``metric`` is a selector string (table in
    the module docstring); per-host selectors yield one independent
    alert lifecycle per host."""

    def __init__(self, name, metric, threshold, op=">", for_seconds=0.0,
                 severity="warning"):
        if op not in _OPS:
            raise ValueError("op must be one of %s, got %r"
                             % (sorted(_OPS), op))
        if severity not in SEVERITIES:
            raise ValueError("severity must be one of %s, got %r"
                             % (SEVERITIES, severity))
        self.name = str(name)
        self.metric = str(metric)
        self.threshold = float(threshold)
        self.op = op
        self.for_seconds = float(for_seconds)
        self.severity = severity

    def __repr__(self):
        return "AlertRule(%s: %s %s %g for %gs, %s)" % (
            self.name, self.metric, self.op, self.threshold,
            self.for_seconds, self.severity)

    def resolve(self, view):
        """{key: value} — fleet-level selectors use the ``""`` key,
        per-host selectors one key per host.  Missing data resolves to
        no entry (absence never fires; ``digest_age`` ages are computed
        by the view itself, so a dark host still surfaces)."""
        m = self.metric
        hosts = view.get("hosts") or {}
        if m == "goodput_ratio":
            v = view.get("goodput_ratio")
            return {} if v is None else {"": v}
        if m.startswith(("p50:", "p99:")):
            q, name = m.split(":", 1)
            p = (view.get("percentiles") or {}).get(name)
            v = p.get(q) if p else None
            return {} if v is None else {"": v}
        if m.startswith("counter:"):
            v = (view.get("counters") or {}).get(m[len("counter:"):])
            return {} if v is None else {"": v}
        if m == "host:straggler":
            return {h: 1.0 if d.get("straggler") else 0.0
                    for h, d in hosts.items()}
        if m == "host:lease_expired":
            return {h: 1.0 for h in (view.get("expired") or {})}
        if m == "host:quarantined":
            return {h: 1.0 for h in (view.get("quarantined") or {})}
        if m in ("host:grad_norm", "host:update_ratio",
                 "host:nonfinite"):
            field = {"host:grad_norm": "grad_norm_max",
                     "host:update_ratio": "update_ratio_min",
                     "host:nonfinite": "nonfinite_total"}[m]
            out = {}
            for h, d in hosts.items():
                v = (d.get("health") or {}).get(field)
                if v is not None:
                    out[h] = v
            return out
        if m.startswith("host:"):
            field = {"step_time": "step_time_s",
                     "digest_age": "digest_age_s",
                     "queue_depth": "queue_depth",
                     "checkpoint_age": "checkpoint_age_s",
                     "goodput_ratio": "goodput_ratio"}.get(m[5:])
            if field is None:
                return {}
            return {h: d[field] for h, d in hosts.items()
                    if d.get(field) is not None}
        return {}


class AlertEngine:
    """Evaluates rules against successive views; owns the firing state.
    Single-threaded by contract (the aggregator calls it under its own
    lock); ``active()`` returns copies."""

    def __init__(self, rules, clock=time.time):
        self.rules = list(rules)
        self._clock = clock
        self._pending = {}       # (rule_name, key) -> breach start ts
        self._active = {}        # (rule_name, key) -> alert dict

    def evaluate(self, view, now=None):
        """One evaluation pass; returns the ``alert`` event records for
        this pass's transitions (firing + resolved), already counted
        into the ``alerts/`` family.  The caller logs them."""
        from .. import monitor

        now = self._clock() if now is None else now
        events = []
        for rule in self.rules:
            vals = rule.resolve(view)
            cmp_fn = _OPS[rule.op]
            for key, v in vals.items():
                k = (rule.name, key)
                if v is not None and cmp_fn(v, rule.threshold):
                    since = self._pending.setdefault(k, now)
                    if k not in self._active \
                            and now - since >= rule.for_seconds:
                        alert = {"rule": rule.name,
                                 "severity": rule.severity,
                                 "metric": rule.metric,
                                 "member_id": key or None,
                                 "value": v,
                                 "threshold": rule.threshold,
                                 "since": round(since, 3),
                                 "fired_at": round(now, 3)}
                        self._active[k] = alert
                        monitor.count("alerts/fired")
                        monitor.count("alerts/severity/" + rule.severity)
                        events.append(dict(alert, event="alert",
                                           state="firing", ts=now))
                else:
                    self._pending.pop(k, None)
                    events.extend(self._resolve(k, now, value=v))
            # an active alert whose key left the view resolves too (the
            # expired host rejoined; the straggler's host dropped)
            for k in [k for k in list(self._active)
                      if k[0] == rule.name and k[1] not in vals]:
                self._pending.pop(k, None)
                events.extend(self._resolve(k, now, value=None))
        if monitor.enabled():
            monitor.registry().gauge("alerts/active").set(
                float(len(self._active)))
        return events

    def _resolve(self, k, now, value=None):
        from .. import monitor

        alert = self._active.pop(k, None)
        if alert is None:
            return []
        monitor.count("alerts/resolved")
        return [dict(alert, event="alert", state="resolved", ts=now,
                     value=value,
                     active_s=round(now - alert["fired_at"], 3))]

    def active(self):
        """Currently-firing alerts (copies), most severe first."""
        order = {s: i for i, s in enumerate(reversed(SEVERITIES))}
        return sorted((dict(a) for a in self._active.values()),
                      key=lambda a: (order.get(a["severity"], 9),
                                     a["rule"], a["member_id"] or ""))


def default_rules(goodput_min=0.5, slo_p99_s=2.5,
                  latency_hist="serving/request_latency_seconds",
                  straggler_for_s=10.0, ckpt_max_age_s=900.0,
                  digest_stale_s=30.0, goodput_for_s=30.0,
                  p99_for_s=15.0, grad_norm_max=1e4,
                  update_ratio_min=1e-7, health_for_s=0.0):
    """The stock rule set (ISSUE 19 + the ISSUE 20 model-health pair):
    every threshold is a parameter so operators (and the CI drill)
    tighten them without subclassing.  The checkpoint-staleness bound
    defaults to 15 minutes — wider than any cadence the CheckFreq
    autotune picks; pass the tuned interval times a safety factor for a
    sharper rule.  The health thresholds are deliberately loose
    (norm > 1e4 = explosion, ratio < 1e-7 = frozen training); both only
    resolve to values on hosts running with FLAGS_health."""
    return [
        AlertRule("grad_norm_explosion", "host:grad_norm", grad_norm_max,
                  op=">", for_seconds=health_for_s, severity="critical"),
        AlertRule("update_ratio_collapse", "host:update_ratio",
                  update_ratio_min, op="<", for_seconds=health_for_s,
                  severity="warning"),
        AlertRule("goodput_collapse", "goodput_ratio", goodput_min,
                  op="<", for_seconds=goodput_for_s, severity="critical"),
        AlertRule("p99_over_slo", "p99:" + latency_hist, slo_p99_s,
                  op=">", for_seconds=p99_for_s, severity="critical"),
        AlertRule("replica_quarantined", "host:quarantined", 0.5,
                  op=">", for_seconds=0.0, severity="critical"),
        AlertRule("lease_expired", "host:lease_expired", 0.5,
                  op=">", for_seconds=0.0, severity="critical"),
        AlertRule("straggler", "host:straggler", 0.5,
                  op=">", for_seconds=straggler_for_s,
                  severity="warning"),
        AlertRule("checkpoint_stale", "host:checkpoint_age",
                  ckpt_max_age_s, op=">", for_seconds=0.0,
                  severity="warning"),
        AlertRule("digest_stale", "host:digest_age", digest_stale_s,
                  op=">", for_seconds=0.0, severity="warning"),
    ]
